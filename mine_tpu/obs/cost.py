"""Cost accounting: FLOPs/bytes per compiled step, peaks, MFU.

The "move MFU off 5.0%" roadmap item needs an MFU *instrument*, not a
bench artifact: XLA's own cost analysis of the compiled executable
(`lowered.compile().cost_analysis()`) gives the FLOPs and bytes the step
actually runs, `memory_analysis()` gives its peak live bytes, and the
published per-device peak tables turn a measured step time into MFU and
achieved-bandwidth fractions. bench.py, the training loop, and the
serving engine all quote THIS module, so every number in a BENCH_*.json,
a metrics.jsonl line, and a /metrics gauge shares one definition.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Any

# Published dense bf16 peak FLOP/s PER JAX DEVICE (what the executable and
# its cost analysis run on). On v2/v3 a jax device is one core (half a chip:
# 45/123 TFLOP per chip => 22.5/61.5 per core); v4 onward exposes one
# megacore device per chip. Sources: Google Cloud TPU docs / "How to Scale
# Your Model"; keyed by jax device_kind.
CHIP_PEAK_FLOPS = {
    "TPU v2": 22.5e12,
    "TPU v3": 61.5e12,
    "TPU v4": 275e12,
    "TPU v4 lite": 137e12,  # v4i
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,       # v5p (kept after the longer v5-lite/v5e keys)
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,       # ironwood, fp8-capable; bf16 peak
}

# Published HBM bandwidth, bytes/s per jax device (same per-core halving on
# v2/v3). Same sources as the FLOPs table.
CHIP_PEAK_HBM_BYTES = {
    "TPU v2": 350e9,
    "TPU v3": 450e9,
    "TPU v4": 1228e9,
    "TPU v4 lite": 614e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
    "TPU7x": 7400e9,
}


def _lookup(table: dict[str, float], device_kind: str) -> float | None:
    if device_kind in table:
        return table[device_kind]
    # prefix match tolerates suffixes like "TPU v4 (podslice)"
    for kind, peak in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if device_kind.startswith(kind):
            return peak
    return None


def chip_peak_flops(device_kind: str) -> float | None:
    """Peak FLOP/s of one jax device of this kind (None when unknown —
    notably "cpu": no honest published number exists for an arbitrary
    host, so CPU runs pass an explicit obs.peak_flops_override instead
    of trusting a made-up table entry)."""
    return _lookup(CHIP_PEAK_FLOPS, device_kind)


def chip_peak_hbm_bytes(device_kind: str) -> float | None:
    """Peak memory bandwidth (bytes/s) of one jax device (None unknown)."""
    return _lookup(CHIP_PEAK_HBM_BYTES, device_kind)


@dataclass(frozen=True)
class StepCost:
    """What one invocation of a compiled executable costs, per XLA."""

    flops: float | None = None
    bytes_accessed: float | None = None
    peak_memory_bytes: float | None = None   # temp + output live bytes
    argument_bytes: float | None = None
    output_bytes: float | None = None

    def to_dict(self) -> dict[str, float | None]:
        return asdict(self)


def compiled_cost(compiled: Any) -> StepCost:
    """Extract FLOPs/bytes from a jax Compiled (lowered.compile() result).

    Every probe is individually guarded: backends differ in which analyses
    they implement (and the tunneled TPU backend can fail mid-call) — a
    partial StepCost beats an exception in an instrument.
    """
    flops = bytes_accessed = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # some backends wrap in a list
            cost = cost[0]
        if cost:
            f = cost.get("flops")
            flops = float(f) if f and f > 0 else None
            b = cost.get("bytes accessed")
            bytes_accessed = float(b) if b and b > 0 else None
    except Exception:  # noqa: BLE001 - backend-dependent surface
        pass
    peak = arg_b = out_b = None
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            peak = float(ma.temp_size_in_bytes + ma.output_size_in_bytes)
            arg_b = float(ma.argument_size_in_bytes)
            out_b = float(ma.output_size_in_bytes)
    except Exception:  # noqa: BLE001
        pass
    return StepCost(
        flops=flops, bytes_accessed=bytes_accessed, peak_memory_bytes=peak,
        argument_bytes=arg_b, output_bytes=out_b,
    )


def compute_mfu(
    flops_per_step: float | None,
    step_seconds: float,
    peak_flops: float | None,
) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s over the device peak.

    None in, None out — an unknown FLOP count or peak must surface as an
    absent gauge, never as a fake 0% or 100%.
    """
    if not flops_per_step or not peak_flops or step_seconds <= 0:
        return None
    return (flops_per_step / step_seconds) / peak_flops


def achieved_fraction(
    amount_per_step: float | None,
    step_seconds: float,
    peak_per_second: float | None,
) -> float | None:
    """Generic achieved/peak fraction (bytes for bandwidth, FLOPs for MFU)."""
    if not amount_per_step or not peak_per_second or step_seconds <= 0:
        return None
    return (amount_per_step / step_seconds) / peak_per_second


def resolve_peak_flops(device: Any = None, override: float = 0.0) -> float | None:
    """The peak the gauges divide by: an explicit override wins (the only
    honest option on CPU meshes); else the per-kind table; else None."""
    if override and override > 0:
        return float(override)
    if device is None:
        import jax

        device = jax.devices()[0]
    return chip_peak_flops(device.device_kind)


def resolve_peak_hbm_bytes(device: Any = None, override: float = 0.0) -> float | None:
    if override and override > 0:
        return float(override)
    if device is None:
        import jax

        device = jax.devices()[0]
    return chip_peak_hbm_bytes(device.device_kind)
