"""Per-component device-time attribution: who owns the step time.

The MFU-climb roadmap item stalls on a question tools/profile_summary.py
cannot answer: raw HLO op rows ("fusion.123", "dot.4") say nothing about
WHICH model component — encoder, decoder, warp, composite, losses,
optimizer — owns the device time. The components are now annotated with
`jax.named_scope` throughout models/, ops/ and training/step.py (the
sharded-update gathers carry zero1_gather, the FSDP weight gather
fsdp_gather), so every XLA op's metadata carries a scope path like

    jit(train_step)/transpose(jvp(...))/losses/composite/reduce_sum

This module turns that metadata plus a captured profile into a
per-component table:

  * `hlo_op_components(hlo_text)` parses the compiled executable's own
    text (`compiled.as_text()`) into {instruction name -> component}: the
    op_name metadata survives fusion, so "fusion.123" still knows which
    scope it came from.
  * `attribute_events(events, op_components)` walks Chrome-trace events
    (jax.profiler device traces: TPU TensorCore lanes carry the scope in
    their event args; CPU runs carry only the HLO instruction name, which
    the HLO map resolves) and buckets durations per component, with an
    explicit `unattributed` remainder row and a coverage fraction — the
    table is only trustworthy when coverage >= COVERAGE_TARGET (0.9).
  * `attribute_profile_dir(dir)` glues both halves for a run directory:
    newest device trace + the `*_hlo.txt` dump training writes next to it.
  * `attribute_hlo(hlo_text, cost)` is the trace-free fallback
    (cost_analysis totals + per-component HLO op counts): CPU runs that
    never captured a profile still get an honest op-count breakdown, with
    time columns absent rather than fabricated.

Everything is stdlib-only at import time (no jax), so the offline
tools/profile_summary.py reader can use it too.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Iterable

# Components in the order the scopes nest, innermost-distinctive first.
# component_of scans an op_name's path segments RIGHT-TO-LEFT, so an op
# inside jit(...)/losses/composite/... attributes to "composite", not
# "losses" — the innermost annotated scope wins, which is what makes the
# nesting (losses wraps the render calls) attribute correctly.
COMPONENT_PATTERNS: tuple[tuple[str, re.Pattern], ...] = tuple(
    (name, re.compile(pat))
    for name, pat in (
        ("zero1_gather", r"^zero1_gather$"),
        ("fsdp_gather", r"^fsdp_gather$"),
        ("optimizer", r"^optimizer$"),
        ("losses", r"^losses$"),
        ("homography_warp", r"^homography_warp$"),
        ("composite", r"^composite$"),
        ("decoder", r"^decoder$"),
        # flax names the encoder module "backbone"; both spellings map
        ("encoder", r"^(encoder|backbone)$"),
    )
)

COMPONENTS = tuple(name for name, _ in COMPONENT_PATTERNS)
UNATTRIBUTED = "unattributed"

# the table "accounts for" the step only above this attributed fraction
# (the acceptance bar every consumer quotes)
COVERAGE_TARGET = 0.9


def component_of(op_name: str | None) -> str | None:
    """Map one op_name metadata path to its component (None = unscoped).

    Scans the '/'-separated path segments innermost-first; transform
    wrappers like "transpose(jvp(main))" around a segment are stripped so
    backward-pass ops attribute to the same component as their forward.
    """
    if not op_name:
        return None
    for seg in reversed(op_name.split("/")):
        # peel transform wrappers: transpose(jvp(encoder)) -> encoder
        while True:
            m = re.fullmatch(r"[\w.\-]+\((.*)\)", seg)
            if m is None:
                break
            seg = m.group(1)
        for name, pat in COMPONENT_PATTERNS:
            if pat.search(seg):
                return name
    return None


# HLO text: `%instr.name = type op(...), ..., metadata={... op_name="..."}`
_HLO_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=")
_HLO_OPNAME_RE = re.compile(r'metadata=\{[^}]*?op_name="([^"]*)"')
# computation references an instruction makes: calls=%f / to_apply=%f /
# {body,condition}=%f (while) / branch_computations={%a, %b} (conditional)
_HLO_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition|branch_computations=\{[^}]*?)"
    r"=?%([\w.\-]+)"
)
# a computation header: `%name (params) -> type {` — no `=` before the body
_HLO_COMP_DEF_RE = re.compile(r"^\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_HLO_REF_RE = re.compile(r"%([\w.\-]+)")


def _parse_hlo(hlo_text: str) -> list[dict]:
    """One record per HLO instruction across every computation in the
    module: name, direct component (op_name metadata), called computation
    names, operand instruction names, and the home computation."""
    records: list[dict] = []
    comp_name = None
    for line in hlo_text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if m is None:
            cm = _HLO_COMP_DEF_RE.match(line)
            if cm is not None and "=" not in line.split("(")[0]:
                comp_name = cm.group(1)
            continue
        om = _HLO_OPNAME_RE.search(line)
        rhs = line.split("=", 1)[1]
        called = set(_HLO_CALLED_RE.findall(line))
        refs = [r for r in _HLO_REF_RE.findall(rhs)
                if r not in called and r != m.group(1)]
        records.append({
            "name": m.group(1),
            "component": component_of(om.group(1)) if om else None,
            "calls": called,
            "operands": refs,
            "computation": comp_name,
        })
    return records


def hlo_op_components(hlo_text: str) -> dict[str, str]:
    """{HLO instruction name -> component} from a compiled module's text.

    Covers every computation in the module (fused computations included:
    CPU thunk events carry inner instruction names like "tanh.5.clone").
    Three resolution rules, run to fixpoint:

      1. direct — the instruction's own op_name metadata names a scope;
      2. called-computation majority — XLA wraps scoped regions in
         metadata-less `call`/`fusion` instructions whose CALLED
         computation's ops still carry the scope;
      3. operand inheritance — a metadata-less op (e.g. the reduce-window
         XLA:CPU inserts for a big reduce) belongs to the scope of the
         values it consumes, when those agree.

    Instructions no rule reaches are omitted — absence means
    `unattributed`, never a guess.
    """
    records = _parse_hlo(hlo_text)
    resolved: dict[str, str] = {
        r["name"]: r["component"] for r in records
        if r["component"] is not None
    }
    for _ in range(8):  # fixpoint: chains are shallow in practice
        # one O(N) sweep per pass: majority component of every computation
        votes: dict[str, dict[str, int]] = {}
        for r in records:
            c = resolved.get(r["name"])
            if c is not None:
                v = votes.setdefault(r["computation"], {})
                v[c] = v.get(c, 0) + 1
        majority = {
            comp: max(v.items(), key=lambda kv: kv[1])[0]
            for comp, v in votes.items()
        }
        changed = False
        for r in records:
            if r["name"] in resolved:
                continue
            comp = None
            # rule 2: the called computation's majority component
            for callee in r["calls"]:
                comp = majority.get(callee)
                if comp is not None:
                    break
            if comp is None and r["operands"]:
                # rule 3: unanimous resolved operands
                ops = {resolved[o] for o in r["operands"] if o in resolved}
                if len(ops) == 1:
                    comp = ops.pop()
            if comp is not None:
                resolved[r["name"]] = comp
                changed = True
        if not changed:
            break
    return resolved


def _event_op_name(ev: dict) -> str | None:
    """The scope-carrying metadata a device trace event itself provides
    (TPU TensorCore lanes); None when only an HLO instruction name exists
    (CPU runs — resolved via the HLO map instead)."""
    args = ev.get("args") or {}
    for key in ("tf_op", "long_name", "op_name"):
        v = args.get(key)
        if isinstance(v, str) and "/" in v:
            return v
    return None


def _is_op_event(ev: dict, device_pids: set | None) -> bool:
    if ev.get("ph") != "X":
        return False
    if device_pids:
        return ev.get("pid") in device_pids
    # no device lane metadata (CPU runs): XLA op executions are exactly
    # the events annotated with their HLO op
    return "hlo_op" in (ev.get("args") or {})


def attribute_events(
    events: Iterable[dict],
    op_components: dict[str, str] | None = None,
    device_pids: set | None = None,
) -> dict:
    """Bucket device-trace op events into per-component time.

    Returns {"rows": [{component, time_ms, pct, calls}...] sorted by time
    (the `unattributed` remainder always last), "total_ms", "attributed_ms",
    "coverage", "covered": coverage >= COVERAGE_TARGET}.
    """
    op_components = op_components or {}
    totals: dict[str, list[float]] = {}
    total_us = 0.0
    for ev in events:
        if not _is_op_event(ev, device_pids):
            continue
        dur = float(ev.get("dur", 0.0))
        total_us += dur
        comp = component_of(_event_op_name(ev))
        if comp is None:
            args = ev.get("args") or {}
            op = args.get("hlo_op") or ev.get("name", "")
            comp = op_components.get(str(op))
        if comp is None:
            comp = UNATTRIBUTED
        tot = totals.setdefault(comp, [0.0, 0])
        tot[0] += dur
        tot[1] += 1
    attributed_us = sum(
        t[0] for comp, t in totals.items() if comp != UNATTRIBUTED
    )
    rows = [
        {
            "component": comp,
            "time_ms": round(t[0] / 1e3, 3),
            "pct": round(100.0 * t[0] / total_us, 1) if total_us else None,
            "calls": int(t[1]),
        }
        for comp, t in totals.items()
    ]
    rows.sort(key=lambda r: (r["component"] == UNATTRIBUTED, -r["time_ms"]))
    coverage = (attributed_us / total_us) if total_us else 0.0
    return {
        "rows": rows,
        "total_ms": round(total_us / 1e3, 3),
        "attributed_ms": round(attributed_us / 1e3, 3),
        "coverage": round(coverage, 4),
        "covered": coverage >= COVERAGE_TARGET,
    }


def attach_cost_estimates(table: dict, flops: float | None,
                          bytes_accessed: float | None) -> dict:
    """Add time-weighted FLOPs/bytes estimates to an attribution table:
    the executable's cost_analysis totals split by each component's share
    of attributed time. An estimate (XLA only totals whole executables),
    labeled as such — the time column is the measurement."""
    if not table.get("rows"):
        return table
    for row in table["rows"]:
        share = (row["time_ms"] / table["total_ms"]) if table["total_ms"] else 0.0
        row["flops_est"] = round(flops * share) if flops else None
        row["bytes_est"] = round(bytes_accessed * share) if bytes_accessed else None
    table["cost_note"] = (
        "flops_est/bytes_est are the executable's cost_analysis totals "
        "split by time share — estimates, not per-op counts"
    )
    return table


def attribute_hlo(hlo_text: str, flops: float | None = None,
                  bytes_accessed: float | None = None) -> dict:
    """Trace-free fallback: per-component HLO op COUNTS (no fabricated
    time column) plus the executable's aggregate cost_analysis figures.
    Coverage here is op-count coverage — which fraction of metadata-scoped
    instructions landed under a named component."""
    resolved = hlo_op_components(hlo_text)
    counts: dict[str, int] = {}
    total = 0
    for rec in _parse_hlo(hlo_text):
        total += 1
        comp = resolved.get(rec["name"], UNATTRIBUTED)
        counts[comp] = counts.get(comp, 0) + 1
    attributed = sum(n for c, n in counts.items() if c != UNATTRIBUTED)
    rows = [
        {"component": c, "hlo_ops": n,
         "pct": round(100.0 * n / total, 1) if total else None}
        for c, n in counts.items()
    ]
    rows.sort(key=lambda r: (r["component"] == UNATTRIBUTED, -r["hlo_ops"]))
    return {
        "rows": rows,
        "hlo_ops_total": total,
        "coverage": round(attributed / total, 4) if total else 0.0,
        "flops_total": flops,
        "bytes_total": bytes_accessed,
        "basis": "hlo_op_count",
        "note": "no device trace: shares are HLO op counts, not time — "
                "capture a profile (obs.profile_steps) for time attribution",
    }


# -- run-directory glue -------------------------------------------------------


def find_trace_files(root: str) -> list[str]:
    out: list[str] = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        out.extend(glob.glob(os.path.join(root, "**", pat), recursive=True))
    return sorted(out)


def load_trace_events(path: str) -> list[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        data = json.load(fh)
    return data.get("traceEvents", data if isinstance(data, list) else [])


def find_hlo_text(root: str) -> str | None:
    """Newest `*_hlo.txt` dump under the run dir (training writes
    `train_step_hlo.txt` next to its profile when obs is enabled)."""
    paths = sorted(glob.glob(os.path.join(root, "**", "*_hlo.txt"),
                             recursive=True))
    if not paths:
        return None
    with open(paths[-1]) as fh:
        return fh.read()


def _device_lane_pids(events: list[dict]) -> set:
    """pids of on-device lanes (TensorCore etc.) — mirrors
    tools/profile_summary.py's device_pids, minus the host-span lane."""
    pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = ev.get("args", {}).get("name", "")
            if "mine_tpu host" in name:
                continue
            if any(k in name.lower() for k in
                   ("tensorcore", "sparsecore", "/device:")):
                pids.add(ev["pid"])
    return pids


def attribute_profile_dir(
    trace_dir: str, hlo_text: str | None = None
) -> dict | None:
    """Attribution table for a captured run directory, or None when no
    trace file holds op events. Newest trace file with op events wins;
    the HLO map comes from `hlo_text` or the dir's own `*_hlo.txt` dump."""
    if hlo_text is None:
        hlo_text = find_hlo_text(trace_dir)
    op_components = hlo_op_components(hlo_text) if hlo_text else {}
    for path in reversed(find_trace_files(trace_dir)):
        try:
            events = load_trace_events(path)
        except (OSError, ValueError):
            continue
        dev_pids = _device_lane_pids(events)
        table = attribute_events(events, op_components, dev_pids or None)
        if table["rows"]:
            table["trace"] = path
            table["hlo_map_ops"] = len(op_components)
            return table
    return None


