"""Append-only perf ledger: every bench number, durable and comparable.

BENCH_r01–r05 are disconnected snapshot files — several null, none
comparable without reading five JSONs and guessing whether the workload
matched. The ledger replaces that with one append-only JSONL file
(default `perf_ledger.jsonl`, override/disable via $MINE_TPU_PERF_LEDGER):
every bench run (bench.py, tools/bench_serve.py, tools/bench_accum.py)
appends one row carrying

  ts, git_rev, metric, value, unit, config_digest (what workload),
  device + backend_class (what hardware), and the perf vitals —
  mfu, step_ms, peak_hbm_bytes, p50_ms/p95_ms where they exist.

`check` compares each (metric, config_digest, device, backend_class,
mesh_shape) stream's NEWEST row against the median of its prior rows (the rolling
baseline) and flags a regression when the newest value moves beyond
`threshold` in the bad direction — the gate every later perf PR quotes
(`python tools/perf_ledger.py check`). Fewer than `min_history` prior
rows => the stream is skipped, never failed: a new workload cannot
regress against nothing.

Rows are one JSON object per line; appends are a single O_APPEND write so
concurrent bench processes interleave whole lines. A malformed line (a
killed writer) is skipped with a note, never a crash — the ledger is an
instrument.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
from statistics import median
from typing import Any

DEFAULT_LEDGER = "perf_ledger.jsonl"
LEDGER_ENV = "MINE_TPU_PERF_LEDGER"

# aux metrics checked alongside `value` when both the newest row and its
# history carry them; value: higher_is_better
AUX_METRICS: dict[str, bool] = {
    "p95_ms": False,
    "peak_hbm_bytes": False,
    # compressed-MPI fleet economics (tools/bench_fleet.py): cache entries
    # the byte budget holds per GiB (∝ 1/bytes-per-entry — a tier or
    # pruning regression shrinks it) and the skew-trace hit rate it buys
    "cache_entries_per_gib": True,
    "cache_hit_rate": True,
}


def ledger_path() -> str | None:
    """The ledger file benches append to: $MINE_TPU_PERF_LEDGER wins
    ("0"/"off"/"none" disables), else ./perf_ledger.jsonl."""
    env = os.environ.get(LEDGER_ENV)
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "none", "false"):
            return None
        return env
    return DEFAULT_LEDGER


def git_rev(cwd: str | None = None) -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:  # noqa: BLE001 - evidence, not correctness
        return None


def set_build_info(registry: Any, backend: str | None = None) -> None:
    """Publish the `mine_build_info{git_rev,jax_version,backend}` info
    gauge (constant value 1, the Prometheus info-metric idiom) on a
    metrics registry. One helper so the training gauges, every replica's
    /metrics, and the fleet router all spell the labels identically — a
    scrape then joins perf-ledger rows (which already carry git_rev)
    without guesswork. `backend` stays whatever the caller KNOWS: the
    router never initializes a jax backend and passes None ("none") —
    this helper must not probe one into existence just for a label."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # noqa: BLE001 - an info gauge must never crash
        jax_version = "unknown"
    registry.gauge(
        "mine_build_info",
        "build/runtime identity (value is always 1; the labels are the "
        "payload): git revision, jax version, backend",
    ).set(
        1,
        git_rev=git_rev() or "unknown",
        jax_version=jax_version,
        backend=backend or "none",
    )


def config_digest(workload: dict[str, Any]) -> str:
    """Short stable digest of the workload knobs that make two rows
    comparable (shape, batch, planes, ... — NOT the measured values)."""
    blob = json.dumps(workload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def backend_class(backend_note: str | None) -> str:
    """'cpu (degraded: ...)' and 'cpu (forced)' are the same hardware
    class; comparisons key on the class, not the prose."""
    if not backend_note:
        return "unknown"
    return str(backend_note).split()[0].split("(")[0] or "unknown"


def make_row(
    metric: str,
    value: float | None,
    workload: dict[str, Any],
    unit: str = "",
    higher_is_better: bool = True,
    **fields: Any,
) -> dict:
    """One ledger row; extra perf vitals ride along as plain fields."""
    row = {
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_rev": git_rev(),
        "metric": metric,
        "value": value,
        "unit": unit,
        "higher_is_better": bool(higher_is_better),
        "config_digest": config_digest(workload),
        "workload": workload,
    }
    row.update({k: v for k, v in fields.items() if v is not None})
    row["backend_class"] = backend_class(row.get("backend"))
    return row


def append(path: str, row: dict) -> dict:
    """Append one row (single write, O_APPEND semantics). Returns the row
    as written."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    line = json.dumps(row, sort_keys=True)
    with open(path, "a") as fh:
        fh.write(line + "\n")
    return row


def read(path: str) -> tuple[list[dict], int]:
    """(rows, malformed-line count); missing file reads as empty."""
    rows: list[dict] = []
    bad = 0
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(row, dict) and "metric" in row:
                    rows.append(row)
                else:
                    bad += 1
    except FileNotFoundError:
        pass
    return rows, bad


def stream_key(row: dict) -> tuple:
    """(metric, workload digest, device, backend class, mesh shape): two
    rows are comparable only when ALL agree — `check` must never grade a
    (4,2)-mesh run against a single-chip baseline stream. `mesh_shape` is
    the 'DxFxP' string (parallel/mesh.py mesh_shape_str); writers OMIT it
    for trivial single-device runs, so pre-mesh history keys identically
    to new single-device rows and baselines carry over."""
    return (
        row.get("metric"),
        row.get("config_digest"),
        row.get("device"),
        row.get("backend_class", backend_class(row.get("backend"))),
        row.get("mesh_shape"),
    )


def rolling_baseline(
    history: list[dict], field: str = "value", window: int = 5
) -> float | None:
    """Median of the last `window` non-null `field` values in `history`
    (oldest-first order preserved from the file)."""
    vals = [row[field] for row in history
            if isinstance(row.get(field), (int, float))]
    if not vals:
        return None
    return float(median(vals[-int(window):]))


def _verdict_for(
    name: str, newest: float, baseline: float, higher_is_better: bool,
    threshold: float,
) -> dict:
    if baseline == 0:
        delta = 0.0
    elif higher_is_better:
        delta = (baseline - newest) / abs(baseline)
    else:
        delta = (newest - baseline) / abs(baseline)
    return {
        "field": name,
        "value": newest,
        "baseline": baseline,
        "vs_baseline": round(newest / baseline, 4) if baseline else None,
        "regression_delta": round(delta, 4),
        "regressed": delta > threshold,
    }


def check_rows(
    rows: list[dict],
    threshold: float = 0.10,
    window: int = 5,
    min_history: int = 2,
) -> dict:
    """Newest row of every comparable stream vs its rolling baseline.

    Returns {"ok", "checked": [...], "skipped": [...], "regressions": N}.
    ok is True when no checked field regressed beyond threshold.
    """
    streams: dict[tuple, list[dict]] = {}
    for row in rows:
        streams.setdefault(stream_key(row), []).append(row)
    checked, skipped = [], []
    regressions = 0
    for key, stream in streams.items():
        newest, history = stream[-1], stream[:-1]
        label = {"metric": key[0], "config_digest": key[1],
                 "device": key[2], "backend_class": key[3]}
        usable = [r for r in history
                  if isinstance(r.get("value"), (int, float))]
        if len(usable) < min_history:
            skipped.append({**label, "reason":
                            f"{len(usable)} prior rows < min_history="
                            f"{min_history}"})
            continue
        if not isinstance(newest.get("value"), (int, float)):
            skipped.append({**label, "reason": "newest row has no value"})
            continue
        fields = [("value", bool(newest.get("higher_is_better", True)))]
        fields += [
            (aux, hib) for aux, hib in AUX_METRICS.items()
            if isinstance(newest.get(aux), (int, float))
            and rolling_baseline(usable, aux, window) is not None
        ]
        verdicts = []
        for field, hib in fields:
            baseline = rolling_baseline(usable, field, window)
            if baseline is None:
                continue
            v = _verdict_for(field, float(newest[field]), baseline, hib,
                             threshold)
            regressions += int(v["regressed"])
            verdicts.append(v)
        checked.append({**label, "history": len(usable),
                        "fields": verdicts})
    return {
        "ok": regressions == 0,
        "threshold": threshold,
        "window": window,
        "min_history": min_history,
        "checked": checked,
        "skipped": skipped,
        "regressions": regressions,
    }


def check(path: str, threshold: float = 0.10, window: int = 5,
          min_history: int = 2) -> dict:
    rows, bad = read(path)
    verdict = check_rows(rows, threshold=threshold, window=window,
                         min_history=min_history)
    verdict.update(ledger=path, rows=len(rows), malformed_lines=bad)
    return verdict


def append_bench_row(result_fields: dict, workload: dict,
                     path: str | None = None) -> dict | None:
    """The one-call integration the bench tools use: build a row from a
    bench's emitted fields, append it to the configured ledger, return
    the row (None when the ledger is disabled). Never raises — a bench
    must emit its number even when the ledger file is unwritable."""
    path = ledger_path() if path is None else path
    if path is None:
        return None
    try:
        row = make_row(workload=workload, **result_fields)
        append(path, row)
        return row
    except Exception as exc:  # noqa: BLE001 - the measurement outranks the ledger
        # but an unwritable ledger must not masquerade as a disabled one:
        # without this note the regression gate checks 0 streams forever
        # and nothing anywhere says why
        import sys

        print(f"# perf-ledger append to {path} failed: {exc}",
              file=sys.stderr)
        return None
