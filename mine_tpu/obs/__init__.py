"""Observability: host-span tracing, flight recorder, cost/MFU accounting.

Three pillars (no reference analog — the reference logs loss lines and
nothing else; VERDICT r5 records five consecutive benchmark rounds that
died with zero diagnostics):

  * obs/trace.py  — lightweight host-side spans with Chrome-trace JSON
    export that merges with the device traces jax.profiler writes.
  * obs/flight.py — flight recorder: signal + stall-watchdog dump of
    all-thread stacks, the last-K spans, and device memory stats.
  * obs/cost.py   — per-compiled-step FLOPs/bytes from XLA's own cost
    analysis, a per-platform peak table, and MFU / achieved-bandwidth
    arithmetic.

Everything is stdlib + jax-optional: the tracer and flight recorder never
import jax at module level, so they work in data-loader processes too.
"""

from mine_tpu.obs.cost import (
    StepCost,
    achieved_fraction,
    chip_peak_flops,
    chip_peak_hbm_bytes,
    compiled_cost,
    compute_mfu,
)
from mine_tpu.obs.flight import FlightRecorder
from mine_tpu.obs.trace import NULL_TRACER, Span, Tracer

__all__ = [
    "FlightRecorder",
    "NULL_TRACER",
    "Span",
    "StepCost",
    "Tracer",
    "achieved_fraction",
    "chip_peak_flops",
    "chip_peak_hbm_bytes",
    "compiled_cost",
    "compute_mfu",
]
