"""Observability: tracing, flight recorder, cost/MFU, attribution, ledger.

Six pillars (no reference analog — the reference logs loss lines and
nothing else; VERDICT r5 records five consecutive benchmark rounds that
died with zero diagnostics):

  * obs/trace.py  — lightweight host-side spans with Chrome-trace JSON
    export that merges with the device traces jax.profiler writes.
  * obs/flight.py — flight recorder: signal + stall-watchdog dump of
    all-thread stacks, the last-K spans, and device memory stats.
  * obs/cost.py   — per-compiled-step FLOPs/bytes from XLA's own cost
    analysis, a per-platform peak table, and MFU / achieved-bandwidth
    arithmetic.
  * obs/attrib.py — per-component device-time attribution: jax.named_scope
    annotations (encoder/decoder/warp/composite/losses/optimizer/
    zero1_gather) joined with profiler traces or compiled HLO metadata
    into a table that must account for >= 90% of device time.
  * obs/memlog.py — live HBM telemetry: device.memory_stats() polled into
    hbm_{live,peak}_bytes gauges + Chrome-trace counter events.
  * obs/ledger.py — append-only JSONL perf ledger with a rolling-baseline
    regression gate (tools/perf_ledger.py check).
  * obs/collect.py — cross-process trace collection: merge N processes'
    span rings (/debug/trace, host_spans_p*.trace.json) into one
    skew-annotated timeline with per-process lanes, per-request hop
    trees, and the multi-host training straggler attribution.
  * obs/slo.py    — SLO/error-budget tracking: declarative availability +
    latency objectives evaluated in rolling windows over the existing
    metric families, published as mine_slo_* gauges.

Everything is stdlib + jax-optional: the tracer, flight recorder, ledger
and attribution parser never import jax at module level, so they work in
data-loader processes and offline tools too.
"""

from mine_tpu.obs.attrib import (
    COMPONENTS,
    UNATTRIBUTED,
    attribute_events,
    attribute_profile_dir,
    component_of,
    hlo_op_components,
)
from mine_tpu.obs.cost import (
    StepCost,
    achieved_fraction,
    chip_peak_flops,
    chip_peak_hbm_bytes,
    compiled_cost,
    compute_mfu,
)
from mine_tpu.obs.flight import FlightRecorder
from mine_tpu.obs.memlog import MemLog
from mine_tpu.obs.slo import Objective, SLOTracker, default_objectives
from mine_tpu.obs.trace import NULL_TRACER, Span, Tracer, new_span_id

__all__ = [
    "COMPONENTS",
    "FlightRecorder",
    "MemLog",
    "NULL_TRACER",
    "Objective",
    "SLOTracker",
    "Span",
    "StepCost",
    "Tracer",
    "UNATTRIBUTED",
    "achieved_fraction",
    "attribute_events",
    "attribute_profile_dir",
    "chip_peak_flops",
    "chip_peak_hbm_bytes",
    "compiled_cost",
    "component_of",
    "compute_mfu",
    "default_objectives",
    "hlo_op_components",
    "new_span_id",
]
