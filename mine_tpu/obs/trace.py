"""Host-side span tracing with Chrome-trace JSON export.

jax.profiler captures what the DEVICE does; nothing in this repo captured
what the HOST does around it — data staging, dispatch, device_get syncs,
checkpoint writes, HTTP request phases. The tracer fills that half:

  * `Tracer.span(name)` is a context manager recording a wall-clock span
    into a bounded ring buffer (deque), with a thread-local stack so spans
    nest and a per-(cat, name) running total for cheap phase summaries.
  * `to_chrome_trace()` / `export()` emit Chrome trace-event JSON whose
    process lane is named `mine_tpu host spans`, so the file drops into
    chrome://tracing / Perfetto NEXT TO a `jax.profiler` device trace and
    tools/profile_summary.py can print one merged host+device table.
  * Disabled (the default everywhere but serving), `span()` returns a
    shared no-op context manager — one attribute check and no allocation,
    so leaving the instrumentation in hot paths costs nothing measurable
    (guarded by a tier-1 smoke in tests/test_obs.py).

Thread-safety: the ring and totals take a reentrant lock (reentrant so a
signal-handler flight dump on the main thread can snapshot the ring even
if it interrupted an append); the span stack is thread-local.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable

# the process-lane name host exports carry; tools/profile_summary.py keys
# its host-vs-device lane split on this string
HOST_PROCESS_NAME = "mine_tpu host spans"

# span args the cross-process trace context rides in (obs/collect.py
# assembles the per-request tree from exactly these): `span_id` names a
# span so a downstream hop can point back at it, `parent_span` is the
# upstream hop's span_id (arrived as the X-Parent-Span header), and
# `request_id` is the trace id (the existing X-Request-Id)
SPAN_ID_ARG = "span_id"
PARENT_SPAN_ARG = "parent_span"
REQUEST_ID_ARG = "request_id"

# the HTTP spellings of the trace context (one definition: the router,
# the replica server, and the peer-fetch client all propagate these)
REQUEST_ID_HEADER = "X-Request-Id"
PARENT_SPAN_HEADER = "X-Parent-Span"

# charset guard for BOTH context headers: a value is echoed into response
# headers and span args, so anything that could smuggle newlines or
# unbounded bytes gets replaced (request id: minted; parent span:
# dropped). One spelling — the router and the replica server must never
# disagree on what a well-formed token is.
TRACE_TOKEN_RE = re.compile(r"^[A-Za-z0-9._\-]{1,128}$")


def new_span_id() -> str:
    """A fresh span id for a cross-process hop (forward, peer fetch, swap
    fan-out): short enough to ride a header, unique enough per ring."""
    return uuid.uuid4().hex[:12]


def resolve_request_id(raw: str | None) -> str:
    """The caller-supplied request id when well-formed (TRACE_TOKEN_RE),
    else a minted one — every request gets an addressable trace id. ONE
    implementation for the router and the replica handlers: the mint
    shape and the charset rule must never drift between them."""
    if raw and TRACE_TOKEN_RE.match(raw):
        return raw
    return uuid.uuid4().hex[:16]


def resolve_parent_span(raw: str | None) -> str | None:
    """The upstream hop's span id when well-formed, else None (a
    malformed parent is dropped, never echoed into span args)."""
    return raw if raw and TRACE_TOKEN_RE.match(raw) else None


@dataclass(frozen=True)
class Span:
    """One completed span. Times are microseconds on the tracer's
    monotonic epoch (perf_counter-based — durations are exact; absolute
    alignment with a device trace is not promised, same as any two
    independent trace clocks)."""

    name: str
    cat: str
    ts_us: float
    dur_us: float
    tid: int
    thread_name: str
    depth: int
    args: dict[str, Any] = field(default_factory=dict)


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> None:
        return None


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager for one enabled span."""

    __slots__ = ("tracer", "name", "cat", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0.0

    def __enter__(self) -> "_LiveSpan":
        self.tracer._push(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        t1 = time.perf_counter()
        self.tracer._pop_and_record(
            self.name, self.cat, self.t0, t1, self.args
        )


class Tracer:
    """Bounded-ring host span recorder; one per subsystem instance.

    on_span: optional callback invoked (outside the lock) with each
    completed Span — the serving stack hooks its trace-counter metric
    family here.
    """

    def __init__(
        self,
        enabled: bool = False,
        max_spans: int = 4096,
        on_span: Callable[[Span], None] | None = None,
    ):
        self.enabled = bool(enabled)
        self.max_spans = int(max_spans)
        self.on_span = on_span
        self._epoch = time.perf_counter()
        self._lock = threading.RLock()
        self._spans: deque[Span] = deque(maxlen=self.max_spans)  # guarded-by: _lock
        self._dropped = 0  # guarded-by: _lock
        # running (cat, name) -> [count, total_us] since last summary reset
        self._totals: dict[tuple[str, str], list[float]] = defaultdict(  # guarded-by: _lock
            lambda: [0.0, 0.0]
        )
        self._local = threading.local()

    # -- recording -----------------------------------------------------------

    def span(self, name: str, cat: str = "host", **args: Any):
        """Context manager timing one phase; no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, cat, args)

    def _stack(self) -> list[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _push(self, name: str) -> None:
        self._stack().append(name)

    def _pop_and_record(
        self, name: str, cat: str, t0: float, t1: float, args: dict
    ) -> None:
        stack = self._stack()
        depth = max(len(stack) - 1, 0)
        if stack and stack[-1] == name:
            stack.pop()
        self._record(name, cat, t0, t1, args, depth)

    def _record(
        self, name: str, cat: str, t0: float, t1: float, args: dict,
        depth: int,
    ) -> None:
        thread = threading.current_thread()
        span = Span(
            name=name,
            cat=cat,
            ts_us=(t0 - self._epoch) * 1e6,
            dur_us=(t1 - t0) * 1e6,
            tid=thread.ident or 0,
            thread_name=thread.name,
            depth=depth,
            args=args,
        )
        with self._lock:
            if len(self._spans) == self.max_spans:
                self._dropped += 1
            self._spans.append(span)
            tot = self._totals[(cat, name)]
            tot[0] += 1
            tot[1] += span.dur_us
        if self.on_span is not None:
            self.on_span(span)

    def record(
        self, name: str, cat: str, t0: float, t1: float, **args: Any
    ) -> None:
        """Record a span from explicit perf_counter endpoints — for phases
        whose start and end live in different stack frames (e.g. the
        batcher's queue-wait, measured from another request's enqueue).
        Never touches the thread-local span stack."""
        if not self.enabled:
            return
        self._record(name, cat, t0, t1, args, depth=0)

    def active_spans(self) -> list[str]:
        """This thread's currently-open span names (outer -> inner)."""
        return list(self._stack())

    # -- reading -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def snapshot(self, last_k: int | None = None) -> list[Span]:
        with self._lock:
            spans = list(self._spans)
        return spans if last_k is None else spans[-int(last_k):]

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def phase_summary(self, reset: bool = False) -> dict[str, dict[str, float]]:
        """(cat.name) -> {count, total_ms, mean_ms} since the last reset —
        the cheap aggregate the training log interval and the bench obs
        snapshot publish without walking the ring."""
        with self._lock:
            out = {
                f"{cat}.{name}": {
                    "count": int(count),
                    "total_ms": round(total_us / 1e3, 3),
                    "mean_ms": round(total_us / 1e3 / count, 3) if count else 0.0,
                }
                for (cat, name), (count, total_us) in self._totals.items()
            }
            if reset:
                self._totals.clear()
        return out

    # -- export --------------------------------------------------------------

    def to_chrome_trace(
        self, last_k: int | None = None,
        extra_events: list[dict] | None = None,
    ) -> dict:
        """Chrome trace-event JSON (dict): `X` duration events per span plus
        process/thread metadata naming the host lane. extra_events (already
        on this tracer's timebase — e.g. obs/memlog.py counter events) are
        appended verbatim so they render in the same lane."""
        pid = os.getpid()
        spans = self.snapshot(last_k)
        events: list[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": HOST_PROCESS_NAME},
        }]
        seen_tids: dict[int, str] = {}
        for s in spans:
            if s.tid not in seen_tids:
                seen_tids[s.tid] = s.thread_name
                events.append({
                    "ph": "M", "pid": pid, "tid": s.tid,
                    "name": "thread_name",
                    "args": {"name": s.thread_name},
                })
            ev = {
                "ph": "X", "pid": pid, "tid": s.tid, "name": s.name,
                "cat": s.cat, "ts": round(s.ts_us, 3),
                "dur": round(s.dur_us, 3),
            }
            if s.args:
                ev["args"] = {k: _jsonable(v) for k, v in s.args.items()}
            events.append(ev)
        if extra_events:
            events.extend(extra_events)
        return {
            "displayTimeUnit": "ms",
            "traceEvents": events,
            "metadata": {
                "producer": HOST_PROCESS_NAME,
                "dropped_spans": self.dropped,
                # clock anchor: the tracer-timebase instant and the wall
                # clock AT EXPORT, captured back to back. A collector maps
                # any span ts onto this process's wall clock as
                #   wall_s = exported_unix_s + (ts_us - exported_ts_us)/1e6
                # which is what lets N processes' rings merge into ONE
                # timeline (obs/collect.py; residual skew between the
                # processes' wall clocks is estimated from probe round
                # trips there and recorded, never silently ignored).
                "clock": {
                    "exported_ts_us": (time.perf_counter() - self._epoch)
                    * 1e6,
                    "exported_unix_s": time.time(),
                },
            },
        }

    def export(
        self, path: str, last_k: int | None = None,
        extra_events: list[dict] | None = None,
    ) -> str:
        """Write the Chrome-trace JSON; name the file `*.trace.json` so
        tools/profile_summary.py's glob finds it next to device traces."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.to_chrome_trace(last_k, extra_events), fh)
        os.replace(tmp, path)
        return path


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# shared disabled tracer: a safe default for call sites that take an
# optional tracer (never enable it — it is process-global)
NULL_TRACER = Tracer(enabled=False)
