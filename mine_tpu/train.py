"""Training CLI: `python -m mine_tpu.train --config mine_tpu/configs/llff.yaml`.

Reference entry point: start_training.sh + train.py (torch.distributed.launch
multi-process spawn). Here there is no launcher layer — one process per host,
SPMD over the mesh; the same command works single-chip, v4-8, or multi-host
(with jax.distributed auto-detection).
"""

from __future__ import annotations

import argparse
import os


def build_dataset(cfg, split: str, global_batch: int,
                  host_slice: tuple[int, int] | None = None):
    """Dataset factory — the registry's table, re-exported here for the
    historical import path (data/registry.py is the implementation; every
    registered loader honors `host_slice=(start, count)`, materializing
    only this host's rows of each global batch). Unknown names raise
    UnknownDatasetError listing what IS registered and pointing at the
    conformance runner (tools/conformance_run.py)."""
    from mine_tpu.data.registry import build_dataset as registry_build

    return registry_build(cfg, split, global_batch, host_slice=host_slice)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--config", action="append", default=[],
        help="YAML config layer(s), later override earlier; the defaults "
        "layer is always implied first",
    )
    parser.add_argument(
        "--extra_config", default=None,
        help="JSON dict of final overrides (reference train.py --extra_config)",
    )
    parser.add_argument("--workspace", default="workspace/run")
    parser.add_argument(
        "--profile-steps", type=int, default=0,
        help="trace this many steps with jax.profiler into "
        "<workspace>/profile (equivalently obs.profile_steps; the window "
        "starts obs.profile_start_offset steps in, and with obs.enabled "
        "the host-span trace lands in the same directory)",
    )
    args = parser.parse_args(argv)

    # JAX_PLATFORMS=cpu must actually mean CPU even when an accelerator
    # plugin self-registers (and could hang on a dead device) — no-op
    # otherwise; must precede any backend touch
    from mine_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    # init_multihost must run before any backend-touching call; Trainer does
    # it first thing, so config parsing is the only work before this point.
    from mine_tpu.config import load_config
    from mine_tpu.training.loop import Trainer

    default = os.path.join(os.path.dirname(__file__), "configs", "default.yaml")
    cfg = load_config(default, *args.config, overrides=args.extra_config)

    trainer = Trainer(cfg, args.workspace, profile_steps=args.profile_steps)
    # the train loader materializes only this host's batch rows (per-host
    # data sharding); eval keeps global batches (the compat path — staging
    # slices them, run_evaluation's weighted meters need every host to see
    # the same metric stream anyway)
    train_ds = build_dataset(
        cfg, "train", trainer.global_batch,
        host_slice=trainer.host_batch_slice(),
    )
    val_ds = build_dataset(cfg, "val", trainer.global_batch)
    trainer.fit(train_ds, val_ds)


if __name__ == "__main__":
    main()
