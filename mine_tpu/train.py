"""Training CLI: `python -m mine_tpu.train --config mine_tpu/configs/llff.yaml`.

Reference entry point: start_training.sh + train.py (torch.distributed.launch
multi-process spawn). Here there is no launcher layer — one process per host,
SPMD over the mesh; the same command works single-chip, v4-8, or multi-host
(with jax.distributed auto-detection).
"""

from __future__ import annotations

import argparse
import os


def build_dataset(cfg, split: str, global_batch: int,
                  host_slice: tuple[int, int] | None = None):
    """Dataset factory (reference train.py:72-164 get_dataset).

    `host_slice` is (start, count) of the global batch THIS host should
    materialize (Trainer.host_batch_slice, off the `^batch/` partition
    row). Loaders that honor it build only their rows — each host's IO
    drops to 1/N of the global batch (the DistributedSampler role).
    Loaders without support ignore it and return global batches; staging
    slices those down on multi-process runs (numerically identical,
    parallel/mesh.py shard_batch — just wasteful host IO)."""
    name = cfg.data.name
    if name == "synthetic":
        # data.num_tgt_views is a no-op here by design: every synthetic batch
        # slot is a fresh procedural scene, so "k targets per source" has no
        # shared-source meaning (the real loaders implement it)
        from mine_tpu.data import SyntheticDataset

        return SyntheticDataset(
            cfg.data.img_h, cfg.data.img_w, global_batch,
            steps_per_epoch=12 if split == "train" else 2,
            n_points=cfg.data.visible_point_count,
            seed=cfg.training.seed + (0 if split == "train" else 10_000),
            host_slice=host_slice,
        )
    if name in ("llff", "nocs_llff"):
        from mine_tpu.data.llff import LLFFDataset

        return LLFFDataset(cfg, split, global_batch)
    if name == "objectron":
        from mine_tpu.data.objectron import ObjectronDataset

        return ObjectronDataset(cfg, split, global_batch)
    raise NotImplementedError(
        f"dataset {name!r} has no pipeline yet (reference parity: train.py:161-162 "
        "raises NotImplementedError for realestate10k/flowers/kitti_raw/dtu too)"
    )


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--config", action="append", default=[],
        help="YAML config layer(s), later override earlier; the defaults "
        "layer is always implied first",
    )
    parser.add_argument(
        "--extra_config", default=None,
        help="JSON dict of final overrides (reference train.py --extra_config)",
    )
    parser.add_argument("--workspace", default="workspace/run")
    parser.add_argument(
        "--profile-steps", type=int, default=0,
        help="trace this many steps with jax.profiler into "
        "<workspace>/profile (equivalently obs.profile_steps; the window "
        "starts obs.profile_start_offset steps in, and with obs.enabled "
        "the host-span trace lands in the same directory)",
    )
    args = parser.parse_args(argv)

    # JAX_PLATFORMS=cpu must actually mean CPU even when an accelerator
    # plugin self-registers (and could hang on a dead device) — no-op
    # otherwise; must precede any backend touch
    from mine_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    # init_multihost must run before any backend-touching call; Trainer does
    # it first thing, so config parsing is the only work before this point.
    from mine_tpu.config import load_config
    from mine_tpu.training.loop import Trainer

    default = os.path.join(os.path.dirname(__file__), "configs", "default.yaml")
    cfg = load_config(default, *args.config, overrides=args.extra_config)

    trainer = Trainer(cfg, args.workspace, profile_steps=args.profile_steps)
    # the train loader materializes only this host's batch rows (per-host
    # data sharding); eval keeps global batches (the compat path — staging
    # slices them, run_evaluation's weighted meters need every host to see
    # the same metric stream anyway)
    train_ds = build_dataset(
        cfg, "train", trainer.global_batch,
        host_slice=trainer.host_batch_slice(),
    )
    val_ds = build_dataset(cfg, "val", trainer.global_batch)
    trainer.fit(train_ds, val_ds)


if __name__ == "__main__":
    main()
