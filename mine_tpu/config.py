"""Typed, layered configuration.

Reference behavior (train.py:33-59): flat dot-key YAML, merge order
default -> dataset -> JSON overrides, where every overriding key must already
exist in the default set. That UX is kept: config files are flat dot-key
YAML, merged in the same order with the same must-pre-exist validation.

Deliberately fixed from the reference (SURVEY.md §5.6): the merged result is
an immutable dataclass tree, not a mutable dict god-object; no live handles
(loggers/writers) ever live inside it; runtime-derived values (step, rank,
workspace paths) are function arguments, not config mutations; and the
undefined-key read `mpi.render_tgt_rgb_depth` (silently aliasing
`mpi.is_bg_depth_inf`, synthesis_task.py:279) does not exist — there is one
key, `mpi.is_bg_depth_inf`, used everywhere the reference meant it.

New TPU-native keys live under `mesh.*` (device mesh layout), `obs.*`
(observability: tracing, flight recorder, MFU accounting) and a few
`training.*`/`model.*` additions (dtype, remat, weight paths); defaults in
mine_tpu/configs/default.yaml.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any

import yaml


@dataclass(frozen=True)
class DataConfig:
    name: str = "llff"
    img_h: int = 384
    img_w: int = 512
    img_pre_downsample_ratio: float = 7.875
    per_gpu_batch_size: int = 4  # per-device batch (reference key name kept)
    # targets sampled per source view; each (src, tgt) pair fills one batch
    # slot, so per_gpu_batch_size must divide by it (the reference defines
    # this key but asserts L==1 at runtime, synthesis_task.py:203-204)
    num_tgt_views: int = 1
    training_set_path: str = ""  # val reuses it with the _val folder suffix
    visible_point_count: int = 256
    # host-side loader prefetch depth; 0 = fully synchronous
    num_workers: int = 4
    # bounded retries (exponential backoff + jitter) for transient per-batch
    # loader/staging errors before the pipeline re-raises; 0 = fail fast
    # (data/pipeline.py prefetch)
    loader_retries: int = 0


@dataclass(frozen=True)
class LRConfig:
    backbone_lr: float = 1.0e-3
    decoder_lr: float = 1.0e-3
    decay_gamma: float = 0.1
    decay_steps: tuple[int, ...] = (5, 10)  # epochs, MultiStep-style
    weight_decay: float = 4.0e-5


@dataclass(frozen=True)
class ModelConfig:
    num_layers: int = 50  # hardcoded in the reference (synthesis_task.py:69)
    pos_encoding_multires: int = 10
    imagenet_pretrained: bool = True
    # path to a converted ResNet .npz (tools/convert_resnet.py); empty =>
    # random init (the reference downloads torchvision weights instead,
    # resnet_encoder.py:56-60 — no egress here)
    pretrained_backbone_path: str = ""
    # compute dtype for conv stacks: "bfloat16" (MXU-native) or "float32"
    dtype: str = "bfloat16"
    # wrap the decoder apply in jax.checkpoint to trade FLOPs for HBM
    remat_decoder: bool = False
    # round decoder up-stage conv widths UP to this multiple (1 = the
    # reference's exact [16,32,64,128,256] widths). A perf experiment knob:
    # the narrow stages use a sliver of the 128-wide MXU, so padded widths
    # waste FLOPs but can still win wall-clock. Changes the architecture —
    # checkpoints are incompatible across different values
    decoder_width_multiple: int = 1


@dataclass(frozen=True)
class MPIConfig:
    disparity_start: float = 1.0
    disparity_end: float = 0.001
    num_bins_coarse: int = 32
    num_bins_fine: int = 0
    is_bg_depth_inf: bool = False
    valid_mask_threshold: float = 2.0
    fix_disparity: bool = False
    use_alpha: bool = False
    sigma_dropout_rate: float = 0.0
    # optional explicit bin-edge list, len == num_bins_coarse + 1
    # (synthesis_task.py:37-52)
    disparity_list: tuple[float, ...] = ()
    # target-view compositor: "dense" materializes every warped plane before
    # compositing (the reference's layout); "streaming" scans plane chunks
    # carrying only running accumulators — O(chunk·H·W) working set instead
    # of O(S·H·W), fused warp-composite Pallas forward on TPU. A numerics
    # no-op (ops/mpi_render.py compositor_from_config; PARITY.md)
    compositor: str = "dense"
    # planes per streaming-scan step (clamped to the largest divisor of the
    # plane count); only read when compositor == "streaming"
    stream_chunk_planes: int = 4


@dataclass(frozen=True)
class LossConfig:
    smoothness_lambda_v1: float = 0.0
    smoothness_lambda_v2: float = 0.01
    smoothness_gmin: float = 2.0
    smoothness_grad_ratio: float = 0.1


@dataclass(frozen=True)
class TrainingConfig:
    epochs: int = 15
    eval_interval: int = 10000
    pretrained_checkpoint_path: str = ""
    # which variable subtrees an .npz warm start must cover ("backbone",
    # "decoder"). The default demands a full converted checkpoint; set e.g.
    # ("backbone",) to warm-start from a backbone-only artifact — the escape
    # hatch for legitimately partial checkpoints that the reference handles
    # via blanket strict=False loading (utils.py:40-67), kept explicit here
    # so a wrong artifact still fails loudly
    pretrained_subtrees: tuple[str, ...] = ("backbone", "decoder")
    src_rgb_blending: bool = True
    use_multi_scale: bool = True
    seed: int = 0
    # gradient accumulation: the train step scans over `accum_steps`
    # micro-batches (the per-device batch reshaped to (k, b/k, ...)),
    # accumulating fp32 gradients before ONE optimizer update — peak
    # activation memory is that of a single micro-batch, so the effective
    # batch decouples from HBM (training/step.py). Must divide
    # data.per_gpu_batch_size. 1 = the plain single-pass step.
    accum_steps: int = 1
    # auto-resume target: "latest" (newest retained checkpoint, the classic
    # behavior) or "last_good" (newest retained step at-or-under the
    # sentinel-vetted pointer, training/checkpoint.py restore_last_good —
    # what an ELASTIC restart after a host loss should trust: the newest
    # step may be a partially-committed or unvetted save from the dying
    # run). Fresh workspaces start at 0 either way.
    resume_from: str = "latest"
    # "adam" (the reference's two-group Adam, the default) or "sgd" (same
    # two LR groups, no moments). SGD exists for cross-topology parity
    # methodology — Adam's first-step sign(grad)*lr amplifies
    # fp-reassociation noise on zero-effective-grad leaves into full ±lr
    # flips (PARITY.md 4.x, tests/test_parallel.py), so elastic-resume
    # equivalence drills compare under SGD
    optimizer: str = "adam"
    log_interval: int = 10  # reference hardcodes 10 (synthesis_task.py:638)
    checkpoint_interval: int = 5000  # reference hardcodes 5000 (:645)
    lpips_weights_path: str = ""  # .npz from tools/convert_lpips.py


@dataclass(frozen=True)
class ObsConfig:
    """Observability knobs (mine_tpu/obs/; no reference analog). Everything
    defaults OFF: the disabled tracer is a no-op context manager, so the
    instrumented hot paths cost nothing until a run opts in."""

    # master switch: host-span tracing + per-step phase breakdown + the
    # flight recorder's signal handlers
    enabled: bool = False
    # bounded span ring (oldest spans drop; the drop count is exported)
    trace_buffer_spans: int = 4096
    # jax.profiler device-trace window: start `profile_start_offset` steps
    # after (re)start, run for `profile_steps` steps (0 = no device trace).
    # Replaces the loop's old hardcoded 5-step window.
    profile_start_offset: int = 5
    profile_steps: int = 0
    # stall watchdog: no completed step for this many seconds => flight
    # dump (thread stacks + last-K spans + device memory). 0 disables.
    flight_watchdog_s: float = 0.0
    flight_last_k_spans: int = 256
    # per-compiled-step cost_analysis + the MFU / achieved-bandwidth gauges
    cost_enabled: bool = True
    # peak FLOP/s the MFU gauge divides by when the device kind has no
    # published table entry (the only honest option on CPU meshes); 0 =
    # use the per-platform table in obs/cost.py
    peak_flops_override: float = 0.0


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs (mine_tpu/resilience/; no reference analog —
    the reference silently trains through NaNs and loses everything since
    the last periodic checkpoint on preemption, SURVEY.md §5.3)."""

    # training sentinel policy when a non-finite loss/grad-norm or a loss
    # spike is detected: "off" (the reference's behavior), "skip" (the
    # jitted step drops the poisoned update in-graph and training
    # continues), "rollback" (restore the last-good checkpoint and rebuild
    # the data iterator at that position), "abort" (raise). Any policy
    # other than "off" enables the in-graph isfinite update mask, so
    # params can never absorb a non-finite update.
    sentinel_policy: str = "off"
    # loss-spike trip: host loss > spike_factor * running median of the
    # last spike_window log-interval losses (after spike_min_history
    # samples). 0.0 disables spike detection (finiteness stays checked).
    sentinel_spike_factor: float = 0.0
    sentinel_spike_window: int = 32
    sentinel_spike_min_history: int = 5
    # rollbacks allowed per fit() before the sentinel escalates to abort
    max_rollbacks: int = 2
    # SIGTERM/SIGUSR2 trigger an out-of-band atomic checkpoint save before
    # the flight recorder's dump-then-terminate runs (training/loop.py)
    preempt_save: bool = True
    # serving admission control: pending render requests beyond this bound
    # are shed with HTTP 503 + Retry-After instead of queuing unboundedly
    # (0 = unbounded, the pre-resilience behavior)
    serve_max_queue_requests: int = 64
    # Retry-After seconds suggested on queue-full 503s
    serve_retry_after_s: float = 1.0
    # default per-request deadline propagated into the micro-batcher;
    # requests still queued past it are dropped with 504 before dispatch.
    # Clamped to the server's request_timeout_s ceiling.
    serve_deadline_s: float = 30.0
    # circuit breaker: consecutive engine failures before the serving
    # breaker opens (0 disables the breaker)
    breaker_failure_threshold: int = 5
    # seconds the breaker stays open before half-opening for one trial
    breaker_reset_s: float = 30.0
    # seeded jitter (+-fraction of breaker_reset_s) applied to each trip's
    # recovery window, so N replicas tripped by one fleet-wide event do
    # not run their half-open trials in lockstep (a synchronized re-probe
    # stampede re-trips every breaker at once). 0 keeps the exact window.
    breaker_reset_jitter: float = 0.2
    # cross-host stall watchdog (resilience/multihost.py): on multi-process
    # runs every host writes a heartbeat file at each log-interval sync; a
    # host whose heartbeat goes stale by more than this window — killed, or
    # stuck in a collective — makes EVERY host (survivors and, if alive,
    # the stuck one itself) write a flight dump and exit with the named
    # abort code instead of hanging in NCCL/ICI forever. Size it to at
    # least 2x the slowest legitimate gap between heartbeats (log interval
    # wall time, checkpoint saves, eval passes). 0 disables the watchdog;
    # heartbeats are still written whenever process_count > 1.
    multihost_watchdog_s: float = 0.0
    # where heartbeat files live; must be storage every host can read
    # (one box: any shared dir; a pod: NFS or similar — a gs:// workspace
    # cannot carry them, plain file IO). Empty = <workspace sidecar>/
    # heartbeats, correct for single-box multi-process and local shared
    # filesystems.
    multihost_heartbeat_dir: str = ""
    # retrying bring-up (resilience/multihost.py bring_up): attempts for
    # fast bring-up failures (coordinator not yet up / connection refused)
    # with exponential backoff. A bring-up TIMEOUT is terminal regardless —
    # the stuck rendezvous thread cannot be torn down in-process, so the
    # process must be rescheduled (parallel/mesh.py MultihostInitTimeout).
    multihost_bringup_attempts: int = 3
    multihost_bringup_backoff_s: float = 2.0


@dataclass(frozen=True)
class ServingConfig:
    """Serving-side representation knobs (mine_tpu/serving/; no reference
    analog). Defaults are a numerics NO-OP: fp32 tier + pruning off caches
    exactly the arrays the predict executable produced (PARITY.md 5.11)."""

    # MPI cache tier: "fp32" (dense, the pre-compression behavior), "bf16"
    # (half the bytes, dequant-on-render), or "int8" (per-plane-scaled
    # affine quantization of the RGB+sigma slabs, 1/4 the slab bytes). The
    # tier is part of every cache key and of the fleet wire format — two
    # tiers of one image are DIFFERENT cache entries (serving/compress.py).
    cache_tier: str = "fp32"
    # transmittance-based plane pruning at predict time: planes whose
    # maximum compositing weight (accumulated transmittance x alpha, the
    # same per-plane quantity the streaming compositor scans) never reaches
    # this threshold anywhere in the image are dropped from the cached MPI
    # — cutting cache bytes AND render FLOPs (the render runs a
    # pruned-plane-count executable bucket). 0.0 disables;
    # serving/compress.py DEFAULT_PRUNE_EPS (1e-3) is the recommended
    # operating point (PSNR within 0.1 dB on the eval scene, PARITY.md).
    prune_transmittance_eps: float = 0.0
    # fleet peer fetch: on a local cache miss a replica asks the ring's
    # owner replica (GET /mpi/<key>) for the compressed MPI before
    # re-running the encoder. This bounds the whole attempt; expiry
    # degrades to a local re-predict, never an error (serving/server.py).
    peer_fetch_timeout_s: float = 2.0
    # SLO objectives (obs/slo.py), evaluated in rolling windows over the
    # existing request counter/histogram families and published as
    # mine_slo_{compliance,burn_rate,error_budget_remaining} gauges on
    # every /metrics scrape (replicas AND the fleet router). Availability
    # counts unplanned 5xx as errors (503 shedding is the admission-
    # control contract, exempt by default); the latency objective reads
    # "p95 <= slo_p95_ms over slo_window_s".
    slo_availability_target: float = 0.995
    slo_p95_ms: float = 2000.0
    slo_window_s: float = 300.0
    # --- elastic fleet (serving/autoscale.py) ---------------------------
    # The controller scrapes the router's /metrics each interval and turns
    # SLO burn rate + router p95 into live membership changes: joins
    # pre-warm their future arc over the peer-fetch wire BEFORE ring
    # admission, drains shed + hand their arc off before leaving.
    # Membership bounds: the controller never drains below min or joins
    # above max, whatever the signals say.
    autoscale_min_replicas: int = 2
    autoscale_max_replicas: int = 6
    # controller tick cadence (one scrape + one decision per interval)
    autoscale_interval_s: float = 10.0
    # hysteresis: scale up after `up_after` CONSECUTIVE ticks with any SLO
    # burn rate >= up_burn_threshold (1.0 = burning budget exactly at the
    # objective's rate); scale down after `down_after` consecutive ticks
    # with every burn rate <= down_burn_threshold. The down path is slower
    # and stricter by default — flapping costs a pre-warm each way.
    autoscale_up_burn_threshold: float = 1.0
    autoscale_down_burn_threshold: float = 0.25
    autoscale_up_after: int = 2
    autoscale_down_after: int = 5
    # no new scale event (either direction) within cooldown_s of the last
    # one — the window in which the previous event's effect reaches the
    # rolling SLO windows
    autoscale_cooldown_s: float = 60.0
    # how many hottest cache entries a join pre-warms / a drain hands off
    # (MPICache.hot_keys order: most-recently-used first)
    autoscale_prewarm_keys: int = 64
    # budget for one join's spawn+pre-warm; expiry retires the joiner
    # without ring admission (membership unchanged)
    autoscale_join_timeout_s: float = 30.0
    # budget for one drain's handoff; expiry abandons the handoff but the
    # drain still completes — survivors fall back to peer-fetch while the
    # victim is alive, then re-predict
    autoscale_drain_timeout_s: float = 30.0
    # --- brownout degradation ladder (serving/degrade.py) ---------------
    # Load-adaptive fidelity degradation engaged BEFORE any 503 shed:
    # L0 normal -> L1 int8+pruned predicts -> L2 stale-while-revalidate
    # -> L3 widened coalescing, with the existing shed only past L3.
    # Off by default: the ladder is an operating MODE — tools/
    # bench_fleet.py --brownout and tools/chaos_drill.py --half brownout
    # prove the availability trade before a fleet turns it on.
    degrade_enabled: bool = False
    # breach/calm thresholds on the batcher queue fraction (depth over
    # serve_max_queue_requests) and the worst SLO burn rate; between the
    # high and low marks is a deadband where the ladder holds position
    degrade_queue_high: float = 0.75
    degrade_queue_low: float = 0.25
    degrade_burn_high: float = 2.0
    degrade_burn_low: float = 0.5
    # hysteresis: escalate one level after `engage_after` CONSECUTIVE
    # breach ticks; relax one level after `relax_after` consecutive calm
    # ticks AND `dwell_s` of residency at the current level (escalation
    # is deliberately faster — availability is the emergency)
    degrade_engage_after: int = 2
    degrade_relax_after: int = 3
    degrade_dwell_s: float = 5.0
    # ladder ceiling (0..3); lower to cap how much fidelity may be traded
    degrade_max_level: int = 3
    # the L3 coalescing window (replaces batcher max_delay_ms while L3
    # holds; restored on relax)
    degrade_coalesce_delay_ms: float = 25.0
    # fleet degradation level at/above which the autoscaler counts a
    # sustained-breach tick (the brownout fast path asks the slow path
    # for capacity); 0 disables the coupling
    degrade_scaleup_level: int = 1


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout: the named (data, fsdp, plane) axes
    (parallel/mesh.py; no reference analog — the reference's only axis is
    NCCL data-parallel process count, train.py:66)."""

    data_parallel: int = -1  # -1: all devices not claimed by the others
    # FSDP axis: batches shard over it LIKE data (data x fsdp is the
    # batch-replica product), and the partition-rule table additionally
    # shards params (and their Adam moments) over it — the first layout
    # where per-device param bytes drop below full replication. The axis
    # size IS the FSDP knob: 1 = off.
    fsdp_parallel: int = 1
    plane_parallel: int = 1  # S-axis sharding (SURVEY.md §5.7 stretch)


@dataclass(frozen=True)
class ParallelConfig:
    """Parallelism strategy knobs beyond mesh LAYOUT (which stays in
    mesh.*): how state is distributed over that mesh. Since the named-mesh
    refactor the layouts live in ONE declarative regex -> PartitionSpec
    table (parallel/rules.py); the knobs here are aliases/overrides that
    resolve to rule rows."""

    # DEPRECATED ALIAS (kept, fully functional): ZeRO-1 optimizer-state
    # sharding. Resolves to the table's Adam-moment rows — moments shard
    # over (fsdp x data) when true (the classic ZeRO-1 layout on an
    # fsdp-less mesh: over `data` alone), over fsdp only (following their
    # param's FSDP shard) when false. Updates are computed on the local
    # moment shard and all-gathered back to each param's own layout;
    # checkpoints stay layout-independent (gather-on-save,
    # training/checkpoint.py).
    zero1: bool = False
    # leaves with fewer elements stay replicated under ANY rule row
    # (sharding a bias buys nothing and costs an all_gather launch)
    zero1_min_size: int = 1024
    # extra partition-rule rows, PREPENDED to the default table (first
    # match wins): "pattern = axes" strings, axes a comma-joined mesh-axis
    # list, `replicated`, or `axes @ dim` to pin the split dimension —
    # e.g. "^params/decoder/ = replicated" to exempt the decoder from
    # FSDP. See parallel/rules.py for the default table.
    rules: tuple[str, ...] = ()


@dataclass(frozen=True)
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    lr: LRConfig = field(default_factory=LRConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    mpi: MPIConfig = field(default_factory=MPIConfig)
    loss: LossConfig = field(default_factory=LossConfig)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)

    def replace(self, **dot_key_values: Any) -> "Config":
        """Functional update by dot-keys: cfg.replace(**{"mpi.num_bins_coarse": 8})."""
        flat = to_flat_dict(self)
        for k, v in dot_key_values.items():
            if k not in flat:
                raise KeyError(f"unknown config key: {k}")
            flat[k] = v
        return from_flat_dict(flat)


_GROUPS = {f.name: f for f in dataclasses.fields(Config)}

# Keys that once existed (reference parity rot, deleted because nothing reads
# them — VERDICT r2) but may still appear in archived params.yaml files next
# to old checkpoints. Loading tolerates exactly these, with a warning; any
# other unknown key is still an error.
_RETIRED_KEYS = frozenset({
    "data.val_set_path",
    "data.rotation_pi_ratio",
    "data.is_exclude_views",
    "model.backbone_normalization",
    "model.decoder_normalization",
    "training.fine_tune",
    "training.sample_interval",
    "testing.frames_apart",
})


def _coerce(value: Any, target_type: Any, key: str) -> Any:
    """Coerce YAML/JSON scalars into the dataclass field type."""
    if target_type is float and isinstance(value, (int, float)):
        return float(value)
    if target_type is int:
        if isinstance(value, bool):
            raise TypeError(f"{key}: expected int, got bool")
        if isinstance(value, float) and value.is_integer():
            return int(value)
        if isinstance(value, int):
            return value
        raise TypeError(f"{key}: expected int, got {value!r}")
    if target_type is bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"{key}: expected bool, got {value!r}")
    if target_type is str:
        return "" if value is None else str(value)
    # tuple fields accept CSV strings (reference lr.decay_steps "60,90,120",
    # train.py:57-58), lists, or tuples
    if isinstance(target_type, str) and target_type.startswith("tuple"):
        if isinstance(value, str):
            value = [v for v in value.replace(" ", "").split(",") if v]
        if "float" in target_type:
            elem = float
        elif "str" in target_type:
            elem = str
        else:
            elem = int
        return tuple(elem(v) for v in value)
    return value


def _field_type_name(f: dataclasses.Field) -> Any:
    t = f.type
    if isinstance(t, str):
        if t.startswith("tuple"):
            return t
        return {"int": int, "float": float, "bool": bool, "str": str}.get(t, t)
    return t


def to_flat_dict(cfg: Config) -> dict[str, Any]:
    """Config -> flat dot-key dict (the reference's native format)."""
    flat: dict[str, Any] = {}
    for gname in _GROUPS:
        group = getattr(cfg, gname)
        for f in dataclasses.fields(group):
            flat[f"{gname}.{f.name}"] = getattr(group, f.name)
    return flat


def from_flat_dict(flat: dict[str, Any]) -> Config:
    """Flat dot-key dict -> Config, with unknown-key and type validation."""
    grouped: dict[str, dict[str, Any]] = {g: {} for g in _GROUPS}
    for key, value in flat.items():
        if "." not in key:
            raise KeyError(f"config keys are dot-keys (group.name); got {key!r}")
        gname, fname = key.split(".", 1)
        if gname not in _GROUPS:
            raise KeyError(f"unknown config group: {key!r}")
        group_cls = _GROUPS[gname].default_factory  # type: ignore[union-attr]
        fields = {f.name: f for f in dataclasses.fields(group_cls)}
        if fname not in fields:
            raise KeyError(f"unknown config key: {key!r}")
        grouped[gname][fname] = _coerce(value, _field_type_name(fields[fname]), key)
    return Config(**{
        g: _GROUPS[g].default_factory(**kv)  # type: ignore[union-attr]
        for g, kv in grouped.items()
    })


def load_config(
    *yaml_paths: str,
    overrides: dict[str, Any] | str | None = None,
) -> Config:
    """Layered load: later files override earlier ones; `overrides` (dict or
    JSON string, the reference's --extra_config) overrides everything.

    Mirrors train.py:33-47: every key in a later layer must already exist.
    The first layer is the dataclass defaults, so all keys always pre-exist
    exactly when they are valid keys.
    """
    flat = to_flat_dict(Config())
    layers: list[dict[str, Any]] = []
    for path in yaml_paths:
        with open(path) as fh:
            layers.append(yaml.safe_load(fh) or {})
    if overrides:
        if isinstance(overrides, str):
            overrides = json.loads(overrides)
        layers.append(overrides)
    for layer in layers:
        for key, value in layer.items():
            if key in _RETIRED_KEYS:
                import logging

                logging.getLogger("mine_tpu").warning(
                    "ignoring retired config key %r (archived params.yaml?)", key
                )
                continue
            if key not in flat:
                raise KeyError(f"unknown config key: {key!r}")
            flat[key] = value
    return from_flat_dict(flat)


def save_config(cfg: Config, path: str) -> None:
    """Dump the merged config as flat dot-key YAML (the reference archives
    params.yaml into the run workspace, train.py:49-54, :206-212; inference
    re-reads it, image_to_video.py:275-277)."""
    flat = {
        k: (list(v) if isinstance(v, tuple) else v)
        for k, v in to_flat_dict(cfg).items()
    }
    with open(path, "w") as fh:
        yaml.safe_dump(flat, fh, sort_keys=True)
