"""Micro-batching queue: coalesce concurrent renders of one MPI.

The render half of the serving asymmetry is a single `lax.map` over poses
(inference/video.py render_many_fn) — rendering 8 poses in one dispatch
costs far less than 8 dispatches of 1 (one executable launch, one
device->host transfer, and the pose-bucketed executables amortize identical
warp/composite setup). When several clients orbit the same scene (the
hot-MPI case the cache exists for), their requests arrive within
milliseconds of each other; the batcher holds the first request back for at
most `max_delay_ms` and folds every same-key request that arrives in that
window into one dispatch.

Shape: a single worker thread over a pending deque guarded by a condition
variable. The worker seeds a group with the oldest request, then sweeps the
deque for requests with the same cache key (requests for OTHER keys are
left in place and seed later groups — coalescing never reorders work within
a key, and a cold key cannot be starved by a hot one for longer than the
hot group's dispatch). Results come back through per-request futures, so
HTTP handler threads just block on their own future with a timeout.

Admission control (resilience PR): the pending deque is BOUNDED —
`max_queue_requests` beyond-capacity submissions raise QueueFull (HTTP 503
+ Retry-After) instead of queuing work no one will wait for; each request
carries an optional monotonic `deadline`, and requests still pending past
it are failed with DeadlineExceeded (HTTP 504) *before* dispatch, so an
overloaded server never spends device time rendering frames whose client
already gave up. `stop()` fails stranded requests with the typed
BatcherStopped so graceful drain maps to 503, not a generic 500.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from mine_tpu.obs.trace import NULL_TRACER, Tracer
from mine_tpu.serving.cache import CacheKey, MPIEntry

# (entry, poses (N,4,4)) -> (rgb (N,H,W,3), disp (N,H,W,1))
RenderFn = Callable[[MPIEntry, np.ndarray], tuple[np.ndarray, np.ndarray]]


class BatcherStopped(RuntimeError):
    """The batcher is stopped (shutdown drain) — maps to HTTP 503."""

    def __init__(self) -> None:
        super().__init__("batcher stopped")


class QueueFull(RuntimeError):
    """Pending queue at capacity — shed with HTTP 503 + Retry-After."""

    def __init__(self, depth: int, bound: int):
        super().__init__(
            f"render queue full ({depth} pending >= bound {bound})"
        )


class DeadlineExceeded(RuntimeError):
    """Request expired while queued; dropped before dispatch (HTTP 504)."""

    def __init__(self, waited_s: float):
        super().__init__(
            f"request deadline exceeded after {waited_s:.3f}s in queue"
        )


@dataclass
class _Pending:
    key: CacheKey
    entry: MPIEntry
    poses: np.ndarray
    deadline: float | None = None  # monotonic; None = no deadline
    request_id: str | None = None  # X-Request-Id for span attribution
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.monotonic)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


def _ids(group: list[_Pending]) -> str | None:
    """Comma-joined request ids of a group's members (span attribution:
    server.trace_for_request splits this back); None when no member
    carried one — absent beats an empty-string arg in every span."""
    ids = [p.request_id for p in group if p.request_id]
    return ",".join(ids) if ids else None


class MicroBatcher:
    """Single-worker coalescing dispatcher with a max-delay/max-batch policy.

    max_delay_ms: how long the oldest request of a group may wait for
      company before the group dispatches (the latency cost of coalescing —
      bounded and configurable; 0 disables waiting entirely).
    max_batch_poses: pose-count ceiling per dispatch; a request is only
      absorbed if the whole group still fits. A single over-sized request
      still dispatches alone (the engine chunks internally).
    max_queue_requests: pending-queue bound; submissions beyond it raise
      QueueFull (0 = unbounded, the pre-admission-control behavior).
    """

    def __init__(
        self,
        render_fn: RenderFn,
        max_delay_ms: float = 4.0,
        max_batch_poses: int = 64,
        max_queue_requests: int = 0,
        metrics: Any | None = None,
        tracer: Tracer | None = None,
    ):
        if max_batch_poses < 1:
            raise ValueError(f"max_batch_poses must be >= 1, got {max_batch_poses}")
        if max_queue_requests < 0:
            raise ValueError(
                f"max_queue_requests must be >= 0, got {max_queue_requests}"
            )
        self._render_fn = render_fn
        self.max_delay_s = max(0.0, max_delay_ms) / 1e3
        self.max_batch_poses = int(max_batch_poses)
        self.max_queue_requests = int(max_queue_requests)
        self._metrics = metrics
        self._tracer = tracer if tracer is not None else NULL_TRACER
        self._pending: deque[_Pending] = deque()  # guarded-by: _cond
        self._cond = threading.Condition()
        self._stop = False  # guarded-by: _cond
        self._worker: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._worker is None or not self._worker.is_alive():
            # under the condition like every other _stop touch: a restart
            # racing a concurrent stop() must not interleave the flag flip
            # with stop()'s drain
            with self._cond:
                self._stop = False
            self._worker = threading.Thread(
                target=self._run, name="mine-serve-batcher", daemon=True
            )
            self._worker.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)
        # fail any requests stranded by shutdown instead of hanging clients;
        # the TYPED exception lets the HTTP layer answer 503 (drain), not 500
        with self._cond:
            stranded = list(self._pending)
            self._pending.clear()
            self._gauge_locked()
        for p in stranded:
            p.future.set_exception(BatcherStopped())

    # -- submission ----------------------------------------------------------

    def submit(
        self,
        key: CacheKey,
        entry: MPIEntry,
        poses: np.ndarray,
        deadline: float | None = None,
        request_id: str | None = None,
    ) -> Future:
        """Enqueue one render request; resolves to (rgb, disp) host arrays.

        deadline: monotonic-clock instant after which the request must NOT
        be dispatched — the worker fails it with DeadlineExceeded instead.
        request_id: trace attribution only — a coalesced dispatch's spans
        carry every member's id, so /debug/trace?request_id= finds them.
        """
        poses = np.asarray(poses, np.float32)
        if poses.ndim != 3 or poses.shape[1:] != (4, 4):
            raise ValueError(f"poses must be (N, 4, 4), got {poses.shape}")
        item = _Pending(key=key, entry=entry, poses=poses, deadline=deadline,
                        request_id=request_id)
        with self._cond:
            if self._stop:
                raise BatcherStopped()
            if (self.max_queue_requests
                    and len(self._pending) >= self.max_queue_requests):
                shed = getattr(self._metrics, "shed_requests", None)
                if shed is not None:
                    shed.inc(reason="queue_full")
                raise QueueFull(len(self._pending), self.max_queue_requests)
            self._pending.append(item)
            self._gauge_locked()
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.batch_requests.inc()
        return item.future

    def cancel(self, future: Future) -> bool:
        """Evict a still-pending request (e.g. its client timed out and is
        gone — rendering for it would be pure waste). True if evicted;
        False when it already dispatched (the result is simply dropped)."""
        with self._cond:
            for item in self._pending:
                if item.future is future:
                    self._pending.remove(item)
                    self._gauge_locked()
                    return True
        return False

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._pending)

    def queue_frac(self) -> float:
        """Queue depth over its admission bound — the degradation
        ladder's primary pressure signal (serving/degrade.py). 0.0 when
        the queue is unbounded (no bound means no queue-full shed to
        preempt)."""
        if not self.max_queue_requests:
            return 0.0
        return self.queue_depth() / self.max_queue_requests

    def set_max_delay_s(self, delay_s: float) -> None:
        """Retarget the coalescing window live (brownout L3 widens it,
        relax restores it). Groups already waiting re-read the attribute
        when the worker sizes their wait, so a widening takes effect on
        the CURRENT queue, not just future submissions."""
        self.max_delay_s = max(0.0, float(delay_s))
        with self._cond:
            # the worker may be sleeping on the old, shorter deadline;
            # wake it so the new window is applied immediately
            self._cond.notify_all()

    # -- worker --------------------------------------------------------------

    def _gauge_locked(self) -> None:
        if self._metrics is not None:
            self._metrics.batch_queue_depth.set(len(self._pending))

    def _fail_expired(self, items: list[_Pending]) -> None:
        """Fail expired requests with the typed 504 exception + counter.
        (Outside the condition lock: set_exception wakes blocked clients.)"""
        now = time.monotonic()
        for item in items:
            timeouts = getattr(self._metrics, "request_timeouts", None)
            if timeouts is not None:
                timeouts.inc(stage="queue")
            item.future.set_exception(
                DeadlineExceeded(now - item.enqueued_at)
            )

    def _take_group(self) -> list[_Pending] | None:
        """Block until work or stop; return one coalesced same-key group.
        Expired requests encountered anywhere — as a would-be seed or
        during the sweep — are failed, never dispatched."""
        expired: list[_Pending] = []
        try:
            with self._cond:
                while True:
                    while not self._pending and not self._stop:
                        self._cond.wait()
                    if not self._pending:
                        return None  # stopping and drained
                    coalesce_t0 = time.perf_counter()
                    seed = self._pending.popleft()
                    if seed.expired(time.monotonic()):
                        expired.append(seed)
                        self._gauge_locked()
                        continue
                    break
                group = [seed]
                n_poses = seed.poses.shape[0]
                deadline = seed.enqueued_at + self.max_delay_s
                while True:
                    # sweep pending for the seed's key, preserving order of
                    # everything not absorbed; a candidate only joins if the
                    # whole group still fits the pose ceiling (an oversized
                    # SEED still dispatches alone — the engine chunks)
                    kept: deque[_Pending] = deque()
                    now = time.monotonic()
                    while self._pending:
                        cand = self._pending.popleft()
                        if cand.expired(now):
                            expired.append(cand)
                        elif (cand.key == seed.key
                                and n_poses + cand.poses.shape[0]
                                <= self.max_batch_poses):
                            group.append(cand)
                            n_poses += cand.poses.shape[0]
                        else:
                            kept.append(cand)
                    self._pending = kept
                    remaining = deadline - time.monotonic()
                    if (n_poses >= self.max_batch_poses or remaining <= 0
                            or self._stop):
                        break
                    self._cond.wait(timeout=remaining)
                self._gauge_locked()
                self._tracer.record(
                    "coalesce", "serve", coalesce_t0, time.perf_counter(),
                    requests=len(group), poses=n_poses,
                    request_ids=_ids(group),
                )
                return group
        finally:
            self._fail_expired(expired)

    def _run(self) -> None:
        while True:
            group = self._take_group()
            if group is None:
                return
            self._dispatch(group)

    def _dispatch(self, group: list[_Pending]) -> None:
        # last line of deadline defense: members can expire during the
        # coalescing wait — drop them here rather than render into the void
        now = time.monotonic()
        expired = [p for p in group if p.expired(now)]
        if expired:
            self._fail_expired(expired)
            group = [p for p in group if not p.expired(now)]
            if not group:
                return
        poses = np.concatenate([p.poses for p in group], axis=0)
        now = time.monotonic()
        if self._metrics is not None:
            self._metrics.batch_dispatches.inc()
            if len(group) >= 2:
                self._metrics.batch_coalesced_dispatches.inc()
            qd = getattr(self._metrics, "queue_delay", None)
            if qd is not None:
                for p in group:
                    qd.observe(now - p.enqueued_at)
        # one queue-wait span per group, from the oldest member's enqueue
        # (enqueued_at is monotonic; the tracer wants perf_counter — map
        # the age onto the tracer clock)
        age = now - group[0].enqueued_at
        t1 = time.perf_counter()
        self._tracer.record("queue_wait", "serve", t1 - age, t1,
                            requests=len(group), request_ids=_ids(group))
        try:
            with self._tracer.span("dispatch", cat="serve",
                                   poses=poses.shape[0],
                                   request_ids=_ids(group)):
                rgb, disp = self._render_fn(group[0].entry, poses)
        except BaseException as exc:  # noqa: BLE001 - forwarded to callers
            for p in group:
                p.future.set_exception(exc)
            return
        offset = 0
        for p in group:
            n = p.poses.shape[0]
            p.future.set_result((rgb[offset:offset + n], disp[offset:offset + n]))
            offset += n
