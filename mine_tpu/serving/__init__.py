"""Serving subsystem: predict-once / render-many as a long-lived engine.

MINE's core economic property is asymmetry (PAPER.md §1): the
encoder-decoder runs ONCE per input image to produce an MPI, after which
every novel view is a cheap homography warp + composite. The one-shot
inference path (mine_tpu/inference/) already exploits this within a single
video render; this subsystem turns it into a service:

  * engine.py  — RenderEngine: AOT-compiled predict / render-many
    executables, shape-bucketed by (H, W, S) and by padded pose count, so a
    serving process performs a bounded number of compiles over its lifetime.
  * cache.py   — byte-budgeted LRU cache of predicted MPIs keyed by
    (image_digest, checkpoint_step, S): an S=32 MPI at 384x512 is ~100 MB
    fp32, so the budget is accounted in bytes, not entries.
  * batcher.py — micro-batching queue coalescing concurrent render requests
    against the same cached MPI into one render-many dispatch.
  * server.py  — stdlib ThreadingHTTPServer exposing /predict, /render,
    /healthz, /metrics, /admin/swap (no new dependencies).
  * metrics.py — the serving metric set on mine_tpu.utils.metrics'
    Prometheus-text registry.
  * fleet.py   — the multi-replica front: consistent-hash digest-affinity
    routing over health-gated replicas, bounded failover, deadline
    propagation, mine_fleet_* metrics, /admin/swap fan-out.
  * fake.py    — FakeEngine: the whole serving stack minus XLA, for
    compile-free fleet/swap tests and the chaos drill's fleet half.

Hot swap: engine.py owns WeightSet generations + swap_weights (validate →
place → verify → atomic flip; SwapRejected rolls back to the serving
generation), server.py owns the orchestration (POST /admin/swap, the
last_good promotion watch).
"""

from mine_tpu.serving.batcher import MicroBatcher
from mine_tpu.serving.cache import MPICache, MPIEntry, mpi_key
from mine_tpu.serving.engine import (
    RenderEngine,
    SwapError,
    SwapInProgress,
    SwapRejected,
    WeightSet,
)
from mine_tpu.serving.metrics import ServingMetrics

# server.py (ServingApp, make_server, the CLI) is imported directly, not
# re-exported here: `python -m mine_tpu.serving.server` would otherwise
# execute the module twice (runpy's found-in-sys.modules warning)
