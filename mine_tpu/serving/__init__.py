"""Serving subsystem: predict-once / render-many as a long-lived engine.

MINE's core economic property is asymmetry (PAPER.md §1): the
encoder-decoder runs ONCE per input image to produce an MPI, after which
every novel view is a cheap homography warp + composite. The one-shot
inference path (mine_tpu/inference/) already exploits this within a single
video render; this subsystem turns it into a service:

  * engine.py  — RenderEngine: AOT-compiled predict / render-many
    executables, shape-bucketed by (H, W, S) and by padded pose count, so a
    serving process performs a bounded number of compiles over its lifetime.
  * cache.py   — byte-budgeted LRU cache of predicted MPIs keyed by
    (image_digest, checkpoint_step, S): an S=32 MPI at 384x512 is ~100 MB
    fp32, so the budget is accounted in bytes, not entries.
  * batcher.py — micro-batching queue coalescing concurrent render requests
    against the same cached MPI into one render-many dispatch.
  * server.py  — stdlib ThreadingHTTPServer exposing /predict, /render,
    /healthz, /metrics (no new dependencies).
  * metrics.py — the serving metric set on mine_tpu.utils.metrics'
    Prometheus-text registry.
"""

from mine_tpu.serving.batcher import MicroBatcher
from mine_tpu.serving.cache import MPICache, MPIEntry, mpi_key
from mine_tpu.serving.engine import RenderEngine
from mine_tpu.serving.metrics import ServingMetrics

# server.py (ServingApp, make_server, the CLI) is imported directly, not
# re-exported here: `python -m mine_tpu.serving.server` would otherwise
# execute the module twice (runpy's found-in-sys.modules warning)
