"""Brownout serving: a load-adaptive degradation ladder (ISSUE 19).

The fleet's only answer to overload used to be shedding: bounded queue ->
503, breaker open -> 503, drain -> 503. Yet the compression ladder
(serving/compress.py) made MPI fidelity a continuously tradeable budget
knob — "Compact and adaptive multiplane images" (arxiv 2102.10086) and
"Adaptive Multiplane Image Generation from a Single Internet Picture"
(arxiv 2011.13317) both show an MPI tolerates aggressive compaction at
negligible PSNR cost. This module spends that budget under pressure:
a per-replica `DegradationController` maps live pressure signals (batcher
queue depth, SLO burn rate, breaker state) onto an ordered ladder of
cheaper serving modes engaged BEFORE any shed:

  L0 normal    full-fidelity serving, the configured operating point.
  L1 compress  new predicts land in the int8 tier with default-eps
               transmittance pruning: quarter slab bytes, fewer planes,
               smaller render buckets — cache capacity and render FLOPs
               reclaimed without touching a single request's admission.
  L2 stale     stale-while-revalidate: on a cache miss an older-step
               entry of the same scene keeps serving (post-swap, the old
               generation's `mpi_key`s stay servable instead of forcing
               re-predicts); the peer-fetch hop is skipped — answer from
               what is resident, now.
  L3 coalesce  the micro-batcher's coalescing window widens so more
               same-scene renders amortize one dispatch; only past this
               does the existing 503 shed fire for the remainder.

The state machine is the autoscale controller's idiom (serving/
autoscale.py): an injectable clock, consecutive-tick hysteresis in both
directions, and a minimum per-level dwell before relaxing — escalation is
deliberately faster than relaxation (availability is the emergency;
fidelity restoration can wait for the dwell). Transitions move ONE level
at a time in BOTH directions: the ladder never skips a level downward,
so every intermediate mode's exit path is exercised on every recovery.

Every degraded response announces itself (`X-Degraded: level=<n>;tier=<t>`
header, `mine_serve_degradation_{level,responses_total}`), is SLO-visible
but 5xx-exempt; the fleet router aggregates a fleet-wide level and the
autoscaler treats sustained L>=1 as a scale-up signal (the slow path that
restores full fidelity once capacity arrives) and L0 stability as the
all-clear to relax.

Everything here is a pure host-side state machine — no clocks started, no
threads, no jax — so tests drive it entirely on a fake clock.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from mine_tpu.serving.compress import DEFAULT_PRUNE_EPS

# The ladder, level -> (name, what it trades). README's "Graceful
# degradation" table is drift-tested against this in both directions
# (tests/test_degrade.py, the test_metrics_docs idiom), so a new level
# added here without its row — or a stale row — fails tier-1.
LADDER: dict[int, tuple[str, str]] = {
    0: ("normal", "full fidelity at the configured operating point"),
    1: ("compress", "new predicts land in the int8 tier + default-eps "
        "pruning (quarter slab bytes, smaller render buckets)"),
    2: ("stale", "stale-while-revalidate: older-generation cache entries "
        "keep serving on a miss; peer-fetch skipped"),
    3: ("coalesce", "micro-batcher coalescing window widened; only past "
        "this does the 503 shed fire"),
}
MAX_LEVEL = max(LADDER)


@dataclass(frozen=True)
class PressureSample:
    """One tick's pressure inputs, gathered by the caller (the serving
    app) from the live components: queue_frac = batcher depth over its
    bound, burn_rate = the worst `mine_slo_burn_rate` the tracker last
    published, breaker_open = admission already rejecting."""

    queue_frac: float = 0.0
    burn_rate: float = 0.0
    breaker_open: bool = False


class DegradationController:
    """The per-replica ladder state machine.

    tick() classifies a PressureSample as breach / calm / deadband:

      breach  queue_frac >= queue_high OR burn_rate >= burn_high OR the
              breaker is open (or a synthetic overload is injected —
              the `overload_spike` chaos seam). `engage_after`
              consecutive breach ticks escalate ONE level.
      calm    queue_frac <= queue_low AND burn_rate <= burn_low AND the
              breaker closed. `relax_after` consecutive calm ticks AND
              `dwell_s` of residency at the current level relax ONE
              level — slower and stricter than escalation by design.
      deadband anything between the thresholds resets both streaks:
              the ladder holds position instead of flapping.

    All time comes from the injected clock; nothing here sleeps or
    spawns. Thread-safe: ticks arrive from every handler thread.
    """

    def __init__(
        self,
        *,
        queue_high: float = 0.75,
        queue_low: float = 0.25,
        burn_high: float = 2.0,
        burn_low: float = 0.5,
        engage_after: int = 2,
        relax_after: int = 3,
        dwell_s: float = 5.0,
        max_level: int = MAX_LEVEL,
        clock=time.monotonic,
        on_level=None,
    ):
        if not 0 <= queue_low <= queue_high:
            raise ValueError(
                f"need 0 <= queue_low <= queue_high, "
                f"got {queue_low}/{queue_high}"
            )
        if not 0 <= burn_low <= burn_high:
            raise ValueError(
                f"need 0 <= burn_low <= burn_high, got {burn_low}/{burn_high}"
            )
        if engage_after < 1 or relax_after < 1:
            raise ValueError(
                f"engage_after/relax_after must be >= 1, "
                f"got {engage_after}/{relax_after}"
            )
        if dwell_s < 0:
            raise ValueError(f"dwell_s must be >= 0, got {dwell_s}")
        if not 0 <= max_level <= MAX_LEVEL:
            raise ValueError(
                f"max_level must be in [0, {MAX_LEVEL}], got {max_level}"
            )
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.engage_after = int(engage_after)
        self.relax_after = int(relax_after)
        self.dwell_s = float(dwell_s)
        self.max_level = int(max_level)
        self._clock = clock
        self._on_level = on_level
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._level = 0
        self._level_since = float(clock())
        self._breach_ticks = 0
        self._calm_ticks = 0
        self._synthetic_ticks = 0
        self._transitions: list[tuple[float, int]] = [(self._level_since, 0)]
        self._degraded_responses = 0

    # -- the state machine ----------------------------------------------------

    def tick(self, sample: PressureSample, now: float | None = None) -> int:
        """Advance one observation; returns the (possibly new) level."""
        callback = None
        with self._lock:
            now = float(self._clock()) if now is None else float(now)
            synthetic = self._synthetic_ticks > 0
            if synthetic:
                self._synthetic_ticks -= 1
            breach = (
                synthetic
                or sample.breaker_open
                or sample.queue_frac >= self.queue_high
                or sample.burn_rate >= self.burn_high
            )
            calm = (
                not breach
                and not sample.breaker_open
                and sample.queue_frac <= self.queue_low
                and sample.burn_rate <= self.burn_low
            )
            if breach:
                self._calm_ticks = 0
                self._breach_ticks += 1
                if (self._breach_ticks >= self.engage_after
                        and self._level < self.max_level):
                    callback = self._move_locked(self._level + 1, now)
            elif calm:
                self._breach_ticks = 0
                self._calm_ticks += 1
                if (self._calm_ticks >= self.relax_after
                        and self._level > 0
                        and now - self._level_since >= self.dwell_s):
                    callback = self._move_locked(self._level - 1, now)
            else:
                # deadband: hold position, restart both streaks
                self._breach_ticks = 0
                self._calm_ticks = 0
            level = self._level
        if callback is not None and self._on_level is not None:
            self._on_level(level)
        return level

    def _move_locked(self, level: int, now: float) -> bool:
        self._level = level
        self._level_since = now
        self._breach_ticks = 0
        self._calm_ticks = 0
        self._transitions.append((now, level))
        return True

    def inject(self, ticks: int | None = None) -> None:
        """Synthetic overload (the `overload_spike@request=N` chaos seam):
        the next `ticks` observations classify as breach whatever the real
        signals say. The default is exactly enough consecutive breaches to
        walk the ladder to max_level, so a drill proves the full climb AND
        the full one-step-at-a-time descent deterministically."""
        if ticks is None:
            ticks = self.engage_after * self.max_level + 1
        with self._lock:
            self._synthetic_ticks = max(self._synthetic_ticks, int(ticks))

    # -- level semantics (what each rung actually changes) --------------------

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def level_since(self) -> float:
        with self._lock:
            return self._level_since

    def tier_override(self) -> str | None:
        """L>=1: new predicts compress to int8 (quarter slab bytes)."""
        return "int8" if self.level >= 1 else None

    def prune_eps_override(self) -> float:
        """L>=1: default-eps transmittance pruning joins the tier drop."""
        return DEFAULT_PRUNE_EPS if self.level >= 1 else 0.0

    def serve_stale(self) -> bool:
        """L>=2: an older-step cache entry of the same scene answers a
        miss (stale-while-revalidate) instead of forcing a re-predict."""
        return self.level >= 2

    def skip_peer_fetch(self) -> bool:
        """L>=2: the peer-fetch hop is skipped — under pressure the wire
        round-trip is latency spent on fidelity nobody can afford."""
        return self.level >= 2

    def widen_coalesce(self) -> bool:
        """L3: the micro-batcher coalescing window widens so more renders
        amortize each dispatch; the 503 shed only fires past this."""
        return self.level >= 3

    def announcement(self, tier: str) -> str:
        """The X-Degraded header value for a response served at the
        current level with effective tier `tier`."""
        return f"level={self.level};tier={tier}"

    def record_response(self) -> None:
        with self._lock:
            self._degraded_responses += 1

    def snapshot(self) -> dict:
        """State for /healthz and the drill's assertions."""
        with self._lock:
            return {
                "level": self._level,
                "name": LADDER[self._level][0],
                "level_since": self._level_since,
                "breach_ticks": self._breach_ticks,
                "calm_ticks": self._calm_ticks,
                "degraded_responses": self._degraded_responses,
            }

    def transitions(self) -> list[tuple[float, int]]:
        """Every (time, level) the ladder has visited, seed L0 included —
        the drill asserts each step is exactly +-1 (never skips a level)."""
        with self._lock:
            return list(self._transitions)


def controller_from_config(
    cfg, clock=time.monotonic, on_level=None
) -> DegradationController:
    """Build the controller from the `serving.degrade_*` knobs
    (mine_tpu/configs/default.yaml documents each; the config-knob-drift
    lint keeps this mapping and the yaml in sync)."""
    return DegradationController(
        queue_high=cfg.serving.degrade_queue_high,
        queue_low=cfg.serving.degrade_queue_low,
        burn_high=cfg.serving.degrade_burn_high,
        burn_low=cfg.serving.degrade_burn_low,
        engage_after=cfg.serving.degrade_engage_after,
        relax_after=cfg.serving.degrade_relax_after,
        dwell_s=cfg.serving.degrade_dwell_s,
        max_level=cfg.serving.degrade_max_level,
        clock=clock,
        on_level=on_level,
    )
