"""SLO-driven elastic fleet: the autoscaling controller.

Closes the loop from the SLO layer (obs/slo.py) into live ring
membership (serving/fleet.py). The controller scrapes the fleet
router's /metrics exposition on a fixed interval and reads three signals:

  - `mine_slo_burn_rate` — how fast each objective is eating its error
    budget (1.0 = exactly at target);
  - router p95, interpolated from the `mine_fleet_request_latency_seconds`
    cumulative histogram (obs.slo.p95_from_exposition);
  - `mine_fleet_degradation_level` — the worst brownout-ladder level any
    replica announced (serving/degrade.py): sustained degradation is
    overload even while every request still answers 200.

Hysteresis turns signals into decisions: scale UP after `up_after`
CONSECUTIVE breached ticks (any burn rate >= the up threshold, p95 over
its ceiling, or the fleet degradation level at/above
`serving.degrade_scaleup_level`), scale DOWN after `down_after`
consecutive calm ticks (every burn rate <= the down threshold AND the
fleet back at L0) — down is deliberately slower
and stricter, because flapping costs a pre-warm each way. A cooldown
blocks any new event until the previous one has had time to reach the
rolling SLO windows, and membership is clamped to
[min_replicas, max_replicas] whatever the signals say.

The scale events themselves are CACHE-AWARE — membership changes move
cache arcs, and a cold arc is an encoder-invocation bill the fleet
already paid once:

  JOIN   spawn -> pre-warm -> admit. The joiner computes its future arc
         against the candidate ring (current members + itself), bulk-
         fetches the hot keys it will own from their current owners over
         the same `GET /mpi/<key>` wire peer-fetch uses, and only THEN
         enters the ring (fleet.add_replica — one arc remapped). A join
         that stalls (chaos seam `join_stall`) or overruns
         `join_timeout_s` is retired un-admitted: the ring never saw it.

  DRAIN  shed -> hand off -> leave. The victim (newest join first) flips
         to shedding (503 + Retry-After on product POSTs — the router
         fails over, clients never see a 5xx) while its /mpi wire stays
         up; its hot entries are pushed to their new owners under the
         survivor ring; then it leaves the ring and the process/thread
         is retired. A handoff that overruns `drain_timeout_s` (chaos
         seam `drain_timeout`) is abandoned — the drain still completes,
         survivors fall back to peer-fetching from whoever has the entry.

Replica lifecycle is behind the ReplicaPool duck type so the same
controller drives in-process FakeEngine replicas (benches, drills,
tests — zero XLA compiles) and real subprocess replicas (the CLI):

    spawn() -> (name, base_url)        bring up a NOT-yet-admitted replica
    retire(name)                       tear one down (never in the ring)
    names() -> [name, ...]             managed replicas, spawn order
    urls() -> {name: base_url}
    hot_keys(name, n) -> [(key, nbytes), ...]   hottest-first
    prewarm(name, keys, sources, timeout_s) -> outcome counts
    set_draining(name, flag)
    configure_peers(members, vnodes)   re-point every managed replica's
                                       peer ring at the new membership
    close()

CLI: `python -m mine_tpu.serving.autoscale --workspace W` brings up an
elastic fleet of real replica subprocesses behind one router and runs
the controller loop against it.
"""

from __future__ import annotations

import argparse
import json
import re
import subprocess
import sys
import threading
import time
from typing import Any, Callable

from mine_tpu.config import Config
from mine_tpu.obs.slo import (
    burn_rates_from_exposition,
    degradation_from_exposition,
    p95_from_exposition,
)
from mine_tpu.resilience import chaos
from mine_tpu.serving.fleet import (
    DEFAULT_VNODES,
    FleetApp,
    HashRing,
    _urllib_transport,
    make_fleet_server,
)


def routing_digest(key_str: str) -> str:
    """The ring-routing digest of a wire mpi_key — its first `:` field,
    exactly what fleet.digest_of_request extracts from /mpi/<key> and
    /render paths (so pre-warm placement agrees with request routing)."""
    return key_str.split(":", 1)[0]


# -- replica pools -----------------------------------------------------------


class _InProcReplica:
    __slots__ = ("app", "server", "thread", "url")

    def __init__(self, app: Any, server: Any, thread: threading.Thread,
                 url: str):
        self.app = app
        self.server = server
        self.thread = thread
        self.url = url


class InProcessPool:
    """ReplicaPool over in-process ServingApps (FakeEngine by default),
    each behind a real ephemeral-port HTTP server — the wire surfaces
    (peer fetch, pre-warm, drain shedding) are the production code path,
    only the XLA halves are stubbed. Used by tools/bench_fleet.py --ramp,
    the chaos drill's scale half, and the tier-1 tests."""

    def __init__(self, app_factory: Callable[[], Any] | None = None,
                 host: str = "127.0.0.1", name_prefix: str = "r"):
        if app_factory is None:
            from mine_tpu.serving.fake import make_fake_app

            app_factory = make_fake_app
        self.app_factory = app_factory
        self.host = host
        self.name_prefix = name_prefix
        self._lock = threading.Lock()
        self._next = 0  # guarded-by: _lock
        self._replicas: dict[str, _InProcReplica] = {}  # guarded-by: _lock
        self._order: list[str] = []  # guarded-by: _lock

    def spawn(self) -> tuple[str, str]:
        from mine_tpu.serving.server import make_server

        with self._lock:
            name = f"{self.name_prefix}{self._next}"
            self._next += 1
        app = self.app_factory()
        server = make_server(app, self.host, 0)
        thread = threading.Thread(target=server.serve_forever, daemon=True,
                                  name=f"pool-{name}")
        thread.start()
        h, p = server.server_address[:2]
        url = f"http://{h}:{p}"
        with self._lock:
            self._replicas = {
                **self._replicas, name: _InProcReplica(app, server, thread, url),
            }
            self._order = [*self._order, name]
        return name, url

    def retire(self, name: str) -> None:
        with self._lock:
            rep = self._replicas.get(name)
            self._replicas = {
                k: v for k, v in self._replicas.items() if k != name
            }
            self._order = [n for n in self._order if n != name]
        if rep is None:
            return
        rep.server.shutdown()
        rep.server.server_close()
        rep.app.close()

    def names(self) -> list[str]:
        with self._lock:
            return list(self._order)

    def urls(self) -> dict[str, str]:
        with self._lock:
            return {n: self._replicas[n].url for n in self._order}

    def app(self, name: str):
        """The managed ServingApp — bench/test introspection (metrics,
        cache counters); not part of the ReplicaPool duck type."""
        with self._lock:
            return self._replicas[name].app

    def hot_keys(self, name: str, n: int) -> list[tuple[str, int]]:
        with self._lock:
            rep = self._replicas[name]
        return rep.app.cache.hot_keys(n)

    def prewarm(self, name: str, keys: list[str], sources: list[str],
                timeout_s: float | None = None) -> dict[str, int]:
        with self._lock:
            rep = self._replicas[name]
        return rep.app.prewarm(list(keys), list(sources), timeout_s=timeout_s)

    def set_draining(self, name: str, draining: bool) -> None:
        with self._lock:
            rep = self._replicas[name]
        rep.app.set_draining(draining)

    def configure_peers(self, members: dict[str, str],
                        vnodes: int = DEFAULT_VNODES) -> None:
        with self._lock:
            managed = dict(self._replicas)
        for name, rep in managed.items():
            if name in members:
                rep.app.configure_peers(dict(members), name, vnodes=vnodes)

    def close(self) -> None:
        for name in reversed(self.names()):
            self.retire(name)


_BOUND_RE = re.compile(r"serving checkpoint step \d+ on (http://\S+)")


class SubprocessPool:
    """ReplicaPool over real `python -m mine_tpu.serving.server`
    subprocesses. spawn() parses the bound URL from the server's startup
    line; everything else drives the replica admin HTTP surface
    (/debug/hot_keys, /admin/prewarm, /admin/drain, /admin/peers)."""

    def __init__(self, workspace: str, host: str = "127.0.0.1",
                 server_args: list[str] | None = None,
                 name_prefix: str = "s", spawn_timeout_s: float = 120.0,
                 request_timeout_s: float = 10.0,
                 transport: Callable | None = None):
        self.workspace = workspace
        self.host = host
        self.server_args = list(server_args or [])
        self.name_prefix = name_prefix
        self.spawn_timeout_s = spawn_timeout_s
        self.request_timeout_s = request_timeout_s
        self.transport = transport if transport is not None else _urllib_transport
        self._lock = threading.Lock()
        self._next = 0  # guarded-by: _lock
        self._procs: dict[str, subprocess.Popen] = {}  # guarded-by: _lock
        self._urls: dict[str, str] = {}  # guarded-by: _lock
        self._order: list[str] = []  # guarded-by: _lock

    def spawn(self) -> tuple[str, str]:
        with self._lock:
            name = f"{self.name_prefix}{self._next}"
            self._next += 1
        cmd = [
            sys.executable, "-m", "mine_tpu.serving.server",
            "--workspace", self.workspace,
            "--host", self.host, "--port", "0", *self.server_args,
        ]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        # a watchdog kills the child if it never prints its bound URL —
        # readline then hits EOF and the spawn fails loudly instead of
        # hanging the controller
        timer = threading.Timer(self.spawn_timeout_s, proc.kill)
        timer.daemon = True
        timer.start()
        url = None
        try:
            for line in proc.stdout:
                m = _BOUND_RE.search(line)
                if m:
                    url = m.group(1).rstrip("/")
                    break
        finally:
            timer.cancel()
        if url is None:
            proc.kill()
            proc.wait(timeout=10)
            raise RuntimeError(
                f"replica {name} exited (or timed out after "
                f"{self.spawn_timeout_s}s) before binding"
            )
        # keep draining the child's stdout so its pipe never fills
        threading.Thread(
            target=self._drain_stdout, args=(proc,), daemon=True,
            name=f"pool-{name}-stdout",
        ).start()
        with self._lock:
            self._procs = {**self._procs, name: proc}
            self._urls = {**self._urls, name: url}
            self._order = [*self._order, name]
        return name, url

    @staticmethod
    def _drain_stdout(proc: subprocess.Popen) -> None:
        for _line in proc.stdout:
            pass

    def retire(self, name: str) -> None:
        with self._lock:
            proc = self._procs.get(name)
            self._procs = {k: v for k, v in self._procs.items() if k != name}
            self._urls = {k: v for k, v in self._urls.items() if k != name}
            self._order = [n for n in self._order if n != name]
        if proc is None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._order)

    def urls(self) -> dict[str, str]:
        with self._lock:
            return dict(self._urls)

    def _base_url(self, name: str) -> str:
        with self._lock:
            return self._urls[name]

    def _call(self, url: str, method: str = "GET",
              payload: dict | None = None) -> dict:
        body = None if payload is None else json.dumps(payload).encode("utf-8")
        headers = {"Content-Type": "application/json"} if body else {}
        status, _, raw = self.transport(
            method, url, body, headers, self.request_timeout_s,
        )
        if status != 200:
            raise RuntimeError(
                f"{method} {url} answered {status}: {raw[:200]!r}"
            )
        return json.loads(raw.decode("utf-8")) if raw else {}

    def hot_keys(self, name: str, n: int) -> list[tuple[str, int]]:
        data = self._call(f"{self._base_url(name)}/debug/hot_keys?n={int(n)}")
        return [
            (d["mpi_key"], int(d["nbytes"])) for d in data["hot_keys"]
        ]

    def prewarm(self, name: str, keys: list[str], sources: list[str],
                timeout_s: float | None = None) -> dict[str, int]:
        payload: dict[str, Any] = {
            "keys": list(keys), "sources": list(sources),
        }
        if timeout_s is not None:
            payload["timeout_s"] = float(timeout_s)
        return self._call(
            f"{self._base_url(name)}/admin/prewarm", "POST", payload,
        )

    def set_draining(self, name: str, draining: bool) -> None:
        self._call(
            f"{self._base_url(name)}/admin/drain", "POST",
            {"draining": bool(draining)},
        )

    def configure_peers(self, members: dict[str, str],
                        vnodes: int = DEFAULT_VNODES) -> None:
        for name in self.names():
            if name in members:
                self._call(
                    f"{self._base_url(name)}/admin/peers", "POST",
                    {"peers": dict(members), "peer_name": name,
                     "vnodes": int(vnodes)},
                )

    def close(self) -> None:
        for name in reversed(self.names()):
            self.retire(name)


# -- the controller ----------------------------------------------------------


class AutoscaleController:
    """SLO signals -> membership changes, with hysteresis + cooldown.

    tick() never raises: a scrape failure is a `hold` decision, a failed
    join/drain is recorded on mine_fleet_autoscale_events_total and the
    next tick tries again. scale_to(n) is the deterministic entry point
    benches and drills use; tick() is what the interval loop (start())
    drives in production. The clock is injectable so hysteresis and
    cooldown are unit-testable without sleeping."""

    def __init__(
        self,
        fleet: FleetApp,
        pool: Any,
        scrape: Callable[[], str] | str | None = None,
        *,
        min_replicas: int = 2,
        max_replicas: int = 6,
        interval_s: float = 10.0,
        up_burn_threshold: float = 1.0,
        down_burn_threshold: float = 0.25,
        up_after: int = 2,
        down_after: int = 5,
        cooldown_s: float = 60.0,
        prewarm_keys: int = 64,
        join_timeout_s: float = 30.0,
        drain_timeout_s: float = 30.0,
        p95_up_threshold_s: float | None = None,
        degrade_up_level: int = 0,
        scrape_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]"
            )
        self.fleet = fleet
        self.pool = pool
        self.scrape = scrape
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.interval_s = float(interval_s)
        self.up_burn_threshold = float(up_burn_threshold)
        self.down_burn_threshold = float(down_burn_threshold)
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.cooldown_s = float(cooldown_s)
        self.prewarm_keys = int(prewarm_keys)
        self.join_timeout_s = float(join_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.p95_up_threshold_s = p95_up_threshold_s
        # brownout coupling (serving/degrade.py): a fleet-wide ladder
        # level >= this sustains a breach — degraded fidelity is capacity
        # debt the slow path (more replicas) pays back; 0 disables
        self.degrade_up_level = int(degrade_up_level)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.clock = clock
        # _lock guards the decision state (cheap, never held over I/O);
        # _scale_lock serializes whole scale EVENTS (network-bearing:
        # spawn, pre-warm, handoff) so tick() and scale_to() never
        # interleave two membership changes
        self._lock = threading.Lock()
        self._scale_lock = threading.Lock()
        self._breach_ticks = 0  # guarded-by: _lock
        self._calm_ticks = 0  # guarded-by: _lock
        self._last_event_at: float | None = None  # guarded-by: _lock
        self._last_burns: dict[str, float] = {}  # guarded-by: _lock
        self._last_p95: float | None = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.fleet.metrics.autoscale_target.set(len(self.fleet.replicas))

    # -- signals -------------------------------------------------------------

    def _scrape_text(self) -> str:
        scrape = self.scrape
        if callable(scrape):
            return scrape()
        if isinstance(scrape, str):
            status, _, body = _urllib_transport(
                "GET", scrape, None, {}, self.scrape_timeout_s,
            )
            if status != 200:
                raise ConnectionError(f"scrape {scrape} answered {status}")
            return body.decode("utf-8", "replace")
        # no scrape target: read the co-located router's registry the way
        # its /metrics endpoint would (SLO gauges refreshed first)
        self.fleet.slo.evaluate()
        return self.fleet.metrics.render()

    # -- decisions -----------------------------------------------------------

    def tick(self, now: float | None = None) -> dict:
        """One control-loop iteration: scrape, decide, maybe scale.
        Returns the decision record; never raises."""
        now = self.clock() if now is None else now
        try:
            text = self._scrape_text()
        except (TimeoutError, ConnectionError, OSError):
            # no signal is not a reason to move the fleet
            self.fleet.metrics.autoscale_decisions.inc(action="hold")
            return {"action": "hold", "reason": "scrape_failed"}
        burns = burn_rates_from_exposition(text)
        p95 = p95_from_exposition(text)
        level = degradation_from_exposition(text)
        with self._scale_lock:
            current = len(self.fleet.replicas)
            with self._lock:
                action = self._decide_locked(burns, p95, level, current, now)
            self.fleet.metrics.autoscale_decisions.inc(action=action)
            record = {
                "action": action, "replicas": current,
                "burn_rates": burns, "router_p95_s": p95,
                "degradation_level": level,
            }
            if action == "scale_up":
                record["ok"] = self._join_locked()
            elif action == "scale_down":
                record["ok"] = self._drain_locked()
            record["replicas_after"] = len(self.fleet.replicas)
        return record

    def _decide_locked(self, burns: dict[str, float], p95: float | None,
                       level: float | None, current: int, now: float) -> str:
        breach = any(
            b >= self.up_burn_threshold for b in burns.values()
        )
        if (not breach and self.p95_up_threshold_s is not None
                and p95 is not None):
            breach = p95 >= self.p95_up_threshold_s
        if (not breach and self.degrade_up_level > 0 and level is not None):
            # sustained brownout IS overload even while every request still
            # answers 200 — the ladder bought availability by spending
            # fidelity; scaling up is what buys the fidelity back
            breach = level >= self.degrade_up_level
        calm = not breach and all(
            b <= self.down_burn_threshold for b in burns.values()
        )
        if calm and self.degrade_up_level > 0 and level is not None:
            # no scale-DOWN while any replica is still degraded: L0
            # stability is the all-clear, shrinking a browned-out fleet
            # would re-trigger the ladder it just climbed down from
            calm = level <= 0
        if breach:
            self._breach_ticks += 1
            self._calm_ticks = 0
        elif calm:
            self._calm_ticks += 1
            self._breach_ticks = 0
        else:
            self._breach_ticks = 0
            self._calm_ticks = 0
        self._last_burns = dict(burns)
        self._last_p95 = p95
        in_cooldown = (
            self._last_event_at is not None
            and now - self._last_event_at < self.cooldown_s
        )
        if self._breach_ticks >= self.up_after:
            if current >= self.max_replicas:
                return "at_max"
            if in_cooldown:
                return "cooldown"
            self._breach_ticks = 0
            return "scale_up"
        if self._calm_ticks >= self.down_after:
            if current <= self.min_replicas:
                return "at_min"
            if in_cooldown:
                return "cooldown"
            self._calm_ticks = 0
            return "scale_down"
        return "hold"

    def status(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self.fleet.replicas),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "breach_ticks": self._breach_ticks,
                "calm_ticks": self._calm_ticks,
                "burn_rates": dict(self._last_burns),
                "router_p95_s": self._last_p95,
            }

    def _mark_event(self) -> None:
        with self._lock:
            self._last_event_at = self.clock()

    # -- scale events --------------------------------------------------------

    def scale_to(self, n: int) -> int:
        """Drive membership to n (clamped to [min, max]) through the same
        join/drain protocols a tick would use; returns the final count.
        The deterministic entry point for benches and drills."""
        with self._scale_lock:
            n = max(self.min_replicas, min(self.max_replicas, int(n)))
            while len(self.fleet.replicas) < n:
                if not self._join_locked():
                    break
            while len(self.fleet.replicas) > n:
                if not self._drain_locked():
                    break
            return len(self.fleet.replicas)

    def _membership(self) -> dict[str, str]:
        # fleet.replicas is replaced wholesale under the fleet lock, so
        # iterating the grabbed reference is a consistent snapshot
        reps = self.fleet.replicas
        return {name: r.base_url for name, r in reps.items()}

    def _join_locked(self) -> bool:
        """spawn -> pre-warm -> admit. Caller holds _scale_lock. A joiner
        that fails ANY step before admission is retired — the ring (and
        the peer maps) never saw it."""
        try:
            name, url = self.pool.spawn()
        except Exception:
            self.fleet.metrics.autoscale_events.inc(
                direction="join", outcome="aborted")
            return False
        try:
            deadline = self.clock() + self.join_timeout_s
            chaos.maybe_raise("join_stall")
            members = self._membership()
            candidate = HashRing([*members, name], vnodes=self.fleet.vnodes)
            for owner, owner_url in members.items():
                budget = deadline - self.clock()
                if budget <= 0:
                    raise TimeoutError("join pre-warm budget exhausted")
                hot = self.pool.hot_keys(owner, self.prewarm_keys)
                arc = [
                    k for k, _nbytes in hot
                    if candidate.candidates(routing_digest(k))[0] == name
                ]
                if arc:
                    self.pool.prewarm(name, arc, [owner_url],
                                      timeout_s=budget)
        except Exception:
            self.pool.retire(name)
            self.fleet.metrics.autoscale_events.inc(
                direction="join", outcome="aborted")
            return False
        # peers first, ring last: the joiner is fully wired before the
        # router remaps its arc onto it
        self.pool.configure_peers({**members, name: url}, self.fleet.vnodes)
        self.fleet.add_replica(name, url)
        self.fleet.metrics.autoscale_events.inc(
            direction="join", outcome="ok")
        self.fleet.metrics.autoscale_target.set(len(self.fleet.replicas))
        self._mark_event()
        return True

    def _drain_locked(self) -> bool:
        """shed -> hand off -> leave. Caller holds _scale_lock. The drain
        ALWAYS completes once shedding starts — a handoff failure only
        costs the cache warmth, never the membership change."""
        members = self._membership()
        managed = [n for n in self.pool.names() if n in members]
        if not managed:
            self.fleet.metrics.autoscale_events.inc(
                direction="drain", outcome="aborted")
            return False
        victim = managed[-1]  # newest join drains first
        victim_url = members[victim]
        survivors = {n: u for n, u in members.items() if n != victim}
        if not survivors:
            self.fleet.metrics.autoscale_events.inc(
                direction="drain", outcome="aborted")
            return False
        self.pool.set_draining(victim, True)
        outcome = "ok"
        try:
            deadline = self.clock() + self.drain_timeout_s
            chaos.maybe_raise("drain_timeout")
            ring = HashRing(list(survivors), vnodes=self.fleet.vnodes)
            by_owner: dict[str, list[str]] = {}
            for k, _nbytes in self.pool.hot_keys(victim, self.prewarm_keys):
                owner = ring.candidates(routing_digest(k))[0]
                by_owner.setdefault(owner, []).append(k)
            for owner, arc in by_owner.items():
                budget = deadline - self.clock()
                if budget <= 0:
                    raise TimeoutError("drain handoff budget exhausted")
                self.pool.prewarm(owner, arc, [victim_url], timeout_s=budget)
        except Exception:
            # the arc stays cold on the new owners; survivors peer-fetch
            # from whoever has each entry, and only then re-predict
            outcome = "handoff_aborted"
        self.fleet.remove_replica(victim)
        self.pool.configure_peers(survivors, self.fleet.vnodes)
        self.pool.retire(victim)
        self.fleet.metrics.autoscale_events.inc(
            direction="drain", outcome=outcome)
        self.fleet.metrics.autoscale_target.set(len(self.fleet.replicas))
        self._mark_event()
        return True

    # -- interval loop -------------------------------------------------------

    def start(self) -> "AutoscaleController":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="autoscale",
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def controller_from_config(
    fleet: FleetApp,
    pool: Any,
    cfg: Config,
    scrape: Callable[[], str] | str | None = None,
    clock: Callable[[], float] = time.monotonic,
) -> AutoscaleController:
    """An AutoscaleController from the one config spelling
    (serving.autoscale_* in configs/default.yaml). The p95 up-signal
    ceiling is the latency SLO itself (serving.slo_p95_ms)."""
    s = cfg.serving
    return AutoscaleController(
        fleet, pool, scrape,
        min_replicas=s.autoscale_min_replicas,
        max_replicas=s.autoscale_max_replicas,
        interval_s=s.autoscale_interval_s,
        up_burn_threshold=s.autoscale_up_burn_threshold,
        down_burn_threshold=s.autoscale_down_burn_threshold,
        up_after=s.autoscale_up_after,
        down_after=s.autoscale_down_after,
        cooldown_s=s.autoscale_cooldown_s,
        prewarm_keys=s.autoscale_prewarm_keys,
        join_timeout_s=s.autoscale_join_timeout_s,
        drain_timeout_s=s.autoscale_drain_timeout_s,
        p95_up_threshold_s=s.slo_p95_ms / 1000.0,
        degrade_up_level=s.degrade_scaleup_level,
        clock=clock,
    )


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(
        description="elastic fleet: replica subprocesses behind one "
        "router, membership driven by the SLO autoscale controller",
    )
    parser.add_argument(
        "--workspace", required=True,
        help="training workspace dir every replica serves from",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9000,
                        help="router port (replicas bind ephemeral ports)")
    parser.add_argument(
        "--replicas", type=int, default=0,
        help="initial fleet size (0 = serving.autoscale_min_replicas)",
    )
    parser.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    parser.add_argument("--probe-interval", type=float, default=2.0)
    parser.add_argument(
        "--extra_config", default=None,
        help="JSON dot-key overrides (e.g. the serving.autoscale_* knobs)",
    )
    parser.add_argument(
        "--server-arg", action="append", default=[], metavar="ARG",
        help="extra argument passed through to every replica's "
        "serving.server CLI (repeatable; e.g. --server-arg=--zoo-buckets)",
    )
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    cfg = Config()
    if args.extra_config:
        cfg = cfg.replace(**json.loads(args.extra_config))
    pool = SubprocessPool(args.workspace, host=args.host,
                          server_args=args.server_arg)
    initial = args.replicas or cfg.serving.autoscale_min_replicas
    fleet = None
    fleet_srv = None
    controller = None
    try:
        urls: dict[str, str] = {}
        for _ in range(initial):
            name, url = pool.spawn()
            urls[name] = url
            print(f"replica {name} up at {url}")
        fleet = FleetApp(urls, probe_interval_s=args.probe_interval,
                         vnodes=args.vnodes).start()
        pool.configure_peers(urls, args.vnodes)
        fleet_srv = make_fleet_server(fleet, args.host, args.port,
                                      verbose=args.verbose)
        host, port = fleet_srv.server_address[:2]
        controller = controller_from_config(
            fleet, pool, cfg, scrape=f"http://{host}:{port}/metrics",
        ).start()
        print(
            f"elastic fleet on http://{host}:{port} "
            f"({len(urls)} replicas, "
            f"[{controller.min_replicas}, {controller.max_replicas}] "
            f"every {controller.interval_s:g}s)"
        )
        fleet_srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if controller is not None:
            controller.close()
        if fleet_srv is not None:
            fleet_srv.shutdown()
            fleet_srv.server_close()
        if fleet is not None:
            fleet.close()
        pool.close()


if __name__ == "__main__":
    main()
