"""The serving metric set, on mine_tpu.utils.metrics' registry.

One place defines every metric name the /metrics endpoint exports, so the
README table, the tests, and tools/bench_serve.py all reference the same
spelling. Prefix: `mine_serve_`.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from mine_tpu.utils.metrics import MetricsRegistry


class RateGauge:
    """Rolling throughput gauge: record(n) events, value() = n/sec over the
    trailing window. Backed by a plain gauge family in the registry that is
    refreshed on every record AND on every scrape (server.py calls
    refresh() before rendering), so an idle server decays to 0 instead of
    freezing at its last burst."""

    def __init__(self, gauge, window_s: float = 30.0):
        self._gauge = gauge
        self._window_s = window_s
        self._events: deque[tuple[float, float]] = deque()
        self._lock = threading.Lock()

    def record(self, n: float, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, float(n)))
            self._gauge.set(self._rate_locked(now))

    def refresh(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            rate = self._rate_locked(now)
            self._gauge.set(rate)
            return rate

    def _rate_locked(self, now: float) -> float:
        cutoff = now - self._window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()
        if not self._events:
            return 0.0
        total = sum(n for _, n in self._events)
        # span from the oldest retained event, floored to avoid a huge rate
        # from a single instantaneous burst
        span = max(now - self._events[0][0], 1.0)
        return total / span


class ServingMetrics:
    """Every serving metric, created against one registry."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry

        # HTTP surface
        self.requests = r.counter(
            "mine_serve_requests_total",
            "HTTP requests by endpoint and status code",
        )
        self.request_latency = r.histogram(
            "mine_serve_request_latency_seconds",
            "request wall time by endpoint (cumulative le buckets)",
        )
        self.queue_delay = r.histogram(
            "mine_serve_queue_delay_seconds",
            "time a render request waited in the micro-batcher before its "
            "group dispatched (the latency cost of coalescing)",
        )

        # admission control + fault tolerance (resilience PR)
        self.shed_requests = r.counter(
            "mine_serve_shed_requests_total",
            "requests rejected before any work, by reason "
            "(queue_full|breaker_open|draining)",
        )
        self.draining = r.gauge(
            "mine_serve_draining",
            "1 while this replica is in the drain shedding state "
            "(/admin/drain — product POSTs answer 503 + Retry-After, the "
            "peer-fetch wire stays served for the arc handoff), else 0",
        )
        self.request_timeouts = r.counter(
            "mine_serve_request_timeouts_total",
            "requests that hit their deadline, by stage (queue = expired "
            "before dispatch -> 504; result = client wait timed out and "
            "the pending entry was evicted -> 504)",
        )
        self.breaker_state = r.gauge(
            "mine_serve_breaker_state",
            "circuit breaker state: 0 closed, 1 half-open, 2 open",
        )

        # brownout degradation ladder (serving/degrade.py): fidelity
        # traded for availability BEFORE any shed. Degraded responses are
        # SLO-visible but 5xx-exempt — they are successes, served cheaper.
        self.degradation_level = r.gauge(
            "mine_serve_degradation_level",
            "brownout ladder level: 0 normal, 1 int8+pruned predicts, "
            "2 stale-while-revalidate, 3 widened coalescing (the 503 "
            "shed only fires past 3)",
        )
        self.degradation_responses = r.counter(
            "mine_serve_degradation_responses_total",
            "product responses served while the brownout ladder was "
            "engaged, by level — every one also carried an X-Degraded "
            "header announcing its level and effective tier",
        )
        self.breaker_trips = r.counter(
            "mine_serve_breaker_trips_total",
            "closed/half-open -> open transitions after consecutive engine "
            "failures",
        )
        self.engine_failures = r.counter(
            "mine_serve_engine_failures_total",
            "engine dispatch failures, by kind (predict/render) — the "
            "breaker's input signal",
        )

        # hot checkpoint swap (serving/engine.py swap_weights + the
        # ServingApp swap worker): generation flips and the named failure
        # modes. A failed swap is NEVER a 5xx — it is these counters.
        self.weight_generation = r.gauge(
            "mine_serve_weight_generation",
            "serving weight generation (0 = the startup checkpoint; "
            "incremented by every successful hot swap)",
        )
        self.swaps = r.counter(
            "mine_serve_swaps_total",
            "successful hot checkpoint swaps (atomic generation flips)",
        )
        self.swap_failures = r.counter(
            "mine_serve_swap_failures_total",
            "hot swaps that did NOT flip, by reason (load = checkpoint "
            "unreadable/corrupt; rejected = tree/shape validation or "
            "verification dispatch failed; in_progress = concurrent swap "
            "refused) — the old generation kept serving in every case",
        )

        # host-span tracing (obs/trace.py wired via ServingApp)
        self.trace_spans = r.counter(
            "mine_serve_trace_spans_total",
            "host spans recorded by the request-lifecycle tracer, by cat",
        )

        # engine
        self.encoder_invocations = r.counter(
            "mine_serve_encoder_invocations_total",
            "full encoder-decoder predict passes actually executed "
            "(cache hits do not count — this is the expensive half)",
        )
        self.engine_compiles = r.counter(
            "mine_serve_engine_compiles_total",
            "XLA executables compiled, by kind (predict/render); bounded by "
            "the shape-bucket and pose-bucket sets",
        )
        self.rendered_frames = r.counter(
            "mine_serve_rendered_frames_total",
            "novel-view frames rendered (padding frames excluded)",
        )
        self.renders_per_sec = RateGauge(r.gauge(
            "mine_serve_renders_per_sec",
            "rendered frames per second over the trailing window",
        ))

        # cost accounting (obs/cost.py): XLA cost analysis of the render
        # executables over measured dispatch time
        self.step_flops = r.gauge(
            "mine_serve_step_flops",
            "FLOPs of the most recently dispatched compiled executable "
            "(XLA cost analysis), by kind",
        )
        self.mfu = r.gauge(
            "mine_serve_mfu",
            "render-dispatch model FLOPs utilization over the device peak "
            "(absent until a render resolves and the peak is known)",
        )
        self.achieved_tflops = r.gauge(
            "mine_serve_achieved_tflops_per_sec",
            "achieved TFLOP/s of the last render dispatch",
        )

        # live HBM telemetry (obs/memlog.py; sampled per dispatch and per
        # /metrics scrape; absent on backends without memory_stats)
        self.hbm_live_bytes = r.gauge(
            "mine_serve_hbm_live_bytes",
            "device.memory_stats() bytes_in_use, max over local devices",
        )
        self.hbm_peak_bytes = r.gauge(
            "mine_serve_hbm_peak_bytes",
            "device.memory_stats() peak_bytes_in_use, max over local "
            "devices — the runtime high-water mark the cache byte budget "
            "and bucket set must stay under",
        )

        # compressed MPI tier (serving/compress.py)
        self.pruned_planes = r.counter(
            "mine_serve_pruned_planes_total",
            "planes dropped from cached MPIs by transmittance pruning "
            "(serving.prune_transmittance_eps) — each one is cache bytes "
            "AND render FLOPs that no longer exist",
        )
        # fleet peer fetch (serving/server.py _peer_fetch): on a local
        # cache miss a replica asks the ring's owner for the compressed
        # MPI before re-running the encoder. Named mine_fleet_* because it
        # is fleet-wire traffic, even though the counter lives on the
        # replica that fetched.
        self.peer_fetch = r.counter(
            "mine_fleet_peer_fetch_total",
            "peer MPI fetch attempts by outcome (hit = adopted a peer's "
            "cached MPI, zero local encoder cost; miss = owner answered "
            "404; incompatible = the peer runs a different pruning "
            "operating point, config drift surfaced; timeout/error = "
            "degraded to a local re-predict)",
        )

        # autoscale pre-warm / handoff (serving/server.py prewarm): bulk
        # adoption of hot entries over the same wire, driven by the
        # controller before a join enters the ring / while a drain leaves
        self.prewarm_keys = r.counter(
            "mine_serve_prewarm_keys_total",
            "pre-warm/handoff key outcomes (fetched = adopted over the "
            "wire; resident = already cached here; miss = no source had "
            "it; error = fetch/adopt failed, skipped)",
        )

        # MPI cache
        self.cache_hits = r.counter(
            "mine_serve_cache_hits_total", "MPI cache hits")
        self.cache_misses = r.counter(
            "mine_serve_cache_misses_total", "MPI cache misses")
        self.cache_evictions = r.counter(
            "mine_serve_cache_evictions_total",
            "MPI cache entries evicted for the byte budget",
        )
        self.cache_bytes_resident = r.gauge(
            "mine_serve_cache_bytes_resident",
            "bytes of MPI data currently cached",
        )
        self.cache_entries = r.gauge(
            "mine_serve_cache_entries", "MPI cache entry count")

        # micro-batcher
        self.batch_dispatches = r.counter(
            "mine_serve_batch_dispatches_total",
            "render-many dispatches issued by the micro-batcher",
        )
        self.batch_requests = r.counter(
            "mine_serve_batch_requests_total",
            "render requests that entered the micro-batcher",
        )
        self.batch_coalesced_dispatches = r.counter(
            "mine_serve_batch_coalesced_dispatches_total",
            "dispatches that coalesced >= 2 requests into one render-many",
        )
        self.batch_queue_depth = r.gauge(
            "mine_serve_batch_queue_depth",
            "render requests waiting in the micro-batcher",
        )

    def render(self) -> str:
        self.renders_per_sec.refresh()
        return self.registry.render()
