"""Compressed MPI representation: quantized tiers + transmittance pruning.

A dense fp32 MPI is the serving stack's unit of cost: at 384x256 S=64 one
cached entry is ~400 MB, so the byte-budgeted MPICache holds a handful of
scenes and the fleet's digest-affinity routing concentrates hits onto
capacity that is not there. "Compact and adaptive multiplane images"
(arxiv 2102.10086) shows MPIs tolerate aggressive compaction with
negligible PSNR loss; this module is that observation as a data type with
three consumers:

  the cache   `CompressedMPI` is a drop-in MPICache value (`.nbytes` is the
              COMPRESSED byte count, so budget/eviction/gauges account what
              is actually resident); the tier is part of every cache key
              (serving/cache.py mpi_key), so fp32/bf16/int8 entries of one
              image never alias.
  the render  `decompress()` is dequant-on-render: the AOT render
              executables stay fp32 pure functions, and the engine converts
              the resident compressed slabs per dispatch (serving/engine.py
              pads the surviving planes up to a pruned-plane-count
              executable bucket — pruning cuts render FLOPs, not just
              bytes).
  the wire    `to_wire`/`from_wire` give a self-describing byte format a
              replica serves over `GET /mpi/<key>` so a peer can adopt a
              cached MPI instead of re-running the encoder — the compressed
              representation is what makes shipping an MPI between replicas
              cheaper than recomputing it.

Tiers:
  fp32   no transformation (with pruning off, `compress_mpi` returns the
         plain MPIEntry unchanged — a numerics NO-OP, PARITY.md 5.11)
  bf16   slabs stored as bfloat16 (ml_dtypes, a jax dependency): half the
         bytes, ~2^-8 relative rounding
  int8   per-plane-scaled AFFINE quantization of rgb and sigma: for each
         plane, q = round((x - lo) / scale) - 128 stored as int8, with the
         (lo, scale) pair carried per plane in fp32. One plane's dynamic
         range cannot poison another's (a nearly-empty far plane quantizes
         its tiny sigma range finely even when a near plane is opaque).

Pruning: `ops/mpi_render.py plane_contributions` computes each plane's
maximum compositing weight (accumulated transmittance x alpha — the same
per-plane quantity the streaming compositor's scan carries, parallax-
dilated so disocclusion content survives); planes that never reach
`prune_eps` anywhere are dropped and the SURVIVING plane disparities
travel with the slabs. Because the renderer re-derives inter-plane
distances from the disparities it is handed, each survivor's sigma is
rescaled by its old/new gap ratio (`_prune_sigma_scale`) so its
transparency is preserved exactly at the source pose — without that, a
kept plane in front of a pruned run would silently brighten.
DEFAULT_PRUNE_EPS (1e-3) is the recommended operating point (PSNR within
0.1 dB of unpruned on the eval scene, tests/test_compress.py gates it via
the convergence harness's scorer).

Everything here is host-side numpy (ml_dtypes for bf16) so the FakeEngine
fleet tests exercise the identical code without an XLA compile; the real
engine device_puts the compressed fields once after compression
(RenderEngine._adopt_entry) and `decompress` is written against the array
API surface numpy and jax share (astype/arithmetic), so dequant runs
wherever the fields live.
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass, field
from typing import Any

import numpy as np

# cache.py imports nothing from this package, so the value types and the
# one byte-accounting rule (`_nbytes`) are shared without a cycle
from mine_tpu.serving.cache import MPIEntry, _nbytes

TIERS = ("fp32", "bf16", "int8")

# the recommended pruning threshold: a plane whose best pixel contributes
# < 0.1% of a ray's color is invisible at 8-bit output depth; measured on
# the eval scene it stays within 0.1 dB of the unpruned render
# (tests/test_compress.py::test_tier_psnr_parity_on_eval_scene)
DEFAULT_PRUNE_EPS = 1e-3

_WIRE_MAGIC = b"MPIC1\n"


def _bf16_dtype():
    import ml_dtypes  # ships with jax

    return ml_dtypes.bfloat16




@dataclass
class CompressedMPI:
    """One compressed cached prediction: everything `decompress` needs to
    hand the render executables fp32 slabs, nothing else.

    rgb/sigma hold the tier's storage dtype ((1, S_kept, H, W, 3/1)):
    fp32/bf16 directly, int8 alongside per-plane (lo, scale) fp32 pairs.
    disparity is the SURVIVING planes' (1, S_kept) — pruning already
    happened, the renderer never sees the dropped planes. bucket is the
    engine shape-bucket identity (H, W, S_coarse) the entry was predicted
    under; num_planes_full is the unpruned plane count (coarse+fine for
    c2f buckets), kept for observability and the wire header.
    """

    tier: str
    rgb: Any  # (1, S_kept, H, W, 3) storage dtype
    sigma: Any  # (1, S_kept, H, W, 1) storage dtype
    disparity: Any  # (1, S_kept) fp32
    k: Any  # (1, 3, 3) fp32
    bucket: tuple[int, int, int]
    num_planes_full: int
    rgb_lo: Any = None  # (1, S_kept, 1, 1, 1) fp32, int8 tier only
    rgb_scale: Any = None
    sigma_lo: Any = None
    sigma_scale: Any = None
    nbytes: int = field(default=0)

    def __post_init__(self) -> None:
        if self.tier not in TIERS:
            raise ValueError(f"unknown tier {self.tier!r}; one of {TIERS}")
        if not self.nbytes:
            self.nbytes = sum(
                _nbytes(a) for a in self._arrays().values() if a is not None
            )

    @property
    def planes_kept(self) -> int:
        return int(self.disparity.shape[1])

    def _arrays(self) -> dict[str, Any]:
        return {
            "rgb": self.rgb, "sigma": self.sigma,
            "disparity": self.disparity, "k": self.k,
            "rgb_lo": self.rgb_lo, "rgb_scale": self.rgb_scale,
            "sigma_lo": self.sigma_lo, "sigma_scale": self.sigma_scale,
        }

    def replace_arrays(self, mapped: dict[str, Any]) -> "CompressedMPI":
        """A copy with array fields substituted (same nbytes — the engine
        uses this to device_put the resident fields without re-deriving
        byte accounting from device array types)."""
        return CompressedMPI(
            tier=self.tier, bucket=self.bucket,
            num_planes_full=self.num_planes_full, nbytes=self.nbytes,
            **mapped,
        )


def _quantize_int8(x: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-plane affine int8: (q, lo, scale) with x ~ (q + 128) * scale + lo.
    x: (1, S, H, W, C). lo/scale: (1, S, 1, 1, 1) fp32."""
    lo = x.min(axis=(2, 3, 4), keepdims=True).astype(np.float32)
    hi = x.max(axis=(2, 3, 4), keepdims=True).astype(np.float32)
    # a constant plane still round-trips exactly: scale 0 would divide by
    # zero, so floor it and let lo carry the value
    scale = np.maximum((hi - lo) / 255.0, 1e-12).astype(np.float32)
    q = np.clip(np.round((x - lo) / scale), 0.0, 255.0) - 128.0
    return q.astype(np.int8), lo, scale


def _dequant_int8(q: Any, lo: Any, scale: Any) -> Any:
    """Array-API-agnostic dequant (numpy in, numpy out; jax in, jax out)."""
    return (q.astype(np.float32) + np.float32(128.0)) * scale + lo


def _prune_sigma_scale(disparity: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """Per-surviving-plane sigma correction for pruning, (K,) fp32.

    The renderer derives inter-plane distances from the disparity list it
    is given: dist_s(q) = (depth_next - depth_s) * ||K^-1 q|| (the last
    plane gets the background pseudo-distance). Dropping planes therefore
    WIDENS the gap of any kept plane that preceded a pruned run, and its
    alpha = 1 - exp(-sigma * dist) would inflate — a kept semi-transparent
    plane could brighten severalfold. The ray norm cancels in the
    old/new-gap ratio, so scaling each surviving plane's sigma by
    orig_gap / new_gap preserves its transparency EXACTLY at the source
    pose (and up to the warp's angular variation at novel poses —
    bounded by the parity gate in tests/test_compress.py).

    Exactness requires that no survivor be promoted into the LAST slot:
    the background pseudo-distance is a CONSTANT (no ray-norm factor), so
    a scalar could not compensate it — which is why compress_mpi always
    keeps the original last plane in sigma mode. A plane that was already
    last keeps its BG slot on both sides (ratio 1)."""
    from mine_tpu.ops.mpi_render import _BG_DIST

    depth = 1.0 / np.asarray(disparity, np.float64).reshape(-1)  # (S,)
    s = depth.shape[0]
    orig_gap = np.empty(s, np.float64)
    orig_gap[:-1] = np.abs(depth[1:] - depth[:-1])
    orig_gap[-1] = _BG_DIST
    kept = np.flatnonzero(keep)
    new_gap = np.empty(kept.shape[0], np.float64)
    new_gap[:-1] = np.abs(depth[kept[1:]] - depth[kept[:-1]])
    new_gap[-1] = _BG_DIST
    return (orig_gap[kept] / np.maximum(new_gap, 1e-12)).astype(np.float32)


def keep_mask(contributions: np.ndarray, prune_eps: float) -> np.ndarray:
    """(S,) bool: planes whose max compositing weight reaches prune_eps.
    The best plane is ALWAYS kept — an empty keep-set would leave nothing
    to render, and an all-transparent MPI degrades to its least-empty
    plane rather than an error."""
    contributions = np.asarray(contributions, np.float64)
    keep = contributions >= float(prune_eps)
    if not keep.any():
        keep[int(np.argmax(contributions))] = True
    return keep


def compress_mpi(
    mpi_rgb: Any,
    mpi_sigma: Any,
    disparity: Any,
    k: Any,
    bucket: tuple[int, int, int],
    tier: str = "fp32",
    prune_eps: float = 0.0,
    use_alpha: bool = False,
):
    """Predict output -> cache value. fp32 + pruning off returns the plain
    MPIEntry (bitwise the input arrays — the numerics no-op the default
    config promises); anything else returns a CompressedMPI.

    Inputs may be device or host arrays; compression itself runs on host
    numpy (one device_get per predict — the price of an order of magnitude
    more cache capacity), and the caller re-places the result
    (RenderEngine._adopt_entry).
    """
    if tier not in TIERS:
        raise ValueError(f"unknown cache tier {tier!r}; one of {TIERS}")
    if tier == "fp32" and not prune_eps:
        return MPIEntry(
            mpi_rgb=mpi_rgb, mpi_sigma=mpi_sigma, disparity=disparity, k=k,
            bucket=tuple(bucket),
        )

    keep = None
    if prune_eps:
        # one source of truth for "contribution": the compositors' own
        # per-plane weight (ops/mpi_render.py), evaluated eagerly — tiny
        # elementwise graph, no AOT executable involved. Computed from the
        # ORIGINAL inputs BEFORE the host pull below: on a real engine the
        # predict outputs are still device-resident, so the reduction runs
        # on device and only the (S,) vector crosses — not a wasted
        # D2H + H2D round trip of the whole sigma slab.
        from mine_tpu.ops import inverse_3x3, plane_contributions

        contrib = np.asarray(plane_contributions(
            mpi_sigma, disparity, inverse_3x3(k), use_alpha=use_alpha,
        ))
        keep = keep_mask(contrib, prune_eps)
        if not use_alpha:
            # the renderer's background slot is a CONSTANT pseudo-distance
            # (ray norms scale only the interior gaps — _src_dists), so a
            # survivor PROMOTED into the last slot could not be compensated
            # by a per-plane scalar. Keeping the original last plane means
            # every widened gap stays interior-to-interior, where the ray
            # norm cancels and the sigma rescale is exact. One plane of
            # bytes buys exactness.
            keep[-1] = True
        if tier == "fp32" and keep.all():
            # nothing to prune and nothing to quantize: the original
            # (device) arrays ARE the entry — skip the pointless
            # full-slab D2H + H2D round trip below
            return MPIEntry(
                mpi_rgb=mpi_rgb, mpi_sigma=mpi_sigma,
                disparity=disparity, k=k, bucket=tuple(bucket),
            )

    rgb = np.asarray(mpi_rgb, np.float32)
    sigma = np.asarray(mpi_sigma, np.float32)
    disp = np.asarray(disparity, np.float32)
    k_host = np.asarray(k, np.float32)
    num_full = rgb.shape[1]

    if keep is not None:
        if not keep.all():
            if not use_alpha:
                # preserve each survivor's transparency under its widened
                # inter-plane gap (see _prune_sigma_scale); alpha-mode
                # composites sigma directly, no distance, no correction
                scale = _prune_sigma_scale(disp, keep)
                sigma = sigma[:, keep] * scale[None, :, None, None, None]
            else:
                sigma = sigma[:, keep]
            rgb = rgb[:, keep]
            disp = disp[:, keep]

    fields: dict[str, Any] = {}
    if tier == "fp32":
        fields.update(rgb=rgb, sigma=sigma)
    elif tier == "bf16":
        bf16 = _bf16_dtype()
        fields.update(rgb=rgb.astype(bf16), sigma=sigma.astype(bf16))
    else:  # int8
        q_rgb, rgb_lo, rgb_scale = _quantize_int8(rgb)
        q_sigma, sigma_lo, sigma_scale = _quantize_int8(sigma)
        fields.update(
            rgb=q_rgb, sigma=q_sigma,
            rgb_lo=rgb_lo, rgb_scale=rgb_scale,
            sigma_lo=sigma_lo, sigma_scale=sigma_scale,
        )
    return CompressedMPI(
        tier=tier, disparity=disp, k=k_host, bucket=tuple(bucket),
        num_planes_full=int(num_full), **fields,
    )


def decompress(entry: CompressedMPI) -> tuple[Any, Any, Any, Any]:
    """CompressedMPI -> (rgb fp32, sigma fp32, disparity, k), the render
    executables' input contract. Written against the array surface numpy
    and jax share, so device-resident fields dequantize on device (the
    dequant IS the render-path cost of the tier) and host fields stay
    host-side (FakeEngine)."""
    if entry.tier == "int8":
        rgb = _dequant_int8(entry.rgb, entry.rgb_lo, entry.rgb_scale)
        sigma = _dequant_int8(entry.sigma, entry.sigma_lo, entry.sigma_scale)
    else:  # fp32 passthrough / bf16 upcast
        rgb = entry.rgb.astype(np.float32)
        sigma = entry.sigma.astype(np.float32)
    return rgb, sigma, entry.disparity, entry.k


# -- wire format --------------------------------------------------------------
#
# One self-describing blob: magic, a JSON header (tier, bucket, plane
# counts, and per-field shape/dtype), then the raw little-endian buffers in
# header order. Plain MPIEntry values serialize as the fp32 tier, so a
# peer fetch works whatever tier the owner runs (the tier-qualified key
# means homogeneous fleets only ever exchange their own tier).


def to_wire(entry: Any) -> bytes:
    """MPIEntry | CompressedMPI -> bytes (the GET /mpi/<key> body)."""
    if isinstance(entry, MPIEntry):
        entry = CompressedMPI(
            tier="fp32",
            rgb=np.asarray(entry.mpi_rgb, np.float32),
            sigma=np.asarray(entry.mpi_sigma, np.float32),
            disparity=np.asarray(entry.disparity, np.float32),
            k=np.asarray(entry.k, np.float32),
            bucket=tuple(entry.bucket),
            num_planes_full=int(np.shape(entry.mpi_rgb)[1]),
        )
    # materialize each field off-device ONCE — an MPI slab is the whole
    # payload, and a second np.asarray would double the D2H transfer the
    # peer-fetch timeout budgets for
    arrays = {
        n: np.ascontiguousarray(np.asarray(a))
        for n, a in entry._arrays().items() if a is not None
    }
    header = {
        "tier": entry.tier,
        "bucket": list(entry.bucket),
        "num_planes_full": entry.num_planes_full,
        "fields": {
            name: {"shape": list(a.shape), "dtype": str(a.dtype)}
            for name, a in arrays.items()
        },
    }
    buf = io.BytesIO()
    head = json.dumps(header).encode()
    buf.write(_WIRE_MAGIC)
    buf.write(len(head).to_bytes(8, "little"))
    buf.write(head)
    for name in header["fields"]:
        buf.write(arrays[name].tobytes())
    return buf.getvalue()


def from_wire(data: bytes) -> Any:
    """bytes -> MPIEntry (fp32 full) | CompressedMPI. Validates structure
    and sizes: a truncated/garbled peer response raises ValueError (the
    fetcher counts it as an error outcome and re-predicts locally)."""
    if not data.startswith(_WIRE_MAGIC):
        raise ValueError("not an MPI wire blob (bad magic)")
    off = len(_WIRE_MAGIC)
    if len(data) < off + 8:
        raise ValueError("truncated MPI wire blob (no header length)")
    head_len = int.from_bytes(data[off:off + 8], "little")
    off += 8
    if head_len <= 0 or head_len > 1 << 20 or len(data) < off + head_len:
        raise ValueError("truncated MPI wire blob (bad header length)")
    header = json.loads(data[off:off + head_len])
    off += head_len
    tier = header["tier"]
    if tier not in TIERS:
        raise ValueError(f"unknown wire tier {tier!r}")
    arrays: dict[str, np.ndarray] = {}
    for name, spec in header["fields"].items():
        shape = tuple(int(v) for v in spec["shape"])
        dtype = (np.dtype(_bf16_dtype()) if spec["dtype"] == "bfloat16"
                 else np.dtype(spec["dtype"]))
        count = int(np.prod(shape)) if shape else 1
        nbytes = count * dtype.itemsize
        if len(data) < off + nbytes:
            raise ValueError(f"truncated MPI wire blob (field {name})")
        # frombuffer straight off the blob at an offset + one .copy(): a
        # bytes slice first would transiently double a multi-hundred-MB
        # slab inside the peer-fetch budget (same discipline as to_wire)
        arrays[name] = np.frombuffer(
            data, dtype=dtype, count=count, offset=off
        ).reshape(shape).copy()
        off += nbytes
    required = {"rgb", "sigma", "disparity", "k"}
    if tier == "int8":
        # a blob missing the quantization sidecars would dequantize into
        # None.astype at RENDER time — the poisoned-cache failure class
        # the adoption fence exists to prevent; refuse it at parse time
        required |= {"rgb_lo", "rgb_scale", "sigma_lo", "sigma_scale"}
    missing = required - set(arrays)
    if missing:
        raise ValueError(
            f"MPI wire blob (tier {tier}) missing fields {sorted(missing)}"
        )
    bucket = tuple(int(v) for v in header["bucket"])
    num_full = int(header["num_planes_full"])
    if tier == "fp32" and arrays["rgb"].shape[1] == num_full:
        return MPIEntry(
            mpi_rgb=arrays["rgb"], mpi_sigma=arrays["sigma"],
            disparity=arrays["disparity"], k=arrays["k"], bucket=bucket,
        )
    return CompressedMPI(
        tier=tier, bucket=bucket, num_planes_full=num_full,
        rgb=arrays["rgb"], sigma=arrays["sigma"],
        disparity=arrays["disparity"], k=arrays["k"],
        rgb_lo=arrays.get("rgb_lo"), rgb_scale=arrays.get("rgb_scale"),
        sigma_lo=arrays.get("sigma_lo"), sigma_scale=arrays.get("sigma_scale"),
    )
