"""FakeEngine: the serving stack with the XLA halves stubbed out.

The fleet-resilience surfaces — hot-swap state machine, consistent-hash
routing, health-gated ring membership, failover, the chaos drill's fleet
half — are all control-plane logic whose correctness has NOTHING to do
with the model. Proving them through real encoder compiles would cost
~30s per replica on this box (K replicas per test!), so this module gives
them a drop-in engine whose predict/render are cheap numpy while
EVERYTHING else is the production code path: `FakeEngine` subclasses
RenderEngine, so bucket validation, the WeightSet generation machinery,
`swap_weights`' validate/place/verify/flip sequence, the chaos seams, and
the metrics plumbing are the real implementations — only the executable
dispatch is replaced.

Usage (tests/test_fleet.py, tools/bench_fleet.py, tools/chaos_drill.py):

    app = make_fake_app(checkpoint_step=3,
                        swap_source=lambda: fake_checkpoint(4))
    server = make_server(app)   # the real HTTP surface

A fake render fills every frame with a constant derived from the MPI's
fill value, which `predict` derives from the generation's checkpoint
step — so an end-to-end test can read a rendered pixel and know which
weight generation produced it.

The fake slabs are digest-seeded and NON-constant: sigma carries a
randomly placed fronto-parallel "surface" (a Gaussian plane profile with a
low-frequency spatial bump), so the transmittance distribution looks like
a real scene's — planes in front of the surface are nearly transparent,
planes behind it occluded. That is what lets the compression-ratio and
transmittance-pruning paths (serving/compress.py) be exercised end to end
without an XLA compile: a constant slab would quantize to nothing and
prune to one plane, proving nothing. The generation marker survives
compression: every plane's corner pixel (0, 0) channel 0 carries the fill
value, so `render` can recover it even from a pruned entry (whose first
planes may be gone) — exactly under the lossless fp32/bf16 tiers, and to
within the per-plane quantization step (~1e-3 of the slab's range) under
int8.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable

import numpy as np

from mine_tpu.config import Config
from mine_tpu.resilience import chaos
from mine_tpu.serving.cache import MPIEntry
from mine_tpu.serving.compress import CompressedMPI, decompress
from mine_tpu.serving.engine import RenderEngine, WeightSet


def fake_variables(checkpoint_step: int = 0) -> tuple[dict, dict]:
    """(params, batch_stats) for a FakeEngine: a tiny tree whose single
    leaf's VALUE carries the step (so a swapped-in tree is distinguishable)
    while its shape/dtype stay fixed (so swaps between fake checkpoints
    pass tree validation, like real same-architecture checkpoints do)."""
    return (
        {"w": np.full((4,), float(checkpoint_step), np.float32)},
        {},
    )


def fake_checkpoint(checkpoint_step: int) -> tuple[dict, dict, int]:
    """A swap_source payload: (params, batch_stats, step)."""
    params, batch_stats = fake_variables(checkpoint_step)
    return params, batch_stats, checkpoint_step


class FakeEngine(RenderEngine):
    """RenderEngine with numpy predict/render dispatches.

    Inherits the real bucket validation, weights()/swap_weights()
    generation machinery, and metrics wiring; overrides only
    `_dispatch_predict` (used by live predicts AND the swap path's
    verification dispatch) and `render`. `render_delay_s` /
    `predict_delay_s` are mutable knobs for overload scenarios."""

    def __init__(
        self,
        cfg: Config | None = None,
        checkpoint_step: int = 0,
        render_delay_s: float = 0.0,
        predict_delay_s: float = 0.0,
        **kwargs: Any,
    ):
        if cfg is None:
            cfg = Config().replace(**{
                "data.img_h": 128, "data.img_w": 128,
                "mpi.num_bins_coarse": 2,
            })
        params, batch_stats = fake_variables(checkpoint_step)
        super().__init__(cfg, params, batch_stats,
                         checkpoint_step=checkpoint_step, **kwargs)
        self.render_delay_s = render_delay_s
        self.predict_delay_s = predict_delay_s
        # fake-executable accounting: the first touch of each (bucket)
        # predict / (bucket, n_planes, n_poses) render "compiles" a marker
        # into the SAME per-bucket slots the real engine fills, ticking the
        # SAME engine.compiles counter — so warm-pool coverage claims
        # ("no compile stall mid-flood for a pre-declared bucket",
        # tools/bench_fleet.py --mixed-bucket) are provable through the
        # control plane without XLA: a request landing on an executable
        # warmup() never built moves the counter, exactly like a real
        # replica would pay a blocking compile there.
        self._fake_lock = threading.Lock()

    def _place_variables(self, params: Any, batch_stats: Any) -> Any:
        # host numpy stays host numpy: no jax backend touch, no stderr
        # fallback note per construction (the fake tree matches no
        # partition rule by design)
        return {"params": params, "batch_stats": batch_stats}

    def _adopt_entry(self, entry, request_id: str | None = None):
        # compressed entries stay host numpy too: the fake render
        # decompresses in numpy, so device placement would only add a
        # backend dependency the fake exists to avoid
        return entry

    # -- fake executable registry (the real engine's compile accounting) ----

    def _build_predict(self, bucket) -> None:
        with self._fake_lock:
            if bucket._predict_exec is None:
                bucket._predict_exec = "fake-exec"
                self._count_compile("predict")

    def _build_render(self, bucket, n_poses: int, n_planes: int) -> None:
        with self._fake_lock:
            if (n_planes, n_poses) not in bucket._render_execs:
                bucket._render_execs[(n_planes, n_poses)] = "fake-exec"
                self._count_compile("render")

    def _dispatch_predict(self, bucket, img, variables):
        self._build_predict(bucket)  # first touch = the would-be compile
        if self.predict_delay_s:
            time.sleep(self.predict_delay_s)
        h, w, _ = bucket.spec
        s = bucket.num_planes
        fill = float(np.asarray(variables["params"]["w"]).flat[0])
        # digest-seeded scene: the same image always produces the same
        # slabs (cache/affinity tests stay deterministic), different
        # images produce different transmittance distributions
        seed = int.from_bytes(hashlib.sha256(
            np.ascontiguousarray(np.asarray(img)).tobytes()
        ).digest()[:8], "big")
        rng = np.random.default_rng(seed)
        planes = np.arange(s, dtype=np.float32)
        # a fronto-parallel surface at a random depth: sigma peaks on its
        # plane(s) and decays fast — in FRONT of it alpha is tiny (prunable
        # planes), BEHIND it the accumulated transmittance is ~0 (occluded,
        # also prunable); the surface band itself is opaque. The spatial
        # bump gives quantization per-pixel structure to preserve.
        surface = float(rng.uniform(0.25, 0.75)) * max(s - 1, 1)
        width = max(s / 8.0, 0.75)
        profile = np.exp(-(((planes - surface) / width) ** 2))
        yy, xx = np.meshgrid(np.linspace(0.0, 1.0, h),
                             np.linspace(0.0, 1.0, w), indexing="ij")
        bump = 0.5 + 0.5 * np.sin(
            2.0 * np.pi * (xx * rng.uniform(1.0, 3.0)
                           + yy * rng.uniform(1.0, 3.0) + rng.uniform())
        )
        mpi_sigma = (
            8.0 * profile[None, :, None, None, None]
            * (0.25 + 0.75 * bump[None, None, :, :, None])
        ).astype(np.float32)
        # rgb encodes the producing generation's step (clipped to [0, 1]
        # at render time) under low-amplitude texture; EVERY plane's
        # (0, 0) corner channel 0 is exactly `fill`, so the marker
        # survives plane pruning
        mpi_rgb = (
            fill + 0.05 * rng.standard_normal((1, s, h, w, 3))
        ).astype(np.float32)
        mpi_rgb[0, :, 0, 0, 0] = fill
        disparity = np.linspace(1.0, 0.01, s, dtype=np.float32)[None]
        return mpi_rgb, mpi_sigma, disparity

    def predict(
        self, image: np.ndarray, spec=None, request_id: str | None = None,
        weights: WeightSet | None = None,
        tier: str | None = None, prune_eps: float | None = None,
    ) -> MPIEntry | CompressedMPI:
        chaos.maybe_raise("predict_raise")  # same seam as the real engine
        ws = weights if weights is not None else self._weights
        bucket = self.bucket(spec)
        mpi_rgb, mpi_sigma, disparity = self._dispatch_predict(
            bucket, image, ws.variables
        )
        # the REAL compression path (tier + transmittance pruning) over the
        # fake slabs — compression-ratio/pruning behavior is exercised
        # compile-free, and _adopt_entry keeps everything host numpy; the
        # explicit tier/prune_eps snapshot overrides flow through exactly
        # like the real engine's (serving/degrade.py L1)
        entry = self._compress(bucket, mpi_rgb, mpi_sigma, disparity,
                               tier=tier, prune_eps=prune_eps)
        if self.metrics is not None:
            self.metrics.encoder_invocations.inc()
        return entry

    def render(
        self, entry: Any, poses: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        chaos.maybe_raise("engine_raise")  # same seam as the real engine
        poses = np.asarray(poses, np.float32)
        if poses.ndim != 3 or poses.shape[1:] != (4, 4):
            raise ValueError(f"poses must be (N, 4, 4), got {poses.shape}")
        if self.render_delay_s:
            # the real engine's cost model in miniature: a pruned entry
            # runs a smaller plane-count executable, so its dispatch is
            # proportionally cheaper — which is what makes the brownout
            # ladder's L1 (int8 + pruning) an actual capacity lever in
            # fake-fleet overload scenarios, not just a byte saving
            delay = self.render_delay_s
            if isinstance(entry, CompressedMPI) and entry.num_planes_full:
                delay *= entry.planes_kept / entry.num_planes_full
            time.sleep(delay)
        n = poses.shape[0]
        h, w, _ = entry.bucket
        # the real engine's executable-selection arithmetic, against the
        # fake registry: which (n_planes, n_poses) executables would this
        # dispatch run? First touch ticks the compile counter.
        bucket = self.bucket(entry.bucket)
        if isinstance(entry, CompressedMPI):
            n_planes = bucket.plane_bucket(entry.planes_kept)
        else:
            n_planes = bucket.num_planes
        max_b = self.pose_buckets[-1]
        for start in range(0, n, max_b):  # n == 0 touches nothing, like
            chunk = min(n - start, max_b)  # the real early return
            self._build_render(bucket, self._pose_bucket(chunk), n_planes)
        if isinstance(entry, CompressedMPI):
            rgb_slab = np.asarray(decompress(entry)[0])  # numpy dequant
        else:
            rgb_slab = np.asarray(entry.mpi_rgb)
        # the generation marker: the first surviving plane's corner pixel
        fill = float(np.clip(rgb_slab[0, 0, 0, 0, 0], 0.0, 1.0))
        rgb = np.full((n, h, w, 3), fill, np.float32)
        disp = np.full((n, h, w, 1), 0.5, np.float32)
        if self.metrics is not None:
            self.metrics.rendered_frames.inc(n)
            self.metrics.renders_per_sec.record(n)
        return rgb, disp


def make_fake_app(
    checkpoint_step: int = 0,
    swap_source: Callable | str | None = None,
    render_delay_s: float = 0.0,
    predict_delay_s: float = 0.0,
    cfg: Config | None = None,
    **app_kwargs: Any,
):
    """A full ServingApp (real cache/batcher/breaker/metrics/HTTP wiring)
    over a FakeEngine — zero XLA compiles. Extra kwargs go to ServingApp."""
    from mine_tpu.serving.server import ServingApp

    engine = FakeEngine(
        cfg=cfg, checkpoint_step=checkpoint_step,
        render_delay_s=render_delay_s, predict_delay_s=predict_delay_s,
    )
    app_kwargs.setdefault("max_delay_ms", 0.0)
    return ServingApp(
        engine.base_cfg, engine=engine, swap_source=swap_source,
        **app_kwargs,
    )
