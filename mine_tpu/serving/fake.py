"""FakeEngine: the serving stack with the XLA halves stubbed out.

The fleet-resilience surfaces — hot-swap state machine, consistent-hash
routing, health-gated ring membership, failover, the chaos drill's fleet
half — are all control-plane logic whose correctness has NOTHING to do
with the model. Proving them through real encoder compiles would cost
~30s per replica on this box (K replicas per test!), so this module gives
them a drop-in engine whose predict/render are cheap numpy while
EVERYTHING else is the production code path: `FakeEngine` subclasses
RenderEngine, so bucket validation, the WeightSet generation machinery,
`swap_weights`' validate/place/verify/flip sequence, the chaos seams, and
the metrics plumbing are the real implementations — only the executable
dispatch is replaced.

Usage (tests/test_fleet.py, tools/bench_fleet.py, tools/chaos_drill.py):

    app = make_fake_app(checkpoint_step=3,
                        swap_source=lambda: fake_checkpoint(4))
    server = make_server(app)   # the real HTTP surface

A fake render fills every frame with a constant derived from the MPI's
fill value, which `predict` derives from the generation's checkpoint
step — so an end-to-end test can read a rendered pixel and know which
weight generation produced it.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import numpy as np

from mine_tpu.config import Config
from mine_tpu.resilience import chaos
from mine_tpu.serving.cache import MPIEntry
from mine_tpu.serving.engine import RenderEngine, WeightSet


def fake_variables(checkpoint_step: int = 0) -> tuple[dict, dict]:
    """(params, batch_stats) for a FakeEngine: a tiny tree whose single
    leaf's VALUE carries the step (so a swapped-in tree is distinguishable)
    while its shape/dtype stay fixed (so swaps between fake checkpoints
    pass tree validation, like real same-architecture checkpoints do)."""
    return (
        {"w": np.full((4,), float(checkpoint_step), np.float32)},
        {},
    )


def fake_checkpoint(checkpoint_step: int) -> tuple[dict, dict, int]:
    """A swap_source payload: (params, batch_stats, step)."""
    params, batch_stats = fake_variables(checkpoint_step)
    return params, batch_stats, checkpoint_step


class FakeEngine(RenderEngine):
    """RenderEngine with numpy predict/render dispatches.

    Inherits the real bucket validation, weights()/swap_weights()
    generation machinery, and metrics wiring; overrides only
    `_dispatch_predict` (used by live predicts AND the swap path's
    verification dispatch) and `render`. `render_delay_s` /
    `predict_delay_s` are mutable knobs for overload scenarios."""

    def __init__(
        self,
        cfg: Config | None = None,
        checkpoint_step: int = 0,
        render_delay_s: float = 0.0,
        predict_delay_s: float = 0.0,
        **kwargs: Any,
    ):
        if cfg is None:
            cfg = Config().replace(**{
                "data.img_h": 128, "data.img_w": 128,
                "mpi.num_bins_coarse": 2,
            })
        params, batch_stats = fake_variables(checkpoint_step)
        super().__init__(cfg, params, batch_stats,
                         checkpoint_step=checkpoint_step, **kwargs)
        self.render_delay_s = render_delay_s
        self.predict_delay_s = predict_delay_s

    def _place_variables(self, params: Any, batch_stats: Any) -> Any:
        # host numpy stays host numpy: no jax backend touch, no stderr
        # fallback note per construction (the fake tree matches no
        # partition rule by design)
        return {"params": params, "batch_stats": batch_stats}

    def _dispatch_predict(self, bucket, img, variables):
        if self.predict_delay_s:
            time.sleep(self.predict_delay_s)
        h, w, _ = bucket.spec
        s = bucket.num_planes
        fill = float(np.asarray(variables["params"]["w"]).flat[0])
        # rgb encodes the producing generation's step (clipped to [0, 1]
        # at render time); sigma dense enough that frames aren't empty
        mpi_rgb = np.full((1, s, h, w, 3), fill, np.float32)
        mpi_sigma = np.full((1, s, h, w, 1), 5.0, np.float32)
        disparity = np.linspace(1.0, 0.01, s, dtype=np.float32)[None]
        return mpi_rgb, mpi_sigma, disparity

    def predict(
        self, image: np.ndarray, spec=None, request_id: str | None = None,
        weights: WeightSet | None = None,
    ) -> MPIEntry:
        chaos.maybe_raise("predict_raise")  # same seam as the real engine
        ws = weights if weights is not None else self._weights
        bucket = self.bucket(spec)
        mpi_rgb, mpi_sigma, disparity = self._dispatch_predict(
            bucket, image, ws.variables
        )
        if self.metrics is not None:
            self.metrics.encoder_invocations.inc()
        return MPIEntry(
            mpi_rgb=mpi_rgb, mpi_sigma=mpi_sigma, disparity=disparity,
            k=np.eye(3, dtype=np.float32)[None], bucket=bucket.spec,
        )

    def render(
        self, entry: MPIEntry, poses: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        chaos.maybe_raise("engine_raise")  # same seam as the real engine
        poses = np.asarray(poses, np.float32)
        if poses.ndim != 3 or poses.shape[1:] != (4, 4):
            raise ValueError(f"poses must be (N, 4, 4), got {poses.shape}")
        if self.render_delay_s:
            time.sleep(self.render_delay_s)
        n = poses.shape[0]
        h, w, _ = entry.bucket
        fill = float(np.clip(np.asarray(entry.mpi_rgb).flat[0], 0.0, 1.0))
        rgb = np.full((n, h, w, 3), fill, np.float32)
        disp = np.full((n, h, w, 1), 0.5, np.float32)
        if self.metrics is not None:
            self.metrics.rendered_frames.inc(n)
            self.metrics.renders_per_sec.record(n)
        return rgb, disp


def make_fake_app(
    checkpoint_step: int = 0,
    swap_source: Callable | str | None = None,
    render_delay_s: float = 0.0,
    predict_delay_s: float = 0.0,
    cfg: Config | None = None,
    **app_kwargs: Any,
):
    """A full ServingApp (real cache/batcher/breaker/metrics/HTTP wiring)
    over a FakeEngine — zero XLA compiles. Extra kwargs go to ServingApp."""
    from mine_tpu.serving.server import ServingApp

    engine = FakeEngine(
        cfg=cfg, checkpoint_step=checkpoint_step,
        render_delay_s=render_delay_s, predict_delay_s=predict_delay_s,
    )
    app_kwargs.setdefault("max_delay_ms", 0.0)
    return ServingApp(
        engine.base_cfg, engine=engine, swap_source=swap_source,
        **app_kwargs,
    )
