"""RenderEngine: the long-lived, compile-bounded core of the serving stack.

The one-shot inference path (mine_tpu/inference/video.py) jits per
(config, pose-count) pair implicitly through jax.jit's trace cache — fine
for a CLI that renders two trajectories and exits, but a server fed
arbitrary request shapes would recompile unboundedly and stall live traffic
for seconds per new shape. The engine makes the compile set explicit and
finite:

  * shape buckets (H, W, S): each bucket owns ONE AOT-compiled predict
    executable and one render executable per padded pose count, built from
    the pure functions the inference module exposes
    (predict_blended_mpi_fn / render_many_fn) via jax.jit().lower().compile()
    — so "did this request recompile?" is an inspectable counter, not a
    guess about jit cache internals.
  * pose-count buckets (powers of two): a render for N poses runs the
    next-bucket executable on poses padded with identities and slices the
    first N frames off the result. Unbounded distinct N collapses onto
    log2(max_bucket) executables.
  * donated request buffers: on accelerator backends the per-request inputs
    (the prepared image for predict, the padded pose stack for render) are
    donated, letting XLA reuse them as scratch instead of growing the
    per-request HBM watermark. CPU ignores donation, so it is only
    requested off-CPU (avoids jax's per-executable warning in tests).
  * every executable is built behind utils/compile_cache.py's persistent
    XLA cache, so a restarted server pre-warms from disk instead of
    recompiling its whole bucket set.

Coarse-to-fine configs compose: a bucket whose config carries
mpi.num_bins_fine > 0 predicts through the two-pass c2f function and caches
the MERGED plane list; its render executables are shaped for
S_coarse + S_fine planes.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from mine_tpu.config import Config
from mine_tpu.obs.cost import StepCost, compiled_cost, resolve_peak_flops
from mine_tpu.obs.trace import NULL_TRACER, Tracer
from mine_tpu.resilience import chaos
from mine_tpu.serving.cache import MPIEntry
from mine_tpu.serving.compress import (
    TIERS,
    CompressedMPI,
    compress_mpi,
    decompress,
)
from mine_tpu.utils.compile_cache import enable_persistent_compile_cache

BucketSpec = tuple[int, int, int]  # (H, W, S_coarse)

_IDENTITY_POSE = np.eye(4, dtype=np.float32)


class SwapError(RuntimeError):
    """Base of the named hot-swap failure modes. Every subclass means the
    PREVIOUS generation is still serving — a swap never takes the engine
    down, it either flips atomically or leaves everything as it was."""


class SwapRejected(SwapError):
    """The candidate weights failed validation (tree structure/shape
    mismatch, or the verification dispatch raised). Old generation keeps
    serving."""


class SwapInProgress(SwapError):
    """A swap is already running; concurrent swaps never interleave."""


@dataclass(frozen=True)
class WeightSet:
    """One immutable weight generation: the device-resident variables, the
    checkpoint step they came from, and a monotonically increasing
    generation id. predict() reads ONE WeightSet reference for its whole
    dispatch, so an in-flight predict completes on the generation it
    started on even if a swap flips mid-dispatch; render() never touches
    weights at all (it consumes cached MPIEntries), so renders are
    generation-free by construction. The MPICache keys on checkpoint_step,
    which fences stale MPIs: post-swap predicts mint new keys, pre-swap
    entries stay servable for clients still holding their mpi_key (they
    age out via LRU)."""

    variables: Any
    checkpoint_step: int
    generation: int


def _abstract(tree: Any) -> Any:
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), tree
    )


class _Bucket:
    """One (H, W, S) shape bucket: configs, constants, and executables."""

    def __init__(self, engine: "RenderEngine", spec: BucketSpec):
        import jax
        import jax.numpy as jnp

        from mine_tpu.inference.video import fov_intrinsics
        from mine_tpu.training.step import make_disparity_list

        h, w, s = spec
        self.spec = spec
        self.engine = engine
        self.cfg = engine.base_cfg.replace(**{
            "data.img_h": h, "data.img_w": w, "mpi.num_bins_coarse": s,
            "mpi.compositor": engine.compositor,
        })
        self.is_c2f = self.cfg.mpi.num_bins_fine > 0
        self.num_planes = s + (self.cfg.mpi.num_bins_fine if self.is_c2f else 0)
        # deterministic serving planes: the fix_disparity branch of the
        # shared sampler (training/step.py make_disparity_list)
        fixed = self.cfg.replace(**{"mpi.fix_disparity": True})
        self.disparity = make_disparity_list(fixed, jax.random.PRNGKey(0), 1)
        self.k = jnp.asarray(fov_intrinsics(h, w, engine.fov_deg))[None]
        self._predict_exec = None
        # render executables keyed (n_planes, n_poses): transmittance
        # pruning (serving/compress.py) makes the plane count variable, so
        # pruned renders run a pruned-plane-count bucket — fewer planes is
        # a genuinely cheaper executable (the FLOPs cut shows up in its
        # StepCost), and the bucket set stays finite: plane_count_buckets x
        # pose_buckets
        self._render_execs: dict[tuple[int, int], Any] = {}
        # XLA cost analysis per executable (obs/cost.py), captured at
        # compile time — what the /metrics MFU gauge divides by step time
        self.predict_cost: StepCost | None = None
        self.render_costs: dict[tuple[int, int], StepCost] = {}
        # pruned-plane executable buckets: powers of two under the full
        # count, plus the full count itself — log2(S) extra shapes at most,
        # compiled lazily only when pruning actually produces that bucket
        self.plane_buckets: tuple[int, ...] = tuple(sorted(
            {self.num_planes}
            | {1 << p for p in range(1, self.num_planes.bit_length())
               if (1 << p) < self.num_planes}
        ))
        self._lock = threading.Lock()

    def plane_bucket(self, n_planes: int) -> int:
        """Smallest plane-count executable bucket >= n_planes."""
        for b in self.plane_buckets:
            if n_planes <= b:
                return b
        return self.plane_buckets[-1]

    # -- executables ---------------------------------------------------------

    # Both getters are double-checked: the lock-free fast path (atomic dict/
    # attribute reads under the GIL) means an already-built executable is
    # NEVER stalled behind another executable's multi-second compile on the
    # same bucket — only genuine compiles serialize on the lock.

    def predict_executable(self):
        import jax

        from mine_tpu.inference.video import (
            predict_blended_mpi_c2f_fn,
            predict_blended_mpi_fn,
        )

        exe = self._predict_exec
        if exe is not None:
            return exe
        with self._lock:
            if self._predict_exec is None:
                h, w, _ = self.spec
                donate = self.engine._donate((2,))
                img = jax.ShapeDtypeStruct((1, h, w, 3), np.float32)
                variables = _abstract(self.engine.variables)
                if self.is_c2f:
                    fn = jax.jit(
                        predict_blended_mpi_c2f_fn, static_argnums=0, **donate
                    )
                    lowered = fn.lower(self.cfg, variables, img, self.k)
                else:
                    fn = jax.jit(
                        predict_blended_mpi_fn, static_argnums=0, **donate
                    )
                    lowered = fn.lower(
                        self.cfg, variables, img, self.disparity, self.k
                    )
                self._predict_exec = lowered.compile()
                self.predict_cost = compiled_cost(self._predict_exec)
                self.engine._count_compile("predict")
            return self._predict_exec

    def render_executable(self, n_poses: int, n_planes: int | None = None):
        import jax

        from mine_tpu.inference.video import render_many_fn

        s = self.num_planes if n_planes is None else int(n_planes)
        key = (s, n_poses)
        exe = self._render_execs.get(key)
        if exe is not None:
            return exe
        with self._lock:
            exe = self._render_execs.get(key)
            if exe is None:
                h, w, _ = self.spec
                donate = self.engine._donate((5,))
                fn = jax.jit(render_many_fn, static_argnums=0, **donate)
                lowered = fn.lower(
                    self.cfg,
                    jax.ShapeDtypeStruct((1, s, h, w, 3), np.float32),
                    jax.ShapeDtypeStruct((1, s, h, w, 1), np.float32),
                    jax.ShapeDtypeStruct((1, s), np.float32),
                    jax.ShapeDtypeStruct((1, 3, 3), np.float32),
                    jax.ShapeDtypeStruct((n_poses, 4, 4), np.float32),
                )
                exe = lowered.compile()
                self.render_costs[key] = compiled_cost(exe)
                self._render_execs[key] = exe
                self.engine._count_compile("render")
            return exe


class RenderEngine:
    """Predict-once / render-many over a fixed checkpoint's weights.

    Thread-safe: predict and render may be called concurrently from HTTP
    handler threads and the batcher worker; compiles are serialized per
    bucket, device dispatches go through jax's own locking.
    """

    def __init__(
        self,
        cfg: Config,
        params: Any,
        batch_stats: Any,
        checkpoint_step: int = 0,
        metrics: Any | None = None,
        pose_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64),
        fov_deg: float = 90.0,
        compositor: str = "streaming",
        peak_flops_override: float = 0.0,
        tracer: Tracer | None = None,
        cache_tier: str | None = None,
        prune_eps: float | None = None,
    ):
        import jax

        from mine_tpu.ops import compositor_from_config

        enable_persistent_compile_cache()
        self.base_cfg = cfg
        # compressed-MPI knobs (serving/compress.py): ctor args override the
        # serving.* config group. Validated here so a typo'd tier fails at
        # startup, not inside the first predict's compression.
        self.cache_tier = (cfg.serving.cache_tier if cache_tier is None
                           else cache_tier)
        if self.cache_tier not in TIERS:
            raise ValueError(
                f"serving.cache_tier={self.cache_tier!r} must be one of "
                f"{TIERS}"
            )
        self.prune_eps = float(
            cfg.serving.prune_transmittance_eps if prune_eps is None
            else prune_eps
        )
        if not 0.0 <= self.prune_eps < 1.0:
            # a compositing weight never reaches 1.0, so eps >= 1 (the
            # classic 1e3-for-1e-3 typo) would silently collapse every
            # cached MPI to its single best plane — fail at startup instead
            raise ValueError(
                f"serving.prune_transmittance_eps={self.prune_eps} must be "
                "in [0, 1) — it thresholds a compositing weight"
            )
        # brownout degradation override (serving/degrade.py L1): while set,
        # NEW predicts compress at these knobs instead of the configured
        # operating point. A caller that mints a tier-qualified cache key
        # must read the effective knobs ONCE and pass them into predict()
        # explicitly — key and entry then agree across a concurrent
        # level flip (the WeightSet snapshot discipline, applied to the
        # compression operating point).
        self._degraded_tier: str | None = None
        self._degraded_prune_eps: float = 0.0
        # Serving defaults to the STREAMING compositor regardless of the
        # checkpoint's training-time knob: render-many never materializes
        # the warped (N_poses, S, H, W, C) slabs, so the resident-MPI render
        # batches (pose buckets) and plane counts can grow without moving
        # the HBM watermark — and the knob is a numerics no-op (parity
        # within 1e-5, tests/test_mpi_render.py; PARITY.md). Pass
        # compositor="dense" to restore the materializing path.
        self.compositor = compositor
        compositor_from_config(
            cfg.replace(**{"mpi.compositor": compositor})
        )  # unknown names fail here, not inside a bucket compile
        # device_put ONCE: a checkpoint restored template-free
        # (training/checkpoint.py load_for_serving) arrives as host numpy
        # leaves, and numpy inputs to a compiled executable re-transfer on
        # every call — the whole params tree per predict, the exact cost a
        # long-lived engine exists to amortize away. The placement flows
        # through the SAME partition-rule table training uses
        # (parallel/rules.py) so a future multi-device serving mesh changes
        # serving and training layouts from one table instead of two code
        # paths; on today's single-device (1,1,1) mesh every row resolves
        # to replicated, and the placement is an OPTIMIZATION — an exotic
        # checkpoint whose variables a table row fails to match falls back
        # to the plain replicated device_put instead of failing startup.
        self._weights = WeightSet(
            variables=self._place_variables(params, batch_stats),
            checkpoint_step=int(checkpoint_step),
            generation=0,
        )
        # serializes swap_weights callers; predict never takes it (the
        # atomic _weights reference read is the whole synchronization)
        self._swap_lock = threading.Lock()
        self.metrics = metrics
        # request-scoped spans (X-Request-Id): predict/render dispatches
        # land in the same ring the HTTP handler spans use, so
        # /debug/trace?request_id= can stitch one request's full tree
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.pose_buckets = tuple(sorted(set(int(n) for n in pose_buckets)))
        if not self.pose_buckets or self.pose_buckets[0] < 1:
            raise ValueError(f"bad pose_buckets {pose_buckets}")
        self.fov_deg = fov_deg
        self.default_bucket: BucketSpec = (
            cfg.data.img_h, cfg.data.img_w, cfg.mpi.num_bins_coarse
        )
        self.compiles = 0  # total executables built (also in metrics)
        # the MFU gauge's denominator (obs/cost.py table, or the explicit
        # override — the only honest choice on CPU); None => no MFU gauge
        self.peak_flops = resolve_peak_flops(
            jax.devices()[0], peak_flops_override
        )
        self._buckets: dict[BucketSpec, _Bucket] = {}  # guarded-by: _buckets_lock
        self._buckets_lock = threading.Lock()

    # -- weight generations --------------------------------------------------

    @property
    def variables(self) -> Any:
        """The serving generation's device-resident variables."""
        return self._weights.variables

    @property
    def checkpoint_step(self) -> int:
        """The serving generation's checkpoint step (MPI cache key part)."""
        return self._weights.checkpoint_step

    @property
    def generation(self) -> int:
        return self._weights.generation

    def weights(self) -> WeightSet:
        """One consistent snapshot of (variables, checkpoint_step,
        generation). Callers that compute a cache key AND dispatch a
        predict must read this ONCE and use it for both — reading
        engine.checkpoint_step and engine.variables separately can
        straddle a swap and cache a new-generation MPI under the old
        step's key."""
        return self._weights

    # -- degraded compression override (serving/degrade.py L1) ----------------

    def set_degraded_compression(self, tier: str, prune_eps: float) -> None:
        """Engage the brownout compression operating point: NEW predicts
        land at `tier` with at least `prune_eps` pruning (the configured
        eps still applies if it is stricter). Cached entries are
        untouched — the tier is part of their keys."""
        if tier not in TIERS:
            raise ValueError(f"degraded tier {tier!r} must be one of {TIERS}")
        if not 0.0 <= float(prune_eps) < 1.0:
            raise ValueError(
                f"degraded prune_eps={prune_eps} must be in [0, 1)"
            )
        self._degraded_prune_eps = float(prune_eps)
        self._degraded_tier = tier

    def clear_degraded_compression(self) -> None:
        self._degraded_tier = None
        self._degraded_prune_eps = 0.0

    def effective_tier(self) -> str:
        """The tier NEW predicts land at right now (cache-key part).
        Callers mint the key from one read and pass the same value into
        predict(tier=...) so key and entry cannot straddle a flip."""
        return self._degraded_tier or self.cache_tier

    def effective_prune_eps(self) -> float:
        if self._degraded_tier is None:
            return self.prune_eps
        return max(self.prune_eps, self._degraded_prune_eps)

    def swap_weights(
        self,
        params: Any,
        batch_stats: Any,
        checkpoint_step: int,
        verify: bool = True,
    ) -> WeightSet:
        """Hot-swap to a new weight generation; returns the new WeightSet.

        The sequence — validate, place, re-prove the warm buckets, flip —
        runs entirely while the OLD generation serves traffic:

          1. validate: the candidate tree must match the serving tree's
             structure/shapes/dtypes exactly (checkpoint.py
             validate_variables_tree). The AOT executables are pure
             functions of abstract shapes, so this is precisely the
             condition under which every warm bucket's executable set
             carries over unchanged — a shape-mismatched checkpoint is a
             SwapRejected here, never a compile failure mid-request.
          2. place: device_put through the partition-rule table (same
             fallback as startup).
          3. verify (re-AOT + prove): for every warm bucket, (re)build its
             predict executable — a no-op when already compiled, the
             background compile when a swap races bucket warm-up — and run
             ONE dispatch against the NEW variables with a zeros image.
             A candidate that cannot execute (poisoned buffers, a device
             rejection) fails HERE, on the swap thread, not on the first
             live request after the flip.
          4. flip: one atomic reference assignment. In-flight predicts
             keep their snapshot; the old variables free once the last
             in-flight dispatch drops them.

        Raises SwapRejected (validation/verify failed — old generation
        still serving) or SwapInProgress (another swap holds the lock).
        """
        from mine_tpu.training.checkpoint import (
            CheckpointTreeMismatch,
            validate_variables_tree,
        )

        if not self._swap_lock.acquire(blocking=False):
            raise SwapInProgress("a weight swap is already in progress")
        try:
            serving = self._weights
            candidate = {"params": params, "batch_stats": batch_stats}
            try:
                validate_variables_tree(
                    _abstract(serving.variables), candidate,
                    context=f"swap candidate (step {checkpoint_step}) vs "
                            f"serving generation {serving.generation}",
                )
            except CheckpointTreeMismatch as exc:
                raise SwapRejected(str(exc)) from exc
            placed = self._place_variables(params, batch_stats)
            if verify:
                for spec in self.bucket_specs():
                    bucket = self.bucket(spec)
                    h, w, _ = spec
                    try:
                        self._dispatch_predict(
                            bucket,
                            np.zeros((1, h, w, 3), np.float32),
                            placed,
                        )
                    except Exception as exc:  # noqa: BLE001 - named rollback
                        raise SwapRejected(
                            f"verification dispatch failed on bucket "
                            f"{spec}: {type(exc).__name__}: {exc}"
                        ) from exc
            new = WeightSet(
                variables=placed,
                checkpoint_step=int(checkpoint_step),
                generation=serving.generation + 1,
            )
            self._weights = new  # the atomic flip
            if self.metrics is not None:
                self.metrics.weight_generation.set(new.generation)
            return new
        finally:
            self._swap_lock.release()

    # -- internals -----------------------------------------------------------

    def _place_variables(self, params: Any, batch_stats: Any) -> Any:
        """device_put a host variables tree through the partition-rule
        table (fallback: plain replicated placement) — shared by startup
        and every hot swap."""
        import jax

        variables = {"params": params, "batch_stats": batch_stats}
        try:
            shardings = self._placement_shardings(
                self.base_cfg, params, batch_stats
            )
            return jax.device_put(variables, shardings)
        except ValueError as exc:
            import sys

            print(f"# serving placement fell back to plain device_put "
                  f"(partition-rule table: {exc})", file=sys.stderr)
            return jax.device_put(variables)

    def _placement_shardings(self, cfg, params, batch_stats):
        """NamedShardings for the resident variables from the partition-rule
        table, on a single-device (1,1,1) mesh — the serving twin of
        training's `distribute_state`. Render/predict executables consume
        the variables wherever this puts them."""
        import numpy as np_

        import jax
        from jax.sharding import Mesh

        from mine_tpu.parallel import AXIS_NAMES, rules as rules_mod

        mesh = Mesh(
            np_.asarray(jax.devices()[:1]).reshape(1, 1, 1), AXIS_NAMES
        )
        table = rules_mod.partition_rules(cfg)
        min_size = cfg.parallel.zero1_min_size
        specs = {
            name: rules_mod.tree_specs(rules_mod.match_partition_rules(
                table, tree, dict(mesh.shape), min_size, prefix=name
            ))
            for name, tree in (
                ("params", params), ("batch_stats", batch_stats),
            )
        }
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    def _donate(self, argnums: tuple[int, ...]) -> dict:
        import jax

        if jax.default_backend() == "cpu":
            return {}  # CPU ignores donation and warns per executable
        return {"donate_argnums": argnums}

    def _count_compile(self, kind: str) -> None:
        self.compiles += 1
        if self.metrics is not None:
            self.metrics.engine_compiles.inc(kind=kind)

    def bucket(self, spec: BucketSpec | None = None) -> _Bucket:
        spec = self.default_bucket if spec is None else tuple(map(int, spec))
        h, w, s = spec
        if h % 128 or w % 128:
            # same constraint the model enforces (training/step.py
            # build_model) — fail at request validation, not inside a trace
            raise ValueError(
                f"bucket H={h}, W={w} must be multiples of 128 "
                "(MPI decoder receptive-field extension)"
            )
        if s < 2:
            raise ValueError(f"bucket S={s} must be >= 2")
        with self._buckets_lock:
            b = self._buckets.get(spec)
            if b is None:
                b = _Bucket(self, spec)
                self._buckets[spec] = b
            return b

    def bucket_specs(self) -> list[BucketSpec]:
        with self._buckets_lock:
            return list(self._buckets)

    def _pose_bucket(self, n: int) -> int:
        for b in self.pose_buckets:
            if n <= b:
                return b
        return self.pose_buckets[-1]

    # -- the two halves ------------------------------------------------------

    def _dispatch_predict(self, bucket: _Bucket, img: Any, variables: Any):
        """One predict-executable dispatch against an explicit variables
        tree; returns (mpi_rgb, mpi_sigma, disparity). Shared by live
        predicts and the swap path's verification dispatch."""
        exe = bucket.predict_executable()
        if bucket.is_c2f:
            return exe(variables, img, bucket.k)
        mpi_rgb, mpi_sigma = exe(variables, img, bucket.disparity, bucket.k)
        return mpi_rgb, mpi_sigma, bucket.disparity

    def predict(
        self, image: np.ndarray, spec: BucketSpec | None = None,
        request_id: str | None = None,
        weights: WeightSet | None = None,
        tier: str | None = None,
        prune_eps: float | None = None,
    ) -> MPIEntry | CompressedMPI:
        """Run the encoder-decoder once; returns the device-resident cache
        value at the engine's tier — a plain MPIEntry at fp32 with pruning
        off (the numerics no-op), a CompressedMPI otherwise.

        image: (h, w, 3) uint8 or float in [0, 1] at any resolution — it is
        resized to the bucket's (H, W) exactly like the one-shot CLI
        (inference/video.py prepare_image).

        weights: an explicit WeightSet snapshot (engine.weights()) so the
        caller's cache key and this dispatch are guaranteed the same
        generation across a concurrent hot swap; defaults to the serving
        generation at call time.

        tier/prune_eps: explicit compression operating point — the same
        snapshot discipline as `weights`, for the degradation ladder: the
        caller that minted a tier-qualified cache key passes the values
        it minted from (engine.effective_tier()/effective_prune_eps()),
        so the entry always lands at its key's tier even when a brownout
        level flips mid-predict. Default: the effective knobs at call
        time.
        """
        from mine_tpu.inference.video import prepare_image

        chaos.maybe_raise("predict_raise")  # fault seam (resilience/chaos.py)
        ws = weights if weights is not None else self._weights
        bucket = self.bucket(spec)
        h, w, _ = bucket.spec
        with self.tracer.span("engine_predict", cat="serve",
                              bucket=str(bucket.spec),
                              request_id=request_id):
            img = prepare_image(image, h, w)
            mpi_rgb, mpi_sigma, disparity = self._dispatch_predict(
                bucket, img, ws.variables
            )
            entry = self._compress(
                bucket, mpi_rgb, mpi_sigma, disparity,
                tier=tier, prune_eps=prune_eps,
            )
        if self.metrics is not None:
            self.metrics.encoder_invocations.inc()
            if bucket.predict_cost is not None and bucket.predict_cost.flops:
                self.metrics.step_flops.set(
                    bucket.predict_cost.flops, kind="predict"
                )
        return entry

    def _compress(self, bucket: _Bucket, mpi_rgb, mpi_sigma, disparity,
                  tier: str | None = None, prune_eps: float | None = None):
        """Predict output -> cache value at the given (or effective)
        tier/prune knobs. The fp32 + pruning-off fast path is a numerics
        no-op: the device arrays the executable produced ARE the entry
        (PARITY.md 5.11); otherwise compression runs host-side (one
        device_get per predict) and the compressed fields are re-placed
        on device."""
        entry = compress_mpi(
            mpi_rgb, mpi_sigma, disparity, bucket.k, bucket=bucket.spec,
            tier=self.effective_tier() if tier is None else tier,
            prune_eps=(self.effective_prune_eps() if prune_eps is None
                       else prune_eps),
            use_alpha=bucket.cfg.mpi.use_alpha,
        )
        if (self.metrics is not None and isinstance(entry, CompressedMPI)
                and entry.planes_kept < entry.num_planes_full):
            self.metrics.pruned_planes.inc(
                entry.num_planes_full - entry.planes_kept
            )
        return self._adopt_entry(entry)

    def _adopt_entry(self, entry, request_id: str | None = None):
        """Make a cache value (fresh from _compress, or fetched off a
        peer's wire) device-resident, exactly like startup device_puts the
        weights: a host-numpy slab fed to a compiled executable would
        re-transfer on EVERY render. nbytes is unchanged — byte accounting
        is a property of the representation, not of where it lives.
        `request_id` attributes the H2D transfer span to the originating
        request (a peer-fetched adoption is real request-path work)."""
        import jax

        with self.tracer.span("adopt_entry", cat="serve",
                              request_id=request_id):
            if isinstance(entry, CompressedMPI):
                return entry.replace_arrays({
                    name: None if a is None else jax.device_put(a)
                    for name, a in entry._arrays().items()
                })
            if isinstance(entry.mpi_rgb, np.ndarray):  # peer-fetched fp32
                return MPIEntry(
                    mpi_rgb=jax.device_put(entry.mpi_rgb),
                    mpi_sigma=jax.device_put(entry.mpi_sigma),
                    disparity=jax.device_put(entry.disparity),
                    k=jax.device_put(entry.k),
                    bucket=entry.bucket, nbytes=entry.nbytes,
                )
            return entry

    def _render_inputs(self, bucket: _Bucket, entry):
        """Cache value -> (rgb, sigma, disparity, k, n_planes) fp32 render
        inputs. Compressed entries dequantize here (dequant-on-render) and
        their surviving planes pad up to a plane-count executable bucket:
        prepended planes reuse the nearest surviving disparity with
        sigma == 0, so alpha is exactly 0 and they contribute nothing —
        the only deviation is the compositor's +1e-6 cumprod epsilon per
        pad plane, orders of magnitude under the quantization tolerance."""
        import jax.numpy as jnp

        if not isinstance(entry, CompressedMPI):
            return (entry.mpi_rgb, entry.mpi_sigma, entry.disparity,
                    entry.k, bucket.num_planes)
        rgb, sigma, disparity, k = decompress(entry)
        kept = entry.planes_kept
        n_planes = bucket.plane_bucket(kept)
        if kept < n_planes:
            pad = n_planes - kept
            _, _, h, w, _ = rgb.shape
            rgb = jnp.concatenate(
                [jnp.zeros((1, pad, h, w, 3), jnp.float32), rgb], axis=1
            )
            sigma = jnp.concatenate(
                [jnp.zeros((1, pad, h, w, 1), jnp.float32), sigma], axis=1
            )
            disparity = jnp.concatenate(
                [jnp.broadcast_to(disparity[:, :1], (1, pad)), disparity],
                axis=1,
            )
        return rgb, sigma, disparity, k, n_planes

    def render(
        self, entry: Any, poses: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Render (N, 4, 4) G_tgt_src poses against a cached MPI
        (MPIEntry or CompressedMPI — compressed entries dequantize per
        dispatch and run a pruned-plane-count executable bucket).

        Pads N up to the next pose bucket (identity poses, discarded) and
        runs that bucket's executable; N beyond the largest bucket chunks
        into largest-bucket dispatches. Returns host arrays
        (rgb (N, H, W, 3) float [0, 1], disparity (N, H, W, 1)).
        """
        import jax

        chaos.maybe_raise("engine_raise")  # fault seam (resilience/chaos.py)
        poses = np.asarray(poses, np.float32)
        if poses.ndim != 3 or poses.shape[1:] != (4, 4):
            raise ValueError(f"poses must be (N, 4, 4), got {poses.shape}")
        n = poses.shape[0]
        if n == 0:
            h, w, _ = entry.bucket
            return (np.zeros((0, h, w, 3), np.float32),
                    np.zeros((0, h, w, 1), np.float32))
        bucket = self.bucket(entry.bucket)
        mpi_rgb, mpi_sigma, disparity, k, n_planes = self._render_inputs(
            bucket, entry
        )
        max_b = self.pose_buckets[-1]
        rgb_parts, disp_parts = [], []
        total_flops = 0.0
        t0 = time.perf_counter()
        for start in range(0, n, max_b):
            chunk = poses[start:start + max_b]
            nb = self._pose_bucket(chunk.shape[0])
            if chunk.shape[0] < nb:
                pad = np.broadcast_to(
                    _IDENTITY_POSE, (nb - chunk.shape[0], 4, 4)
                )
                padded = np.concatenate([chunk, pad], axis=0)
            else:
                padded = chunk
            exe = bucket.render_executable(nb, n_planes)
            rgb, disp = exe(
                mpi_rgb, mpi_sigma, disparity, k,
                jax.numpy.asarray(padded),
            )
            rgb_parts.append(np.asarray(jax.device_get(rgb))[:chunk.shape[0]])
            disp_parts.append(np.asarray(jax.device_get(disp))[:chunk.shape[0]])
            cost = bucket.render_costs.get((n_planes, nb))
            if cost is not None and cost.flops:
                total_flops += cost.flops
        elapsed = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.rendered_frames.inc(n)
            self.metrics.renders_per_sec.record(n)
            # live cost gauges: the compiled executables' XLA FLOPs over
            # the measured dispatch wall time (device_get included — the
            # number a capacity plan sees, not a device-only ideal)
            if total_flops and elapsed > 0:
                achieved = total_flops / elapsed
                self.metrics.step_flops.set(total_flops, kind="render")
                self.metrics.achieved_tflops.set(achieved / 1e12)
                if self.peak_flops:
                    self.metrics.mfu.set(achieved / self.peak_flops)
        if len(rgb_parts) == 1:
            return rgb_parts[0], disp_parts[0]
        return np.concatenate(rgb_parts), np.concatenate(disp_parts)

    # -- pre-warming ---------------------------------------------------------

    def _build_predict(self, bucket: _Bucket) -> None:
        """Warmup hook: materialize one bucket's predict executable
        (FakeEngine overrides with marker registration so the warm-pool
        accounting is provable without XLA)."""
        bucket.predict_executable()

    def _build_render(self, bucket: _Bucket, n_poses: int,
                      n_planes: int) -> None:
        """Warmup hook: materialize one (n_planes, n_poses) render
        executable."""
        bucket.render_executable(n_poses, n_planes)

    def warmup(
        self,
        specs: list[BucketSpec] | None = None,
        pose_counts: tuple[int, ...] | None = None,
    ) -> int:
        """Compile the expected executable set before taking traffic
        (persisted by the XLA compile cache across restarts). Returns the
        number of executables built by this call.

        With pruning on, a render may land on ANY pruned-plane-count
        bucket, so those executables are part of the expected set too —
        otherwise the first live render of each (planes, poses) pair would
        pay a blocking compile on the request path, the cold start warmup
        exists to avoid. log2(S) x pose buckets, bounded.

        This IS the per-bucket warm-pool contract the mixed-bucket fleet
        bench gates (tools/bench_fleet.py --mixed-bucket): after
        warmup(declared_buckets), the `compiles` counter must stay FLAT
        through any flood that requests only declared buckets — and
        through hot swaps, whose verify step re-proves each warm bucket's
        executables against the new weights instead of rebuilding them
        (swap_weights step 3)."""
        before = self.compiles
        for spec in (specs if specs is not None else [self.default_bucket]):
            bucket = self.bucket(spec)
            self._build_predict(bucket)
            plane_counts = (bucket.plane_buckets if self.prune_eps
                            else (bucket.num_planes,))
            for nb in (pose_counts if pose_counts is not None
                       else self.pose_buckets):
                for n_planes in plane_counts:
                    self._build_render(bucket, self._pose_bucket(nb),
                                       n_planes)
        return self.compiles - before

    def warm_pool(self) -> dict[str, dict]:
        """Per-bucket executable inventory — which buckets hold a resident
        predict executable and which (n_planes, n_poses) render
        executables exist. Surfaced via /healthz so an operator (and the
        mixed-bucket bench) can see whether a replica's declared buckets
        are actually warm before traffic lands on them."""
        out: dict[str, dict] = {}
        for spec in self.bucket_specs():
            with self._buckets_lock:
                bucket = self._buckets[spec]
            out["x".join(str(v) for v in spec)] = {
                "predict": bucket._predict_exec is not None,
                "render": sorted(list(bucket._render_execs)),
            }
        return out
