"""`python -m mine_tpu.serving` == `python -m mine_tpu.serving.server`."""

from mine_tpu.serving.server import main

if __name__ == "__main__":
    main()
