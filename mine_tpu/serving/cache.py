"""Byte-budgeted LRU cache of predicted MPIs.

The serving asymmetry only pays off if the expensive half (one
encoder-decoder pass per image) is amortized across many renders — which
means MPIs must stay device-resident between requests. They are large: an
S=32 MPI at 384x512 holds rgb (S,H,W,3) + sigma (S,H,W,1) fp32 ≈ 100 MB,
three orders of magnitude bigger than a typical KV-cache entry. An
entry-counted LRU would let a handful of high-resolution predicts silently
exhaust HBM, so the budget — and the eviction accounting — is in BYTES.

Keys are (image_digest, checkpoint_step, H, W, S, tier): the same image
predicted under a newer checkpoint, at a different resolution, at a
different plane count, or cached at a different compression tier
(serving/compress.py — an int8 entry is NOT the fp32 entry) is a DIFFERENT
MPI — omitting any of these would alias entries and silently serve frames
at the wrong operating point. The digest is of the uploaded image bytes,
computed by the caller (server.py) before any decode.

Values are anything with `.nbytes` (the COMPRESSED byte count for
quantized/pruned entries) and `.bucket`: the cache accounts whatever is
actually resident, which is exactly what makes a quantized tier worth
having — the same byte budget holds tier-ratio more scenes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any

# (image_digest, checkpoint_step, H, W, S, tier) — S is the engine bucket's
# coarse plane count (its spec identity; c2f renders at coarse + fine),
# tier the compression tier the entry is stored at ("fp32"|"bf16"|"int8")
CacheKey = tuple[str, int, int, int, int, str]


def mpi_key(
    image_digest: str, checkpoint_step: int, bucket: tuple[int, int, int],
    tier: str = "fp32",
) -> CacheKey:
    h, w, s = bucket
    return (image_digest, int(checkpoint_step), int(h), int(w), int(s),
            str(tier))


def key_to_str(key: CacheKey) -> str:
    """Wire encoding of a cache key (the `mpi_key` field in HTTP responses)."""
    return ":".join(str(part) for part in key)


def key_from_str(s: str) -> CacheKey:
    parts = s.split(":")
    if len(parts) == 5:
        # pre-tier wire keys (a client that cached an mpi_key across a
        # server upgrade): they named the then-only fp32 representation
        digest, step, h, w, planes = parts
        tier = "fp32"
    elif len(parts) == 6:
        digest, step, h, w, planes, tier = parts
    else:
        raise ValueError(f"malformed mpi_key {s!r}")
    return (digest, int(step), int(h), int(w), int(planes), tier)


def _nbytes(arr: Any) -> int:
    """Bytes of one array leaf (jax Array and np.ndarray both expose
    size/dtype; jax's .nbytes can be missing on some array types)."""
    return int(arr.size) * int(arr.dtype.itemsize)


@dataclass
class MPIEntry:
    """One cached prediction: everything render-many needs, device-resident.

    disparity is carried per-entry (not re-derived from config) because a
    coarse-to-fine predict renders at its MERGED plane list — the cached
    arrays and the disparity they were predicted at travel together
    (inference/video.py predict_blended_mpi_c2f_fn).
    """

    mpi_rgb: Any  # (1, S, H, W, 3)
    mpi_sigma: Any  # (1, S, H, W, 1)
    disparity: Any  # (1, S)
    k: Any  # (1, 3, 3) shared src/tgt intrinsics (single-image serving)
    bucket: tuple[int, int, int]  # (H, W, S) engine shape bucket
    nbytes: int = field(default=0)

    def __post_init__(self) -> None:
        if not self.nbytes:
            self.nbytes = sum(
                _nbytes(a)
                for a in (self.mpi_rgb, self.mpi_sigma, self.disparity, self.k)
            )


class MPICache:
    """Thread-safe LRU over MPIEntry/CompressedMPI values with
    byte-accounted eviction (bytes = each value's own `.nbytes`, i.e. the
    compressed size for quantized tiers).

    `get` refreshes recency; `put` evicts least-recently-used entries until
    the resident total fits the budget. A single entry larger than the whole
    budget is still admitted (after evicting everything else): refusing it
    would make oversized requests uncacheable and re-run the encoder on
    every render — strictly worse than a temporarily overshot budget. The
    overshoot is visible in the bytes-resident gauge.
    """

    def __init__(self, byte_budget: int, metrics: Any | None = None):
        if byte_budget <= 0:
            raise ValueError(f"byte_budget must be positive, got {byte_budget}")
        self.byte_budget = int(byte_budget)
        self._lock = threading.Lock()
        self._entries: OrderedDict[CacheKey, Any] = OrderedDict()
        self._bytes = 0
        self._metrics = metrics

    @property
    def bytes_resident(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> list[CacheKey]:
        with self._lock:
            return list(self._entries)

    def hot_keys(self, n: int) -> list[tuple[str, int]]:
        """The up-to-n most-recently-used entries as (wire key, compressed
        nbytes), hottest first — exactly the REVERSE of eviction order, so
        a pre-warm that fetches this list front-to-back moves the entries
        eviction would take last. One surface serves both the autoscale
        bulk fetch and the operator debug endpoint (serving/autoscale.py,
        GET /debug/hot_keys)."""
        if n <= 0:
            return []
        out: list[tuple[str, int]] = []
        with self._lock:
            for key in reversed(self._entries):
                out.append((key_to_str(key), int(self._entries[key].nbytes)))
                if len(out) >= n:
                    break
        return out

    def stale_key(self, key: CacheKey) -> CacheKey | None:
        """Stale-while-revalidate lookup (serving/degrade.py L2): the
        newest RESIDENT key for the same scene at the same shape bucket —
        same digest/H/W/S, ANY tier — whose checkpoint step is older than
        `key`'s. Post-swap, the old generation's entries are exactly
        these: under brownout they keep serving instead of forcing a
        re-predict per scene. Returns None when nothing stale is
        resident (the caller falls through to the normal miss path)."""
        digest, step, h, w, s, _ = key
        best: CacheKey | None = None
        with self._lock:
            for cand in self._entries:
                if (cand[0] == digest and cand[2:5] == (h, w, s)
                        and cand[1] < step
                        and (best is None or cand[1] > best[1])):
                    best = cand
        return best

    def get(self, key: CacheKey, record: bool = True) -> Any | None:
        """Lookup + LRU touch. record=False skips the hit/miss counters —
        for internal re-checks (the predict singleflight's under-lock peek)
        that would otherwise double-count one logical request."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if record and self._metrics is not None:
            if entry is not None:
                self._metrics.cache_hits.inc()
            else:
                self._metrics.cache_misses.inc()
        return entry

    def put(self, key: CacheKey, entry: Any) -> list[CacheKey]:
        """Insert (or refresh) an entry; returns the keys evicted for it."""
        evicted: list[CacheKey] = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += entry.nbytes
            # evict from the LRU end, never the entry just inserted
            while self._bytes > self.byte_budget and len(self._entries) > 1:
                victim_key, victim = self._entries.popitem(last=False)
                self._bytes -= victim.nbytes
                evicted.append(victim_key)
            self._update_gauges_locked()
        if self._metrics is not None and evicted:
            self._metrics.cache_evictions.inc(len(evicted))
        return evicted

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._update_gauges_locked()

    def _update_gauges_locked(self) -> None:
        if self._metrics is not None:
            self._metrics.cache_bytes_resident.set(self._bytes)
            self._metrics.cache_entries.set(len(self._entries))
