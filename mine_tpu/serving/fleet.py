"""Multi-replica fleet front: digest-affinity routing over health-gated
replicas.

MINE's predict-once/render-many split makes the MPI cache the unit of
serving economics: one encoder pass amortizes over every render of that
image, but ONLY on the replica holding the cached MPI. So the fleet's
routing key is the image digest (the first component of every mpi_key) and
the routing function is a consistent-hash ring — cache hits concentrate
per replica, and a membership change remaps only the dead replica's arc
instead of reshuffling every digest (which would cold-miss the whole
fleet's cache at once).

Pieces, all stdlib + injectable for deterministic tests:

  HashRing     consistent hashing with virtual nodes; `candidates(digest)`
               yields the orderd failover sequence (owner first, then the
               next distinct replicas clockwise).
  HealthGate   per-replica probe hysteresis: `down_after` consecutive
               failures eject, `up_after` consecutive successes readmit —
               one flaky probe cannot flap the ring.
  FleetApp     the routing logic: forward with bounded failover retries on
               connect-error/503 (a 503's Retry-After opens a per-replica
               cooldown the router honors before re-offering it traffic),
               deadline propagation (each attempt gets the REMAINING
               budget, expiry is an honest 504), request-path failure
               signals feeding the same hysteresis gate as the probe loop,
               `mine_fleet_*` metrics, an aggregated /healthz, and
               /admin/swap fan-out (a training job promotes weights into
               the whole fleet through one endpoint).
  FleetHTTPServer / main()  the stdlib HTTP surface + CLI, mirroring
               serving/server.py. `python -m mine_tpu.serving.fleet trace`
               is the offline collector front (obs/collect.py).

Observability: the router owns a span ring (obs/trace.py) — every
forwarded hop, failover retry, and swap fan-out is a span carrying the
request's trace context (X-Request-Id + X-Parent-Span, minted here when
the client sent none), served raw at GET /debug/trace and merged
fleet-wide (router + every replica's ring, skew-annotated, one lane per
process) at GET /debug/trace?request_id=. An SLO tracker (obs/slo.py)
evaluates availability + p95 objectives over the router's own request
families on every /metrics scrape (mine_slo_* gauges).

Numerics: routing and failover never touch pixels — a fleet answer is byte
-identical to the owning replica's answer (PARITY.md).
"""

from __future__ import annotations

import argparse
import bisect
import hashlib
import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs
from typing import Any, Callable

from mine_tpu.obs.ledger import set_build_info
from mine_tpu.obs.slo import SLOTracker, default_objectives
from mine_tpu.obs.trace import (
    PARENT_SPAN_HEADER,
    REQUEST_ID_HEADER,
    TRACE_TOKEN_RE,
    Tracer,
    new_span_id,
    resolve_parent_span,
    resolve_request_id,
)
from mine_tpu.utils.metrics import MetricsRegistry


class NoHealthyReplica(RuntimeError):
    """Every candidate was down/cooling/exhausted — maps to HTTP 503."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"no replica available; retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s


class FleetDeadlineExceeded(RuntimeError):
    """The request's deadline expired before any replica answered — 504."""


def _point(name: str) -> int:
    return int.from_bytes(
        hashlib.sha256(name.encode()).digest()[:8], "big"
    )


# THE vnode count. The router's ring and every replica's peer ring
# (server.py configure_peers) must agree on it, or the two sides order
# failover/peer-fetch candidates differently and a "fetch from the owner"
# silently asks a non-owner. One spelling, imported everywhere — the
# router, configure_peers' default, and both CLIs (serving/server.py,
# serving/autoscale.py, tools/bench_fleet.py).
DEFAULT_VNODES = 64


class HashRing:
    """Consistent-hash ring with virtual nodes (replicated hash points per
    member smooth the arc distribution, the classic Karger construction).
    Immutable once built — membership changes build a new ring, so readers
    never see a half-updated point list."""

    def __init__(self, members: list[str], vnodes: int = DEFAULT_VNODES):
        self.members = sorted(set(members))
        self.vnodes = vnodes
        points: list[tuple[int, str]] = []
        for m in self.members:
            for v in range(vnodes):
                points.append((_point(f"{m}#{v}"), m))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._owners = [p[1] for p in points]

    def candidates(self, digest: str) -> list[str]:
        """Every member, ordered by ring distance from the digest's point:
        the owner first, then the failover sequence. Deterministic for a
        given membership, so retries and cache affinity agree."""
        if not self.members:
            return []
        start = bisect.bisect_left(self._hashes, _point(digest))
        seen: list[str] = []
        n = len(self._owners)
        for i in range(n):
            owner = self._owners[(start + i) % n]
            if owner not in seen:
                seen.append(owner)
                if len(seen) == len(self.members):
                    break
        return seen


class HealthGate:
    """Hysteresis for one replica's membership: state flips DOWN only after
    `down_after` consecutive bad observations and back UP only after
    `up_after` consecutive good ones. Probe results and request-path
    connect errors feed the same gate."""

    def __init__(self, up_after: int = 2, down_after: int = 2,
                 healthy: bool = True):
        self.healthy = healthy
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self._good = 0
        self._bad = 0

    def observe(self, ok: bool) -> bool:
        """Feed one observation; returns True when the state FLIPPED."""
        if ok:
            self._good += 1
            self._bad = 0
            if not self.healthy and self._good >= self.up_after:
                self.healthy = True
                return True
        else:
            self._bad += 1
            self._good = 0
            if self.healthy and self._bad >= self.down_after:
                self.healthy = False
                return True
        return False


class Replica:
    def __init__(self, name: str, base_url: str, up_after: int,
                 down_after: int):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.gate = HealthGate(up_after=up_after, down_after=down_after)
        self.not_before = 0.0  # Retry-After cooldown (router clock)
        self.last_probe: dict | None = None
        # last X-Degraded level this replica announced (0 = full fidelity;
        # serving/degrade.py) — refreshed on every answered forward, fed
        # into the fleet-wide mine_fleet_degradation_level gauge
        self.degraded_level = 0


def _urllib_transport(
    method: str, url: str, body: bytes | None, headers: dict[str, str],
    timeout_s: float,
) -> tuple[int, dict[str, str], bytes]:
    """Default transport: (status, headers, body). HTTP error statuses are
    RETURNED (they are answers); transport-level failures raise — a
    TimeoutError when the attempt's time budget ran out (the REPLICA may be
    fine, the budget wasn't), a ConnectionError for everything that means
    the replica is unreachable (the failover + health-gate signal)."""
    import socket

    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as err:
        return err.code, dict(err.headers), err.read()
    except socket.timeout as err:  # raised mid-read (body stalled)
        raise TimeoutError(str(err)) from err
    except urllib.error.URLError as err:
        if isinstance(err.reason, (socket.timeout, TimeoutError)):
            raise TimeoutError(str(err.reason)) from err
        # unwrap to a transport failure the forward loop can failover on
        raise ConnectionError(str(err.reason)) from err
    except http.client.HTTPException as err:
        # a replica dying MID-RESPONSE (IncompleteRead after headers,
        # BadStatusLine on a half-written status) is a connect-class
        # failure for the router — it must fail over + feed the health
        # gate, not escape as a router 500. (RemoteDisconnected happens to
        # be a ConnectionResetError too, but its siblings are not OSError.)
        raise ConnectionError(f"{type(err).__name__}: {err}") from err


class FleetMetrics:
    """mine_fleet_* families on the shared registry."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None else MetricsRegistry()
        r = self.registry
        self.requests = r.counter(
            "mine_fleet_requests_total",
            "router responses by endpoint and status code",
        )
        self.request_latency = r.histogram(
            "mine_fleet_request_latency_seconds",
            "router-side request wall time by endpoint",
        )
        self.routed = r.counter(
            "mine_fleet_routed_total",
            "upstream dispatches by replica (first attempts + failovers)",
        )
        self.failovers = r.counter(
            "mine_fleet_failovers_total",
            "attempts abandoned for the next candidate, by reason "
            "(connect_error|unavailable_503|attempt_timeout)",
        )
        self.no_replica = r.counter(
            "mine_fleet_no_replica_total",
            "requests answered 503 because every candidate was "
            "down/cooling/exhausted",
        )
        self.replica_up = r.gauge(
            "mine_fleet_replica_up",
            "health-gated ring membership by replica (1 in, 0 out)",
        )
        self.ring_size = r.gauge(
            "mine_fleet_ring_size", "replicas currently in the ring",
        )
        self.ring_transitions = r.counter(
            "mine_fleet_ring_transitions_total",
            "hysteresis state flips by replica and direction (to=up|down)",
        )
        self.probes = r.counter(
            "mine_fleet_probes_total",
            "health probes by replica and outcome (ok|fail)",
        )
        self.ring_changes = r.counter(
            "mine_fleet_ring_changes_total",
            "explicit membership changes by op (join|leave) — autoscale/"
            "admin admissions and retirements, distinct from the health "
            "gate's hysteresis flips (ring_transitions)",
        )
        self.autoscale_decisions = r.counter(
            "mine_fleet_autoscale_decisions_total",
            "controller tick decisions by action "
            "(hold|scale_up|scale_down|cooldown|at_min|at_max)",
        )
        self.autoscale_events = r.counter(
            "mine_fleet_autoscale_events_total",
            "completed scale events by direction (join|drain) and outcome "
            "(ok|aborted|handoff_aborted)",
        )
        self.autoscale_target = r.gauge(
            "mine_fleet_autoscale_target_replicas",
            "the autoscale controller's current desired replica count",
        )
        self.degradation_level = r.gauge(
            "mine_fleet_degradation_level",
            "worst brownout-ladder level any ring replica last announced "
            "via X-Degraded (serving/degrade.py; 0 = full fidelity)",
        )

    def render(self) -> str:
        return self.registry.render()


class FleetApp:
    """Routing + health state for one fleet; transport and clock are
    injectable so the state machines are unit-testable without sockets."""

    def __init__(
        self,
        replicas: dict[str, str] | list[str],
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 2.0,
        up_after: int = 2,
        down_after: int = 2,
        max_attempts: int = 3,
        deadline_s: float = 30.0,
        retry_after_s: float = 1.0,
        vnodes: int = DEFAULT_VNODES,
        metrics: FleetMetrics | None = None,
        transport: Callable | None = None,
        clock: Callable[[], float] = time.monotonic,
        trace_enabled: bool = True,
        trace_buffer_spans: int = 4096,
        slo_objectives: Any = None,
    ):
        if isinstance(replicas, list):
            replicas = {f"r{i}": url for i, url in enumerate(replicas)}
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.metrics = metrics if metrics is not None else FleetMetrics()
        # router-side spans: every forwarded hop (and every failover
        # attempt) is a span carrying the request's trace context, so the
        # router's /debug/trace ring holds ITS half of every request tree
        self.tracer = Tracer(enabled=trace_enabled,
                             max_spans=trace_buffer_spans)
        # SLO layer (obs/slo.py): availability + p95 over the router's own
        # request families, evaluated on every /metrics scrape
        self.slo = SLOTracker(
            self.metrics.registry,
            slo_objectives if slo_objectives is not None
            else default_objectives(family_prefix="mine_fleet"),
            clock=clock,
        )
        set_build_info(self.metrics.registry, backend=None)
        self.up_after = up_after
        self.down_after = down_after
        self.replicas = {
            name: Replica(name, url, up_after, down_after)
            for name, url in replicas.items()
        }
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.max_attempts = max(1, int(max_attempts))
        self.deadline_s = float(deadline_s)
        self.retry_after_s = float(retry_after_s)
        self.vnodes = vnodes
        self.transport = transport if transport is not None else _urllib_transport
        self.clock = clock
        self._lock = threading.Lock()
        self._ring = HashRing(list(self.replicas), vnodes=vnodes)  # guarded-by: _lock
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._started_at = time.time()
        for name in self.replicas:
            self.metrics.replica_up.set(1, replica=name)
        self.metrics.ring_size.set(len(self.replicas))

    # -- ring membership -------------------------------------------------------

    def ring_members(self) -> list[str]:
        with self._lock:
            return list(self._ring.members)

    def add_replica(self, name: str, base_url: str) -> Replica:
        """Admit a NEW replica into the live membership (an autoscale
        join). The caller is responsible for having the replica
        request-ready first — pre-warmed cache, warm pools — because the
        moment this returns, its arc's traffic routes to it. Membership
        mutates by whole-dict replacement so concurrent iterators
        (probe_once, swap_all, health) only ever see a complete
        membership, never a half-built one."""
        with self._lock:
            if name in self.replicas:
                raise ValueError(f"replica {name!r} is already in the fleet")
            replica = Replica(name, base_url, self.up_after, self.down_after)
            self.replicas = {**self.replicas, name: replica}
            self._rebuild_ring_locked()
            self.metrics.ring_changes.inc(op="join")
        return replica

    def remove_replica(self, name: str) -> None:
        """Retire a replica from the live membership (an autoscale drain's
        last step). Its arc remaps to the ring neighbors — ONE arc, the
        consistent-hash contract. Refuses to empty the fleet: a routerful
        of nothing answers 503 forever with no path back."""
        with self._lock:
            if name not in self.replicas:
                raise ValueError(f"replica {name!r} is not in the fleet")
            remaining = {k: v for k, v in self.replicas.items() if k != name}
            if not remaining:
                raise ValueError(
                    "refusing to remove the last replica — an empty fleet "
                    "cannot recover"
                )
            self.replicas = remaining
            self._rebuild_ring_locked()
            self.metrics.replica_up.set(0, replica=name)
            self.metrics.ring_changes.inc(op="leave")

    def _rebuild_ring_locked(self) -> None:
        """Rebuild the ring from the healthy members. Caller holds _lock."""
        members = [r.name for r in self.replicas.values() if r.gate.healthy]
        self._ring = HashRing(members, vnodes=self.vnodes)
        for r in self.replicas.values():
            self.metrics.replica_up.set(
                1 if r.gate.healthy else 0, replica=r.name
            )
        self.metrics.ring_size.set(len(members))

    def _observe(self, replica: Replica, ok: bool) -> None:
        """Feed one health observation (probe or request-path); rebuild the
        ring on a hysteresis flip."""
        with self._lock:
            flipped = replica.gate.observe(ok)
            if flipped:
                self._rebuild_ring_locked()
                self.metrics.ring_transitions.inc(
                    replica=replica.name,
                    to="up" if replica.gate.healthy else "down",
                )

    def _republish_degradation(self) -> None:
        """Fleet-wide brownout visibility: the worst ladder level any
        replica last announced — via X-Degraded on a forwarded response
        or its /healthz degradation snapshot — is the autoscaler's
        scale-up signal."""
        with self._lock:
            self.metrics.degradation_level.set(max(
                (r.degraded_level for r in self.replicas.values()),
                default=0,
            ))

    def probe_once(self) -> dict[str, bool]:
        """One /healthz sweep over every replica (in or out of the ring —
        ejected replicas must keep being probed to ever rejoin)."""
        results: dict[str, bool] = {}
        for replica in list(self.replicas.values()):
            try:
                status, _, body = self.transport(
                    "GET", replica.base_url + "/healthz", None, {},
                    self.probe_timeout_s,
                )
                ok = status == 200
                replica.last_probe = {"status": status}
                try:
                    replica.last_probe.update(json.loads(body))
                except ValueError:
                    pass
                else:
                    # an idle replica announces recovery through its
                    # /healthz degradation snapshot — without this, the
                    # level last seen on a forwarded response would stay
                    # stale (and hold the fleet gauge up) until the next
                    # product request happened to land there
                    deg = replica.last_probe.get("degradation")
                    if isinstance(deg, dict):
                        replica.degraded_level = int(deg.get("level") or 0)
                        self._republish_degradation()
            except Exception as exc:  # noqa: BLE001 - a probe may die anyhow
                ok = False
                replica.last_probe = {"error": f"{type(exc).__name__}: {exc}"}
            self.metrics.probes.inc(replica=replica.name,
                                    outcome="ok" if ok else "fail")
            self._observe(replica, ok)
            results[replica.name] = ok
        return results

    def start(self) -> "FleetApp":
        if self._probe_thread is None:
            def loop():
                while not self._probe_stop.wait(self.probe_interval_s):
                    self.probe_once()

            self._probe_thread = threading.Thread(
                target=loop, name="mine-fleet-probe", daemon=True
            )
            self._probe_thread.start()
        return self

    def close(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)

    # -- forwarding ------------------------------------------------------------

    def candidates_for(self, digest: str) -> list[Replica]:
        with self._lock:
            names = self._ring.candidates(digest)
            replicas = self.replicas
        # membership may have changed between a racing reader's ring
        # snapshot and here; a just-removed name is simply not a candidate
        return [replicas[n] for n in names if n in replicas]

    def forward(
        self,
        digest: str,
        method: str,
        path: str,
        body: bytes | None,
        headers: dict[str, str],
        timeout_s: float | None = None,
        request_id: str | None = None,
        parent_span: str | None = None,
    ) -> tuple[int, dict[str, str], bytes, str]:
        """Route one request by digest with bounded failover.

        Walks the ring's candidate order (owner first), skipping replicas
        inside a Retry-After cooldown. Each attempt gets the REMAINING
        deadline budget. Failover happens on transport errors and 503s
        (the replica is shedding — its Retry-After opens the cooldown);
        every other status, including 404/504/500, is the replica's honest
        ANSWER and passes through (re-dispatching a 404 elsewhere cannot
        find an MPI that only the owner would have had).

        Trace context: every attempt (first dispatch AND each failover
        retry) records a router span with a fresh span_id and sends the
        replica `X-Request-Id: request_id` + `X-Parent-Span: <span_id>`,
        so the replica's spans hang off exactly the attempt that reached
        it and a failed attempt is visible as a childless span.

        Returns (status, headers, body, replica_name). Raises
        NoHealthyReplica (-> 503) or FleetDeadlineExceeded (-> 504).
        """
        deadline = self.clock() + (
            timeout_s if timeout_s and timeout_s > 0 else self.deadline_s
        )
        candidates = self.candidates_for(digest)
        if not candidates:
            self.metrics.no_replica.inc()
            raise NoHealthyReplica(self.retry_after_s)
        min_cooldown = None
        attempts = 0
        for replica in candidates:
            if attempts >= self.max_attempts:
                break
            now = self.clock()
            if replica.not_before > now:
                min_cooldown = (replica.not_before - now
                                if min_cooldown is None
                                else min(min_cooldown,
                                         replica.not_before - now))
                continue
            remaining = deadline - now
            if remaining <= 0:
                raise FleetDeadlineExceeded(
                    f"deadline expired after {attempts} attempt(s)"
                )
            attempts += 1
            self.metrics.routed.inc(replica=replica.name)
            span_id = new_span_id()
            send_headers = dict(headers)
            if request_id:
                send_headers[REQUEST_ID_HEADER] = request_id
                send_headers[PARENT_SPAN_HEADER] = span_id
            span = self.tracer.span(
                "forward", cat="fleet", request_id=request_id,
                replica=replica.name, path=path, attempt=attempts,
                span_id=span_id, parent_span=parent_span,
            )
            try:
                with span:
                    status, resp_headers, resp_body = self.transport(
                        method, replica.base_url + path, body, send_headers,
                        remaining,
                    )
                    if hasattr(span, "args"):  # live span: the answer
                        span.args["status"] = status
            except TimeoutError:
                # the ATTEMPT's budget ran out, not necessarily the
                # replica: a busy-but-healthy replica under an impatient
                # client deadline must NOT be ejected (losing its arc
                # cold-misses its whole MPI cache) — the probe loop, with
                # its own timeout, is the judge of replica health. Fail
                # over with whatever budget remains. (TimeoutError is an
                # OSError subclass — this clause must come first.)
                self.metrics.failovers.inc(reason="attempt_timeout")
                continue
            except (ConnectionError, OSError):
                # transport failure: feed the hysteresis gate (2 of these
                # eject the replica without waiting for the probe loop) and
                # fail over
                self._observe(replica, False)
                self.metrics.failovers.inc(reason="connect_error")
                continue
            if status == 503:
                # the replica is shedding (queue full / breaker open /
                # draining): honor its Retry-After as a cooldown so the
                # ring does not hammer a replica that asked for air.
                # Deliberately NEUTRAL for the health gate — neither a
                # connect failure nor a success that could mask the probe
                # loop's degraded verdict (the probe reads /healthz 503
                # as down; a render 503 must not keep resetting that).
                retry_after = _parse_retry_after(resp_headers)
                replica.not_before = self.clock() + retry_after
                min_cooldown = (retry_after if min_cooldown is None
                                else min(min_cooldown, retry_after))
                self.metrics.failovers.inc(reason="unavailable_503")
                continue
            # any other answered request is evidence of life: reset the
            # gate's failure streak so two SPORADIC connect errors with
            # hundreds of successes in between cannot eject the replica
            # (the hysteresis contract is about consecutive signal)
            self._observe(replica, True)
            # fleet-wide brownout visibility: every answered forward
            # refreshes the replica's announced ladder level (absence of
            # X-Degraded IS the L0 announcement) and republishes the worst
            # level across the fleet — the autoscaler's scale-up signal
            replica.degraded_level = _parse_degraded_level(resp_headers)
            self._republish_degradation()
            return status, resp_headers, resp_body, replica.name
        if self.clock() >= deadline:
            raise FleetDeadlineExceeded(
                f"deadline expired after {attempts} attempt(s)"
            )
        self.metrics.no_replica.inc()
        raise NoHealthyReplica(
            min_cooldown if min_cooldown is not None else self.retry_after_s
        )

    # -- fleet-wide operations -------------------------------------------------

    def health(self) -> dict:
        members = self.ring_members()
        return {
            "status": "ok" if members else "degraded",
            "uptime_s": round(time.time() - self._started_at, 1),
            "ring_size": len(members),
            "replicas": {
                r.name: {
                    "base_url": r.base_url,
                    "in_ring": r.gate.healthy,
                    "last_probe": r.last_probe,
                }
                for r in self.replicas.values()
            },
        }

    def aggregated_trace(self, request_id: str,
                         timeout_s: float | None = None) -> dict:
        """GET /debug/trace?request_id= across the WHOLE fleet: the
        router's own spans for this request plus every replica's
        /debug/trace?request_id= ring, merged into one skew-annotated
        Chrome-trace doc with per-process lanes and the cross-process hop
        tree in metadata (obs/collect.py). Unreachable replicas are named
        in metadata, never silently missing."""
        from mine_tpu.obs import collect

        timeout = timeout_s if timeout_s else self.probe_timeout_s

        def fetch(url: str, t: float) -> dict:
            # ride the app's transport so tests inject fakes and the
            # error taxonomy matches every other router-replica call
            status, _, body = self.transport("GET", url, None, {}, t)
            if status != 200:
                raise RuntimeError(f"/debug/trace answered {status}")
            return json.loads(body)

        return collect.collect_fleet_trace(
            {r.name: r.base_url for r in self.replicas.values()},
            request_id=request_id,
            # the router's OWN lane is filtered to the request too —
            # replicas answer pre-filtered, and a busy router's ring
            # holds every other request's spans, which must not leak
            # into this request's merged doc
            local={"name": "router", "doc": collect.filter_doc_to_request(
                self.tracer.to_chrome_trace(), request_id
            )},
            timeout_s=timeout,
            fetch_fn=fetch,
        )

    def swap_all(self, wait: bool = True,
                 timeout_s: float = 600.0,
                 request_id: str | None = None,
                 parent_span: str | None = None) -> dict[str, dict]:
        """Fan POST /admin/swap out to EVERY configured replica
        (sequentially: a rolling upgrade — at most one replica is warming a
        generation at a time, the rest serve). Deliberately not limited to
        ring members: a replica the health gate has temporarily ejected
        (shedding under load) would otherwise rejoin serving STALE weights
        with nothing to reconcile it — an unreachable replica simply
        reports its transport error. Returns per-replica outcomes, each
        tagged `in_ring`; a replica "succeeded" only when its swap status
        says so (state ok/noop), never on a bare 202 (a refused concurrent
        swap also answers in_progress)."""
        payload = json.dumps({"wait": wait}).encode()
        results: dict[str, dict] = {}
        in_ring = set(self.ring_members())
        for name, replica in self.replicas.items():
            span_id = new_span_id()
            headers = {"Content-Type": "application/json"}
            if request_id:
                # the fan-out carries the trace context too: a rolling
                # fleet upgrade is one request whose hops are the replicas
                headers[REQUEST_ID_HEADER] = request_id
                headers[PARENT_SPAN_HEADER] = span_id
            span = self.tracer.span(
                "swap_fanout", cat="fleet", request_id=request_id,
                replica=name, span_id=span_id, parent_span=parent_span,
            )
            try:
                with span:
                    status, _, body = self.transport(
                        "POST", replica.base_url + "/admin/swap", payload,
                        headers, timeout_s,
                    )
                try:
                    results[name] = {"status": status, **json.loads(body)}
                except ValueError:
                    results[name] = {"status": status}
            except Exception as exc:  # noqa: BLE001 - per-replica verdicts
                results[name] = {"error": f"{type(exc).__name__}: {exc}"}
            results[name]["in_ring"] = name in in_ring
        return results


def _parse_retry_after(headers: dict[str, str]) -> float:
    for key, value in headers.items():
        if key.lower() == "retry-after":
            try:
                return max(0.1, float(value))
            except ValueError:
                break
    return 1.0


def _parse_degraded_level(headers: dict[str, str]) -> int:
    """The ladder level out of an `X-Degraded: level=<n>;tier=<t>` header
    (serving/degrade.py announcement); 0 when absent or malformed — a
    replica that says nothing is serving at full fidelity."""
    for key, value in headers.items():
        if key.lower() == "x-degraded":
            for part in value.split(";"):
                name, _, val = part.strip().partition("=")
                if name == "level":
                    try:
                        return max(0, int(val))
                    except ValueError:
                        return 0
    return 0


def digest_of_request(path: str, body: bytes,
                      content_type: str) -> tuple[str, float | None]:
    """(routing digest, body-declared timeout_s) for one fleet request.

    /predict: sha256 of the IMAGE BYTES — the same digest the replica
    computes for its cache key, so the ring sends repeats of one image to
    one replica. /render: the digest component of the mpi_key (minted by a
    /predict this router routed, so it lands on the replica holding the
    MPI). /mpi/<key>: the key's digest — the compressed-container fetch
    (serving/compress.py wire) routes to the owner exactly like the
    renders that hit its cache."""
    if path == "/predict":
        if content_type == "application/json":
            req = json.loads(body)
            import base64

            image_bytes = base64.b64decode(req["image_b64"])
            return (hashlib.sha256(image_bytes).hexdigest(),
                    _float_or_none(req.get("timeout_s")))
        return hashlib.sha256(body).hexdigest(), None
    if path == "/render":
        req = json.loads(body)
        digest = str(req["mpi_key"]).split(":", 1)[0]
        return digest, _float_or_none(req.get("timeout_s"))
    if path.startswith("/mpi/") and len(path) > len("/mpi/"):
        return path[len("/mpi/"):].split(":", 1)[0], None
    raise ValueError(f"unroutable path {path}")


def _float_or_none(v: Any) -> float | None:
    try:
        return float(v) if v is not None else None
    except (TypeError, ValueError):
        return None


class _FleetHandler(BaseHTTPRequestHandler):
    server: "FleetHTTPServer"
    protocol_version = "HTTP/1.1"

    _FORWARD_HEADERS = ("Content-Type",)

    def log_message(self, fmt: str, *args: Any) -> None:  # noqa: A003
        if self.server.verbose:
            super().log_message(fmt, *args)

    def _send(self, code: int, payload: bytes, content_type: str,
              extra: dict[str, str] | None = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        rid = getattr(self, "request_id", None)
        if rid and not (extra and REQUEST_ID_HEADER in extra):
            # every router response names its request — the id keys the
            # aggregated /debug/trace?request_id= lookup
            self.send_header(REQUEST_ID_HEADER, rid)
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def _send_json(self, code: int, obj: dict,
                   extra: dict[str, str] | None = None) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json", extra)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _route(self, method: str, path: str) -> tuple[int, str]:
        app = self.server.app
        if method == "GET" and path == "/healthz":
            health = app.health()
            code = 200 if health["status"] == "ok" else 503
            self._send_json(code, health)
            return code, "healthz"
        if method == "GET" and path == "/metrics":
            # SLO gauges refresh on scrape cadence, like everything else
            # on the page (obs/slo.py)
            app.slo.evaluate()
            self._send(200, app.metrics.render().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return 200, "metrics"
        if method == "GET" and path == "/debug/trace":
            query = parse_qs(self.path.partition("?")[2])
            rid = (query.get("request_id") or [None])[0]
            if rid and not TRACE_TOKEN_RE.match(rid):
                # the query-param path gets the SAME charset guard as
                # the header path: a malformed id interpolated into K
                # replica fetch URLs would fail every fetch and read as
                # a fleet-wide outage instead of the client error it is
                self._send_json(400, {
                    "error": f"malformed request_id {rid[:64]!r}",
                })
                return 400, "debug_trace"
            if rid:
                # fleet-wide: router spans + every replica's ring for
                # this request, merged with per-process lanes
                self._send_json(200, app.aggregated_trace(rid))
            else:
                self._send_json(200, app.tracer.to_chrome_trace())
            return 200, "debug_trace"
        if method == "POST" and path == "/admin/swap":
            body = self._read_body()
            wait = True
            try:
                if body:
                    wait = bool(json.loads(body).get("wait", True))
            except ValueError:
                pass
            results = app.swap_all(
                wait=wait, request_id=self.request_id,
                parent_span=self._span_id,
            )
            # with wait (the default), success means the swap RESOLVED on
            # every in-ring replica — a 202/in_progress is not a flip.
            # Out-of-ring replicas are best-effort (reported, not gating):
            # an unreachable one cannot fail a fleet upgrade it never saw.
            done_states = ("ok", "noop") if wait else ("ok", "noop",
                                                       "in_progress")
            ok = all(
                r.get("state") in done_states
                for r in results.values() if r.get("in_ring")
            )
            self._send_json(200 if ok else 422, {"replicas": results})
            return 200 if ok else 422, "admin_swap"
        if method == "POST" and path in ("/predict", "/render"):
            return self._forward(app, path), path.lstrip("/")
        if method == "GET" and path.startswith("/mpi/"):
            # compressed-MPI fetch routes to the key's owner like a render
            return self._forward(app, path, method="GET"), "mpi"
        self._send_json(404, {"error": f"no route {method} {path}"})
        return 404, "unknown"

    def _forward(self, app: FleetApp, path: str, method: str = "POST") -> int:
        body = self._read_body() if method == "POST" else None
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        try:
            digest, timeout_s = digest_of_request(path, body or b"", ctype)
        except (ValueError, KeyError, TypeError) as exc:
            self._send_json(400, {"error": f"unroutable request: {exc}"})
            return 400
        headers = {
            k: self.headers[k] for k in self._FORWARD_HEADERS
            if self.headers.get(k)
        }
        try:
            status, resp_headers, resp_body, replica = app.forward(
                digest, method, path, body, headers, timeout_s=timeout_s,
                request_id=self.request_id, parent_span=self._span_id,
            )
        except NoHealthyReplica as exc:
            retry_after = max(exc.retry_after_s, 0.1)
            self._send_json(
                503, {"error": str(exc), "retry_after_s": retry_after},
                {"Retry-After": f"{retry_after:.1f}"},
            )
            return 503
        except FleetDeadlineExceeded as exc:
            self._send_json(504, {"error": str(exc)})
            return 504
        extra = {"X-Mine-Replica": replica}
        for k, v in resp_headers.items():
            # X-Degraded passes through untouched: a client of the ROUTER
            # still learns its answer was served degraded (and at what
            # level/tier) exactly as a direct-replica client would
            if k.lower() in ("retry-after", "x-request-id", "x-degraded"):
                extra[k] = v
        self._send(status, resp_body,
                   resp_headers.get("Content-Type", "application/json"),
                   extra)
        return status

    def _handle(self, method: str) -> None:
        app = self.server.app
        path = self.path.split("?", 1)[0]
        # trace context off the headers — the ONE resolve implementation
        # shared with the replica server (obs/trace.py)
        self.request_id = resolve_request_id(
            self.headers.get(REQUEST_ID_HEADER)
        )
        # the router-side root of this request's span tree: forward /
        # swap_fanout spans point at it via parent_span, and an upstream
        # caller's X-Parent-Span (if any) becomes ITS parent
        self._span_id = new_span_id()
        client_parent = resolve_parent_span(
            self.headers.get(PARENT_SPAN_HEADER)
        )
        t0 = time.monotonic()
        p0 = time.perf_counter()
        try:
            code, endpoint = self._route(method, path)
        except (BrokenPipeError, ConnectionResetError):
            raise
        except Exception as exc:  # noqa: BLE001 - HTTP boundary
            code, endpoint = 500, path.lstrip("/") or "unknown"
            try:
                self._send_json(500, {"error": f"{type(exc).__name__}: {exc}"})
            except Exception:  # noqa: BLE001 - client already gone
                pass
        if endpoint not in ("metrics", "healthz", "debug_trace"):
            # scrape/introspection traffic stays out of the ring — the
            # trace exists for routed product requests
            app.tracer.record(
                "request", "fleet", p0, time.perf_counter(),
                request_id=self.request_id, endpoint=endpoint,
                status=code, span_id=self._span_id,
                parent_span=client_parent,
            )
        app.metrics.requests.inc(endpoint=endpoint, status=str(code))
        app.metrics.request_latency.observe(
            time.monotonic() - t0, endpoint=endpoint
        )

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self._handle("GET")

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        self._handle("POST")


class FleetHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr: tuple[str, int], app: FleetApp,
                 verbose: bool = False):
        super().__init__(addr, _FleetHandler)
        self.app = app
        self.verbose = verbose


def make_fleet_server(
    app: FleetApp, host: str = "127.0.0.1", port: int = 0,
    verbose: bool = False,
) -> FleetHTTPServer:
    return FleetHTTPServer((host, port), app, verbose=verbose)


def _parse_members(specs: list[str]) -> dict[str, str]:
    """--replica values (URL or NAME=URL) -> {name: url}."""
    members: dict[str, str] = {}
    for i, spec in enumerate(specs):
        name, sep, url = spec.partition("=")
        if sep and not name.startswith("http"):
            members[name] = url
        else:
            members[f"r{i}"] = spec
    return members


def trace_main(argv: list[str]) -> None:
    """`python -m mine_tpu.serving.fleet trace`: pull /debug/trace from
    every member (replicas and/or the router), estimate per-member clock
    skew from the probe round trips, and write ONE merged Chrome-trace
    JSON with per-process lanes — openable in Perfetto or summarized by
    tools/profile_summary.py. With --request-id, the doc is filtered to
    that request and carries its cross-process hop tree in metadata."""
    from mine_tpu.obs import collect

    parser = argparse.ArgumentParser(
        prog="fleet trace", description=trace_main.__doc__
    )
    parser.add_argument(
        "--replica", action="append", default=[], metavar="[NAME=]URL",
        help="member to pull /debug/trace from (repeatable); include the "
        "router's URL to get its lane too",
    )
    parser.add_argument("--request-id", default=None,
                        help="filter to one request + build its hop tree")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--out", default=None,
                        help="write the merged trace here (default: stdout)")
    args = parser.parse_args(argv)
    if not args.replica:
        parser.error("at least one --replica URL is required")
    if args.request_id and not TRACE_TOKEN_RE.match(args.request_id):
        parser.error(f"malformed --request-id {args.request_id[:64]!r} "
                     "(allowed: [A-Za-z0-9._-], max 128 chars)")
    doc = collect.collect_fleet_trace(
        _parse_members(args.replica), request_id=args.request_id,
        timeout_s=args.timeout,
    )
    meta = doc["metadata"]
    summary = {
        "members": {
            name: ({"error": m["error"]} if "error" in m else {
                "skew_s": (round(m["skew_s"], 6)
                           if m.get("skew_s") is not None else None),
                "rtt_s": round(m.get("rtt_s") or 0.0, 6),
            })
            for name, m in meta["members"].items()
        },
        "events": sum(1 for ev in doc["traceEvents"]
                      if ev.get("ph") == "X"),
    }
    if args.request_id:
        tree = meta.get("request_tree", {})
        summary["request_id"] = args.request_id
        summary["span_count"] = tree.get("span_count", 0)
        summary["processes"] = tree.get("processes", [])
        summary["tree_depth"] = collect.tree_depth(tree.get("tree", []))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(doc, fh)
        summary["out"] = args.out
        print(json.dumps(summary))
    else:
        print(json.dumps(doc))
        print(json.dumps(summary), file=__import__("sys").stderr)


def main(argv: list[str] | None = None) -> None:
    if argv is None:
        import sys

        argv = sys.argv[1:]
    if argv and argv[0] == "trace":
        return trace_main(argv[1:])
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--replica", action="append", default=[], metavar="URL",
        required=False,
        help="replica base URL (repeatable), e.g. http://10.0.0.5:8000",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8800)
    parser.add_argument("--probe-interval", type=float, default=2.0)
    parser.add_argument("--max-attempts", type=int, default=3)
    parser.add_argument("--deadline", type=float, default=30.0)
    parser.add_argument("--vnodes", type=int, default=DEFAULT_VNODES,
                        help="virtual nodes per ring member; every "
                        "replica's configure_peers MUST use the same "
                        "value (DEFAULT_VNODES)")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)
    if not args.replica:
        parser.error("at least one --replica URL is required")
    app = FleetApp(
        list(args.replica), probe_interval_s=args.probe_interval,
        max_attempts=args.max_attempts, deadline_s=args.deadline,
        vnodes=args.vnodes,
    ).start()
    server = make_fleet_server(app, args.host, args.port,
                               verbose=args.verbose)
    host, port = server.server_address[:2]
    print(f"fleet router over {len(args.replica)} replicas on "
          f"http://{host}:{port} (/predict /render /healthz /metrics "
          f"/admin/swap /debug/trace)")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        app.close()


if __name__ == "__main__":
    main()
