"""HTTP serving surface: /predict, /render, /healthz, /metrics.

Stdlib only (http.server.ThreadingHTTPServer — this image has no web
framework and the hard constraint is no new dependencies). Handler threads
do the cheap work (decode, digest, cache lookup) and block on futures for
the expensive work, which single-file through the engine/batcher; the
threading model mirrors the reference's asymmetry: many waiters, one
device.

Endpoints:
  POST /predict   image bytes (PNG/JPEG, raw body or JSON {"image_b64"})
                  -> {"mpi_key", "cached", "bucket", "planes", "mpi_bytes"}.
                  Runs the encoder-decoder ONCE per distinct
                  (image bytes, checkpoint step, plane count); repeats are
                  cache hits that never touch the network.
  POST /render    JSON {"mpi_key", "poses" (N,4,4) | "offsets" (N,3)}
                  -> {"frames_png_b64": [...], ...}. 404 when the MPI fell
                  out of the cache (client re-predicts). Concurrent renders
                  of one MPI coalesce into one dispatch (batcher.py).
  GET  /mpi/<key> the cached MPI as its compressed wire container
                  (serving/compress.py to_wire) — the fleet peer-fetch
                  surface: on a local cache miss a peer replica adopts this
                  instead of re-running the encoder. 404 when not resident.
  GET  /healthz   liveness + engine/bucket/cache snapshot (including the
                  serving weight generation + swap state).
  GET  /metrics   Prometheus text exposition (serving/metrics.py names).
  POST /admin/swap  hot checkpoint swap (serving/engine.py swap_weights):
                  reload the workspace's newest checkpoint into a NEW
                  weight generation, validate/verify it against the
                  serving tree, atomically flip. 202 async (default),
                  {"wait": true} blocks; a rejected/corrupt swap answers
                  422 with the named error and the OLD generation keeps
                  serving — never a 5xx. GET returns the last status.
                  --watch-last-good N polls the training job's last_good
                  pointer and promotes newer vetted checkpoints
                  automatically.
  GET  /debug/trace  the request-lifecycle host spans (parse, queue-wait,
                  coalesce, dispatch, encode — obs/trace.py) as
                  Chrome-trace JSON: drop it into chrome://tracing, or
                  point tools/profile_summary.py at a saved copy.

Admission control (resilience PR): the render queue is bounded — beyond
`resilience.serve_max_queue_requests` pending requests the server sheds
with 503 + Retry-After instead of accepting work it cannot finish; every
render carries a deadline (body `timeout_s`, default
`resilience.serve_deadline_s`, both clamped to request_timeout_s) that the
batcher enforces BEFORE dispatch (504, and the client's wait timing out
evicts the pending entry); and a circuit breaker around the engine trips
after `resilience.breaker_failure_threshold` consecutive dispatch
failures, shedding immediately (503) and reporting /healthz as degraded
(HTTP 503) until a half-open trial succeeds. Overload is always an honest
503/504 — never a hang, never a 500.

Brownout (serving/degrade.py, `serving.degrade_enabled`): BEFORE any of
those sheds fire, a per-replica degradation ladder trades fidelity for
availability — int8 + pruned predicts (L1), stale-while-revalidate over
older-generation cache entries with the peer-fetch hop skipped (L2), a
widened coalescing window (L3) — and only past L3 does the existing 503
shed engage. Every degraded product answer announces itself with an
`X-Degraded: level=<n>;tier=<t>` header and ticks
mine_serve_degradation_responses_total{level=}; degraded 200s are
SLO-visible but never 5xx.

CLI: python -m mine_tpu.serving.server --workspace <train workspace>
restores params only (training/checkpoint.py load_for_serving), pre-warms
the default bucket's executables, and serves until killed.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import io
import itertools
import json
import os
import threading
import time
from urllib.parse import parse_qs
from concurrent.futures import Future, TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import numpy as np

from mine_tpu.config import Config
from mine_tpu.obs.ledger import set_build_info
from mine_tpu.obs.memlog import MemLog
from mine_tpu.obs.slo import tracker_from_config
from mine_tpu.obs.trace import (
    PARENT_SPAN_HEADER,
    REQUEST_ID_HEADER,
    Tracer,
    new_span_id,
    resolve_parent_span,
    resolve_request_id,
)
from mine_tpu.resilience import BreakerOpen, CircuitBreaker, chaos
from mine_tpu.serving.batcher import (
    BatcherStopped,
    DeadlineExceeded,
    MicroBatcher,
    QueueFull,
)
from mine_tpu.serving.cache import MPICache, key_from_str, key_to_str, mpi_key
from mine_tpu.serving.compress import CompressedMPI, from_wire, to_wire
from mine_tpu.serving.degrade import PressureSample, controller_from_config
from mine_tpu.serving.fleet import DEFAULT_VNODES
from mine_tpu.serving.engine import (
    BucketSpec,
    RenderEngine,
    SwapError,
    SwapInProgress,
    SwapRejected,
)
from mine_tpu.serving.metrics import ServingMetrics


class RequestTimeout(RuntimeError):
    """The handler thread's wait on its future timed out; the pending
    request (if still queued) was evicted. Maps to HTTP 504."""


# distinct default breaker-jitter seeds for apps built in one process (a
# bench/drill fleet): replicas that tripped together must not re-probe in
# lockstep (resilience/breaker.py reset_jitter)
_APP_SEQ = itertools.count(1)


def _decode_image(data: bytes) -> np.ndarray:
    from PIL import Image

    with Image.open(io.BytesIO(data)) as im:
        return np.asarray(im.convert("RGB"))


def _encode_png(frame_u8: np.ndarray) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(frame_u8).save(buf, format="PNG")
    return buf.getvalue()


def _poses_from_body(body: dict) -> np.ndarray:
    """(N, 4, 4) pose stack from a /render body: full poses, or camera-center
    offsets the single-image app's trajectory module turns into identity-
    rotation poses (inference/trajectory.py poses_from_offsets)."""
    if "poses" in body:
        poses = np.asarray(body["poses"], np.float32)
        if poses.ndim == 2 and poses.shape[1] == 16:
            poses = poses.reshape(-1, 4, 4)
        if poses.ndim != 3 or poses.shape[1:] != (4, 4):
            raise ValueError(
                f"poses must be (N, 4, 4) (or N x 16 flat), got {poses.shape}"
            )
        return poses
    if "offsets" in body:
        from mine_tpu.inference.trajectory import poses_from_offsets

        offsets = np.asarray(body["offsets"], np.float64)
        if offsets.ndim != 2 or offsets.shape[1] != 3:
            raise ValueError(f"offsets must be (N, 3), got {offsets.shape}")
        return poses_from_offsets(offsets)
    raise ValueError('render body needs "poses" or "offsets"')


class ServingApp:
    """Engine + cache + batcher + metrics assembled for one checkpoint."""

    def __init__(
        self,
        cfg: Config,
        params: Any = None,
        batch_stats: Any = None,
        checkpoint_step: int = 0,
        cache_bytes: int = 2 << 30,
        max_delay_ms: float = 4.0,
        max_batch_poses: int = 64,
        fov_deg: float = 90.0,
        request_timeout_s: float = 300.0,
        metrics: ServingMetrics | None = None,
        allowed_buckets: list[BucketSpec] | None = None,
        trace_enabled: bool = True,
        trace_buffer_spans: int = 4096,
        peak_flops_override: float = 0.0,
        max_queue_requests: int | None = None,
        deadline_s: float | None = None,
        retry_after_s: float | None = None,
        breaker_failure_threshold: int | None = None,
        breaker_reset_s: float | None = None,
        engine: RenderEngine | None = None,
        swap_source: Any = None,
        peers: dict[str, str] | None = None,
        peer_name: str | None = None,
        peer_fetch_timeout_s: float | None = None,
        breaker_jitter_seed: int | None = None,
    ):
        res = cfg.resilience  # ctor args override the resilience.* knobs

        def knob(override, default):
            return default if override is None else override

        self.metrics = metrics if metrics is not None else ServingMetrics()
        # circuit breaker around the engine: consecutive dispatch failures
        # open it; while open, requests shed immediately (503) instead of
        # riding into a dead backend; half-opens on a timer for one trial
        self.breaker = CircuitBreaker(
            failure_threshold=knob(
                breaker_failure_threshold, res.breaker_failure_threshold
            ),
            reset_after_s=knob(breaker_reset_s, res.breaker_reset_s),
            # de-synchronized half-open probes: fleet replicas that tripped
            # on one shared backend fault re-probe at distinct instants
            reset_jitter=res.breaker_reset_jitter,
            jitter_seed=(next(_APP_SEQ) if breaker_jitter_seed is None
                         else breaker_jitter_seed),
            on_state=self.metrics.breaker_state.set,
            on_trip=self.metrics.breaker_trips.inc,
        )
        self.deadline_s = knob(deadline_s, res.serve_deadline_s)
        self.retry_after_s = knob(retry_after_s, res.serve_retry_after_s)
        # request-lifecycle spans default ON (unlike training): a span is
        # nanoseconds against a millisecond render, and /debug/trace on a
        # misbehaving server is worth far more than the ring's few MB.
        # Every recorded span also ticks the trace-counter family.
        self.tracer = Tracer(
            enabled=trace_enabled, max_spans=trace_buffer_spans,
            on_span=lambda span: self.metrics.trace_spans.inc(cat=span.cat),
        )
        # live HBM gauges (obs/memlog.py): sampled after each engine
        # dispatch and on every /metrics scrape
        self.memlog = MemLog(
            tracer=self.tracer,
            live_gauge=self.metrics.hbm_live_bytes,
            peak_gauge=self.metrics.hbm_peak_bytes,
        )
        if engine is not None:
            # a prebuilt engine (the fake one from serving/fake.py, or a
            # caller-tuned real one) adopts this app's metrics + tracer so
            # its dispatches land in the same registry and span ring
            engine.metrics = self.metrics
            engine.tracer = self.tracer
            self.engine = engine
        else:
            self.engine = RenderEngine(
                cfg, params, batch_stats, checkpoint_step=checkpoint_step,
                metrics=self.metrics, fov_deg=fov_deg,
                peak_flops_override=peak_flops_override,
                tracer=self.tracer,
            )
        self.metrics.weight_generation.set(self.engine.generation)
        # SLO layer (obs/slo.py): availability + p95 objectives over the
        # families this registry already counts, refreshed on scrape
        self.slo = tracker_from_config(self.metrics.registry, cfg)
        # mine_build_info: scrapes join perf-ledger rows on git_rev
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 - identity, never a crash
            backend = None
        set_build_info(self.metrics.registry, backend=backend)
        # hot-swap source: a workspace path (str — the production shape:
        # POST /admin/swap re-reads its newest checkpoint, validated
        # against the serving tree) or a zero-arg callable returning
        # (params, batch_stats, step) (tests, the chaos drill's fake
        # fleet). None disables /admin/swap with a 400.
        self.swap_source = swap_source
        self._swap_lock = threading.Lock()
        self._swap_thread: threading.Thread | None = None
        self._swap_status: dict[str, Any] = {
            "state": "idle", "generation": self.engine.generation,
            "checkpoint_step": self.engine.checkpoint_step,
        }
        self._promote_stop = threading.Event()
        self._promote_thread: threading.Thread | None = None
        # shapes an untrusted /predict body may request: each admitted spec
        # costs a full XLA compile + an O(S*H*W) resident MPI, so the set is
        # operator-configured, never client-grown (the compile-boundedness
        # the engine's bucket design exists for)
        self.allowed_buckets: set[BucketSpec] = {self.engine.default_bucket}
        for spec in allowed_buckets or ():
            self.allowed_buckets.add(tuple(int(v) for v in spec))
        # fleet peer fetch (the compressed wire's consumer): `peers` is the
        # FULL fleet membership {name: base_url} including this replica,
        # `peer_name` which one we are. On a local cache miss, predict asks
        # the replicas MORE authoritative than us for this digest (earlier
        # in the consistent-hash candidate order — after a membership
        # change the previous owner is exactly there) for the compressed
        # MPI before paying an encoder pass. Bounded by
        # serving.peer_fetch_timeout_s per attempt; every failure mode
        # degrades to the local predict, never an error.
        self.peer_fetch_timeout_s = knob(
            peer_fetch_timeout_s, cfg.serving.peer_fetch_timeout_s
        )
        if self.peer_fetch_timeout_s <= 0:
            # same fail-fast contract as the engine's serving.* knobs: a
            # zero/negative budget would make every _peer_fetch deadline
            # already-expired — peer fetch silently off, no counter ever
            # ticking, every relocated miss paying the encoder again
            raise ValueError(
                f"serving.peer_fetch_timeout_s={self.peer_fetch_timeout_s} "
                "must be > 0"
            )
        self.peers: dict[str, str] = {}
        self.peer_name = None
        self._peer_ring = None
        self.configure_peers(peers, peer_name)
        # drain shedding state (autoscale retirement, serving/autoscale.py):
        # while True, product POSTs answer 503 + Retry-After (the router's
        # cooldown steers traffic off this replica) but GET /mpi/<key> and
        # the admin/debug surfaces stay served — the arc handoff and the
        # survivors' peer fetch need exactly those. A plain bool, flipped
        # atomically by set_draining; readers tolerate either value.
        self.draining = False
        self.metrics.draining.set(0)
        self.cache = MPICache(cache_bytes, metrics=self.metrics)
        self.batcher = MicroBatcher(
            self._guarded_render, max_delay_ms=max_delay_ms,
            max_batch_poses=max_batch_poses,
            max_queue_requests=knob(
                max_queue_requests, res.serve_max_queue_requests
            ),
            metrics=self.metrics, tracer=self.tracer,
        ).start()
        self.request_timeout_s = request_timeout_s
        self._started_at = time.time()
        # brownout ladder (serving/degrade.py): load-adaptive degradation
        # engaged BEFORE any 503 shed. Disabled by default — overload tests
        # and operators that want shed-only behavior keep the old contract;
        # the bench/drill fleets and production turn it on via config.
        self._last_burn = 0.0  # worst mine_slo_burn_rate at last scrape
        self._normal_delay_s = self.batcher.max_delay_s
        self._degraded_delay_s = cfg.serving.degrade_coalesce_delay_ms / 1e3
        self.degrade = (
            controller_from_config(cfg, on_level=self._apply_degradation)
            if cfg.serving.degrade_enabled else None
        )
        self.metrics.degradation_level.set(0)
        # predict singleflight: concurrent misses for one key share one
        # encoder pass (the batcher's coalescing idea applied to the
        # expensive half — without it, N simultaneous uploads of one image
        # run N encoder passes and materialize N ~100 MB MPIs)
        self._inflight: dict[Any, Future] = {}
        self._inflight_lock = threading.Lock()

    # -- brownout ladder (serving/degrade.py) ----------------------------------

    def _degrade_tick(self) -> int:
        """One ladder observation: gather the live pressure sample (queue
        depth and breaker state are read live; the burn rate is the worst
        one the SLO tracker published at the last scrape), advance the
        state machine, return the level. Called per product request and
        per /metrics scrape — an idle replica still relaxes on the
        autoscaler's scrape cadence. No-op (level 0) when disabled."""
        if self.degrade is None:
            return 0
        return self.degrade.tick(PressureSample(
            queue_frac=self.batcher.queue_frac(),
            burn_rate=self._last_burn,
            breaker_open=self.breaker.state == "open",
        ))

    def _apply_degradation(self, level: int) -> None:
        """Apply one level's semantics to the live components — the
        controller's on_level hook, fired only on transitions (so the
        batcher's condition is not re-notified per request). L1's
        compression override routes through the engine, where the predict
        path snapshots it once per request (key and entry always agree);
        L3 widens — and any lower level restores — the batcher's
        coalescing window for the CURRENT queue, not just future work."""
        tier = self.degrade.tier_override()
        if tier is not None:
            self.engine.set_degraded_compression(
                tier, self.degrade.prune_eps_override()
            )
        else:
            self.engine.clear_degraded_compression()
        self.batcher.set_max_delay_s(
            self._degraded_delay_s if self.degrade.widen_coalesce()
            else self._normal_delay_s
        )
        self.metrics.degradation_level.set(level)

    def slo_scrape(self) -> None:
        """Scrape-cadence SLO refresh (obs/slo.py) + one ladder
        observation: the burn rates the tracker just published become the
        ladder's burn signal until the next scrape."""
        report = self.slo.evaluate()
        self._last_burn = max(
            (row.get("burn_rate", 0.0) for row in report.values()),
            default=0.0,
        )
        self._degrade_tick()

    # -- circuit breaker around the engine ------------------------------------

    def _breaker_guard(self, kind: str, fn, *args):
        """Run one engine dispatch under the breaker: open -> immediate
        BreakerOpen (no device touch; half-open admits one trial); outcomes
        feed the state machine. Client-side errors never reach here — the
        callers validate first, so a failure IS an engine failure."""
        if not self.breaker.allow():
            self.metrics.shed_requests.inc(reason="breaker_open")
            raise BreakerOpen(self.breaker.retry_after_s() or self.retry_after_s)
        try:
            result = fn(*args)
        except BaseException:
            self.metrics.engine_failures.inc(kind=kind)
            self.breaker.record_failure()
            raise
        self.breaker.record_success()
        self.memlog.sample()  # HBM watermark after the dispatch
        return result

    def _guarded_render(self, entry, poses):
        return self._breaker_guard("render", self.engine.render, entry, poses)

    # -- hot checkpoint swap ---------------------------------------------------

    def swap_status(self) -> dict:
        with self._swap_lock:
            return dict(self._swap_status)

    def swap(self, wait: bool = False, step: int | None = None) -> dict:
        """Trigger a hot checkpoint swap from `swap_source`.

        Asynchronous by default (the production shape: POST /admin/swap
        answers 202 immediately and the load/validate/verify/flip sequence
        runs on a worker thread while the old generation serves). With
        `wait`, blocks until the attempt resolves — the drill and tests
        use this for deterministic assertions. `step` pins a workspace
        source to a specific retained checkpoint (the promotion watch
        passes the vetted step; manual /admin/swap takes the newest).
        Returns the status dict; NEVER raises for a failed swap (the
        failure is named in the status and counted in
        mine_serve_swap_failures_total) — only for a missing swap_source
        (ValueError: a config error, not a runtime fault)."""
        if self.swap_source is None:
            raise ValueError(
                "no swap source configured (start the server with a "
                "--workspace, or pass swap_source=)"
            )
        with self._swap_lock:
            if self._swap_status.get("state") == "in_progress":
                self.metrics.swap_failures.inc(reason="in_progress")
                return dict(self._swap_status)
            self._swap_status = {
                "state": "in_progress",
                "generation": self.engine.generation,
                "checkpoint_step": self.engine.checkpoint_step,
                "started_at": time.time(),
            }
            thread = threading.Thread(
                target=self._run_swap, args=(step,), name="mine-swap",
                daemon=True,
            )
            self._swap_thread = thread
            thread.start()
        if wait:
            thread.join()
        return self.swap_status()

    def _load_swap_source(self, step: int | None = None):
        """(params, batch_stats, step) from the configured source; the
        corrupt-checkpoint chaos seam fires here (a ChaosFault stands in
        for orbax choking on a truncated/corrupt file)."""
        chaos.maybe_raise("corrupt_swap")  # fault seam (resilience/chaos.py)
        if chaos.should("corrupt_ckpt"):
            # integrity-specific corruption: a checkpoint whose BYTES no
            # longer match the sha256-of-manifest sidecar written at save
            # time — the named rejection verify_checkpoint_integrity
            # raises on a real workspace, injected here so the fake-fleet
            # drill proves the swap is refused and the old generation
            # keeps serving (reason="corrupt", never a 5xx)
            from mine_tpu.training.checkpoint import CheckpointCorrupt

            raise CheckpointCorrupt(
                "chaos-injected corrupt checkpoint",
                ["manifest sha256 mismatch (chaos seam)"],
            )
        if callable(self.swap_source):
            return self.swap_source()
        from mine_tpu.training.checkpoint import load_for_serving

        import jax

        expected = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.engine.variables,
        )
        _, params, batch_stats, step = load_for_serving(
            self.swap_source, expected_variables=expected, step=step
        )
        return params, batch_stats, step

    def _run_swap(self, target_step: int | None = None) -> None:
        # The status update is unconditional: if ANY exception escaped this
        # worker, _swap_status would stay "in_progress" forever and every
        # future swap (manual and promotion watch alike) would be refused —
        # the swap subsystem must degrade to a named failure, never wedge.
        try:
            outcome = self._swap_attempt(target_step)
        except Exception as exc:  # noqa: BLE001 - the never-wedge backstop
            self.metrics.swap_failures.inc(reason="internal")
            outcome = {"state": "failed", "reason": "internal",
                       "error": f"{type(exc).__name__}: {exc}"}
        with self._swap_lock:
            started = self._swap_status.get("started_at")
            self._swap_status = {
                **outcome,
                "generation": self.engine.generation,
                "checkpoint_step": self.engine.checkpoint_step,
                "duration_s": (round(time.time() - started, 3)
                               if started else None),
            }

    def _swap_attempt(self, target_step: int | None) -> dict[str, Any]:
        from mine_tpu.training.checkpoint import CheckpointCorrupt

        try:
            params, batch_stats, step = self._load_swap_source(target_step)
        except CheckpointCorrupt as exc:
            # integrity-rejected BEFORE generic load failures: the sidecar
            # mismatch has its own reason so an operator can tell "the
            # bytes rotted" from "orbax could not restore"
            self.metrics.swap_failures.inc(reason="corrupt")
            return {"state": "failed", "reason": "corrupt",
                    "error": f"{type(exc).__name__}: {exc}"}
        except Exception as exc:  # noqa: BLE001 - named, counted, no 5xx
            self.metrics.swap_failures.inc(reason="load")
            return {"state": "failed", "reason": "load",
                    "error": f"{type(exc).__name__}: {exc}"}
        if int(step) == self.engine.checkpoint_step:
            return {"state": "noop", "note": f"already serving step {step}"}
        try:
            ws = self.engine.swap_weights(params, batch_stats, step)
        except SwapInProgress as exc:
            self.metrics.swap_failures.inc(reason="in_progress")
            return {"state": "failed", "reason": "in_progress",
                    "error": str(exc)}
        except SwapError as exc:
            self.metrics.swap_failures.inc(reason="rejected")
            return {"state": "failed", "reason": "rejected",
                    "error": f"{type(exc).__name__}: {exc}"}
        # a non-SwapError out of swap_weights (a device OOM placing the
        # candidate, a racing bucket compile failure) is caught by the
        # _run_swap backstop: reason "internal", old generation serving
        self.metrics.swaps.inc()
        return {"state": "ok", "swapped_to_step": ws.checkpoint_step}

    def maybe_promote(self) -> dict | None:
        """One promotion check: when the training job's last_good pointer
        (workspace sidecar, training/checkpoint.py) vets a step newer than
        the serving generation, swap to the newest RETAINED step at or
        under the pointer — never to a fresher, not-yet-vetted checkpoint
        (the whole point of watching last_good instead of latest; the
        sentinel may be about to roll the newest one back). A pointer
        whose vetted steps were all GC'd resolves to nothing newer and is
        a quiet no-op — not an endless restore-and-noop loop. Returns the
        swap status when one was triggered, None otherwise. Only
        meaningful for a workspace-path swap_source."""
        if not isinstance(self.swap_source, str):
            return None
        from mine_tpu.training.checkpoint import (
            checkpoint_manager,
            last_good_step,
        )

        pointer = last_good_step(self.swap_source)
        if pointer is None or pointer <= self.engine.checkpoint_step:
            return None
        vetted = [
            int(s) for s in checkpoint_manager(self.swap_source).all_steps()
            if int(s) <= pointer
        ]
        target = max(vetted) if vetted else None
        if target is None or target <= self.engine.checkpoint_step:
            return None
        if self.swap_status().get("state") == "in_progress":
            return None
        return self.swap(wait=True, step=target)

    def start_promotion_watch(self, interval_s: float = 30.0) -> None:
        """Poll the last_good pointer on a daemon thread: a training job
        continuously promotes vetted weights into the live server
        (--watch-last-good). Idempotent; stopped by close()."""
        if self._promote_thread is not None:
            return

        def watch():
            while not self._promote_stop.wait(interval_s):
                try:
                    self.maybe_promote()
                except Exception as exc:  # noqa: BLE001 - keep watching
                    print(f"# last_good promotion check failed: {exc}",
                          file=__import__("sys").stderr)

        self._promote_thread = threading.Thread(
            target=watch, name="mine-last-good-watch", daemon=True
        )
        self._promote_thread.start()

    def predict(
        self, image_bytes: bytes, spec: BucketSpec | None = None,
        request_id: str | None = None, parent_span: str | None = None,
    ) -> dict:
        digest = hashlib.sha256(image_bytes).hexdigest()
        if spec is not None:
            spec = tuple(int(v) for v in spec)
            if spec not in self.allowed_buckets:
                raise ValueError(
                    f"bucket {list(spec)} is not served; allowed: "
                    f"{sorted(list(b) for b in self.allowed_buckets)} "
                    "(extend with --bucket H,W,S at server start)"
                )
        bucket = self.engine.bucket(spec)  # validates the requested shape
        self._degrade_tick()  # ladder observation BEFORE the operating
        # point is snapshotted: this request serves at the level it ticked
        # ONE weights snapshot keys the cache AND runs the dispatch: reading
        # checkpoint_step and variables separately could straddle a hot swap
        # and file a new-generation MPI under the old generation's key. The
        # compression operating point obeys the SAME discipline: tier and
        # prune_eps are read ONCE here and passed into the engine dispatch
        # explicitly, so a brownout level flip mid-request can never file
        # an int8 entry under an fp32 key (or vice versa).
        weights = self.engine.weights()
        tier = self.engine.effective_tier()
        prune_eps = self.engine.effective_prune_eps()
        key = mpi_key(digest, weights.checkpoint_step, bucket.spec, tier)

        def response(entry, cached: bool, entry_key=None) -> dict:
            return {
                "mpi_key": key_to_str(key if entry_key is None else entry_key),
                "cached": cached,
                "bucket": list(bucket.spec),
                "planes": bucket.num_planes,
                "planes_kept": (entry.planes_kept
                                if isinstance(entry, CompressedMPI)
                                else bucket.num_planes),
                "tier": key[5] if entry_key is None else entry_key[5],
                "mpi_bytes": entry.nbytes,
            }

        with self.tracer.span("cache_lookup", cat="serve", endpoint="predict",
                              request_id=request_id):
            entry = self.cache.get(key)
        if entry is not None:
            return response(entry, cached=True)
        if self.degrade is not None and self.degrade.serve_stale():
            # L2 stale-while-revalidate: the newest OLDER-step resident
            # entry for this scene answers the miss — post-swap, the old
            # generation's mpi_keys keep serving instead of forcing a
            # re-predict per scene while the replica is under pressure.
            # The response carries the STALE key so follow-up renders hit.
            stale = self.cache.stale_key(key)
            if stale is not None:
                old = self.cache.get(stale, record=False)
                if old is not None:
                    out = response(old, cached=True, entry_key=stale)
                    out["stale"] = True
                    return out
        with self._inflight_lock:
            future = self._inflight.get(key)
            owner = future is None
            if owner:
                # re-check under the lock: the owner publishes to the cache
                # BEFORE dropping its inflight marker, so "no marker" can
                # mean "just finished" (counted above already, record=False)
                entry = self.cache.get(key, record=False)
                if entry is not None:
                    return response(entry, cached=True)
                future = Future()
                self._inflight[key] = future
        if not owner:
            # follower: share the owner's encoder pass (its exception too)
            try:
                return response(
                    future.result(timeout=self.request_timeout_s), cached=True
                )
            except FutureTimeout:
                self.metrics.request_timeouts.inc(stage="result")
                raise RequestTimeout(
                    f"predict singleflight wait exceeded "
                    f"{self.request_timeout_s}s"
                ) from None
        from_peer = False
        try:
            # decode FIRST (outside the breaker guard): undecodable bytes
            # are the client's fault (400), never an engine failure — and
            # never worth a peer round trip (no peer can hold a digest
            # whose bytes never decoded anywhere; a garbage-bytes flood
            # must not amplify into fleet GET /mpi traffic)
            image = _decode_image(image_bytes)
            # an OPEN breaker sheds BEFORE any peer network work: the old
            # fast Retry-After contract — and a replica that cannot render
            # must not answer 200 predicts it can only 503 renders for.
            # (Pure admission probe; the half-open trial slot is consumed
            # at dispatch, exactly as in render().)
            if self.breaker.rejecting():
                self.metrics.shed_requests.inc(reason="breaker_open")
                raise BreakerOpen(
                    self.breaker.retry_after_s() or self.retry_after_s
                )
            # then the fleet wire: a peer holding this exact key hands us
            # the compressed MPI for network bytes instead of encoder FLOPs
            # — unless the ladder is at L2+, where the wire round-trip is
            # latency spent on fidelity nobody can afford right now
            entry = None
            if self.degrade is None or not self.degrade.skip_peer_fetch():
                entry = self._peer_fetch(key, digest, request_id=request_id,
                                         parent_span=parent_span)
            from_peer = entry is not None
            if entry is None:
                entry = self._breaker_guard(
                    "predict", self.engine.predict, image, bucket.spec,
                    request_id, weights, tier, prune_eps,
                )
            self.cache.put(key, entry)
            future.set_result(entry)
        except BaseException as exc:
            future.set_exception(exc)
            raise
        finally:
            with self._inflight_lock:
                self._inflight.pop(key, None)
        return response(entry, cached=from_peer)

    def configure_peers(self, peers: dict[str, str] | None,
                        peer_name: str | None,
                        vnodes: int = DEFAULT_VNODES) -> None:
        """(Re)declare fleet membership for peer fetch. Callable after
        construction because a replica's own URL typically exists only once
        its server has bound a port (tools/bench_fleet.py builds the apps
        first, then the servers). None/empty disables peer fetch.

        `vnodes` MUST match the router's — which is why the default IS the
        router's (fleet.DEFAULT_VNODES, one spelling): the replica-side
        ring exists to agree with the router about who owns a digest — a
        mismatched vnode count silently reorders candidates and peer fetch
        asks the wrong peers (pure waste, never an error)."""
        if not peers:
            self.peers, self.peer_name, self._peer_ring = {}, None, None
            return
        # validate BEFORE any assignment: a rejected reconfigure must
        # leave the previous (working) membership fully in effect, never a
        # new peer map paired with the old ring
        if not peer_name or peer_name not in peers:
            raise ValueError(
                "peer_name must name this replica inside peers "
                f"(got {peer_name!r}, peers {sorted(peers)})"
            )
        from mine_tpu.serving.fleet import HashRing

        ring = HashRing(list(peers), vnodes=vnodes)
        self.peers, self.peer_name, self._peer_ring = dict(peers), peer_name, ring

    def _peer_fetch(self, key, digest: str, request_id: str | None = None,
                    parent_span: str | None = None):
        """Try to adopt this key's compressed MPI from a MORE authoritative
        peer (every replica earlier than us in the consistent-hash
        candidate order for this digest — when we ARE the owner the list is
        empty and no network is touched; after a membership change the
        previous owner is exactly the replica before us). Returns the
        device-adopted entry or None; NEVER raises — every failure outcome
        is a counter tick and a fallthrough to the local predict.

        The GET carries the originating request's trace context
        (X-Request-Id + X-Parent-Span = this hop's span id), so the peer's
        ring records the hop under the SAME request id — before this, the
        peer hop was invisible to the request's merged trace."""
        # ONE consistent membership snapshot: configure_peers may swap
        # ring/peers/name under a live server (bench_fleet does), and a
        # name resolved against the old ring must not KeyError against the
        # new peer map — that would 500 a predict the never-raises
        # contract promises to serve locally
        ring, peers, self_name = self._peer_ring, self.peers, self.peer_name
        if ring is None:
            return None
        candidates = ring.candidates(digest)
        try:
            upstream = candidates[:candidates.index(self_name)]
        except ValueError:  # we are not on the ring (config drift): ask the owner
            upstream = candidates[:1]
        if not upstream:
            return None
        # one transport, one error taxonomy: the router's (fleet.py
        # _urllib_transport) — statuses are answers, TimeoutError is a
        # blown budget, ConnectionError is an unreachable/mid-response-dead
        # peer. A second hand-rolled urllib client here would fork the
        # classification the fleet already hardened.
        from mine_tpu.serving.fleet import _urllib_transport

        key_str = key_to_str(key)
        # ONE deadline for the whole fetch (the documented contract of
        # serving.peer_fetch_timeout_s): up to two upstream peers — the
        # owner plus one failover — SHARE the budget, so a blackholed
        # owner cannot stack a second full timeout on top of its own
        deadline = time.monotonic() + self.peer_fetch_timeout_s
        for name in upstream[:2]:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            base_url = peers.get(name)
            if base_url is None:  # membership changed mid-flight
                continue
            url = f"{base_url.rstrip('/')}/mpi/{key_str}"
            outcome = "error"
            hop_id = new_span_id()
            hop_headers: dict[str, str] = {}
            if request_id:
                hop_headers[REQUEST_ID_HEADER] = request_id
                hop_headers[PARENT_SPAN_HEADER] = hop_id
            try:
                with self.tracer.span("peer_fetch", cat="serve", peer=name,
                                      request_id=request_id,
                                      span_id=hop_id,
                                      parent_span=parent_span):
                    status, _, body = _urllib_transport(
                        "GET", url, None, hop_headers, remaining
                    )
                if status == 200:
                    entry = from_wire(body)
                    if tuple(entry.bucket) != tuple(key[2:5]):
                        raise ValueError(
                            f"peer {name} returned bucket {entry.bucket} "
                            f"for key bucket {key[2:5]}"
                        )
                    # config drift between peers is NOT all key-fenced:
                    # the tier is, but prune_eps and the full plane count
                    # (mpi.num_bins_fine rides the bucket's S_coarse key
                    # unchanged) are not. A pruned entry would break this
                    # replica's no-prune contract; a wrong-plane-count
                    # entry would 500 every render with an XLA shape
                    # error until evicted. Surface the drift as its own
                    # outcome and pay the local predict.
                    full = self.engine.bucket(key[2:5]).num_planes
                    if isinstance(entry, CompressedMPI):
                        drifted = (
                            entry.tier != key[5]
                            or entry.num_planes_full != full
                            or (not self.engine.prune_eps
                                and entry.planes_kept
                                < entry.num_planes_full)
                        )
                    else:
                        drifted = int(np.shape(entry.mpi_rgb)[1]) != full
                    if drifted:
                        self.metrics.peer_fetch.inc(outcome="incompatible")
                        return None
                    entry = self.engine._adopt_entry(
                        entry, request_id=request_id
                    )
                    self.metrics.peer_fetch.inc(outcome="hit")
                    return entry
                outcome = "miss" if status == 404 else "error"
            except TimeoutError:
                outcome = "timeout"
            except Exception:  # noqa: BLE001 - degrade to local predict
                outcome = "error"
            self.metrics.peer_fetch.inc(outcome=outcome)
        return None

    def set_draining(self, draining: bool) -> None:
        """Flip the drain shedding state (POST /admin/drain). Reversible:
        an aborted drain flips back to serving with its cache intact."""
        self.draining = bool(draining)
        self.metrics.draining.set(1 if self.draining else 0)

    def prewarm(self, keys: list[str], sources: list[str],
                timeout_s: float | None = None,
                request_id: str | None = None) -> dict[str, int]:
        """Bulk-adopt cached MPIs over the fleet wire (GET /mpi/<key>)
        BEFORE this replica serves their traffic — the autoscale join's
        pre-warm and the drain handoff's receiving side. `keys` are wire
        mpi_keys, hottest first (MPICache.hot_keys order, so an expired
        budget kept the hottest); `sources` are base URLs of the current
        owners, tried in order per key. Each attempt is bounded by the
        peer-fetch budget; `timeout_s` additionally bounds the WHOLE pass.
        Never raises: a short pre-warm is a warmer-than-nothing cache, and
        anything it missed degrades to the ring's peer-fetch path. Returns
        outcome counts (also ticked on mine_serve_prewarm_keys_total)."""
        from mine_tpu.serving.fleet import _urllib_transport

        counts = {"fetched": 0, "resident": 0, "miss": 0, "error": 0}
        deadline = (time.monotonic() + timeout_s
                    if timeout_s and timeout_s > 0 else None)
        for key_str in keys:
            if deadline is not None and time.monotonic() >= deadline:
                break
            try:
                key = key_from_str(key_str)
            except ValueError:
                counts["error"] += 1
                self.metrics.prewarm_keys.inc(outcome="error")
                continue
            if self.cache.get(key, record=False) is not None:
                counts["resident"] += 1
                self.metrics.prewarm_keys.inc(outcome="resident")
                continue
            outcome = "miss"
            for base_url in sources:
                budget = self.peer_fetch_timeout_s
                if deadline is not None:
                    budget = min(budget, deadline - time.monotonic())
                if budget <= 0:
                    break
                url = f"{base_url.rstrip('/')}/mpi/{key_str}"
                try:
                    with self.tracer.span("prewarm_fetch", cat="serve",
                                          request_id=request_id,
                                          key=key_str[:16]):
                        status, _, body = _urllib_transport(
                            "GET", url, None, {}, budget
                        )
                    if status != 200:
                        continue
                    entry = from_wire(body)
                    if tuple(entry.bucket) != tuple(key[2:5]):
                        raise ValueError(
                            f"source returned bucket {entry.bucket} for "
                            f"key bucket {key[2:5]}"
                        )
                    entry = self.engine._adopt_entry(
                        entry, request_id=request_id
                    )
                    self.cache.put(key, entry)
                    outcome = "fetched"
                    break
                except TimeoutError:
                    continue
                except Exception:  # noqa: BLE001 - degrade, never raise
                    outcome = "error"
                    continue
            counts[outcome] += 1
            self.metrics.prewarm_keys.inc(outcome=outcome)
        return counts

    def compressed_blob(self, key_str: str) -> bytes | None:
        """The cached entry for `key_str` as wire bytes (the GET /mpi/<key>
        body), or None when not resident. record=False: a peer's probe is
        not this replica's client traffic — hit/miss rates stay about the
        images THIS replica was asked to serve."""
        entry = self.cache.get(key_from_str(key_str), record=False)
        return None if entry is None else to_wire(entry)

    def render(
        self,
        key_str: str,
        poses: np.ndarray,
        timeout_s: float | None = None,
        request_id: str | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        key = key_from_str(key_str)
        self._degrade_tick()  # renders feel queue pressure first: the
        # ladder's L3 (widened coalescing) acts on exactly this path
        with self.tracer.span("cache_lookup", cat="serve", endpoint="render",
                              request_id=request_id):
            entry = self.cache.get(key)
        if entry is None:
            raise KeyError(key_str)
        if self.breaker.rejecting():
            # pure admission probe — the half-open trial slot is consumed
            # at dispatch time (_guarded_render), not here
            self.metrics.shed_requests.inc(reason="breaker_open")
            raise BreakerOpen(self.breaker.retry_after_s() or self.retry_after_s)
        # per-request deadline, propagated INTO the batcher: if the queue
        # outlives it the worker drops the request before dispatch (504)
        timeout = min(
            timeout_s if timeout_s and timeout_s > 0 else self.deadline_s,
            self.request_timeout_s,
        )
        future = self.batcher.submit(
            key, entry, poses, deadline=time.monotonic() + timeout,
            request_id=request_id,
        )
        try:
            return future.result(timeout=timeout)
        except FutureTimeout:
            # evict the pending entry so the worker never renders for a
            # client that already gave up; if it is mid-dispatch the result
            # is simply dropped
            self.batcher.cancel(future)
            self.metrics.request_timeouts.inc(stage="result")
            raise RequestTimeout(
                f"render did not complete within {timeout:.1f}s"
            ) from None

    def trace_for_request(self, request_id: str) -> dict:
        """One request's span tree as Chrome-trace JSON: every span whose
        args carry this request_id — the handler-side parse/predict/render/
        cache_lookup/encode spans plus the batcher/engine spans of any
        dispatch that included it (their request_ids list). The matching
        rule is obs/collect.py's — the SAME one the fleet aggregation
        applies to the router's ring, so the two surfaces can never
        disagree about which spans belong to a request."""
        from mine_tpu.obs.collect import filter_doc_to_request

        return filter_doc_to_request(
            self.tracer.to_chrome_trace(), request_id
        )

    def health(self) -> dict:
        import jax

        breaker_state = self.breaker.state
        # "degraded" (503) only while OPEN. Half-open must report healthy:
        # the breaker needs one real request to run its recovery trial, and
        # a load balancer honoring a 503 here would starve it of exactly
        # that traffic — the replica would stay drained forever.
        status = {"closed": "ok", "half_open": "recovering"}.get(
            breaker_state, "degraded"
        )
        if self.draining:
            # a draining replica is deliberately out of service for product
            # traffic: report it so routers/probes stop offering it work
            # (the peer-fetch wire stays served regardless)
            status = "draining"
        return {
            "status": status,
            "draining": self.draining,
            "uptime_s": round(time.time() - self._started_at, 1),
            "backend": jax.default_backend(),
            "checkpoint_step": self.engine.checkpoint_step,
            "weight_generation": self.engine.generation,
            "swap_state": self.swap_status().get("state", "idle"),
            "buckets": [list(s) for s in self.engine.bucket_specs()],
            "compiles": self.engine.compiles,
            # per-bucket executable inventory (engine.warm_pool): is every
            # DECLARED bucket actually warm before traffic lands on it?
            "warm_pool": self.engine.warm_pool(),
            "cache_entries": len(self.cache),
            "cache_bytes_resident": self.cache.bytes_resident,
            "queue_depth": self.batcher.queue_depth(),
            "queue_bound": self.batcher.max_queue_requests,
            "breaker": breaker_state,
            "breaker_trips": self.breaker.trips,
            "degradation": (None if self.degrade is None
                            else self.degrade.snapshot()),
            "trace_enabled": self.tracer.enabled,
            "trace_spans_buffered": len(self.tracer),
        }

    def close(self) -> None:
        self._promote_stop.set()
        if self._promote_thread is not None:
            self._promote_thread.join(timeout=5)
        self.batcher.stop()


class _BodyTooLarge(Exception):
    """Request body over _Handler.MAX_BODY_BYTES — mapped to HTTP 413."""

    def __init__(self, length: int):
        super().__init__(f"request body of {length} bytes exceeds the "
                         f"{_Handler.MAX_BODY_BYTES}-byte limit")


class _Handler(BaseHTTPRequestHandler):
    # one ThreadingHTTPServer thread per in-flight request; the shared app
    # object is thread-safe by construction (cache/batcher/engine locks)
    server: "ServingHTTPServer"
    protocol_version = "HTTP/1.1"

    # -- plumbing ------------------------------------------------------------

    def log_message(self, fmt: str, *args: Any) -> None:  # noqa: A003
        if self.server.verbose:
            super().log_message(fmt, *args)

    def _observe(self, code: int) -> None:
        """Count + time this request EXACTLY once, BEFORE its response
        bytes hit the socket: a client that saw its answer and immediately
        scrapes /metrics must find the request already counted. The old
        order (observe after wfile.write, at the end of _handle) left a
        window where the response existed but the counters had not — which
        tests/test_obs.py could only paper over by polling the scrape."""
        if getattr(self, "_observed", True) or not hasattr(self, "_t0"):
            return
        self._observed = True
        app = self.server.app
        app.metrics.requests.inc(endpoint=self._endpoint, status=str(code))
        app.metrics.request_latency.observe(
            time.monotonic() - self._t0, endpoint=self._endpoint
        )

    def _degraded_headers(self, app: ServingApp) -> dict[str, str] | None:
        """The X-Degraded announcement for a product answer served while
        the brownout ladder is engaged (serving/degrade.py): every
        degraded 200 names its level and effective tier and ticks the
        per-level response counter — degradation is always announced,
        never silent. None (no header) at L0 or with the ladder off."""
        degrade = app.degrade
        if degrade is None or degrade.level <= 0:
            return None
        degrade.record_response()
        app.metrics.degradation_responses.inc(level=str(degrade.level))
        return {
            "X-Degraded": degrade.announcement(app.engine.effective_tier()),
        }

    def _send(
        self, code: int, payload: bytes, content_type: str,
        extra_headers: dict[str, str] | None = None,
    ) -> None:
        self._observe(code)
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        # every response names its request: the id the client sent (or the
        # one minted for it) keys /debug/trace?request_id=
        rid = getattr(self, "request_id", None)
        if rid:
            self.send_header("X-Request-Id", rid)
        for name, value in (extra_headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(payload)

    def _send_json(
        self, code: int, obj: dict,
        extra_headers: dict[str, str] | None = None,
    ) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json",
                   extra_headers)

    # One request body must not be able to exhaust host RAM: the largest
    # legitimate payload is a source image for /predict (a full-res PNG is
    # a few MB; base64 inflates 4/3) or a /render pose list (KBs). Same
    # client-cannot-grow-resources discipline as allowed_buckets.
    MAX_BODY_BYTES = 64 * 1024 * 1024

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        if length > self.MAX_BODY_BYTES:
            raise _BodyTooLarge(length)
        return self.rfile.read(length) if length else b""

    def _overload_response(self, exc: Exception) -> int | None:
        """Map the typed admission-control outcomes onto honest HTTP codes
        (shed/drain -> 503 with Retry-After, deadline -> 504); None for
        anything else (the caller's normal handling proceeds)."""
        app = self.server.app
        if isinstance(exc, BreakerOpen):
            retry_after = max(exc.retry_after_s, 0.1)
            self._send_json(
                503, {"error": str(exc), "retry_after_s": retry_after},
                {"Retry-After": f"{retry_after:.1f}"},
            )
            return 503
        if isinstance(exc, QueueFull):
            retry_after = max(app.retry_after_s, 0.1)
            self._send_json(
                503, {"error": str(exc), "retry_after_s": retry_after},
                {"Retry-After": f"{retry_after:.1f}"},
            )
            return 503
        if isinstance(exc, BatcherStopped):
            app.metrics.shed_requests.inc(reason="draining")
            self._send_json(503, {"error": f"{exc} (server draining)"})
            return 503
        if isinstance(exc, (DeadlineExceeded, RequestTimeout)):
            self._send_json(504, {"error": str(exc)})
            return 504
        return None

    def _route(self, method: str, path: str) -> tuple[int, str]:
        # each branch stashes its endpoint label BEFORE dispatching, so
        # _observe (which fires inside _send, before the response bytes)
        # labels the requests/latency families with the same endpoint
        # names the families have always carried
        app = self.server.app
        if method == "GET" and path == "/healthz":
            self._endpoint = "healthz"
            health = app.health()
            # degraded (breaker OPEN) and draining answer 503 so load
            # balancers/probes drain this replica; "recovering" (half-open)
            # answers 200 so the recovery trial can arrive; the body
            # carries the full snapshot
            code = (503 if health["status"] in ("degraded", "draining")
                    else 200)
            self._send_json(code, health)
            return code, "healthz"
        if method == "GET" and path == "/metrics":
            self._endpoint = "metrics"
            # scrape-cadence HBM sample: the gauges stay current even when
            # no dispatch has run since the last scrape (obs/memlog.py);
            # the SLO gauges refresh on the same cadence (obs/slo.py), and
            # the brownout ladder gets an observation too — an IDLE
            # overloaded-then-recovered replica relaxes on scrape cadence
            # instead of waiting for its next product request
            app.memlog.sample()
            app.slo_scrape()
            self._send(200, app.metrics.render().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")
            return 200, "metrics"
        if method == "GET" and path == "/debug/trace":
            self._endpoint = "debug_trace"
            query = parse_qs(self.path.partition("?")[2])
            rid = (query.get("request_id") or [None])[0]
            if rid:
                self._send_json(200, app.trace_for_request(rid))
            else:
                self._send_json(200, app.tracer.to_chrome_trace(
                    extra_events=app.memlog.counter_events()
                ))
            return 200, "debug_trace"
        if method == "POST" and path in ("/predict", "/render"):
            self._endpoint = path.lstrip("/")
            if app.draining:
                # drain shedding: product traffic bounces with the same
                # 503 + Retry-After contract as overload — the router's
                # cooldown steers the arc to its new owner while the
                # peer-fetch wire below keeps serving the handoff
                app.metrics.shed_requests.inc(reason="draining")
                retry_after = max(app.retry_after_s, 0.1)
                self._send_json(
                    503,
                    {"error": "replica draining",
                     "retry_after_s": retry_after},
                    {"Retry-After": f"{retry_after:.1f}"},
                )
                return 503, path.lstrip("/")
            if path == "/predict":
                return self._predict(app), "predict"
            return self._render(app), "render"
        if method == "GET" and path.startswith("/mpi/"):
            self._endpoint = "mpi"
            # the fleet wire: the compressed container for one cache key,
            # served to peer replicas (serving/compress.py to_wire)
            key_str = path[len("/mpi/"):]
            try:
                blob = app.compressed_blob(key_str)
            except ValueError as exc:
                self._send_json(400, {"error": f"bad mpi key: {exc}"})
                return 400, "mpi"
            if blob is None:
                self._send_json(404, {
                    "error": f"mpi_key {key_str} not cached here",
                })
                return 404, "mpi"
            self._send(200, blob, "application/octet-stream")
            return 200, "mpi"
        if method == "GET" and path == "/admin/swap":
            self._endpoint = "admin_swap"
            self._send_json(200, app.swap_status())
            return 200, "admin_swap"
        if method == "POST" and path == "/admin/swap":
            self._endpoint = "admin_swap"
            return self._admin_swap(app), "admin_swap"
        if method == "GET" and path == "/debug/hot_keys":
            self._endpoint = "debug_hot_keys"
            # the hot-key surface (MPICache.hot_keys): what a joining
            # replica pre-warms and what an operator reads to see the arc
            query = parse_qs(self.path.partition("?")[2])
            try:
                n = int((query.get("n") or ["64"])[0])
            except ValueError:
                self._send_json(400, {"error": "n must be an integer"})
                return 400, "debug_hot_keys"
            self._send_json(200, {"hot_keys": [
                {"mpi_key": k, "nbytes": b}
                for k, b in app.cache.hot_keys(n)
            ]})
            return 200, "debug_hot_keys"
        if method == "POST" and path == "/admin/drain":
            self._endpoint = "admin_drain"
            return self._admin_drain(app), "admin_drain"
        if method == "POST" and path == "/admin/peers":
            self._endpoint = "admin_peers"
            return self._admin_peers(app), "admin_peers"
        if method == "POST" and path == "/admin/prewarm":
            self._endpoint = "admin_prewarm"
            return self._admin_prewarm(app), "admin_prewarm"
        self._endpoint = "unknown"
        self._send_json(404, {"error": f"no route {method} {path}"})
        return 404, "unknown"

    def _admin_drain(self, app: ServingApp) -> int:
        """Flip the drain shedding state: {"draining": true|false}."""
        try:
            req = json.loads(self._read_body() or b"{}")
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
            draining = bool(req.get("draining", True))
        except (ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad drain body: {exc}"})
            return 400
        app.set_draining(draining)
        self._send_json(200, {"draining": app.draining})
        return 200

    def _admin_peers(self, app: ServingApp) -> int:
        """(Re)declare fleet membership for peer fetch on a LIVE replica:
        {"peers": {name: url}, "peer_name": str, "vnodes"?: int} — the
        autoscale controller fans this out after every membership change so
        each replica's peer ring keeps agreeing with the router's."""
        try:
            req = json.loads(self._read_body() or b"{}")
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
            peers = req.get("peers") or None
            if peers is not None and not (
                isinstance(peers, dict)
                and all(isinstance(k, str) and isinstance(v, str)
                        for k, v in peers.items())
            ):
                raise ValueError("peers must map name -> base URL")
            vnodes = int(req.get("vnodes", DEFAULT_VNODES))
            app.configure_peers(peers, req.get("peer_name"), vnodes=vnodes)
        except (ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad peers body: {exc}"})
            return 400
        self._send_json(200, {
            "peers": sorted(app.peers), "peer_name": app.peer_name,
        })
        return 200

    def _admin_prewarm(self, app: ServingApp) -> int:
        """Bulk pre-warm over the fleet wire: {"keys": [mpi_key...],
        "sources": [base_url...], "timeout_s"?: float} -> outcome counts
        (ServingApp.prewarm — never fails the pass; a short pre-warm
        reports its counts honestly)."""
        try:
            req = json.loads(self._read_body() or b"{}")
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
            keys = req.get("keys") or []
            sources = req.get("sources") or []
            if not all(isinstance(k, str) for k in keys) or not all(
                isinstance(s, str) for s in sources
            ):
                raise ValueError("keys and sources must be string lists")
            timeout_s = req.get("timeout_s")
            if timeout_s is not None:
                timeout_s = float(timeout_s)
        except (ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad prewarm body: {exc}"})
            return 400
        counts = app.prewarm(list(keys), list(sources), timeout_s=timeout_s,
                             request_id=self.request_id)
        self._send_json(200, counts)
        return 200

    def _handle(self, method: str) -> None:
        app = self.server.app
        path = self.path.split("?", 1)[0]
        # trace context off the headers (obs/trace.py — the ONE resolve
        # implementation shared with the fleet router): a well-formed
        # X-Request-Id is kept, else minted; a malformed X-Parent-Span
        # (set by the router's forward/fan-out and a peer's fetch) drops
        self.request_id = resolve_request_id(
            self.headers.get(REQUEST_ID_HEADER)
        )
        # this request's root span id on THIS replica: downstream hops
        # (peer fetch) point at it; the upstream hop (router forward /
        # peer GET) is its parent — the links obs/collect.py request_tree
        # assembles the cross-process tree from
        self._span_id = new_span_id()
        self._parent_span = resolve_parent_span(
            self.headers.get(PARENT_SPAN_HEADER)
        )
        if chaos.should("overload_spike") and app.degrade is not None:
            # synthetic pressure spike (resilience/chaos.py): the ladder's
            # next observations classify as breach regardless of the real
            # signals — the drill's deterministic full climb + descent
            app.degrade.inject()
        if chaos.should("replica_kill"):  # fault seam (resilience/chaos.py)
            # replica death, as a fleet router sees it: the listener goes
            # away and the triggering connection drops with NO response —
            # not a clean 5xx. shutdown() must run off-thread (it joins the
            # serve_forever loop this handler is running under).
            def die(srv):
                srv.shutdown()
                srv.server_close()

            threading.Thread(target=die, args=(self.server,),
                             daemon=True).start()
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
            return
        # request accounting state for _observe: the endpoint label is
        # stashed per-branch by _route; the observation itself fires inside
        # _send, BEFORE the response bytes are written (tests/test_obs.py)
        self._t0 = time.monotonic()
        self._observed = False
        self._endpoint = path.lstrip("/") or "unknown"
        p0 = time.perf_counter()
        try:
            code, endpoint = self._route(method, path)
        except (BrokenPipeError, ConnectionResetError):
            raise
        except _BodyTooLarge as exc:
            # refuse WITHOUT reading: the oversized body is never buffered
            code, endpoint = 413, path.lstrip("/") or "unknown"
            self._endpoint = endpoint
            try:
                self._send_json(413, {"error": str(exc)})
            except Exception:  # noqa: BLE001 - client already gone
                pass
        except Exception as exc:  # noqa: BLE001 - HTTP boundary
            code, endpoint = 500, path.lstrip("/") or "unknown"
            self._endpoint = endpoint
            try:
                self._send_json(500, {"error": f"{type(exc).__name__}: {exc}"})
            except Exception:  # noqa: BLE001 - client already gone
                pass
        if endpoint not in ("metrics", "healthz", "debug_trace",
                            "debug_hot_keys"):
            # the request-root span: carries this replica's span_id (what
            # a downstream peer fetch points at) and the upstream hop's
            # parent — scrape traffic stays out of the ring
            app.tracer.record(
                "request", "serve", p0, time.perf_counter(),
                request_id=self.request_id, endpoint=endpoint,
                status=code, span_id=self._span_id,
                parent_span=self._parent_span,
            )
        # backstop for a response the client never received (its socket
        # died before _send could run): the request still happened
        self._observe(code)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self._handle("GET")

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        self._handle("POST")

    # -- endpoints -----------------------------------------------------------

    def _predict(self, app: ServingApp) -> int:
        rid = self.request_id
        with app.tracer.span("parse", cat="serve", endpoint="predict",
                             request_id=rid):
            body = self._read_body()
            spec = None
            ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
            if ctype == "application/json":
                try:
                    req = json.loads(body)
                    image_bytes = base64.b64decode(req["image_b64"])
                    if req.get("bucket") is not None:
                        spec = tuple(int(v) for v in req["bucket"])
                except (KeyError, ValueError, TypeError) as exc:
                    self._send_json(400, {"error": f"bad predict body: {exc}"})
                    return 400
            else:
                image_bytes = body  # raw PNG/JPEG bytes
        if not image_bytes:
            self._send_json(400, {"error": "empty image"})
            return 400
        try:
            with app.tracer.span("predict", cat="serve", request_id=rid):
                result = app.predict(image_bytes, spec, request_id=rid,
                                     parent_span=self._span_id)
        except (BreakerOpen, RequestTimeout) as exc:
            return self._overload_response(exc)
        except (ValueError, OSError) as exc:
            # bad bucket (ValueError) or undecodable/truncated image bytes —
            # PIL's UnidentifiedImageError subclasses OSError, not ValueError
            self._send_json(400, {"error": str(exc)})
            return 400
        self._send_json(200, result, self._degraded_headers(app))
        return 200

    def _admin_swap(self, app: ServingApp) -> int:
        """Trigger a hot checkpoint swap. 202 + status for an accepted
        async swap; body {"wait": true} blocks until the attempt resolves
        (200 on flip/noop, 409 when another swap is running, 422 for a
        named rejection/load failure). A failed swap is NEVER a 5xx: the
        old generation is still serving, which is the opposite of a server
        error."""
        try:
            body = self._read_body()
            req = json.loads(body) if body else {}
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad swap body: {exc}"})
            return 400
        wait = bool(req.get("wait"))
        try:
            status = app.swap(wait=wait)
        except ValueError as exc:  # no swap source configured
            self._send_json(400, {"error": str(exc)})
            return 400
        # with wait, an "in_progress" answer can only mean ANOTHER swap
        # holds the slot (a freshly accepted one would have been joined to
        # completion) — that is a refusal, not an acceptance: 409
        code = {
            "ok": 200, "noop": 200, "idle": 200,
            "in_progress": 409 if wait else 202,
            "failed": 409 if status.get("reason") == "in_progress" else 422,
        }.get(status.get("state"), 200)
        self._send_json(code, status)
        return code

    def _render(self, app: ServingApp) -> int:
        rid = self.request_id
        try:
            with app.tracer.span("parse", cat="serve", endpoint="render",
                                 request_id=rid):
                req = json.loads(self._read_body())
                key_str = req["mpi_key"]
                key_from_str(key_str)  # malformed keys are a 400, not a 500
                poses = _poses_from_body(req)
                timeout_s = req.get("timeout_s")
                if timeout_s is not None:
                    timeout_s = float(timeout_s)
        except (KeyError, ValueError, TypeError) as exc:
            self._send_json(400, {"error": f"bad render body: {exc}"})
            return 400
        try:
            rgb, disp = app.render(key_str, poses, timeout_s=timeout_s,
                                   request_id=rid)
        except (BreakerOpen, QueueFull, BatcherStopped, DeadlineExceeded,
                RequestTimeout) as exc:
            # overload/drain/deadline: honest 503/504, never a hang or 500
            return self._overload_response(exc)
        except KeyError:
            self._send_json(404, {
                "error": f"mpi_key {key_str} not cached (evicted or never "
                "predicted) — POST /predict again",
            })
            return 404
        from mine_tpu.inference.video import normalize_disparity, to_uint8

        with app.tracer.span("encode", cat="serve",
                             frames=int(rgb.shape[0]), request_id=rid):
            frames = [
                base64.b64encode(_encode_png(f)).decode()
                for f in to_uint8(np.clip(rgb, 0.0, 1.0))
            ]
            out: dict[str, Any] = {
                "mpi_key": key_str,
                "num_frames": int(rgb.shape[0]),
                "height": int(rgb.shape[1]),
                "width": int(rgb.shape[2]),
                "frames_png_b64": frames,
            }
            if req.get("include_disparity"):
                out["disparity_png_b64"] = [
                    base64.b64encode(_encode_png(f)).decode()
                    for f in to_uint8(normalize_disparity(disp))[..., 0]
                ]
        self._send_json(200, out, self._degraded_headers(app))
        return 200


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # long-lived localhost sockets; rebinding a just-closed test port is fine
    allow_reuse_address = True

    def __init__(self, addr: tuple[str, int], app: ServingApp,
                 verbose: bool = False):
        super().__init__(addr, _Handler)
        self.app = app
        self.verbose = verbose


def make_server(
    app: ServingApp, host: str = "127.0.0.1", port: int = 0,
    verbose: bool = False,
) -> ServingHTTPServer:
    """Bind (port=0 -> ephemeral, server.server_address reports it); the
    caller drives serve_forever(), usually on a thread."""
    return ServingHTTPServer((host, port), app, verbose=verbose)


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workspace", required=True,
        help="training workspace dir (params.yaml + checkpoints/)",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument(
        "--cache-mb", type=int, default=2048,
        help="MPI cache byte budget in MiB",
    )
    parser.add_argument("--max-delay-ms", type=float, default=4.0,
                        help="micro-batching max coalescing delay")
    parser.add_argument("--max-batch-poses", type=int, default=64)
    parser.add_argument(
        "--bucket", action="append", default=[], metavar="H,W,S",
        help="additional (H, W, S) shape bucket clients may request via "
        "/predict's \"bucket\" field (repeatable; the config's own shape "
        "is always served). Each bucket costs one-time XLA compiles and "
        "O(S*H*W) cache bytes per entry — hence operator-allowlisted.",
    )
    parser.add_argument(
        "--zoo-buckets", action="store_true",
        help="allowlist the pretrained-zoo capability-envelope shapes "
        "(RealEstate10K 256x384x64, KITTI 256x768x64, Flowers 384x512x64, "
        "LLFF 384x512x32 — data/conformance/contract.py ZOO_BUCKETS) in "
        "one flag; warmup pre-compiles them all, so mixed zoo traffic "
        "never eats a compile stall mid-flood",
    )
    parser.add_argument("--fov", type=float, default=90.0)
    parser.add_argument(
        "--extra_config", default=None,
        help="JSON dot-key overrides layered over the archived params.yaml",
    )
    parser.add_argument(
        "--no-warmup", action="store_true",
        help="skip pre-compiling the default bucket before binding",
    )
    parser.add_argument(
        "--allow-random-init", action="store_true",
        help="serve untrained weights when no checkpoint exists (smoke only)",
    )
    parser.add_argument(
        "--no-trace", action="store_true",
        help="disable request-lifecycle host spans (/debug/trace serves an "
        "empty trace; the trace-counter metric family stays at 0)",
    )
    parser.add_argument(
        "--peer", action="append", default=[], metavar="NAME=URL",
        help="fleet peer replica (repeatable; include THIS replica too and "
        "name it with --peer-name). On a local cache miss the server asks "
        "the digest's ring owner for the compressed MPI (GET /mpi/<key>) "
        "before re-running the encoder — cache capacity becomes "
        "fleet-wide.",
    )
    parser.add_argument(
        "--peer-name", default=None,
        help="this replica's name inside the --peer set",
    )
    parser.add_argument(
        "--watch-last-good", type=float, default=0.0, metavar="SECS",
        help="poll the workspace's last_good pointer every SECS seconds "
        "and hot-swap to newer vetted checkpoints (0 disables; "
        "POST /admin/swap always works regardless)",
    )
    parser.add_argument(
        "--peak-flops", type=float, default=0.0,
        help="peak FLOP/s for the MFU gauge when the device kind has no "
        "published table entry (obs/cost.py) — e.g. a CPU smoke",
    )
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    peers = {}
    for spec in args.peer:
        name, _, url = spec.partition("=")
        if not name or not url:
            parser.error(f"--peer must be NAME=URL, got {spec!r}")
        peers[name] = url
    if peers and (not args.peer_name or args.peer_name not in peers):
        parser.error(
            f"--peer-name must name this replica inside the --peer set "
            f"(got {args.peer_name!r}, peers {sorted(peers)})"
        )

    from mine_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    from mine_tpu.training.checkpoint import load_for_serving

    cfg, params, batch_stats, step = load_for_serving(
        args.workspace, overrides=args.extra_config,
        allow_random_init=args.allow_random_init,
    )
    extra_buckets = [
        tuple(int(v) for v in spec.split(",")) for spec in args.bucket
    ]
    if args.zoo_buckets:
        from mine_tpu.data.conformance.contract import ZOO_BUCKETS

        extra_buckets.extend(ZOO_BUCKETS)
    app = ServingApp(
        cfg, params, batch_stats, checkpoint_step=step,
        cache_bytes=args.cache_mb << 20, max_delay_ms=args.max_delay_ms,
        max_batch_poses=args.max_batch_poses, fov_deg=args.fov,
        allowed_buckets=extra_buckets,
        trace_enabled=not args.no_trace,
        peak_flops_override=args.peak_flops,
        swap_source=args.workspace,
        peers=peers or None, peer_name=args.peer_name,
    )
    if args.watch_last_good > 0:
        # a training job advancing the workspace's last_good pointer
        # (resilience/preempt.py + sentinel vetting) continuously promotes
        # vetted weights into this live server via the hot-swap path
        app.start_promotion_watch(interval_s=args.watch_last_good)
    # flight recorder: SIGTERM/SIGUSR1 dump thread stacks + the last-K
    # request spans to the workspace sidecar (no stall watchdog here — an
    # idle server is healthy, unlike a training step that stopped)
    from mine_tpu.obs import FlightRecorder
    from mine_tpu.training.checkpoint import local_sidecar_dir

    flight = FlightRecorder(
        os.path.join(local_sidecar_dir(args.workspace), "flight"),
        tracer=app.tracer,
        # health + the last HBM sample (obs/memlog.py): what was resident
        # when it died rides every dump's meta.json
        get_status=lambda: {**app.health(), "hbm": app.memlog.last()},
    ).start()
    if not args.no_warmup:
        built = app.engine.warmup(specs=sorted(app.allowed_buckets))
        print(f"warmup: {built} executables compiled "
              f"(buckets {sorted(app.allowed_buckets)})")
    server = make_server(app, args.host, args.port, verbose=args.verbose)
    host, port = server.server_address[:2]
    print(f"serving checkpoint step {step} on http://{host}:{port} "
          f"(/predict /render /healthz /metrics /debug/trace)")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
        app.close()
        flight.stop()


if __name__ == "__main__":
    main()
