"""Predict-once / render-many novel-view video generation.

Reference: visualizations/image_to_video.py:92-257 (VideoGenerator). The
network pass runs once per image; every frame after that is warp + composite
only (the reference's key inference property, SURVEY.md §3.3). TPU redesign:
instead of the reference's per-pose eager loop (:227-245), the whole pose
trajectory renders inside ONE jitted `lax.map` — one compile, on-device frame
loop, a single device->host transfer of the finished uint8-ready stack.

The stale `render_pose` path of the reference (undefined `self.mpi_all_src`,
image_to_video.py:206-219) is deliberately not replicated.
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from mine_tpu import ops
from mine_tpu.config import Config
from mine_tpu.inference.trajectory import camera_trajectories
from mine_tpu.training.step import (
    build_model,
    make_disparity_list,
    render_novel_view,
)
from mine_tpu.utils import normalize_disparity_for_vis


def fov_intrinsics(height: int, width: int, fov_deg: float = 90.0) -> np.ndarray:
    """Pinhole K for a given horizontal FoV, principal point at the center
    (image_to_video.py:194-204: the single-image app fakes a fov-90 camera)."""
    fov = math.radians(fov_deg)
    fx = width * 0.5 / math.tan(fov * 0.5)
    return np.array(
        [[fx, 0.0, width * 0.5], [0.0, fx, height * 0.5], [0.0, 0.0, 1.0]],
        dtype=np.float32,
    )


def prepare_image(image: np.ndarray, height: int, width: int) -> Array:
    """HWC numpy image (uint8 or float in [0,1]) -> (1, height, width, 3)
    float32, bilinear-resized (reference resizes with cv2 INTER_LINEAR,
    image_to_video.py:104)."""
    img = np.asarray(image)
    if img.ndim != 3 or img.shape[-1] != 3:
        raise ValueError(f"expected (H, W, 3) rgb image, got shape {img.shape}")
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    img = jnp.asarray(img, jnp.float32)[None]
    if img.shape[1:3] != (height, width):
        img = jax.image.resize(img, (1, height, width, 3), method="bilinear")
    return jnp.clip(img, 0.0, 1.0)


def render_many_fn(
    cfg: Config,
    mpi_rgb: Array,
    mpi_sigma: Array,
    disparity: Array,
    k: Array,
    poses: Array,
) -> tuple[Array, Array]:
    """Render one source MPI into every pose of a trajectory (pure function;
    `render_many` is its module-level jit, the serving engine compiles its
    own per-bucket executables from this — mine_tpu/serving/engine.py).

    poses: (N, 4, 4) G_tgt_src stack. Returns (rgb (N, H, W, 3),
    disparity (N, H, W, 1)), all computed in one jitted on-device `lax.map`
    (the reference's per-frame python loop, image_to_video.py:227-245).
    Intrinsics are shared between source and target (single-image app).

    The per-pose warp+composite resolves cfg.mpi.compositor inside
    render_novel_view: with "streaming" each frame's (S, H, W, C) warped
    slab is never materialized (ops/mpi_render.py), which is what lets the
    serving engine grow its resident-MPI render buckets
    (serving/engine.py defaults its bucket configs to streaming).
    """
    k_inv = ops.inverse_3x3(k)

    def one_pose(g: Array) -> tuple[Array, Array]:
        out = render_novel_view(
            cfg, mpi_rgb, mpi_sigma, disparity, g[None], k_inv, k,
            scale_factor=None,  # reference passes 1.0 (image_to_video.py:236)
        )
        return out["tgt_imgs_syn"][0], out["tgt_disparity_syn"][0]

    return lax.map(one_pose, poses)


# cfg is a static (hashable) argument, so each (config, trajectory length)
# pair compiles once and the MPI/pose arrays stay runtime inputs
render_many = partial(jax.jit, static_argnums=0)(render_many_fn)


def normalize_disparity(disparity: np.ndarray) -> np.ndarray:
    """Per-frame min-max normalization to [0, 1] for visualization
    (image_to_video.py:53-63; shares the TB-vis helper, utils/logging.py)."""
    return np.clip(normalize_disparity_for_vis(disparity), 0.0, 1.0)


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[0,1] float -> uint8 (image_to_video.py:66-75)."""
    return np.clip(np.round(np.asarray(img) * 255.0), 0, 255).astype(np.uint8)


def colorize_heat(gray_u8: np.ndarray) -> np.ndarray:
    """(..., H, W) uint8 -> (..., H, W, 3) rgb heat colormap (the reference's
    cv2.COLORMAP_HOT disparity vis, image_to_video.py:73-74); grayscale
    fallback when cv2 is unavailable."""
    try:
        import cv2
    except ImportError:
        return np.repeat(gray_u8[..., None], 3, axis=-1)
    flat = gray_u8.reshape(-1, *gray_u8.shape[-2:])
    out = np.stack(
        [
            cv2.cvtColor(cv2.applyColorMap(f, cv2.COLORMAP_HOT), cv2.COLOR_BGR2RGB)
            for f in flat
        ]
    )
    return out.reshape(*gray_u8.shape, 3)


def write_video(frames: np.ndarray, path: str, fps: int = 30) -> str:
    """Write (N, H, W, 3) uint8 rgb frames to mp4 (cv2 backend); falls back to
    a PNG sequence directory when no mp4 encoder exists (this image has no
    ffmpeg; the reference uses moviepy, image_to_video.py:248-257).

    Returns the path actually written (the .mp4, or the PNG directory).
    """
    frames = np.asarray(frames)
    assert frames.dtype == np.uint8 and frames.ndim == 4 and frames.shape[-1] == 3, (
        f"write_video wants (N, H, W, 3) uint8 frames, got {frames.dtype} "
        f"{frames.shape}"
    )
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    try:
        import cv2

        h, w = frames.shape[1:3]
        writer = cv2.VideoWriter(
            path, cv2.VideoWriter_fourcc(*"mp4v"), fps, (w, h)
        )
        if writer.isOpened():
            for frame in frames:
                writer.write(frame[..., ::-1])  # rgb -> bgr
            writer.release()
            return path
    except ImportError:
        pass
    import imageio.v3 as iio

    frame_dir = os.path.splitext(path)[0]
    os.makedirs(frame_dir, exist_ok=True)
    for i, frame in enumerate(frames):
        iio.imwrite(os.path.join(frame_dir, f"{i:04d}.png"), frame)
    return frame_dir


def _blend_src_rgb(
    cfg: Config, img: Array, mpi_rgb: Array, mpi_sigma: Array,
    disparity: Array, k: Array,
) -> Array:
    """Src RGB blending (image_to_video.py:145-156): plane RGB is replaced
    by the real source pixels wherever the source view sees them; network
    RGB survives only where occluded. The single blend home for both the
    single-pass and coarse-to-fine predicts."""
    _, _, blend_weights, _ = ops.render_src(
        mpi_rgb, mpi_sigma, disparity, ops.inverse_3x3(k),
        use_alpha=cfg.mpi.use_alpha,
        is_bg_depth_inf=cfg.mpi.is_bg_depth_inf,
    )
    return blend_weights * img[:, None] + (1.0 - blend_weights) * mpi_rgb


def predict_blended_mpi_fn(
    cfg: Config, variables: Any, img: Array, disparity: Array, k: Array
) -> tuple[Array, Array]:
    """One network pass + src RGB blending (image_to_video.py:136-156).
    Pure function; `predict_blended_mpi` is its module-level jit (repeated
    VideoGenerators with one config compile once) and the serving engine
    AOT-compiles per-bucket executables from it (serving/engine.py)."""
    model = build_model(cfg)
    mpi = model.apply(variables, img, disparity, False)[0]
    mpi_rgb, mpi_sigma = mpi[..., 0:3], mpi[..., 3:4]
    mpi_rgb = _blend_src_rgb(cfg, img, mpi_rgb, mpi_sigma, disparity, k)
    return mpi_rgb, mpi_sigma


predict_blended_mpi = partial(jax.jit, static_argnums=0)(predict_blended_mpi_fn)


def predict_blended_mpi_c2f_fn(
    cfg: Config, variables: Any, img: Array, k: Array
) -> tuple[Array, Array, Array]:
    """Coarse-to-fine predict (two network passes over coarse + PDF-refined
    planes, training/step.py forward_coarse_to_fine) + src RGB blending.
    Returns (mpi_rgb, mpi_sigma, merged_disparity) — the plane count is
    num_bins_coarse + num_bins_fine, so the caller must render with the
    RETURNED disparity, not its own list. The reference ships this path
    dead (params_default.yaml:30) and its inference app has no analog;
    evaluating a c2f-trained model any other way would score a different
    operating point than the one trained."""
    from mine_tpu.training.step import forward_coarse_to_fine

    model = build_model(cfg)
    fixed_cfg = cfg.replace(**{"mpi.fix_disparity": True})
    mpis, disparity, _ = forward_coarse_to_fine(
        fixed_cfg, model, variables["params"], variables["batch_stats"],
        img, ops.inverse_3x3(k),
        key_disparity=jax.random.PRNGKey(0),
        key_fine=jax.random.PRNGKey(1), train=False,
    )
    mpi = mpis[0]
    mpi_rgb, mpi_sigma = mpi[..., 0:3], mpi[..., 3:4]
    mpi_rgb = _blend_src_rgb(cfg, img, mpi_rgb, mpi_sigma, disparity, k)
    return mpi_rgb, mpi_sigma, disparity


predict_blended_mpi_c2f = partial(jax.jit, static_argnums=0)(
    predict_blended_mpi_c2f_fn
)


class VideoGenerator:
    """Predict an MPI from one image, then render camera-path videos
    (image_to_video.py:92-257)."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        batch_stats: Any,
        image: np.ndarray,
        fov_deg: float = 90.0,
    ):
        self.cfg = cfg
        h, w = cfg.data.img_h, cfg.data.img_w
        self.img = prepare_image(image, h, w)
        self.k = jnp.asarray(fov_intrinsics(h, w, fov_deg))[None]

        variables = {"params": params, "batch_stats": batch_stats}
        if cfg.mpi.num_bins_fine > 0:
            # a c2f-trained model must be rendered at its merged plane list
            self.mpi_rgb, self.mpi_sigma, self.disparity = (
                predict_blended_mpi_c2f(cfg, variables, self.img, self.k)
            )
        else:
            # Inference planes are deterministic: the fix_disparity branch
            # of the shared sampler (linspace, or the explicit bin list when
            # configured — synthesis_task.py:36-45).
            fixed_cfg = cfg.replace(**{"mpi.fix_disparity": True})
            self.disparity = make_disparity_list(
                fixed_cfg, jax.random.PRNGKey(0), 1
            )
            self.mpi_rgb, self.mpi_sigma = predict_blended_mpi(
                cfg, variables, self.img, self.disparity, self.k
            )

    def render_poses(self, poses: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Render (N, 4, 4) poses -> (rgb (N,H,W,3) float [0,1],
        disparity (N,H,W,1) float, unnormalized)."""
        rgb, disp = render_many(
            self.cfg, self.mpi_rgb, self.mpi_sigma, self.disparity,
            self.k, jnp.asarray(poses),
        )
        return np.asarray(jax.device_get(rgb)), np.asarray(jax.device_get(disp))

    def render_videos(self, output_dir: str, basename: str) -> list[str]:
        """Render every preset trajectory for this dataset and write
        <basename>_<traj>_{rgb,disp} videos (image_to_video.py:221-257).
        Returns the written paths."""
        trajectories, fps = camera_trajectories(self.cfg.data.name)
        written = []
        for name, poses in trajectories:
            rgb, disp = self.render_poses(poses)
            rgb_u8 = to_uint8(rgb)
            disp_u8 = colorize_heat(to_uint8(normalize_disparity(disp))[..., 0])
            written.append(write_video(
                rgb_u8, os.path.join(output_dir, f"{basename}_{name}_rgb.mp4"), fps
            ))
            written.append(write_video(
                disp_u8, os.path.join(output_dir, f"{basename}_{name}_disp.mp4"), fps
            ))
        return written


def load_video_generator(
    workspace: str,
    image: np.ndarray,
    fov_deg: float = 90.0,
    allow_random_init: bool = False,
) -> VideoGenerator:
    """Build a VideoGenerator from a training workspace: config from the
    paired params.yaml, weights from the newest orbax checkpoint
    (image_to_video.py:273-285; checkpoint+config travel as a pair)."""
    import jax.random as jrandom

    from mine_tpu.training import checkpoint as ckpt
    from mine_tpu.training.optimizer import make_optimizer
    from mine_tpu.training.step import init_state

    cfg = ckpt.load_paired_config(workspace)
    model = build_model(cfg)
    tx = make_optimizer(cfg, steps_per_epoch=1)
    # template only — the restore overwrites it, so don't require the
    # training-time pretrained .npz to exist on this host
    template = init_state(cfg, model, tx, jrandom.PRNGKey(0), load_pretrained=False)
    manager = ckpt.checkpoint_manager(workspace)
    state, step = ckpt.restore(manager, template)
    if step == 0 and not allow_random_init:
        raise FileNotFoundError(
            f"no checkpoint found under {workspace}/checkpoints "
            "(pass allow_random_init=True for an untrained smoke run)"
        )
    return VideoGenerator(cfg, state.params, state.batch_stats, image, fov_deg)
