"""Inference application: single image -> camera-path novel-view video.

Reference: visualizations/image_to_video.py. The key property preserved from
the reference (SURVEY.md §3.3): the expensive network pass runs ONCE; each
frame costs only warp + composite. The TPU redesign goes further — the whole
trajectory renders inside one jitted `lax.map`, so per-frame work is one
compiled program with a single host transfer at the end, instead of the
reference's per-frame eager dispatch loop.
"""

from mine_tpu.inference.trajectory import (
    TRAJECTORY_PRESETS,
    path_planning,
    trajectory_preset,
    camera_trajectories,
)
from mine_tpu.inference.video import (
    VideoGenerator,
    fov_intrinsics,
    load_video_generator,
    normalize_disparity,
    predict_blended_mpi,
    predict_blended_mpi_fn,
    render_many,
    render_many_fn,
    to_uint8,
    write_video,
)
