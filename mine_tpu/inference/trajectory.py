"""Camera-path generation for novel-view videos.

Reference: visualizations/image_to_video.py:24-50 (path_planning) and
:158-192 (per-dataset shift ranges). Pure numpy on the host — trajectories
are tiny (N,3) arrays; only the renderer runs on device.
"""

from __future__ import annotations

import numpy as np

# Per-dataset trajectory recipes (image_to_video.py:158-177). Keys are
# config `data.name` values; every supported dataset renders a zoom-in
# (double-straight-line) and a swing (circle).
_DEFAULT_PRESET = {
    "fps": 30,
    "num_frames": 90,
    "x_shift_range": (0.0, -0.16),
    "y_shift_range": (0.0, -0.0),
    "z_shift_range": (-0.30, -0.2),
    "traj_types": ("double-straight-line", "circle"),
    "name": ("zoom-in", "swing"),
}
TRAJECTORY_PRESETS: dict[str, dict] = {
    "kitti_raw": {
        **_DEFAULT_PRESET,
        "x_shift_range": (0.0, -0.8),
        "z_shift_range": (-1.5, -1.0),
    },
    **{
        name: dict(_DEFAULT_PRESET)
        for name in (
            "nyu", "ibims", "realestate10k", "llff", "objectron",
            "nocs_llff", "synthetic",
        )
    },
}


def trajectory_preset(dataset_name: str) -> dict:
    """Shift ranges / fps / frame count for a dataset (image_to_video.py:158-177)."""
    try:
        return dict(TRAJECTORY_PRESETS[dataset_name])
    except KeyError:
        raise ValueError(
            f"no trajectory preset for dataset {dataset_name!r}; "
            f"known: {sorted(TRAJECTORY_PRESETS)}"
        ) from None


def _quadratic_through(points: np.ndarray, t: np.ndarray) -> np.ndarray:
    """Quadratic Lagrange interpolation through 3 points at t=0, .5, 1
    (the scipy interp1d(kind='quadratic') call at image_to_video.py:29,
    without the scipy dependency)."""
    p0, p1, p2 = points
    l0 = (t - 0.5) * (t - 1.0) / ((0.0 - 0.5) * (0.0 - 1.0))
    l1 = (t - 0.0) * (t - 1.0) / ((0.5 - 0.0) * (0.5 - 1.0))
    l2 = (t - 0.0) * (t - 0.5) / ((1.0 - 0.0) * (1.0 - 0.5))
    return l0[:, None] * p0 + l1[:, None] * p1 + l2[:, None] * p2


def path_planning(
    num_frames: int, x: float, y: float, z: float, path_type: str, s: float = 0.3
) -> np.ndarray:
    """Camera-center offsets along a canned path, (N, 3) float64
    (image_to_video.py:24-50; N == num_frames for straight-line/circle,
    2 * (num_frames // 2) for double-straight-line — same as the reference's
    concat of two int(num_frames*0.5) halves)."""
    shift = np.array([x, y, z], dtype=np.float64)
    if path_type == "straight-line":
        corners = np.stack([np.zeros(3), 0.5 * shift, shift])
        t = np.linspace(0.0, 1.0, num_frames)
        return _quadratic_through(corners, t)
    if path_type == "double-straight-line":
        # linear from s*shift out to -shift, then retrace backwards
        t = np.linspace(0.0, 1.0, int(num_frames * 0.5))
        fwd = (1.0 - t)[:, None] * (s * shift)[None] + t[:, None] * (-shift)[None]
        return np.concatenate([fwd, np.flip(fwd, axis=0)], axis=0)
    if path_type == "circle":
        v = np.arange(-2.0, 2.0, 4.0 / num_frames)
        xs = np.cos(v * np.pi) * x
        ys = np.sin(v * np.pi) * y
        zs = np.cos(v * np.pi / 2.0) * z - s * z
        return np.stack([xs, ys, zs], axis=-1)
    raise ValueError(f"unknown path type {path_type!r}")


def poses_from_offsets(offsets: np.ndarray) -> np.ndarray:
    """Offsets (N, 3) -> G_tgt_src stack (N, 4, 4): identity rotation with the
    offset as translation (image_to_video.py:179-191)."""
    n = offsets.shape[0]
    poses = np.tile(np.eye(4, dtype=np.float32)[None], (n, 1, 1))
    poses[:, :3, 3] = offsets.astype(np.float32)
    return poses


def camera_trajectories(dataset_name: str) -> tuple[list[tuple[str, np.ndarray]], int]:
    """All canned trajectories for a dataset.

    Returns ([(name, poses (N,4,4)), ...], fps) — one entry per preset
    trajectory type (zoom-in, swing).
    """
    preset = trajectory_preset(dataset_name)
    out = []
    for i, traj_type in enumerate(preset["traj_types"]):
        offsets = path_planning(
            preset["num_frames"],
            preset["x_shift_range"][i],
            preset["y_shift_range"][i],
            preset["z_shift_range"][i],
            path_type=traj_type,
        )
        out.append((preset["name"][i], poses_from_offsets(offsets)))
    return out, preset["fps"]
