"""Training subsystem: state, optimizer, jitted step (reference:
synthesis_task.py + train.py)."""

from mine_tpu.training.state import TrainState
from mine_tpu.training.optimizer import make_optimizer, learning_rates
from mine_tpu.training.step import (
    build_model,
    make_disparity_list,
    forward_coarse_to_fine,
    render_novel_view,
    loss_fcn_per_scale,
    loss_fcn,
    make_train_step,
    make_eval_step,
    init_state,
)
