"""The training loop: epochs, logging, eval, checkpointing.

Reference: synthesis_task.py train/train_epoch/run_eval (:609-690, :496-527)
+ train.py main/train (:167-216). Differences by design (SURVEY.md §5.3-5.5,
§7.5): eval runs on every replica (not rank 0 only); checkpoints carry
step/optimizer/PRNG for bitwise resume and auto-resume from the workspace;
every log line carries imgs/sec; loss fetches happen once per log interval so
steps stay fully async on device.
"""

from __future__ import annotations

import os
from typing import Any, Iterable

import jax
import numpy as np

from mine_tpu.config import Config
from mine_tpu.data import prefetch
from mine_tpu.losses import load_lpips_params
from mine_tpu.parallel import (
    DATA_AXIS,
    init_multihost,
    make_mesh,
    make_parallel_eval_step,
    make_parallel_train_step,
    model_axes,
    replicate_state,
    shard_batch,
)
from mine_tpu.training import checkpoint as ckpt
from mine_tpu.training.optimizer import learning_rates, make_optimizer
from mine_tpu.training.step import build_model, init_state
from mine_tpu.utils import (
    AverageMeter,
    MetricWriter,
    StepTimer,
    make_logger,
    normalize_disparity_for_vis,
)

LOSS_KEYS = (
    "loss", "loss_rgb_src", "loss_ssim_src", "loss_disp_pt3dsrc",
    "loss_smooth_src", "loss_smooth_tgt", "loss_smooth_src_v2",
    "loss_smooth_tgt_v2", "loss_rgb_tgt", "loss_ssim_tgt", "lpips_tgt",
    "psnr_tgt", "loss_disp_pt3dtgt",
)


def staged_batches(mesh, num_workers: int, epoch_iter: Iterable[dict]) -> Iterable[dict]:
    """Two-stage pipeline overlap (SURVEY.md §7.4.7; the reference builds
    every batch synchronously in the step loop, nerf_dataset.py:199-236):
    host batches are produced up to `num_workers` ahead, but at most 2 of
    them are device-staged (shard_batch) at a time — double-buffered H2D
    without pinning num_workers full batches in HBM."""
    host = prefetch(epoch_iter, max(num_workers - 2, 0))
    return prefetch(
        host, min(num_workers, 2), transfer=lambda b: shard_batch(mesh, b)
    )


class Trainer:
    """Owns mesh, model, state, and the jitted steps; `fit` runs epochs."""

    def __init__(self, cfg: Config, workspace: str, profile_steps: int = 0):
        init_multihost()
        self.cfg = cfg
        self.workspace = workspace
        # URL-scheme workspaces (gs://…) are valid for checkpoints (orbax
        # writes them remotely); params.yaml / logs / TB events / profiler
        # traces use plain file IO and land in a derived local dir instead
        self.local_dir = ckpt.local_sidecar_dir(workspace)
        self.profile_steps = profile_steps
        self.mesh = make_mesh(cfg.mesh.data_parallel, cfg.mesh.plane_parallel)
        self.logger = make_logger(self.local_dir)
        self.writer = MetricWriter(self.local_dir)
        self.model = build_model(cfg, **model_axes(self.mesh))
        self.global_batch = cfg.data.per_gpu_batch_size * self.mesh.shape[DATA_AXIS]
        if jax.process_index() == 0:
            os.makedirs(self.local_dir, exist_ok=True)
            ckpt.save_paired_config(cfg, self.local_dir)
            if self.local_dir != workspace:
                self.logger.info(
                    "workspace %s is remote: checkpoints go there via orbax; "
                    "params.yaml/logs/tensorboard/profiles go to %s",
                    workspace, self.local_dir,
                )

    def _staged_batches(self, epoch_iter: Iterable[dict]) -> Iterable[dict]:
        return staged_batches(self.mesh, self.cfg.data.num_workers, epoch_iter)

    def fit(self, train_ds: Any, val_ds: Any | None = None) -> dict[str, float]:
        cfg = self.cfg
        steps_per_epoch = len(train_ds)
        tx = make_optimizer(cfg, steps_per_epoch)
        manager = ckpt.checkpoint_manager(
            self.workspace,
            keep_period=max(cfg.training.eval_interval // cfg.training.checkpoint_interval, 1),
        )
        # pretrained backbone weights only matter on a fresh start; on resume
        # or warm start the restore overwrites them, and the .npz need not
        # exist on this host
        resuming = (
            manager.latest_step() is not None
            or bool(cfg.training.pretrained_checkpoint_path)
        )
        state = init_state(
            cfg, self.model, tx, jax.random.PRNGKey(cfg.training.seed),
            load_pretrained=not resuming,
        )
        # auto-resume from this workspace; else warm-start from a path
        state, start_step = ckpt.restore(manager, state)
        warm_path = cfg.training.pretrained_checkpoint_path
        if start_step == 0 and warm_path:
            if warm_path.endswith(".npz"):
                # a converted MINE torch checkpoint (backbone + decoder from
                # tools/convert_mine_checkpoint.py): weights transfer, the
                # optimizer/step/RNG start fresh — the reference's
                # restore_model semantics (utils.py:40-67), strictly checked
                from mine_tpu.models import apply_pretrained_npz

                # training.pretrained_subtrees defaults to the full
                # (backbone, decoder) checkpoint; ("backbone",) accepts a
                # backbone-only artifact (partial-restore escape hatch —
                # the strict analog of the reference's strict=False load)
                variables = apply_pretrained_npz(
                    {"params": state.params, "batch_stats": state.batch_stats},
                    warm_path,
                    expect_subtrees=cfg.training.pretrained_subtrees,
                )
                state = state.replace(
                    params=variables["params"],
                    batch_stats=variables["batch_stats"],
                )
                self.logger.info("warm-started from converted %s", warm_path)
            else:
                warm = ckpt.checkpoint_manager(warm_path)
                state, warm_step = ckpt.restore(warm, state)
                if warm_step == 0:
                    # restore() returns the template silently; a typo'd
                    # warm-start path must not degrade into training from
                    # random init
                    raise FileNotFoundError(
                        "training.pretrained_checkpoint_path="
                        f"{warm_path!r} contains no checkpoint"
                    )
                self.logger.info(
                    "warm-started from %s @ step %d", warm_path, warm_step
                )
        state = replicate_state(state, self.mesh)

        lpips_params = load_lpips_params(cfg.training.lpips_weights_path)
        train_step = make_parallel_train_step(cfg, self.model, tx, self.mesh)
        eval_step = make_parallel_eval_step(cfg, self.model, self.mesh, lpips_params)

        meters = {k: AverageMeter(k) for k in LOSS_KEYS}
        timer = StepTimer(self.global_batch)
        start_epoch = start_step // steps_per_epoch + 1

        if start_step:
            self.logger.info("resumed from step %d (epoch %d)", start_step, start_epoch)
        self.logger.info(
            "training on mesh %s, global batch %d, %d steps/epoch",
            dict(self.mesh.shape), self.global_batch, steps_per_epoch,
        )

        self._live_state = state  # emergency-save target from the first step on
        try:
            last_val = self._fit_epochs(
                cfg, train_ds, val_ds, state, train_step, eval_step,
                manager, meters, timer, start_step,
            )
        except (KeyboardInterrupt, Exception):
            # failure containment (SURVEY.md §5.3 — the reference has none):
            # whatever just died, persist the last completed step so the next
            # run auto-resumes instead of losing the epoch. The emergency save
            # itself may fail (e.g. the device poisoned the state arrays) —
            # never let that mask the original error.
            try:
                host_state = jax.device_get(self._live_state)
                step_now = int(host_state.step)
                self.logger.exception(
                    "training interrupted at step %d; writing emergency "
                    "checkpoint", step_now,
                )
                ckpt.save(manager, host_state, step_now)
                ckpt.wait_until_finished(manager)
            except BaseException:  # noqa: BLE001 - incl. a second Ctrl+C
                self.logger.exception("emergency checkpoint failed")
            raise
        finally:
            self._live_state = None  # don't pin the state in HBM after fit
        return last_val

    def _fit_epochs(
        self, cfg, train_ds, val_ds, state, train_step, eval_step,
        manager, meters, timer, start_step,
    ) -> dict[str, float]:
        steps_per_epoch = len(train_ds)
        global_step = start_step
        start_epoch = start_step // steps_per_epoch + 1
        last_val: dict[str, float] = {}
        for epoch in range(start_epoch, cfg.training.epochs + 1):
            for m in meters.values():
                m.reset()
            batches = self._staged_batches(train_ds.epoch(epoch))
            for step_in_epoch, batch in enumerate(batches, start=1):
                if self.profile_steps and global_step == start_step + 5:
                    jax.profiler.start_trace(os.path.join(self.local_dir, "profile"))
                state, loss_dict = train_step(state, batch)
                self._live_state = state  # for the emergency checkpoint
                global_step += 1
                timer.tick()
                if self.profile_steps and global_step == start_step + 5 + self.profile_steps:
                    jax.block_until_ready(loss_dict["loss"])
                    jax.profiler.stop_trace()
                    self.logger.info("profile trace written to %s/profile", self.local_dir)

                if step_in_epoch % cfg.training.log_interval == 0:
                    # one transfer for the whole dict: per-key float() would
                    # block on a device sync PER KEY per log step
                    host_losses = {
                        k: float(v)
                        for k, v in jax.device_get(
                            {k: loss_dict[k] for k in LOSS_KEYS}
                        ).items()
                    }
                    for k, v in host_losses.items():
                        meters[k].update(v, cfg.training.log_interval)
                    lrs = learning_rates(cfg, steps_per_epoch, global_step)
                    rate = timer.rate_and_reset()
                    self.logger.info(
                        "epoch [%03d] step [%d/%d] global_step=%d "
                        "loss=%.4f rgb_tgt=%.4f ssim_tgt=%.4f disp_src=%.4f "
                        "psnr=%.2f lr=%.6f imgs/sec=%.1f",
                        epoch, step_in_epoch, steps_per_epoch, global_step,
                        host_losses["loss"], host_losses["loss_rgb_tgt"],
                        host_losses["loss_ssim_tgt"], host_losses["loss_disp_pt3dsrc"],
                        host_losses["psnr_tgt"], lrs["backbone_lr"], rate,
                    )
                    self.writer.scalars(host_losses, global_step, prefix="train/")
                    self.writer.scalar("train/imgs_per_sec", rate, global_step)
                    self.writer.scalar("train/backbone_lr", lrs["backbone_lr"], global_step)

                if global_step % cfg.training.checkpoint_interval == 0:
                    ckpt.save(manager, jax.device_get(state), global_step)
                    self.logger.info("checkpoint saved @ step %d", global_step)

                if val_ds is not None and (
                    global_step == 2000  # reference quirk: first eval at 2000
                    or global_step % cfg.training.eval_interval == 0
                ):
                    last_val = self.evaluate(eval_step, state, val_ds, global_step)

            # end-of-epoch summary from the meters (log-interval samples,
            # weighted by interval) — the running averages the reference
            # accumulates but never reports (synthesis_task.py:146-167)
            if any(m.count for m in meters.values()):
                epoch_avg = {k: m.avg for k, m in meters.items()}
                self.logger.info(
                    "epoch [%03d] avg: loss=%.4f rgb_tgt=%.4f ssim_tgt=%.4f "
                    "psnr=%.2f",
                    epoch, epoch_avg["loss"], epoch_avg["loss_rgb_tgt"],
                    epoch_avg["loss_ssim_tgt"], epoch_avg["psnr_tgt"],
                )
                self.writer.scalars(epoch_avg, global_step, prefix="train_epoch/")

        ckpt.save(manager, jax.device_get(state), global_step)
        ckpt.wait_until_finished(manager)
        self.writer.flush()
        return last_val

    def evaluate(self, eval_step, state, val_ds: Any, global_step: int) -> dict[str, float]:
        """Full-val-set metric pass (synthesis_task.py:496-527)."""
        return run_evaluation(
            self.cfg, self.mesh, self.logger, self.writer,
            eval_step, state, val_ds, global_step,
        )


def run_evaluation(
    cfg: Config, mesh, logger, writer, eval_step, state, val_ds: Any,
    global_step: int,
) -> dict[str, float]:
    """The metric pass itself, shared by the train loop's eval intervals and
    the standalone `python -m mine_tpu.evaluate` CLI (the reference can only
    evaluate from inside a training job, synthesis_task.py:660-663)."""
    meters = {k: AverageMeter(k) for k in LOSS_KEYS}
    key = jax.random.PRNGKey(cfg.training.seed + 17)
    viz = None
    n_examples = 0
    for i, batch in enumerate(staged_batches(mesh, cfg.data.num_workers, val_ds.epoch(0))):
        loss_dict, viz = eval_step(state, batch, jax.random.fold_in(key, i))
        # metric values are weighted means over GENUINE examples only
        # (wrap-padded slots carry eval_weight 0, training/step.py
        # make_eval_step); weighting the meter by the genuine count matches
        # the reference's update(..., n=B) over its ragged final batch
        n_batch = int(round(float(loss_dict["eval_examples"])))
        n_examples += n_batch
        for k in LOSS_KEYS:
            meters[k].update(float(loss_dict[k]), n=n_batch)
    expected = getattr(val_ds, "num_eval_examples", None)
    if expected is not None and n_examples != expected:
        raise RuntimeError(
            f"eval example count mismatch: metered {n_examples}, dataset "
            f"holds {expected} — the wrap-pad mask is miscounting"
        )
    result = {k: m.avg for k, m in meters.items()}
    logger.info(
        "eval @ %d: " + " ".join(f"{k}=%.4f" for k in ("loss", "loss_rgb_tgt", "psnr_tgt", "lpips_tgt")),
        global_step, *[result[k] for k in ("loss", "loss_rgb_tgt", "psnr_tgt", "lpips_tgt")],
    )
    writer.scalars(result, global_step, prefix="val/")
    if viz is not None:
        tgt = np.asarray(jax.device_get(viz["tgt_imgs_syn"]))[:4]
        src = np.asarray(jax.device_get(viz["src_imgs_syn"]))[:4]
        tgt_disp = normalize_disparity_for_vis(
            np.asarray(jax.device_get(viz["tgt_disparity_syn"]))[:4]
        )
        writer.image_grid("val/tgt_syn", tgt, global_step)
        writer.image_grid("val/src_syn", src, global_step)
        writer.image_grid("val/tgt_disparity", tgt_disp, global_step)
    writer.flush()
    return result
