"""The training loop: epochs, logging, eval, checkpointing.

Reference: synthesis_task.py train/train_epoch/run_eval (:609-690, :496-527)
+ train.py main/train (:167-216). Differences by design (SURVEY.md §5.3-5.5,
§7.5): eval runs on every replica (not rank 0 only); checkpoints carry
step/optimizer/PRNG for bitwise resume and auto-resume from the workspace;
every log line carries imgs/sec; loss fetches happen once per log interval so
steps stay fully async on device.

Observability (cfg.obs.*, mine_tpu/obs/): when enabled, every step is
broken into host spans (data/step/sync/log/ckpt) on a bounded ring with
Chrome-trace export next to the jax.profiler device traces; a flight
recorder dumps thread stacks + the last-K spans on SIGTERM/SIGUSR1 or a
stall; and the train step is AOT-compiled once so XLA's own cost analysis
feeds a live MFU gauge (utils/metrics.py registry + MetricWriter scalars).
Disabled (the default), the spans are shared no-op context managers and
none of it costs anything.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from itertools import islice
from typing import Any, Callable, Iterable

import jax
import numpy as np

from mine_tpu.config import Config
from mine_tpu.data import prefetch
from mine_tpu.losses import load_lpips_params
from mine_tpu.obs import FlightRecorder, MemLog, Tracer
from mine_tpu.obs.attrib import attach_cost_estimates, attribute_profile_dir
from mine_tpu.obs.cost import (
    achieved_fraction,
    compiled_cost,
    compute_mfu,
    resolve_peak_flops,
    resolve_peak_hbm_bytes,
)
from mine_tpu.parallel import (
    DATA_AXIS,
    FSDP_AXIS,
    data_replica_count,
    distribute_state,
    fsdp_enabled,
    host_batch_slice,
    make_mesh,
    make_parallel_eval_step,
    make_parallel_train_step,
    mesh_shape_str,
    model_axes,
    shard_batch,
    zero1_enabled,
)
from mine_tpu.parallel import rules as rules_mod
from mine_tpu.resilience import (
    MultihostSurvival,
    PreemptedError,
    PreemptionGuard,
    SentinelAbort,
    SentinelRollback,
    TrainingSentinel,
    chaos,
)
from mine_tpu.resilience import multihost as multihost_mod
from mine_tpu.training import checkpoint as ckpt
from mine_tpu.training.optimizer import learning_rates, make_optimizer
from mine_tpu.training.step import build_model, init_state
from mine_tpu.utils import (
    AverageMeter,
    MetricsRegistry,
    MetricWriter,
    make_logger,
    normalize_disparity_for_vis,
)

LOSS_KEYS = (
    "loss", "loss_rgb_src", "loss_ssim_src", "loss_disp_pt3dsrc",
    "loss_smooth_src", "loss_smooth_tgt", "loss_smooth_src_v2",
    "loss_smooth_tgt_v2", "loss_rgb_tgt", "loss_ssim_tgt", "lpips_tgt",
    "psnr_tgt", "loss_disp_pt3dtgt",
)


def staged_batches(
    mesh,
    num_workers: int,
    epoch_iter: Iterable[dict],
    retries: int = 0,
    on_retry: Callable[[int, BaseException], None] | None = None,
    rules: tuple | None = None,
    global_rows: int | None = None,
) -> Iterable[dict]:
    """Two-stage pipeline overlap (SURVEY.md §7.4.7; the reference builds
    every batch synchronously in the step loop, nerf_dataset.py:199-236):
    host batches are produced up to `num_workers` ahead, but at most 2 of
    them are device-staged (shard_batch) at a time — double-buffered H2D
    without pinning num_workers full batches in HBM.

    `retries` (data.loader_retries) bounds transient-error retries of the
    host stage (exponential backoff + jitter, data/pipeline.py), which also
    hosts the `loader_raise` chaos seam; the device-staging stage never
    retries (a failed device transfer is not a loader hiccup).

    `global_rows` is the GLOBAL batch size, threaded into shard_batch for
    multi-process runs: host batches may then be either this host's local
    slice (per-host loaders) or the full global batch (compat loaders —
    sliced down at staging, numerically identical)."""
    host = prefetch(
        epoch_iter, max(num_workers - 2, 0),
        retries=retries, on_retry=on_retry, fault_seam="loader_raise",
    )
    # `rules` is the config's partition-rule table: a `parallel.rules`
    # batch-row override must place host batches exactly where the compiled
    # step's table-derived in_shardings expect them (None = default table)
    return prefetch(
        host, min(num_workers, 2),
        transfer=lambda b: shard_batch(mesh, b, rules, global_rows=global_rows),
    )


class TrainObsMetrics:
    """Training's live gauge set on a utils/metrics.py registry — the
    queryable twin of the MetricWriter scalars (prefix: `mine_train_`)."""

    def __init__(self):
        self.registry = MetricsRegistry()
        r = self.registry
        self.mfu = r.gauge(
            "mine_train_mfu",
            "model FLOPs utilization: XLA cost-analysis FLOPs per step over "
            "measured step time, divided by the device peak",
        )
        self.tflops_per_sec = r.gauge(
            "mine_train_tflops_per_sec",
            "achieved model TFLOP/s of the compiled train step",
        )
        self.step_flops = r.gauge(
            "mine_train_step_flops",
            "FLOPs of one compiled train step (XLA cost analysis)",
        )
        self.hbm_fraction = r.gauge(
            "mine_train_achieved_hbm_fraction",
            "bytes-accessed per step over step time, divided by peak HBM "
            "bandwidth (absent when the peak is unknown)",
        )
        self.imgs_per_sec = r.gauge(
            "mine_train_imgs_per_sec", "global training throughput",
        )
        self.sync_wait_ms = r.gauge(
            "mine_train_sync_wait_ms",
            "wall time of the log-interval device_get sync (labeled by "
            "process_index). On multi-process runs the collectives block "
            "until the SLOWEST host, so a host whose sync wait is LOW "
            "while its peers' are high is the straggler everyone waits "
            "for — the per-host distribution is the straggler-attribution "
            "signal (resilience/multihost.py straggler_table)",
        )
        self.grad_norm = r.gauge(
            "mine_train_grad_norm",
            "global gradient norm at the latest logged step",
        )
        self.data_retries = r.counter(
            "mine_train_data_retries_total",
            "host batches retried after transient loader/staging errors "
            "(data.loader_retries; labeled by process_index so a pod-scale "
            "flaky mount is attributable to a host)",
        )
        self.data_host_bytes = r.counter(
            "mine_train_data_host_bytes_total",
            "bytes of host batch data THIS process materialized (labeled "
            "by process_index). Under per-host data sharding each of N "
            "hosts counts ~1/N of the global batch bytes; a host counting "
            "the full product is on the global-load-then-slice compat path",
        )
        self.accum_steps = r.gauge(
            "mine_train_accum_steps",
            "micro-batches accumulated per optimizer update "
            "(training.accum_steps)",
        )
        self.effective_batch = r.gauge(
            "mine_train_effective_batch",
            "examples per optimizer UPDATE across the whole mesh "
            "(per_gpu_batch_size x data_parallel; accumulation splits it "
            "into micro-batches, it does not multiply it)",
        )
        self.micro_step_flops = r.gauge(
            "mine_train_flops_per_micro_step",
            "step_flops / accum_steps: FLOPs of one micro-batch "
            "forward+backward (step_flops stays per UPDATE — the two "
            "gauges exist so neither is double-counted into the other)",
        )
        self.component_time_ms = r.gauge(
            "mine_train_component_time_ms",
            "device time per named component over the last captured "
            "profile window (obs/attrib.py; labels: component — encoder/"
            "decoder/homography_warp/composite/losses/optimizer/"
            "zero1_gather, plus the unattributed remainder)",
        )
        self.attrib_coverage = r.gauge(
            "mine_train_attrib_coverage",
            "fraction of profiled device time attributed to a named "
            "component (the table is only trustworthy >= 0.9)",
        )
        self.hbm_live_bytes = r.gauge(
            "mine_train_hbm_live_bytes",
            "device.memory_stats() bytes_in_use, max over local devices, "
            "sampled each log interval (obs/memlog.py; absent on backends "
            "without memory stats)",
        )
        self.hbm_peak_bytes = r.gauge(
            "mine_train_hbm_peak_bytes",
            "device.memory_stats() peak_bytes_in_use, max over local "
            "devices — the runtime's own high-water mark, unlike the "
            "per-executable memory_analysis figure",
        )


class Trainer:
    """Owns mesh, model, state, and the jitted steps; `fit` runs epochs."""

    def __init__(self, cfg: Config, workspace: str, profile_steps: int = 0):
        # multi-host bring-up FIRST (must precede any backend touch): the
        # retrying wrapper around init_multihost — a no-op on single-host
        # runs, bounded-backoff retry for a coordinator that is not up yet
        # (resilience/multihost.py bring_up)
        multihost_mod.bring_up(
            attempts=cfg.resilience.multihost_bringup_attempts,
            backoff_s=cfg.resilience.multihost_bringup_backoff_s,
        )
        self.cfg = cfg
        self.workspace = workspace
        # URL-scheme workspaces (gs://…) are valid for checkpoints (orbax
        # writes them remotely); params.yaml / logs / TB events / profiler
        # traces use plain file IO and land in a derived local dir instead
        self.local_dir = ckpt.local_sidecar_dir(workspace)
        # the CLI flag wins; else the obs.profile_steps knob (both count
        # steps; the window starts obs.profile_start_offset steps in)
        self.profile_steps = profile_steps or cfg.obs.profile_steps
        self.tracer = Tracer(
            enabled=cfg.obs.enabled, max_spans=cfg.obs.trace_buffer_spans
        )
        self.obs_metrics = TrainObsMetrics()
        # HBM telemetry rides the obs switch like the tracer: a disabled
        # memlog is never sampled (obs/memlog.py)
        self.memlog = MemLog(
            tracer=self.tracer,
            live_gauge=self.obs_metrics.hbm_live_bytes,
            peak_gauge=self.obs_metrics.hbm_peak_bytes,
        )
        self._progress: dict[str, Any] = {}
        self.flight: FlightRecorder | None = None
        if cfg.obs.enabled:
            self.flight = FlightRecorder(
                os.path.join(self.local_dir, "flight"),
                tracer=self.tracer,
                watchdog_timeout_s=cfg.obs.flight_watchdog_s,
                last_k_spans=cfg.obs.flight_last_k_spans,
                get_status=self._flight_status,
            )
        self._manager: Any = None  # live CheckpointManager during fit()
        self._train_cost = None  # StepCost of the AOT-compiled step
        self._compiled_train_step = None
        self._peak_flops = None
        self._peak_hbm = None
        self.mesh = make_mesh(
            cfg.mesh.data_parallel, cfg.mesh.plane_parallel,
            cfg.mesh.fsdp_parallel,
        )
        # the config's partition-rule table, resolved once: host batches
        # must land where the compiled step's table-derived in_shardings
        # expect them even under a parallel.rules batch-row override
        self._rules = rules_mod.partition_rules(cfg)
        self.logger = make_logger(self.local_dir)
        self.writer = MetricWriter(self.local_dir)
        self.sentinel = TrainingSentinel(
            cfg.resilience, self.obs_metrics.registry, self.logger,
            flight=self.flight,
        )
        # multi-host survival (None single-process): heartbeat exchange on
        # the shared sidecar + the cross-host stall watchdog that turns a
        # dead/wedged peer into a bounded named abort (resilience/multihost)
        self.multihost = MultihostSurvival.maybe_create(
            cfg, self.local_dir, flight=self.flight, logger=self.logger,
        )
        self._host_bytes = 0  # host-materialized batch bytes, this process
        self._last_sync_wait_ms: float | None = None
        self.model = build_model(cfg, **model_axes(self.mesh))
        # effective batch PER UPDATE. Accumulation splits each device's
        # batch into accum_steps micro-batches inside the step; it never
        # multiplies the loader batch, so throughput (imgs/sec) and the
        # effective-batch gauge both stay per-update quantities.
        # batches shard over the data x fsdp product (parallel/mesh.py)
        self.global_batch = (
            cfg.data.per_gpu_batch_size * data_replica_count(self.mesh)
        )
        self.accum_steps = max(int(cfg.training.accum_steps), 1)
        if cfg.data.per_gpu_batch_size % self.accum_steps:
            raise ValueError(
                f"training.accum_steps={self.accum_steps} must divide "
                f"data.per_gpu_batch_size={cfg.data.per_gpu_batch_size} "
                "(the per-device batch reshapes to (k, b/k, ...))"
            )
        self.obs_metrics.accum_steps.set(self.accum_steps)
        self.obs_metrics.effective_batch.set(self.global_batch)
        # the SAME predicate distribute_state places by (a 1-wide data axis
        # degrades the knob to replicated), so the sidecar below records
        # what actually runs
        self.zero1 = zero1_enabled(cfg, self.mesh)
        # mine_build_info{git_rev,jax_version,backend}: the join key that
        # lets a scrape line up with perf-ledger rows (obs/ledger.py) —
        # the mesh above already initialized the backend, so naming it
        # here costs nothing
        from mine_tpu.obs.ledger import set_build_info

        set_build_info(self.obs_metrics.registry,
                       backend=jax.default_backend())
        if jax.process_index() == 0:
            os.makedirs(self.local_dir, exist_ok=True)
            ckpt.save_paired_config(cfg, self.local_dir)
            # layout sidecar: checkpoints themselves are gathered/layout-free
            # (training/checkpoint.py), this records what produced the run so
            # a resume/rollback can re-place into the live layout knowingly
            ckpt.record_opt_layout(self.workspace, {
                "zero1": self.zero1,
                "data_parallel": self.mesh.shape[DATA_AXIS],
                "fsdp_parallel": self.mesh.shape[FSDP_AXIS],
                "mesh_shape": mesh_shape_str(self.mesh),
                "fsdp": fsdp_enabled(self.mesh),
                "zero1_min_size": cfg.parallel.zero1_min_size,
            })
            if self.local_dir != workspace:
                self.logger.info(
                    "workspace %s is remote: checkpoints go there via orbax; "
                    "params.yaml/logs/tensorboard/profiles go to %s",
                    workspace, self.local_dir,
                )

    def host_batch_slice(self) -> tuple[int, int]:
        """(start, count) of the global batch THIS host's loader should
        materialize (parallel/mesh.py host_batch_slice off the `^batch/`
        partition row). (0, global_batch) single-process."""
        return host_batch_slice(self.mesh, self.global_batch, self._rules)

    def _count_host_bytes(self, epoch_iter: Iterable[dict]) -> Iterable[dict]:
        """Meter the host-materialized batch bytes (the per-host
        data-sharding measurement: with N hosts each should count ~1/N of
        the global batch bytes per step). A delegating iterator — not a
        generator — so the source's `retry_safe_iter` contract survives:
        a raise does not close anything, and a retried `__next__` reaches
        the source's own `__next__` (data/pipeline.py pull retry)."""
        trainer = self
        pidx = str(jax.process_index())

        class _Counting:
            retry_safe_iter = getattr(epoch_iter, "retry_safe_iter", False)

            def __init__(self):
                self._src = iter(epoch_iter)

            def __iter__(self):
                return self

            def __next__(self):
                batch = next(self._src)
                n = sum(
                    np.asarray(leaf).nbytes
                    for leaf in jax.tree.leaves(batch)
                )
                trainer._host_bytes += n
                trainer.obs_metrics.data_host_bytes.inc(
                    n, process_index=pidx
                )
                return batch

        return _Counting()

    def _staged_batches(self, epoch_iter: Iterable[dict]) -> Iterable[dict]:
        return staged_batches(
            self.mesh, self.cfg.data.num_workers,
            self._count_host_bytes(epoch_iter),
            retries=self.cfg.data.loader_retries,
            on_retry=self._on_loader_retry,
            rules=self._rules,
            global_rows=self.global_batch,
        )

    def _on_loader_retry(self, attempt: int, exc: BaseException) -> None:
        self.obs_metrics.data_retries.inc(
            process_index=str(jax.process_index())
        )
        self.logger.warning(
            "transient loader error (retry %d): %s: %s",
            attempt, type(exc).__name__, exc,
        )

    def fit(self, train_ds: Any, val_ds: Any | None = None) -> dict[str, float]:
        cfg = self.cfg
        steps_per_epoch = len(train_ds)
        tx = make_optimizer(cfg, steps_per_epoch)
        manager = ckpt.checkpoint_manager(
            self.workspace,
            keep_period=max(cfg.training.eval_interval // cfg.training.checkpoint_interval, 1),
        )
        # pretrained backbone weights only matter on a fresh start; on resume
        # or warm start the restore overwrites them, and the .npz need not
        # exist on this host
        resuming = (
            manager.latest_step() is not None
            or bool(cfg.training.pretrained_checkpoint_path)
        )
        state = init_state(
            cfg, self.model, tx, jax.random.PRNGKey(cfg.training.seed),
            load_pretrained=not resuming,
        )
        # auto-resume from this workspace; else warm-start from a path.
        # training.resume_from=last_good trusts only the sentinel-vetted
        # pointer — the elastic-restart stance: after a host loss the
        # NEWEST step may be a partially-committed save from the dying run
        if cfg.training.resume_from not in ("latest", "last_good"):
            raise ValueError(
                f"training.resume_from={cfg.training.resume_from!r} "
                "(known: latest, last_good)"
            )
        if cfg.training.resume_from == "last_good":
            try:
                state, start_step = ckpt.restore_last_good(
                    manager, state, self.workspace
                )
            except FileNotFoundError:
                start_step = 0  # fresh workspace: nothing to trust yet
        else:
            state, start_step = ckpt.restore(manager, state)
        warm_path = cfg.training.pretrained_checkpoint_path
        if start_step == 0 and warm_path:
            if warm_path.endswith(".npz"):
                # a converted MINE torch checkpoint (backbone + decoder from
                # tools/convert_mine_checkpoint.py): weights transfer, the
                # optimizer/step/RNG start fresh — the reference's
                # restore_model semantics (utils.py:40-67), strictly checked
                from mine_tpu.models import apply_pretrained_npz

                # training.pretrained_subtrees defaults to the full
                # (backbone, decoder) checkpoint; ("backbone",) accepts a
                # backbone-only artifact (partial-restore escape hatch —
                # the strict analog of the reference's strict=False load)
                variables = apply_pretrained_npz(
                    {"params": state.params, "batch_stats": state.batch_stats},
                    warm_path,
                    expect_subtrees=cfg.training.pretrained_subtrees,
                )
                state = state.replace(
                    params=variables["params"],
                    batch_stats=variables["batch_stats"],
                )
                self.logger.info("warm-started from converted %s", warm_path)
            else:
                warm = ckpt.checkpoint_manager(warm_path)
                state, warm_step = ckpt.restore(warm, state)
                if warm_step == 0:
                    # restore() returns the template silently; a typo'd
                    # warm-start path must not degrade into training from
                    # random init
                    raise FileNotFoundError(
                        "training.pretrained_checkpoint_path="
                        f"{warm_path!r} contains no checkpoint"
                    )
                self.logger.info(
                    "warm-started from %s @ step %d", warm_path, warm_step
                )
        # single placement entry point: whatever layout the partition-rule
        # table resolves on this mesh — replicated, FSDP param shards,
        # ZeRO-1 moment shards (parallel/rules.py). Restores always pass
        # through here, so a gathered (layout-free) checkpoint lands back
        # in the live layout.
        state = distribute_state(state, cfg, self.mesh)

        lpips_params = load_lpips_params(cfg.training.lpips_weights_path)
        train_step = make_parallel_train_step(
            cfg, self.model, tx, self.mesh, state=state
        )
        eval_step = make_parallel_eval_step(
            cfg, self.model, self.mesh, lpips_params, state=state
        )

        meters = {k: AverageMeter(k) for k in LOSS_KEYS}
        start_epoch = start_step // steps_per_epoch + 1

        if start_step:
            self.logger.info("resumed from step %d (epoch %d)", start_step, start_epoch)
        self.logger.info(
            "training on mesh %s, global batch %d, %d steps/epoch",
            dict(self.mesh.shape), self.global_batch, steps_per_epoch,
        )

        if self.flight is not None:
            self.flight.start()
        if self.multihost is not None:
            # heartbeats begin at the first completed log interval (the
            # initial compile must not trip the window); the watchdog
            # judges only files that exist (resilience/multihost.py)
            self.multihost.start()
            self._clear_stale_host_trace_exports()
        # preemption guard AFTER the flight recorder, so its SIGTERM handler
        # chains: atomic save -> flight dump -> re-delivered termination
        guard: PreemptionGuard | None = None
        if cfg.resilience.preempt_save:
            guard = PreemptionGuard(self._preempt_save, logger=self.logger)
            guard.install()
        self._manager = manager
        self._live_state = state  # emergency-save target from the first step on
        fit_ok = False
        try:
            last_val = self._fit_epochs(
                cfg, train_ds, val_ds, state, train_step, eval_step,
                manager, meters, start_step,
            )
            fit_ok = True
        except (KeyboardInterrupt, Exception):
            # failure containment (SURVEY.md §5.3 — the reference has none):
            # whatever just died, persist the last completed step so the next
            # run auto-resumes instead of losing the epoch. The emergency save
            # itself may fail (e.g. the device poisoned the state arrays) —
            # never let that mask the original error.
            if self.multihost is not None:
                # a multi-process failure path can block on dead peers at
                # every remaining step (the emergency device_get, the jax
                # shutdown barrier) — bound it NOW, before attempting any
                # of them (resilience/multihost.py arm_failsafe)
                self.multihost.arm_failsafe()
            if self.flight is not None:
                self.flight.dump("train_exception")
            try:
                # multi-process: peers skip — only process 0's write lands
                # (checkpoint.py save), and a peer's device_get here could
                # block on a DEAD peer's unfinished collective (the
                # failsafe above bounds process 0's attempt too)
                if jax.process_index() == 0:
                    host_state = jax.device_get(self._live_state)
                    step_now = int(host_state.step)
                    self.logger.exception(
                        "training interrupted at step %d; writing emergency "
                        "checkpoint", step_now,
                    )
                    ckpt.save(manager, host_state, step_now)
                    ckpt.wait_until_finished(manager)
            except BaseException:  # noqa: BLE001 - incl. a second Ctrl+C
                self.logger.exception("emergency checkpoint failed")
            raise
        finally:
            if guard is not None:
                guard.uninstall()
            self._live_state = None  # don't pin the state in HBM after fit
            self._manager = None
            if self.multihost is not None:
                # done=True ONLY on clean completion: it exempts this host
                # from peers' staleness judgment, and a crashing host's
                # silence is exactly what peers must detect
                self.multihost.stop(
                    done=fit_ok, step=self._progress.get("global_step"),
                    data_bytes=self._host_bytes,
                    sync_wait_ms=self._last_sync_wait_ms,
                )
            if self.flight is not None:
                self.flight.stop()
            self._export_host_trace()
            try:
                # every exit path — normal, emergency, preempted — drains
                # pending async checkpoint writes before the process can die
                ckpt.wait_until_finished(manager)
            except Exception:  # noqa: BLE001 - never mask the original error
                self.logger.exception("checkpoint drain failed")
        return last_val

    def _host_state_for_save(self, state):
        """device_get for a checkpoint write — on multi-process runs only
        process 0 writes (training/checkpoint.py save), so peers skip the
        full-state D2H gather entirely (N-1 wasted state-sized transfers
        per checkpoint interval otherwise)."""
        return jax.device_get(state) if jax.process_index() == 0 else None

    def _preempt_save(self, reason: str) -> None:
        """Out-of-band atomic checkpoint (resilience/preempt.py): runs in
        the SIGTERM/SIGUSR2 handler on the main thread, i.e. between
        bytecodes of the step loop — `_live_state` is always the last
        COMPLETED step. Skips steps already on disk, waits for the write,
        and advances the last-good pointer. Multi-process: only process 0
        writes, so peers return outright."""
        state, manager = self._live_state, self._manager
        if state is None or manager is None:
            return  # not inside fit()
        if jax.process_index() != 0:
            return  # the save and the pointer are process-0 writes
        host_state = jax.device_get(state)
        step = int(host_state.step)
        self.logger.warning(
            "preemption save (%s): persisting step %d", reason, step
        )
        ckpt.wait_until_finished(manager)  # don't race a periodic async save
        if step not in {int(s) for s in manager.all_steps()}:
            ckpt.save(manager, host_state, step)
            ckpt.wait_until_finished(manager)
        # the pointer stays sentinel-vetted even out-of-band: vet() never
        # raises (we are in a signal handler) — a bad verdict leaves the
        # old pointer in place and defers the policy trip to the next
        # check() (matters for SIGUSR2 save-and-continue)
        if self.sentinel.vet(step):
            ckpt.mark_last_good(self.workspace, step)
        else:
            self.logger.warning(
                "preemption save: step %d saved but NOT marked last-good "
                "(unvetted non-finite flags)", step,
            )

    def _flight_status(self) -> dict:
        """What a flight dump's meta.json records about this trainer: the
        progress counters plus the live gauge values (a stalled run's last
        known MFU/throughput is exactly the evidence the dump exists for)."""
        m = self.obs_metrics
        return {
            **self._progress,
            "gauges": {
                "mfu": m.mfu.value(),
                "tflops_per_sec": m.tflops_per_sec.value(),
                "step_flops": m.step_flops.value(),
                "imgs_per_sec": m.imgs_per_sec.value(),
            },
            # what was resident when it died (obs/memlog.py)
            "hbm": self.memlog.last(),
        }

    def _clear_stale_host_trace_exports(self) -> None:
        """Process 0 removes the PREVIOUS run's per-process host-span
        exports at multi-process start — exports only happen at run exit,
        so an elastic restart at fewer hosts would otherwise merge the
        dead 4th host's old lane into this run's timeline
        (obs/collect.py training_timeline). Age-gated with the heartbeat
        sweep's margin so a racing peer's late just-exited export from
        THIS relaunch window is left alone (the bare single-process
        filename is cleared too: it would collide with p0)."""
        if jax.process_index() != 0:
            return
        import glob as glob_mod

        now = time.time()
        pattern = os.path.join(self.local_dir, "profile",
                               "host_spans*.trace.json")
        for path in glob_mod.glob(pattern):
            try:
                if (now - os.path.getmtime(path)
                        > multihost_mod._CLEANUP_MIN_AGE_S):
                    os.remove(path)
            except OSError:
                pass

    def _host_trace_path(self) -> str:
        """Host spans land next to the device traces (`<sidecar>/profile`)
        with a `*.trace.json` name, so tools/profile_summary.py's glob
        picks up both halves of a run from one directory. Multi-process
        runs share ONE sidecar, so each process exports its own
        `host_spans_p<idx>.trace.json` — before this, N processes raced
        one filename and the merged timeline lost N-1 hosts; the
        single-process name is unchanged (existing tooling globs)."""
        if jax.process_count() > 1:
            name = f"host_spans_p{jax.process_index()}.trace.json"
        else:
            name = "host_spans.trace.json"
        return os.path.join(self.local_dir, "profile", name)

    def _export_host_trace(self) -> None:
        if not self.tracer.enabled or not len(self.tracer):
            return
        try:
            # HBM counter samples ride the host lane as Chrome `C` events,
            # so the memory curve draws under the step spans
            self.tracer.export(
                self._host_trace_path(),
                extra_events=self.memlog.counter_events(),
            )
        except OSError:
            self.logger.exception("host trace export failed")

    def _per_update_cost(self, cost):
        """Normalize an executable's StepCost to per-UPDATE figures.

        XLA's cost_analysis counts a while/scan body ONCE — the trip count
        is opaque to it — so under accumulation the raw flops/bytes of the
        compiled step are ~one MICRO-batch forward+backward (plus the
        reduce/optimizer epilogue), not the k the executable actually runs
        (tools/bench_accum.py shows raw flops flat in k at equal effective
        batch). The MFU/bandwidth gauges divide by per-update wall time, so
        scale by accum_steps here; the epilogue gets over-counted k-fold,
        a <~1% error at real model sizes. peak_memory_bytes is a max, not
        a sum — it stays untouched."""
        if self.accum_steps <= 1:
            return cost
        scale = lambda v: v * self.accum_steps if v else v  # noqa: E731
        return dataclasses.replace(
            cost,
            flops=scale(cost.flops),
            bytes_accessed=scale(cost.bytes_accessed),
        )

    def _prepare_cost_accounting(self, train_step, state, batch):
        """AOT-compile the train step once (jit would compile the same HLO
        anyway — this just makes the Compiled handle inspectable), pull
        XLA's own FLOPs/bytes from it, and resolve the device peaks the
        MFU/bandwidth gauges divide by. Any failure falls back to the jit
        path: cost accounting is an instrument, never a crash."""
        cfg = self.cfg
        try:
            with self.tracer.span("aot_compile", cat="train"):
                compiled = train_step.lower(state, batch).compile()
            self._train_cost = self._per_update_cost(compiled_cost(compiled))
            self._compiled_train_step = compiled
        except Exception:  # noqa: BLE001 - backend-dependent surface
            self.logger.exception(
                "AOT train-step cost accounting unavailable; continuing "
                "on the jit path without MFU gauges"
            )
            return train_step
        self._dump_step_hlo(compiled)
        self._peak_flops = resolve_peak_flops(
            jax.devices()[0], cfg.obs.peak_flops_override
        )
        self._peak_hbm = resolve_peak_hbm_bytes(jax.devices()[0])
        if self._train_cost.flops:
            # _train_cost is per UPDATE (_per_update_cost); the micro gauge
            # is the division back down — never a second cost_analysis that
            # could double-count against it
            self.obs_metrics.step_flops.set(self._train_cost.flops)
            self.obs_metrics.micro_step_flops.set(
                self._train_cost.flops / self.accum_steps
            )
            self.writer.scalar(
                "obs/step_flops", self._train_cost.flops, int(state.step)
            )
        self.logger.info(
            "obs cost accounting: step flops=%s bytes=%s peak_flops=%s",
            self._train_cost.flops, self._train_cost.bytes_accessed,
            self._peak_flops,
        )
        return compiled

    def _dump_step_hlo(self, compiled) -> None:
        """Write the compiled step's HLO text next to the profile dir: the
        instruction -> named-scope map obs/attrib.py joins device-trace op
        events against (CPU op events carry only the HLO instruction name;
        the scope lives in this file's metadata)."""
        try:
            path = os.path.join(
                self.local_dir, "profile", "train_step_hlo.txt"
            )
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as fh:
                fh.write(compiled.as_text())
        except Exception:  # noqa: BLE001 - instrument, never a crash
            self.logger.exception("train-step HLO dump failed")

    def _publish_attribution(self, global_step: int) -> None:
        """After a profile window closes: join the captured device trace
        with the step HLO into the per-component table, publish the
        mine_train_component_time_ms gauges, and log coverage — the
        attribution the MFU-climb item optimizes against."""
        try:
            table = attribute_profile_dir(os.path.join(self.local_dir, "profile"))
        except Exception:  # noqa: BLE001 - instrument, never a crash
            self.logger.exception("profile attribution failed")
            return
        if table is None or not table["rows"]:
            self.logger.info(
                "profile attribution: no op events in the captured trace "
                "(backend emits none?) — component gauges not set"
            )
            return
        if self._train_cost is not None:
            # time/FLOPs/bytes table (labeled estimates: the executable's
            # cost_analysis totals split by measured time share)
            attach_cost_estimates(
                table, self._train_cost.flops, self._train_cost.bytes_accessed
            )
        m = self.obs_metrics
        for row in table["rows"]:
            m.component_time_ms.set(row["time_ms"], component=row["component"])
            self.writer.scalar(
                f"obs/component_{row['component']}_ms", row["time_ms"],
                global_step,
            )
        m.attrib_coverage.set(table["coverage"])
        self.writer.scalar("obs/attrib_coverage", table["coverage"], global_step)
        self.logger.info(
            "profile attribution (coverage %.1f%%%s): %s",
            100.0 * table["coverage"],
            "" if table["covered"] else " — BELOW the 90% accounting bar",
            " ".join(
                f"{r['component']}={r['time_ms']:.1f}ms({r['pct']}%)"
                for r in table["rows"]
            ),
        )

    def _publish_mfu(self, step_seconds: float, global_step: int) -> None:
        cost = self._train_cost
        if cost is None or not cost.flops or step_seconds <= 0:
            return
        achieved = cost.flops / step_seconds
        self.obs_metrics.tflops_per_sec.set(achieved / 1e12)
        self.writer.scalar("obs/tflops_per_sec", achieved / 1e12, global_step)
        self.writer.scalar("obs/step_flops", cost.flops, global_step)
        mfu = compute_mfu(cost.flops, step_seconds, self._peak_flops)
        if mfu is not None:
            self.obs_metrics.mfu.set(mfu)
            self.writer.scalar("obs/mfu", mfu, global_step)
        hbm = achieved_fraction(cost.bytes_accessed, step_seconds, self._peak_hbm)
        if hbm is not None:
            self.obs_metrics.hbm_fraction.set(hbm)
            self.writer.scalar("obs/achieved_hbm_fraction", hbm, global_step)

    def _publish_phases(self, global_step: int) -> None:
        for phase, stats in self.tracer.phase_summary(reset=True).items():
            if phase.startswith("train."):
                self.writer.scalar(
                    f"obs/phase_{phase[len('train.'):]}_ms",
                    stats["mean_ms"], global_step,
                )

    def _fit_epochs(
        self, cfg, train_ds, val_ds, state, train_step, eval_step,
        manager, meters, start_step,
    ) -> dict[str, float]:
        """Rollback driver around the epoch runner: a SentinelRollback
        restores the last-good checkpoint, rebuilds the data iterator at
        that position (the runner's mid-epoch start), and retries — at most
        resilience.max_rollbacks times before escalating to abort."""
        global_step = start_step
        rollbacks = 0
        while True:
            try:
                return self._run_epochs(
                    cfg, train_ds, val_ds, state, train_step, eval_step,
                    manager, meters, global_step,
                )
            except SentinelRollback as trip:
                rollbacks += 1
                self.sentinel.rollbacks.inc()
                if rollbacks > cfg.resilience.max_rollbacks:
                    raise SentinelAbort(
                        f"{rollbacks} sentinel rollbacks exceed "
                        f"resilience.max_rollbacks="
                        f"{cfg.resilience.max_rollbacks}: {trip}"
                    ) from trip
                ckpt.wait_until_finished(manager)
                live = self._live_state if self._live_state is not None else state
                template = jax.device_get(live)
                try:
                    host_state, restored = ckpt.restore_last_good(
                        manager, template, self.workspace
                    )
                except FileNotFoundError as exc:
                    raise SentinelAbort(
                        f"rollback impossible ({exc}); original trip: {trip}"
                    ) from trip
                self.logger.warning(
                    "sentinel rollback #%d (%s): restored last-good step %d; "
                    "re-seeding the data iterator there", rollbacks, trip,
                    restored,
                )
                state = distribute_state(host_state, self.cfg, self.mesh)
                self._live_state = state
                global_step = restored
                self.sentinel.reset_after_rollback()

    def _run_epochs(
        self, cfg, train_ds, val_ds, state, train_step, eval_step,
        manager, meters, start_step,
    ) -> dict[str, float]:
        steps_per_epoch = len(train_ds)
        global_step = start_step
        start_epoch = start_step // steps_per_epoch + 1
        # data-iterator position restore: loaders are deterministic in
        # (epoch, step), so a mid-epoch start is "skip the first k host
        # batches of epoch start_epoch" — the resumed run then sees exactly
        # the stream the uninterrupted run would have (bitwise resume)
        skip_into_epoch = start_step % steps_per_epoch
        chaos_sched = chaos.active()
        last_val: dict[str, float] = {}
        tracer = self.tracer
        cost_pending = cfg.obs.enabled and cfg.obs.cost_enabled
        profile_at = start_step + cfg.obs.profile_start_offset
        t_log = time.perf_counter()
        steps_since_log = 0  # actual count: epoch tails leave remainders, so
        # the first log of an epoch can span MORE than log_interval steps
        for epoch in range(start_epoch, cfg.training.epochs + 1):
            for m in meters.values():
                m.reset()
            self._progress.update(epoch=epoch, global_step=global_step)
            epoch_iter = train_ds.epoch(epoch)
            step_in_epoch = 0
            if epoch == start_epoch and skip_into_epoch:
                # islice consumes the skipped batches lazily on the host
                # side, before the prefetch stages ever stage them on device
                epoch_iter = islice(epoch_iter, skip_into_epoch, None)
                step_in_epoch = skip_into_epoch
                self.logger.info(
                    "mid-epoch resume: skipping %d already-trained batches "
                    "of epoch %d", skip_into_epoch, epoch,
                )
            batches = iter(self._staged_batches(epoch_iter))
            while True:
                with tracer.span("data", cat="train"):
                    batch = next(batches, None)
                if batch is None:
                    break
                step_in_epoch += 1
                if cost_pending:
                    cost_pending = False
                    train_step = self._prepare_cost_accounting(
                        train_step, state, batch
                    )
                if self.profile_steps and global_step == profile_at:
                    jax.profiler.start_trace(os.path.join(self.local_dir, "profile"))
                if (chaos_sched is not None
                        and chaos_sched.should("nan_loss", at=global_step + 1)):
                    # poison through the REAL graph: NaN pixels make the
                    # loss/grads non-finite exactly as a corrupt shard would
                    self.logger.warning(
                        "chaos: poisoning step %d's batch with NaNs",
                        global_step + 1,
                    )
                    batch = dict(batch)
                    batch["src_img"] = batch["src_img"] * float("nan")
                with tracer.span("step", cat="train", step=global_step + 1):
                    state, loss_dict = train_step(state, batch)
                self._live_state = state  # for the emergency checkpoint
                global_step += 1
                steps_since_log += 1
                self._progress["global_step"] = global_step
                self.sentinel.observe(
                    global_step, loss_dict.get("update_skipped")
                )
                if self.flight is not None:
                    self.flight.heartbeat(step=global_step)
                if chaos_sched is not None:
                    if chaos_sched.should("preempt_exit", at=global_step):
                        raise PreemptedError(
                            f"chaos preempt_exit after step {global_step}"
                        )
                    if chaos_sched.should("sigusr2", at=global_step):
                        os.kill(os.getpid(), signal.SIGUSR2)
                    if chaos_sched.should("sigterm", at=global_step):
                        os.kill(os.getpid(), signal.SIGTERM)
                    if chaos_sched.should("host_kill", at=global_step):
                        # a host dying: SIGKILL — no dump, no save, no
                        # goodbye. Survivors' watchdogs are the proof
                        # target (resilience/multihost.py).
                        self.logger.warning(
                            "chaos: host_kill after step %d", global_step
                        )
                        os.kill(os.getpid(), signal.SIGKILL)
                    if chaos_sched.should("host_stall", at=global_step):
                        # a wedged host (hung collective / dead ICI link):
                        # stop making progress but stay alive. Every
                        # host's watchdog — including this one's own —
                        # must abort boundedly (EXIT_HOST_STALL).
                        self.logger.warning(
                            "chaos: host_stall after step %d — sleeping "
                            "until the watchdog aborts this process",
                            global_step,
                        )
                        while True:
                            time.sleep(3600.0)
                if (self.profile_steps
                        and global_step == profile_at + self.profile_steps):
                    jax.block_until_ready(loss_dict["loss"])
                    jax.profiler.stop_trace()
                    self._export_host_trace()
                    self.logger.info("profile trace written to %s/profile", self.local_dir)
                    # stop_trace's xplane post-processing plus the trace
                    # parse below legitimately take minutes on CPU; beat
                    # the stall watchdog around them so a profile window
                    # cannot read as a hung step
                    if self.flight is not None:
                        self.flight.heartbeat(step=global_step)
                    self._publish_attribution(global_step)
                    if self.flight is not None:
                        self.flight.heartbeat(step=global_step)

                if step_in_epoch % cfg.training.log_interval == 0:
                    # one transfer for the whole dict: per-key float() would
                    # block on a device sync PER KEY per log step. The wall
                    # time of this block IS the sync wait: it blocks until
                    # every in-flight collective resolves, i.e. until the
                    # slowest host — measured unconditionally (the tracer
                    # may be off) because it feeds the straggler gauge and
                    # the heartbeat below.
                    t_sync0 = time.perf_counter()
                    with tracer.span("sync", cat="train", step=global_step):
                        fetch = {k: loss_dict[k] for k in LOSS_KEYS}
                        if "grad_norm" in loss_dict:
                            fetch["grad_norm"] = loss_dict["grad_norm"]
                        host_vals = jax.device_get(fetch)
                        grad_norm = host_vals.pop("grad_norm", None)
                        host_losses = {
                            k: float(v) for k, v in host_vals.items()
                        }
                    sync_wait_ms = (time.perf_counter() - t_sync0) * 1e3
                    self._last_sync_wait_ms = sync_wait_ms
                    self.obs_metrics.sync_wait_ms.set(
                        sync_wait_ms,
                        process_index=str(jax.process_index()),
                    )
                    with tracer.span("log", cat="train", step=global_step):
                        for k, v in host_losses.items():
                            meters[k].update(v, cfg.training.log_interval)
                        lrs = learning_rates(cfg, steps_per_epoch, global_step)
                        now = time.perf_counter()
                        interval_s = max(now - t_log, 1e-9)
                        t_log = now
                        n_steps = max(steps_since_log, 1)
                        steps_since_log = 0
                        rate = n_steps * self.global_batch / interval_s
                        self.obs_metrics.imgs_per_sec.set(rate)
                        self.logger.info(
                            "epoch [%03d] step [%d/%d] global_step=%d "
                            "loss=%.4f rgb_tgt=%.4f ssim_tgt=%.4f disp_src=%.4f "
                            "psnr=%.2f lr=%.6f imgs/sec=%.1f",
                            epoch, step_in_epoch, steps_per_epoch, global_step,
                            host_losses["loss"], host_losses["loss_rgb_tgt"],
                            host_losses["loss_ssim_tgt"], host_losses["loss_disp_pt3dsrc"],
                            host_losses["psnr_tgt"], lrs["backbone_lr"], rate,
                        )
                        self.writer.scalars(host_losses, global_step, prefix="train/")
                        self.writer.scalar("train/imgs_per_sec", rate, global_step)
                        self.writer.scalar("train/backbone_lr", lrs["backbone_lr"], global_step)
                        if grad_norm is not None:
                            self.obs_metrics.grad_norm.set(float(grad_norm))
                            self.writer.scalar(
                                "train/grad_norm", float(grad_norm), global_step
                            )
                        self._publish_mfu(interval_s / n_steps, global_step)
                        if cfg.obs.enabled:
                            # live HBM gauges + the counter-event curve the
                            # host-trace export draws (obs/memlog.py)
                            self.memlog.sample(step=global_step)
                        if self.multihost is not None:
                            # cross-host heartbeat, piggybacked on the sync
                            # this block already paid for: one tiny atomic
                            # file write per log interval. The sync wait
                            # rides along so every host can see every
                            # OTHER host's wait — the cross-host half of
                            # the straggler attribution.
                            self.multihost.beat(
                                global_step, data_bytes=self._host_bytes,
                                sync_wait_ms=sync_wait_ms,
                            )
                            if jax.process_index() == 0:
                                # straggler attribution BEFORE the watchdog
                                # has to kill anything: a wedged-but-alive
                                # host shows up here first (N tiny file
                                # reads per interval, process 0 only)
                                table = self.multihost.stragglers()
                                if (table["suspect"] is not None
                                        and any(
                                            r["behind_steps"] >= 2
                                            for r in table["rows"])):
                                    self.logger.warning(
                                        "straggler: host %s is %s; table %s",
                                        table["suspect"],
                                        f"{table['skew_fraction']:.0%} behind",
                                        table["rows"],
                                    )
                    if tracer.enabled:
                        # AFTER the log span closes, so this interval's own
                        # sync/log phases are in the summary it publishes
                        self._publish_phases(global_step)
                    # the scalars are logged/written first, THEN the
                    # sentinel judges them: a trip leaves its evidence in
                    # the log stream it is about to interrupt
                    self.sentinel.check(host_losses["loss"], global_step)

                if global_step % cfg.training.checkpoint_interval == 0:
                    # resolve pending finiteness flags BEFORE the save: a
                    # trip here rolls back/aborts instead of blessing a
                    # suspect step as the new last-good
                    self.sentinel.flush(global_step)
                    with tracer.span("ckpt", cat="train", step=global_step):
                        ckpt.save(
                            manager, self._host_state_for_save(state),
                            global_step,
                        )
                    ckpt.mark_last_good(self.workspace, global_step)
                    self.logger.info("checkpoint saved @ step %d", global_step)

                if val_ds is not None and (
                    global_step == 2000  # reference quirk: first eval at 2000
                    or global_step % cfg.training.eval_interval == 0
                ):
                    last_val = self.evaluate(eval_step, state, val_ds, global_step)

            # end-of-epoch summary from the meters (log-interval samples,
            # weighted by interval) — the running averages the reference
            # accumulates but never reports (synthesis_task.py:146-167)
            if any(m.count for m in meters.values()):
                epoch_avg = {k: m.avg for k, m in meters.items()}
                self.logger.info(
                    "epoch [%03d] avg: loss=%.4f rgb_tgt=%.4f ssim_tgt=%.4f "
                    "psnr=%.2f",
                    epoch, epoch_avg["loss"], epoch_avg["loss_rgb_tgt"],
                    epoch_avg["loss_ssim_tgt"], epoch_avg["psnr_tgt"],
                )
                self.writer.scalars(epoch_avg, global_step, prefix="train_epoch/")

        self.sentinel.flush(global_step)
        with tracer.span("ckpt", cat="train", step=global_step):
            # an exact-resume restart (or a preemption save that landed on
            # the final step) may already hold this step on disk
            if global_step not in {int(s) for s in manager.all_steps()}:
                ckpt.save(
                    manager, self._host_state_for_save(state), global_step
                )
            ckpt.wait_until_finished(manager)
            ckpt.mark_last_good(self.workspace, global_step)
        self.writer.flush()
        return last_val

    def evaluate(self, eval_step, state, val_ds: Any, global_step: int) -> dict[str, float]:
        """Full-val-set metric pass (synthesis_task.py:496-527)."""
        return run_evaluation(
            self.cfg, self.mesh, self.logger, self.writer,
            eval_step, state, val_ds, global_step,
        )


def run_evaluation(
    cfg: Config, mesh, logger, writer, eval_step, state, val_ds: Any,
    global_step: int,
) -> dict[str, float]:
    """The metric pass itself, shared by the train loop's eval intervals and
    the standalone `python -m mine_tpu.evaluate` CLI (the reference can only
    evaluate from inside a training job, synthesis_task.py:660-663)."""
    meters = {k: AverageMeter(k) for k in LOSS_KEYS}
    key = jax.random.PRNGKey(cfg.training.seed + 17)
    viz = None
    n_examples = 0
    for i, batch in enumerate(staged_batches(
        mesh, cfg.data.num_workers, val_ds.epoch(0),
        rules=rules_mod.partition_rules(cfg),
        # multi-process: val loaders are global-batch (the compat path —
        # shard_batch slices each host's rows out); the global row count
        # disambiguates local-slice from global input
        global_rows=cfg.data.per_gpu_batch_size * data_replica_count(mesh),
    )):
        loss_dict, viz = eval_step(state, batch, jax.random.fold_in(key, i))
        # metric values are weighted means over GENUINE examples only
        # (wrap-padded slots carry eval_weight 0, training/step.py
        # make_eval_step); weighting the meter by the genuine count matches
        # the reference's update(..., n=B) over its ragged final batch
        n_batch = int(round(float(loss_dict["eval_examples"])))
        n_examples += n_batch
        for k in LOSS_KEYS:
            meters[k].update(float(loss_dict[k]), n=n_batch)
    expected = getattr(val_ds, "num_eval_examples", None)
    if expected is not None and n_examples != expected:
        raise RuntimeError(
            f"eval example count mismatch: metered {n_examples}, dataset "
            f"holds {expected} — the wrap-pad mask is miscounting"
        )
    result = {k: m.avg for k, m in meters.items()}
    logger.info(
        "eval @ %d: " + " ".join(f"{k}=%.4f" for k in ("loss", "loss_rgb_tgt", "psnr_tgt", "lpips_tgt")),
        global_step, *[result[k] for k in ("loss", "loss_rgb_tgt", "psnr_tgt", "lpips_tgt")],
    )
    writer.scalars(result, global_step, prefix="val/")
    if viz is not None:
        tgt = np.asarray(jax.device_get(viz["tgt_imgs_syn"]))[:4]
        src = np.asarray(jax.device_get(viz["src_imgs_syn"]))[:4]
        tgt_disp = normalize_disparity_for_vis(
            np.asarray(jax.device_get(viz["tgt_disparity_syn"]))[:4]
        )
        writer.image_grid("val/tgt_syn", tgt, global_step)
        writer.image_grid("val/src_syn", src, global_step)
        writer.image_grid("val/tgt_disparity", tgt_disp, global_step)
    writer.flush()
    return result
