"""Optimizer and LR schedule.

Reference: two-param-group Adam — backbone_lr / decoder_lr — with L2 weight
decay folded into the gradient (torch Adam semantics, synthesis_task.py:85-89)
and a per-epoch MultiStepLR decay (synthesis_task.py:118-120, stepped once per
epoch at synthesis_task.py:685).

optax construction: add_decayed_weights BEFORE scale_by_adam reproduces
torch's grad += wd * p (not decoupled AdamW); multi_transform splits the two
LR groups on the top-level param keys ('backbone' / 'decoder' — the module
names in MPINetwork); the MultiStep schedule becomes a piecewise-constant
schedule over global steps with epoch boundaries scaled by steps_per_epoch.
"""

from __future__ import annotations

import optax

from mine_tpu.config import Config


def _multistep(base_lr: float, decay_steps, gamma: float, steps_per_epoch: int):
    boundaries = {int(e) * steps_per_epoch: gamma for e in decay_steps}
    return optax.piecewise_constant_schedule(base_lr, boundaries)


def make_optimizer(cfg: Config, steps_per_epoch: int) -> optax.GradientTransformation:
    # training.optimizer: "adam" is reference parity; "sgd" keeps the update
    # linear in the gradient — the cross-topology parity methodology (mesh
    # shapes / elastic host counts only fp-epsilon-match under it, because
    # Adam's first step is sign(grad)*lr and amplifies reassociation noise
    # on zero-effective-grad leaves into full ±lr flips; PARITY.md)
    if cfg.training.optimizer not in ("adam", "sgd"):
        raise ValueError(
            f"training.optimizer={cfg.training.optimizer!r} (known: adam, sgd)"
        )

    def group(base_lr: float) -> optax.GradientTransformation:
        scale = _multistep(
            base_lr, cfg.lr.decay_steps, cfg.lr.decay_gamma, steps_per_epoch
        )
        if cfg.training.optimizer == "sgd":
            return optax.chain(
                optax.add_decayed_weights(cfg.lr.weight_decay),
                optax.scale_by_learning_rate(scale),
            )
        return optax.chain(
            optax.add_decayed_weights(cfg.lr.weight_decay),
            optax.scale_by_adam(),  # b1/b2/eps defaults match torch Adam
            optax.scale_by_learning_rate(scale),
        )

    return optax.multi_transform(
        {
            "backbone": group(cfg.lr.backbone_lr),
            "decoder": group(cfg.lr.decoder_lr),
        },
        param_labels=lambda params: {k: k for k in params},
    )


def learning_rates(cfg: Config, steps_per_epoch: int, step: int) -> dict[str, float]:
    """Current LRs for logging (reference logs encoder lr,
    synthesis_task.py:582-601)."""
    return {
        "backbone_lr": float(
            _multistep(cfg.lr.backbone_lr, cfg.lr.decay_steps, cfg.lr.decay_gamma, steps_per_epoch)(step)
        ),
        "decoder_lr": float(
            _multistep(cfg.lr.decoder_lr, cfg.lr.decay_steps, cfg.lr.decay_gamma, steps_per_epoch)(step)
        ),
    }
