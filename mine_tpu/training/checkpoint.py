"""Checkpoint save/restore via orbax.

Reference mechanism (synthesis_task.py:645-679, utils.py:40-67): rank-0 torch
.pth of backbone/decoder/optimizer; step and RNG are NOT saved, so a resumed
run restarts schedules from zero (SURVEY.md §5.3-5.4). Here the whole
TrainState (params, batch_stats, optimizer state, step, PRNG key) is one
orbax pytree; a restore resumes bitwise where training stopped — the
preemption-tolerance TPU pods require. The config travels next to the
checkpoints as params.yaml (the reference's checkpoint+config pairing,
image_to_video.py:275-277).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

import orbax.checkpoint as ocp

from mine_tpu.config import Config, load_config, save_config

_LATEST_EVERY = "state"  # item name inside each step directory


def checkpoint_path(workspace: str) -> str:
    """<workspace>/checkpoints, preserving URL schemes.

    `os.path.abspath` would mangle `gs://bucket/run` into an absolute local
    path, silently blocking remote durability — so URL-scheme workspaces
    (anything with `://`) pass through verbatim and only local paths are
    absolutized (orbax requires absolute local directories)."""
    if "://" in workspace:
        return workspace.rstrip("/") + "/checkpoints"
    return os.path.abspath(os.path.join(workspace, "checkpoints"))


def local_sidecar_dir(workspace: str) -> str:
    """Local directory for the workspace's non-checkpoint artifacts
    (params.yaml, logs, tensorboard events, profiler traces).

    For an ordinary local workspace this IS the workspace. For a URL-scheme
    workspace (`gs://bucket/run`) those writers use plain open()/makedirs and
    cannot target object storage — without this mapping they would create a
    literal local `gs:/…` directory. They land in a per-run directory under a
    STABLE root instead — $MINE_TPU_RUNS_DIR or ~/.cache/mine_tpu/runs, never
    the process CWD, so a resume launched from a different directory finds
    the same logs/params.yaml — keyed by the full URL including its scheme
    (`gs://x/y` and `s3://x/y` must not collide). Checkpoints alone go remote
    via orbax; the reference likewise keeps sidecars local between periodic
    HDFS pushes (synthesis_task.py:654-679).
    """
    if "://" not in workspace:
        return workspace
    url = workspace.rstrip("/")
    # readable prefix + URL hash: flattening '://' and '/' to '_' alone would
    # collide distinct workspaces (gs://b/my_run vs gs://b/my/run)
    digest = hashlib.sha1(url.encode()).hexdigest()[:10]
    sanitized = url.replace("://", "_").replace("/", "_")
    root = os.environ.get(
        "MINE_TPU_RUNS_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "mine_tpu", "runs"),
    )
    return os.path.abspath(os.path.join(root, f"{sanitized}-{digest}"))


def checkpoint_manager(
    workspace: str, max_to_keep: int = 3, keep_period: int | None = None
) -> ocp.CheckpointManager:
    """Manager writing to <workspace>/checkpoints/<step>/.

    max_to_keep bounds the rolling 'latest' set (reference keeps one rolling
    checkpoint_latest.pth); keep_period pins every k-th step forever (the
    reference's immutable checkpoint_%012d at eval intervals).

    A URL-scheme workspace (`gs://bucket/run`, `file://…`) passes through
    un-mangled, so orbax writes checkpoints durably to object storage — the
    analog of the reference's HDFS upload (synthesis_task.py:654-658,
    utils.py:20-37 `run_shell_cmd` hadoop put), minus the rank-0 shell-out.

    Multi-process runs: every save path in this repo is gather-on-save —
    host numpy arrays identical on every process — so orbax's collective
    multi-host write protocol (which shards writes by process and
    barriers all of them) is exactly wrong for it: N processes would race
    identical bytes into one tmp directory (observed: rename ENOENT
    corruption). The manager is therefore scoped PROCESS-LOCAL
    (`active_processes={self}`: barriers become singleton no-ops) and
    `save()` below writes from process 0 alone; reads (restore /
    latest_step / all_steps) stay safe from every process because they
    only see atomically-committed step directories. NOTE: this is the
    replicated/gathered-checkpoint contract — saving layout-SHARDED
    global arrays across hosts would need the collective protocol back
    (README Multi-host).
    """
    import jax

    path = checkpoint_path(workspace)
    create = True
    kwargs = {}
    if jax.process_count() > 1:
        me = jax.process_index()
        kwargs["multiprocessing_options"] = ocp.options.MultiprocessingOptions(
            primary_host=me, active_processes={me},
            barrier_sync_key_prefix=f"mine_tpu_p{me}",
        )
        # orbax refuses create=True under active_processes; local paths we
        # can make ourselves (exist_ok absorbs the N-process race), remote
        # schemes rely on the object store's implicit-prefix semantics
        create = False
        if "://" not in path:
            os.makedirs(path, exist_ok=True)
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        keep_period=keep_period,
        create=create,
        **kwargs,
    )
    return ocp.CheckpointManager(path, options=options)


def save(manager: ocp.CheckpointManager, state: Any, step: int) -> None:
    """Write one gathered (host-array) checkpoint. Multi-process: process
    0 writes alone — the state is replicated host data on every process
    (see checkpoint_manager); peers return immediately and rely on the
    atomic commit for read-side consistency.

    After the commit an integrity sidecar (sha256-of-manifest, below) is
    recorded so the serving-side swap path can verify the bytes it is
    about to promote instead of deferring to an opaque restore error."""
    import jax

    if jax.process_index() != 0:
        return
    manager.save(step, args=ocp.args.StandardSave(state))
    path = str(manager.directory)
    if "://" not in path:
        # the sidecar hashes committed files, so the async save must land
        # first; remote (gs://) checkpoint trees cannot be walked with
        # plain os IO and rely on the object store's own integrity
        manager.wait_until_finished()
        write_integrity_sidecar(os.path.dirname(path), step)


def restore(manager: ocp.CheckpointManager, state_template: Any) -> tuple[Any, int]:
    """Restore the newest step, shaped like state_template.
    Returns (state, step); (template, 0) when no checkpoint exists."""
    step = manager.latest_step()
    if step is None:
        return state_template, 0
    state = manager.restore(step, args=ocp.args.StandardRestore(state_template))
    return state, step


def save_paired_config(cfg: Config, workspace: str) -> None:
    """Archive the merged config into the workspace (train.py:206-212)."""
    save_config(cfg, os.path.join(workspace, "params.yaml"))


def load_paired_config(workspace: str, overrides: str | None = None) -> Config:
    """Inference re-reads the archived config (image_to_video.py:275-277).

    Resolves through local_sidecar_dir, so a remote (`gs://…`) workspace
    finds the params.yaml its training run archived locally — the same
    mapping save_paired_config wrote through (identity for local paths)."""
    path = os.path.join(local_sidecar_dir(workspace), "params.yaml")
    if not os.path.isfile(path) and "://" in workspace:
        raise FileNotFoundError(
            f"{path} not found. Workspace {workspace!r} is remote: its "
            "checkpoints live in object storage, but params.yaml is a local "
            "sidecar of the machine that trained (see local_sidecar_dir). "
            "Copy that file here or set MINE_TPU_RUNS_DIR to its root."
        )
    return load_config(path, overrides=overrides)


def wait_until_finished(manager: ocp.CheckpointManager) -> None:
    manager.wait_until_finished()


# -- state layout sidecar (partition-rule table, parallel/rules.py) -----------
#
# Checkpoints themselves are LAYOUT-INDEPENDENT: every save path goes
# through jax.device_get, which gathers sharded leaves into full global
# arrays — so a checkpoint written under ZeRO-1 restores into a replicated
# run and vice versa, and the last_good/rollback machinery never has to
# know how the optimizer state was placed. The sidecar records what
# produced the workspace anyway, so tooling (and the next resume) can see
# which layout a run trained under and re-place accordingly.


def _opt_layout_path(workspace: str) -> str:
    return os.path.join(local_sidecar_dir(workspace), "opt_layout.json")


def record_opt_layout(workspace: str, layout: dict) -> None:
    """Atomically record the optimizer-state layout of this run, e.g.
    {"zero1": true, "data_parallel": 8, "zero1_min_size": 1024,
    "gathered_on_save": true}. Same atomic-rename discipline as
    mark_last_good, and for the same reason: a preemption mid-write must
    leave old-or-new, never half."""
    path = _opt_layout_path(workspace)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(dict(layout, gathered_on_save=True), fh)
    os.replace(tmp, path)


def opt_layout(workspace: str) -> dict | None:
    """The recorded layout, or None for pre-zero1 workspaces (which are by
    construction replicated + gathered — the only layout that existed)."""
    try:
        with open(_opt_layout_path(workspace)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


# -- last-good pointer (resilience/sentinel.py rollback target) ---------------


def _last_good_path(workspace: str) -> str:
    # plain-file IO -> sidecar mapping, like params.yaml/logs: a remote
    # (gs://) workspace keeps its pointer on the training host
    return os.path.join(local_sidecar_dir(workspace), "last_good.json")


def mark_last_good(workspace: str, step: int) -> None:
    """Atomically record `step` as the newest checkpoint known healthy
    (saved while the training sentinel saw only finite losses). Distinct
    from `latest_step()`: the newest checkpoint may postdate a trip.
    Multi-process: the pointer is global state like the checkpoint itself
    — process 0 writes it (same gating as save())."""
    import jax

    if jax.process_index() != 0:
        return
    path = _last_good_path(workspace)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"step": int(step)}, fh)
    os.replace(tmp, path)  # atomic on POSIX: readers see old or new, never half


def last_good_step(workspace: str) -> int | None:
    try:
        with open(_last_good_path(workspace)) as fh:
            return int(json.load(fh)["step"])
    except (OSError, ValueError, KeyError):
        return None


def restore_last_good(
    manager: ocp.CheckpointManager, state_template: Any, workspace: str,
) -> tuple[Any, int]:
    """Restore the newest RETAINED step <= the last-good pointer.

    The pointer may name a step the manager's retention policy has since
    deleted; the newest surviving step at-or-before it is the best
    available rollback target. With no pointer (or nothing at/under it),
    falls back to the newest retained step — under any sentinel policy the
    in-graph mask guarantees even post-trip checkpoints never absorbed a
    non-finite update, so newest-retained is safe, merely less vetted.
    Raises FileNotFoundError when no checkpoint exists at all.
    """
    steps = sorted(int(s) for s in manager.all_steps())
    if not steps:
        raise FileNotFoundError(
            f"rollback requested but {workspace} holds no checkpoint"
        )
    pointer = last_good_step(workspace)
    candidates = [s for s in steps if pointer is None or s <= pointer]
    step = max(candidates) if candidates else max(steps)
    state = manager.restore(step, args=ocp.args.StandardRestore(state_template))
    return state, step


# -- integrity sidecar (sha256-of-manifest; serving swap verification) --------
#
# Orbax's atomic rename guarantees a step directory is either absent or
# complete AT COMMIT TIME; it says nothing about the bytes afterwards
# (bit rot, a partial copy between machines, an overzealous cleanup job).
# Before this sidecar a corrupted checkpoint surfaced as whatever opaque
# error the restore happened to hit — or worse, restored plausibly. Now
# every local save records a manifest (relative path -> size + sha256 of
# every file under the step directory) plus the sha256 of that manifest,
# and the serving swap path re-hashes before promoting: a divergence is
# the NAMED CheckpointCorrupt (counter reason=corrupt), the old
# generation keeps serving. Pre-sidecar checkpoints verify vacuously —
# absence of the sidecar is legacy, not corruption.


class CheckpointCorrupt(ValueError):
    """A checkpoint's bytes no longer match the sha256-of-manifest
    sidecar recorded at save time. The NAMED corrupt-rejection error: the
    hot-swap path surfaces it as swap_failures{reason=corrupt} and keeps
    the old generation serving (serving/server.py _swap_attempt)."""

    def __init__(self, context: str, problems: list[str]):
        self.problems = problems
        shown = "; ".join(problems[:5])
        more = f" (+{len(problems) - 5} more)" if len(problems) > 5 else ""
        super().__init__(f"{context}: {shown}{more}")


def _integrity_path(workspace: str, step: int) -> str:
    # plain-file IO -> sidecar mapping, like last_good.json
    return os.path.join(
        local_sidecar_dir(workspace), "integrity", f"{int(step)}.json"
    )


def _step_manifest(workspace: str, step: int) -> dict[str, dict]:
    """relative path -> {"bytes": n, "sha256": hex} for every file under
    the committed step directory, sorted-walk deterministic."""
    root = os.path.join(checkpoint_path(workspace), str(int(step)))
    if not os.path.isdir(root):
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {workspace}",
            [f"step directory missing: {root}"],
        )
    manifest: dict[str, dict] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            digest = hashlib.sha256()
            with open(full, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    digest.update(chunk)
            manifest[os.path.relpath(full, root)] = {
                "bytes": os.path.getsize(full),
                "sha256": digest.hexdigest(),
            }
    return manifest


def _manifest_sha256(manifest: dict[str, dict]) -> str:
    return hashlib.sha256(
        json.dumps(manifest, sort_keys=True).encode()
    ).hexdigest()


def write_integrity_sidecar(workspace: str, step: int) -> None:
    """Record the step's manifest + its sha256. Same atomic-rename
    discipline as mark_last_good: a crash mid-write leaves old-or-new,
    never a half-written sidecar that would condemn a healthy step."""
    manifest = _step_manifest(workspace, step)
    path = _integrity_path(workspace, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({
            "step": int(step),
            "manifest_sha256": _manifest_sha256(manifest),
            "files": manifest,
        }, fh, sort_keys=True)
    os.replace(tmp, path)


def verify_checkpoint_integrity(workspace: str, step: int) -> None:
    """Re-hash the step directory against its recorded sidecar; raise
    CheckpointCorrupt naming the first diverging files on mismatch.

    No sidecar (pre-sidecar checkpoint) or a remote (URL-scheme)
    workspace verifies vacuously — absence is legacy, and remote trees
    cannot be walked with plain os IO (the object store carries its own
    integrity)."""
    if "://" in workspace:
        return
    try:
        with open(_integrity_path(workspace, step)) as fh:
            recorded = json.load(fh)
    except OSError:
        return  # legacy checkpoint: saved before the sidecar existed
    except ValueError as exc:
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {workspace}",
            [f"unreadable integrity sidecar: {exc}"],
        ) from None
    actual = _step_manifest(workspace, step)
    want = recorded.get("files", {})
    problems: list[str] = []
    for name in sorted(set(want) - set(actual)):
        problems.append(f"missing file {name}")
    for name in sorted(set(actual) - set(want)):
        problems.append(f"unexpected file {name}")
    for name in sorted(set(want) & set(actual)):
        if want[name] != actual[name]:
            problems.append(
                f"file {name}: recorded {want[name]['bytes']}B "
                f"sha256 {want[name]['sha256'][:12]}…, found "
                f"{actual[name]['bytes']}B "
                f"sha256 {actual[name]['sha256'][:12]}…"
            )
    if not problems and recorded.get("manifest_sha256") != \
            _manifest_sha256(actual):
        problems.append("manifest sha256 mismatch")
    if problems:
        raise CheckpointCorrupt(
            f"checkpoint step {step} under {workspace}", problems
        )


class CheckpointTreeMismatch(ValueError):
    """A restored checkpoint's param tree does not match the structure/
    shapes the consumer expects. The NAMED swap-rejection error: before
    this existed a bad checkpoint surfaced as an opaque XLA compile or
    dispatch failure deep inside the first predict; now the first
    mismatched leaf is named at load time and the caller (the engine's
    hot-swap path) can roll back to the serving generation."""

    def __init__(self, context: str, problems: list[str]):
        self.problems = problems
        shown = "; ".join(problems[:5])
        more = f" (+{len(problems) - 5} more)" if len(problems) > 5 else ""
        super().__init__(f"{context}: {shown}{more}")


def _tree_signature(tree: Any) -> dict[str, tuple]:
    """'/'-joined leaf path -> (shape, dtype) for a pytree of arrays."""
    import jax

    sig: dict[str, tuple] = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        name = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        sig[name] = (shape, dtype)
    return sig


def validate_variables_tree(
    expected: Any, got: Any, context: str = "restored checkpoint"
) -> None:
    """Raise CheckpointTreeMismatch unless `got` carries exactly the leaf
    paths of `expected` with matching shapes and dtypes. `expected` may be
    a tree of real arrays or of jax.ShapeDtypeStruct — only shape/dtype
    are read. Value content is deliberately NOT inspected: weights are
    opaque, layout is the contract."""
    want, have = _tree_signature(expected), _tree_signature(got)
    problems: list[str] = []
    for name in sorted(set(want) - set(have)):
        problems.append(f"missing leaf {name} {want[name][0]}")
    for name in sorted(set(have) - set(want)):
        problems.append(f"unexpected leaf {name} {have[name][0]}")
    for name in sorted(set(want) & set(have)):
        if want[name] != have[name]:
            problems.append(
                f"leaf {name}: expected {want[name][0]}/{want[name][1]}, "
                f"got {have[name][0]}/{have[name][1]}"
            )
    if problems:
        raise CheckpointTreeMismatch(context, problems)


def load_for_serving(
    workspace: str,
    overrides: str | None = None,
    allow_random_init: bool = False,
    expected_variables: Any | None = None,
    step: int | None = None,
) -> tuple[Config, Any, Any, int]:
    """Restore (cfg, params, batch_stats, step) for inference/serving.

    Unlike the training resume path (restore() against an init_state
    template), this never materializes optimizer state: the checkpoint is
    read template-free and only the params/batch_stats subtrees are kept —
    for a serving process the Adam moments would be pure dead weight (2x
    params bytes) competing with the MPI cache for device memory.

    Returns step = the checkpoint step served (0 with allow_random_init and
    no checkpoint — smoke runs only; the step is part of every MPI cache
    key, so serving a random init never aliases a trained model's cache).

    `expected_variables` ({"params": ..., "batch_stats": ...}, arrays or
    ShapeDtypeStructs) turns on tree validation: a restored tree whose
    structure or leaf shapes diverge raises CheckpointTreeMismatch instead
    of letting the mismatch surface later as an opaque compile/dispatch
    failure. This is the hot-swap rejection path (serving/engine.py
    swap_weights validates against the serving generation's tree).

    `step` restores that specific retained step instead of the newest —
    the last_good promotion watch passes the VETTED step so a freshly
    written, not-yet-vetted checkpoint is never promoted into a live
    server. An absent step raises FileNotFoundError (named, with the
    retained set listed).
    """
    cfg = load_paired_config(workspace, overrides)
    manager = checkpoint_manager(workspace)
    if step is not None:
        retained = sorted(int(s) for s in manager.all_steps())
        if int(step) not in retained:
            raise FileNotFoundError(
                f"checkpoint step {step} not retained under "
                f"{workspace}/checkpoints (retained: {retained})"
            )
        step = int(step)
    else:
        step = manager.latest_step()
    if step is None:
        if not allow_random_init:
            raise FileNotFoundError(
                f"no checkpoint found under {workspace}/checkpoints "
                "(pass allow_random_init=True for an untrained smoke run)"
            )
        import jax
        import jax.numpy as jnp

        from mine_tpu.training.step import build_model

        model = build_model(cfg)
        variables = model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, cfg.data.img_h, cfg.data.img_w, 3), jnp.float32),
            jnp.linspace(
                cfg.mpi.disparity_start, cfg.mpi.disparity_end,
                cfg.mpi.num_bins_coarse,
            )[None, :],
            True,
        )
        return cfg, variables["params"], variables.get("batch_stats", {}), 0
    # the promotion/swap fence: bytes must still match their save-time
    # sidecar BEFORE any of them are parsed — a mismatch is the named
    # CheckpointCorrupt here, never an opaque restore error downstream
    verify_checkpoint_integrity(workspace, step)
    # template-free restore: a raw pytree of host arrays (the explicit
    # StandardRestore arg matters — a fresh manager has no handler registered
    # for the saved item and a bare restore(step) raises)
    raw = manager.restore(step, args=ocp.args.StandardRestore())
    missing = [
        f"missing collection {name!r}"
        for name in ("params", "batch_stats")
        if not isinstance(raw, dict) or name not in raw
    ]
    if missing:
        # a truncated/partial checkpoint must fail HERE with its collections
        # named, not as a flax missing-collection error inside the first
        # predict's compiled dispatch
        raise CheckpointTreeMismatch(
            f"checkpoint step {step} under {workspace}",
            missing if isinstance(raw, dict) else
            [f"restored object is not a state dict (got {type(raw).__name__})"],
        )
    params, batch_stats = raw["params"], raw["batch_stats"]
    if expected_variables is not None:
        validate_variables_tree(
            expected_variables,
            {"params": params, "batch_stats": batch_stats},
            context=f"checkpoint step {step} under {workspace}",
        )
    return cfg, params, batch_stats, int(step)
