"""Checkpoint save/restore via orbax.

Reference mechanism (synthesis_task.py:645-679, utils.py:40-67): rank-0 torch
.pth of backbone/decoder/optimizer; step and RNG are NOT saved, so a resumed
run restarts schedules from zero (SURVEY.md §5.3-5.4). Here the whole
TrainState (params, batch_stats, optimizer state, step, PRNG key) is one
orbax pytree; a restore resumes bitwise where training stopped — the
preemption-tolerance TPU pods require. The config travels next to the
checkpoints as params.yaml (the reference's checkpoint+config pairing,
image_to_video.py:275-277).
"""

from __future__ import annotations

import os
from typing import Any

import orbax.checkpoint as ocp

from mine_tpu.config import Config, load_config, save_config

_LATEST_EVERY = "state"  # item name inside each step directory


def checkpoint_manager(
    workspace: str, max_to_keep: int = 3, keep_period: int | None = None
) -> ocp.CheckpointManager:
    """Manager writing to <workspace>/checkpoints/<step>/.

    max_to_keep bounds the rolling 'latest' set (reference keeps one rolling
    checkpoint_latest.pth); keep_period pins every k-th step forever (the
    reference's immutable checkpoint_%012d at eval intervals).
    """
    path = os.path.abspath(os.path.join(workspace, "checkpoints"))
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        keep_period=keep_period,
        create=True,
    )
    return ocp.CheckpointManager(path, options=options)


def save(manager: ocp.CheckpointManager, state: Any, step: int) -> None:
    manager.save(step, args=ocp.args.StandardSave(state))


def restore(manager: ocp.CheckpointManager, state_template: Any) -> tuple[Any, int]:
    """Restore the newest step, shaped like state_template.
    Returns (state, step); (template, 0) when no checkpoint exists."""
    step = manager.latest_step()
    if step is None:
        return state_template, 0
    state = manager.restore(step, args=ocp.args.StandardRestore(state_template))
    return state, step


def save_paired_config(cfg: Config, workspace: str) -> None:
    """Archive the merged config into the workspace (train.py:206-212)."""
    save_config(cfg, os.path.join(workspace, "params.yaml"))


def load_paired_config(workspace: str) -> Config:
    """Inference re-reads the archived config (image_to_video.py:275-277)."""
    return load_config(os.path.join(workspace, "params.yaml"))


def wait_until_finished(manager: ocp.CheckpointManager) -> None:
    manager.wait_until_finished()
