"""The jitted training/eval step: forward, 4-scale loss graph, backward,
cross-replica reduction, optimizer update — one compiled program.

Reference graph: synthesis_task.py — network_forward (:420-453),
loss_fcn_per_scale (:234-390), loss_fcn multi-scale aggregation (:392-418),
render_novel_view (:455-494), train_epoch body (:627-635). There each piece
is a separate eager call with DDP allreduce on backward; here the whole step
(including the cross-replica loss averaging that induces the gradient
reduction, and BN stats sync via `axis_name`) is one XLA
program, so warp/composite/loss all fuse around the conv stacks.

Batch pytree (host loader contract, replacing init_data/set_data buffer
staging at synthesis_task.py:172-212):
  src_img, tgt_img: (B, H, W, 3) float32 in [0, 1]
  k_src, k_tgt:     (B, 3, 3)
  g_tgt_src:        (B, 4, 4)  src-frame -> tgt-frame rigid transform
  pt3d_src, pt3d_tgt: (B, N, 3) sparse COLMAP points in each camera frame

The reference's L==1 single-target assert (synthesis_task.py:203-204) is a
memory ceiling, not a design choice; here each batch slot is one (src, tgt)
pair and `data.num_tgt_views` targets per source are flattened into the batch
by the loaders (data/llff.py, data/objectron.py), so multi-target supervision
is a batch-size knob rather than a fifth tensor axis.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax import Array, lax

from mine_tpu import ops
from mine_tpu.config import Config
from mine_tpu.losses import (
    compute_scale_factor,
    edge_aware_loss,
    edge_aware_loss_v2,
    log_disparity_loss,
    lpips as lpips_fn,
    psnr,
    ssim,
)
from mine_tpu.models import MPINetwork, predict_mpi_coarse_to_fine
from mine_tpu.training.state import TrainState
from mine_tpu.utils.jax_compat import axis_size, has_vma


def _combined_axis_index(axes: tuple[str, ...]) -> Array:
    """Row-major index over a tuple of named mesh axes (major-first) — the
    chunk index a P((a1, a2)) partition assigns this device."""
    idx = jnp.zeros((), jnp.int32)
    for ax in axes:
        idx = idx * axis_size(ax) + lax.axis_index(ax)
    return idx


def _gather_placed(tree: Any, placements: Any) -> Any:
    """all_gather every sharded leaf back to its full shape — the FSDP
    weight gather. `placements` is the param-structured Placement tree from
    parallel/rules.py (duck-typed here: .replicated/.dim/.axes — step.py
    must not import the parallel package at module scope). Gathers run
    minor-axis-first so chunks reassemble in P-order; replicated leaves
    pass through untouched, so the whole call is a no-op on an unsharded
    layout."""
    if placements is None:
        return tree

    def gather(x, pl):
        if pl.replicated:
            return x
        for ax in reversed(pl.axes):
            x = lax.all_gather(x, ax, axis=pl.dim, tiled=True)
        return x

    return jax.tree.map(gather, tree, placements)


def _slice_placed(tree: Any, placements: Any) -> Any:
    """The inverse of _gather_placed: each device's chunk of every sharded
    leaf (full replicated-value trees in, local shards out)."""

    def slc(x, pl):
        if pl.replicated:
            return x
        n = 1
        for ax in pl.axes:
            n *= axis_size(ax)
        chunk = x.shape[pl.dim] // n
        start = _combined_axis_index(pl.axes) * chunk
        return lax.dynamic_slice_in_dim(x, start, chunk, axis=pl.dim)

    return jax.tree.map(slc, tree, placements)


def sharded_update(
    tx: optax.GradientTransformation,
    grads: Any,
    opt_state_local: Any,
    params_full: Any,
    update_placements: Any,
    param_placements: Any,
) -> tuple[Any, Any]:
    """The table-driven sharded optimizer step (subsumes the old
    parallel/zero1.py `shard_update`), called INSIDE shard_map with fully
    reduced (replicated-in-value) grads, the gathered full params, and the
    LOCAL shard of the optimizer state.

    Each device slices its optimizer-shard chunk of every partitioned
    grad/param leaf (`update_placements` — the moment rows of the rule
    table, resolved per param shape), runs tx.update on the shard (exact —
    the chain is elementwise per leaf), and all_gathers each update chunk
    back to ITS PARAM'S layout: over the trailing axes the param is not
    itself sharded on. Under plain ZeRO-1 (param replicated, moments over
    data) that is the classic full-update gather; under FSDP + ZeRO-1
    (param over fsdp, moments over fsdp x data) only the `data`-axis
    gather runs — 1/fsdp of the ZeRO-1 traffic — and the params stay
    sharded end to end. Returns (param-layout updates, new LOCAL opt
    state)."""
    grads_local = _slice_placed(grads, update_placements)
    params_local = _slice_placed(params_full, update_placements)
    updates_local, new_opt_local = tx.update(
        grads_local, opt_state_local, params_local
    )

    def regather(u, upl, ppl):
        if upl.replicated:
            return u
        extra = upl.axes if ppl.replicated else upl.axes[len(ppl.axes):]
        for ax in reversed(extra):
            u = lax.all_gather(u, ax, axis=upl.dim, tiled=True)
        return u

    # component scope (obs/attrib.py): the sharded-optimizer traffic is its
    # own attribution bucket, distinct from the elementwise optimizer math
    with jax.named_scope("zero1_gather"):
        updates = jax.tree.map(
            regather, updates_local, update_placements, param_placements
        )
    return updates, new_opt_local

# datasets without metric COLMAP scale: disparity point losses are off and the
# scale factor is 1 (synthesis_task.py:216-218, :312)
NO_DISP_SUPERVISION = ("flowers", "kitti_raw", "dtu")


def build_model(
    cfg: Config,
    axis_name: str | None = None,
    plane_axis: str | None = None,
    scales: tuple[int, ...] = (0, 1, 2, 3),
) -> MPINetwork:
    """axis_name: data-replica BN sync axis; plane_axis: the S-plane mesh
    axis under plane sharding (use parallel.model_axes(mesh) to derive both).
    scales: which pyramid levels get output heads AND loss terms — the loss
    graph (loss_fcn) follows model.scales, so a reduced tuple shrinks the
    whole compiled step (used by the multichip dryrun; 0 must be included)."""
    # Architecture constraint shared with the reference: the decoder's
    # receptive-field extension pools the /32 feature twice and upsamples
    # twice (depth_decoder.py:56-57, 93-96 — MaxPool2d(3,2,1) ceil-halves),
    # so the round trip restores H/32 only when H/32 % 4 == 0, i.e. H and W
    # must be multiples of 128 (all reference recipes are: 384x512, 768x256,
    # 384x256, 512x384). Fail here with the real reason instead of a shape
    # mismatch deep inside tracing.
    for dim, name in ((cfg.data.img_h, "data.img_h"), (cfg.data.img_w, "data.img_w")):
        if dim % 128 != 0:
            raise ValueError(
                f"{name}={dim} is not a multiple of 128; the MPI decoder's "
                "encoder-extension (pool x2 + up x2 over the /32 feature) "
                "requires it — same constraint as the reference "
                "(depth_decoder.py:93-96)"
            )
    return MPINetwork(
        num_layers=cfg.model.num_layers,
        multires=cfg.model.pos_encoding_multires,
        use_alpha=cfg.mpi.use_alpha,
        sigma_dropout_rate=cfg.mpi.sigma_dropout_rate,
        scales=scales,
        axis_name=axis_name,
        plane_axis=plane_axis,
        dtype=jnp.bfloat16 if cfg.model.dtype == "bfloat16" else jnp.float32,
        decoder_width_multiple=cfg.model.decoder_width_multiple,
    )


def make_disparity_list(cfg: Config, key: Array, batch_size: int) -> Array:
    """Per-step plane disparities, (B, S_coarse) descending
    (synthesis_task.py:32-61)."""
    m = cfg.mpi
    has_list = len(m.disparity_list) == m.num_bins_coarse + 1
    if m.fix_disparity:
        if has_list:
            edges = jnp.asarray(m.disparity_list, jnp.float32)
            return jnp.broadcast_to(edges[1:][None], (batch_size, m.num_bins_coarse))
        return ops.fixed_disparity_linspace(
            batch_size, m.num_bins_coarse, m.disparity_start, m.disparity_end
        )
    if has_list:
        return ops.uniform_disparity_from_bins(
            key, batch_size, jnp.asarray(m.disparity_list, jnp.float32)
        )
    return ops.uniform_disparity_from_linspace_bins(
        key, batch_size, m.num_bins_coarse, m.disparity_start, m.disparity_end
    )


def forward_coarse_to_fine(
    cfg: Config,
    model: MPINetwork,
    params: Any,
    batch_stats: Any,
    src_img: Array,
    k_src_inv: Array,
    key_disparity: Array,
    key_fine: Array | None = None,
    key_dropout: Array | None = None,
    train: bool = True,
    plane_axis: str | None = None,
) -> tuple[dict[int, Array], Array, Any]:
    """Full forward incl. optional coarse-to-fine plane refinement
    (mpi_rendering.py:244-276). All shipped configs run the single-pass path
    (num_bins_fine: 0, params_default.yaml:30).

    With `plane_axis` (inside shard_map over a mesh carrying that axis), the
    full S-plane disparity list is sampled identically on every plane device
    (the key must not be folded by plane index) and each device runs the
    decoder on its own S_local contiguous chunk — the activation memory of
    decoder + renderer divides by the plane-axis size (SURVEY.md §5.7).
    Coarse-to-fine composes with the sharding: the refinement PDF is
    per-plane scalar weights, so one (B, S) all_gather rebuilds the global
    PDF, every device samples identical fine planes, and the merged list
    re-shards — both plane counts must divide the plane-axis size
    (validated in parallel/data_parallel.py).
    """
    b, h, w, _ = src_img.shape
    disparity = make_disparity_list(cfg, key_disparity, b)
    disparity_full = disparity  # full-S list, identical on all plane devices
    if plane_axis is not None:
        n_plane = axis_size(plane_axis)
        s_local = cfg.mpi.num_bins_coarse // n_plane
        start = lax.axis_index(plane_axis) * s_local
        disparity = lax.dynamic_slice_in_dim(disparity, start, s_local, axis=1)

    stats_cell = [batch_stats]

    def predictor(img: Array, disp: Array) -> dict[int, Array]:
        variables = {"params": params, "batch_stats": stats_cell[0]}
        rngs = {"dropout": key_dropout} if key_dropout is not None else None

        def apply(v, im, dsp):
            if train:
                return model.apply(v, im, dsp, True, rngs=rngs, mutable=["batch_stats"])
            return model.apply(v, im, dsp, False, rngs=rngs), None

        if cfg.model.remat_decoder:
            apply = jax.checkpoint(apply)
        out, updates = apply(variables, img, disp)
        if updates is not None:
            stats_cell[0] = updates["batch_stats"]
        return out

    if cfg.mpi.num_bins_fine > 0 and plane_axis is not None:
        # Plane-sharded coarse-to-fine: the refinement PDF is per-plane
        # SCALAR weights (mean compositing weight per plane — the same
        # statistic the dense path uses, mpi_rendering.py:258), so the only
        # cross-device traffic is a (B, S_local) -> (B, S) all_gather —
        # the "ship statistics, not activations" discipline of
        # parallel/plane_sharding.py extended to plane placement. Every
        # device then samples IDENTICAL fine disparities (key_fine is
        # shared across plane devices — see the key-split rationale in
        # loss_fcn), sorts the identical merged list, and re-slices its
        # chunk of the new (S_coarse + S_fine)-plane axis.
        from mine_tpu.models.mpi import merge_fine_disparity
        from mine_tpu.parallel.plane_sharding import (
            sharded_plane_volume_rendering,
        )

        assert key_fine is not None, "coarse-to-fine sampling needs a PRNG key"
        n_plane = axis_size(plane_axis)
        # floor division + dynamic_slice clamping would otherwise render a
        # silently wrong plane subset for non-dividing counts (the
        # production path validates in parallel/data_parallel.py; direct
        # callers must hit a loud error too)
        if cfg.mpi.num_bins_coarse % n_plane or cfg.mpi.num_bins_fine % n_plane:
            raise ValueError(
                f"plane-sharded coarse-to-fine needs both num_bins_coarse="
                f"{cfg.mpi.num_bins_coarse} and num_bins_fine="
                f"{cfg.mpi.num_bins_fine} to divide the plane-axis size "
                f"{n_plane}"
            )
        coarse = lax.stop_gradient(predictor(src_img, disparity))
        mpi0 = coarse[0]  # full-scale local chunk (B, S_local, H, W, 4)
        grid = ops.homogeneous_pixel_grid(h, w)
        xyz_local = ops.get_src_xyz_from_plane_disparity(
            grid, disparity, k_src_inv
        )
        _, _, _, weights = sharded_plane_volume_rendering(
            mpi0[..., 0:3], mpi0[..., 3:4], xyz_local, plane_axis,
            cfg.mpi.is_bg_depth_inf,
        )
        w_local = jnp.mean(weights, axis=(2, 3, 4))  # (B, S_local)
        w_full = lax.all_gather(
            w_local, plane_axis, axis=1, tiled=True
        )  # (B, S) in mesh-position order == plane order
        disparity_all = merge_fine_disparity(
            key_fine, disparity_full, w_full, cfg.mpi.num_bins_fine
        )
        s_local2 = (
            cfg.mpi.num_bins_coarse + cfg.mpi.num_bins_fine
        ) // n_plane
        start2 = lax.axis_index(plane_axis) * s_local2
        disparity = lax.dynamic_slice_in_dim(
            disparity_all, start2, s_local2, axis=1
        )
        mpis = predictor(src_img, disparity)
    elif cfg.mpi.num_bins_fine > 0:
        grid = ops.homogeneous_pixel_grid(h, w)
        xyz_coarse = ops.get_src_xyz_from_plane_disparity(grid, disparity, k_src_inv)
        mpis, disparity = predict_mpi_coarse_to_fine(
            predictor,
            src_img,
            xyz_coarse,
            disparity,
            cfg.mpi.num_bins_fine,
            key=key_fine,
            is_bg_depth_inf=cfg.mpi.is_bg_depth_inf,
        )
    else:
        mpis = predictor(src_img, disparity)
    return mpis, disparity, stats_cell[0]


def render_novel_view(
    cfg: Config,
    mpi_rgb: Array,
    mpi_sigma: Array,
    disparity: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    scale_factor: Array | None = None,
    compositor: ops.Compositor | None = None,
) -> dict[str, Array]:
    """Warp + composite the source MPI into the target camera
    (synthesis_task.py:455-494). scale_factor divides the pose translation
    under stop_gradient (the reference's no_grad at :459-462).

    compositor defaults to the one cfg.mpi.compositor names
    (ops.compositor_from_config) — "streaming" scans plane chunks instead of
    materializing every warped plane; explicit callers (the plane-sharded
    step) pass their mesh-aware twin."""
    if compositor is None:
        compositor = ops.compositor_from_config(cfg)
    if scale_factor is not None:
        sf = lax.stop_gradient(scale_factor)
        g_tgt_src = g_tgt_src.at[:, :3, 3].set(g_tgt_src[:, :3, 3] / sf[:, None])

    # no xyz precompute: the warp evaluates per-plane xyz analytically at
    # its own sample coords (ops/mpi_render.py warp_mpi_to_tgt)
    tgt_rgb_syn, tgt_depth_syn, tgt_mask = compositor.render_tgt_rgb_depth(
        mpi_rgb,
        mpi_sigma,
        disparity,
        g_tgt_src,
        k_src_inv,
        k_tgt,
        use_alpha=cfg.mpi.use_alpha,
        is_bg_depth_inf=cfg.mpi.is_bg_depth_inf,
    )
    return {
        "tgt_imgs_syn": tgt_rgb_syn,
        "tgt_disparity_syn": 1.0 / tgt_depth_syn,
        "tgt_mask_syn": tgt_mask,
    }


def _project_points(k: Array, pt3d: Array) -> Array:
    """Camera-frame points -> pixel coords (synthesis_task.py:299-302)."""
    uvw = jnp.einsum("bij,bnj->bni", k, pt3d)
    return uvw[..., :2] / uvw[..., 2:3]


def loss_fcn_per_scale(
    cfg: Config,
    scale: int,
    batch: dict[str, Array],
    mpi: Array,
    disparity: Array,
    scale_factor: Array | None,
    is_val: bool,
    lpips_params: dict | None,
    compositor: ops.Compositor | None = None,
    per_example: bool = False,
) -> tuple[dict[str, Array], dict[str, Array], Array]:
    """One scale of the supervision graph (synthesis_task.py:234-390).

    With `per_example`, every loss_dict entry is (B,) per-example means
    instead of batch-mean scalars (bit-identical train path stays on the
    scalar branch). The decomposition is exact for every term — uniform
    pixel/point counts, and psnr/ssim/lpips are per-image by construction —
    which is what lets the val wrap-pad be masked without bias: the eval
    step weights these vectors by batch["eval_weight"] so duplicated pad
    slots contribute zero (VERDICT r4 #5).

    All S-axis reductions go through `compositor` — the plane-sharded twin
    makes this same graph run on S_local plane chunks with psum composites
    (mine_tpu/parallel/plane_sharding.py); everything downstream of the
    composited (B, H, W) maps is plane-replicated and unchanged.

    Returns (loss_dict, visualization_dict, scale_factor).
    """
    if compositor is None:
        compositor = ops.compositor_from_config(cfg)
    stride = 2**scale
    # nearest downsample == strided slice (reference nn.Upsample(size=…),
    # default nearest, synthesis_task.py:131-135: out[i] = in[i * 2^s])
    src_img = batch["src_img"][:, ::stride, ::stride]
    tgt_img = batch["tgt_img"][:, ::stride, ::stride]
    b = src_img.shape[0]

    k_src = ops.scale_intrinsics(batch["k_src"], scale)
    k_tgt = ops.scale_intrinsics(batch["k_tgt"], scale)
    k_src_inv = ops.inverse_3x3(k_src)

    assert mpi.shape[2] == src_img.shape[1] and mpi.shape[3] == src_img.shape[2], (
        f"MPI spatial dims {mpi.shape[2:4]} != scale-{scale} image dims "
        f"{src_img.shape[1:3]} — the multi-scale loss must downsample both"
    )
    mpi_rgb = mpi[..., 0:3]
    mpi_sigma = mpi[..., 3:4]

    # the source sweep is fronto-parallel, so compositing needs only the
    # disparity list + intrinsics — no (B, S, H, W, 3) xyz tensor
    # (ops/mpi_render.py render_src)
    src_syn, src_depth, blend_weights, weights = compositor.render_src(
        mpi_rgb, mpi_sigma, disparity, k_src_inv,
        use_alpha=cfg.mpi.use_alpha, is_bg_depth_inf=cfg.mpi.is_bg_depth_inf,
    )
    if cfg.training.src_rgb_blending:
        # visible-from-src parts take the real pixels; occluded parts keep the
        # network's rgb (synthesis_task.py:282-290)
        mpi_rgb = blend_weights * src_img[:, None] + (1.0 - blend_weights) * mpi_rgb
        src_syn, src_depth = compositor.weighted_sum_src(
            mpi_rgb, disparity, weights, is_bg_depth_inf=cfg.mpi.is_bg_depth_inf
        )
    src_disparity_syn = 1.0 / src_depth

    # sparse-point disparity supervision + scale calibration (:292-339)
    disp_supervised = cfg.data.name not in NO_DISP_SUPERVISION
    if disp_supervised:
        src_pt_disp = 1.0 / batch["pt3d_src"][..., 2:3]  # (B, N, 1)
        src_pt_disp_syn = ops.gather_pixel_by_pxpy(
            src_disparity_syn, _project_points(k_src, batch["pt3d_src"])
        )
        if scale_factor is None:
            scale_factor = compute_scale_factor(src_pt_disp_syn, src_pt_disp)
        loss_disp_src = log_disparity_loss(
            src_pt_disp_syn, src_pt_disp, scale_factor,
            size_average=not per_example,
        )
    else:
        if scale_factor is None:
            scale_factor = jnp.ones((b,), jnp.float32)
        loss_disp_src = jnp.zeros((b,) if per_example else ())

    render_results = render_novel_view(
        cfg, mpi_rgb, mpi_sigma, disparity,
        batch["g_tgt_src"], k_src_inv, k_tgt, scale_factor=scale_factor,
        compositor=compositor,
    )
    tgt_syn = render_results["tgt_imgs_syn"]
    tgt_disparity_syn = render_results["tgt_disparity_syn"]
    tgt_mask = render_results["tgt_mask_syn"]

    if disp_supervised:
        tgt_pt_disp = 1.0 / batch["pt3d_tgt"][..., 2:3]
        tgt_pt_disp_syn = ops.gather_pixel_by_pxpy(
            tgt_disparity_syn, _project_points(k_tgt, batch["pt3d_tgt"])
        )
        loss_disp_tgt = log_disparity_loss(
            tgt_pt_disp_syn, tgt_pt_disp, scale_factor,
            size_average=not per_example,
        )
    else:
        loss_disp_tgt = jnp.zeros((b,) if per_example else ())

    sa = not per_example  # size_average for every decomposable metric
    # target-frame supervised terms (:341-356)
    valid_mask = (tgt_mask >= cfg.mpi.valid_mask_threshold).astype(jnp.float32)
    rgb_err_tgt = jnp.abs(tgt_syn - tgt_img) * valid_mask
    loss_rgb_tgt = jnp.mean(rgb_err_tgt) if sa else jnp.mean(
        rgb_err_tgt, axis=(1, 2, 3)
    )
    loss_ssim_tgt = 1.0 - ssim(tgt_syn, tgt_img, size_average=sa)
    loss_smooth_tgt = cfg.loss.smoothness_lambda_v1 * edge_aware_loss(
        tgt_img, tgt_disparity_syn,
        gmin=cfg.loss.smoothness_gmin, grad_ratio=cfg.loss.smoothness_grad_ratio,
        size_average=sa,
    )
    loss_smooth_tgt_v2 = cfg.loss.smoothness_lambda_v2 * edge_aware_loss_v2(
        tgt_img, tgt_disparity_syn, size_average=sa
    )
    loss_smooth_src_v2 = cfg.loss.smoothness_lambda_v2 * edge_aware_loss_v2(
        src_img, src_disparity_syn, size_average=sa
    )

    # logged-only src terms, grad-blocked (reference torch.no_grad :312-323)
    src_syn_ng = lax.stop_gradient(src_syn)
    src_disp_ng = lax.stop_gradient(src_disparity_syn)
    rgb_err_src = jnp.abs(src_syn_ng - src_img)
    loss_rgb_src = jnp.mean(rgb_err_src) if sa else jnp.mean(
        rgb_err_src, axis=(1, 2, 3)
    )
    loss_ssim_src = 1.0 - ssim(src_syn_ng, src_img, size_average=sa)
    loss_smooth_src = edge_aware_loss(
        src_img, src_disp_ng,
        gmin=cfg.loss.smoothness_gmin, grad_ratio=cfg.loss.smoothness_grad_ratio,
        size_average=sa,
    )

    # eval-only metrics (:357-363)
    tgt_syn_ng = lax.stop_gradient(tgt_syn)
    psnr_tgt = psnr(tgt_syn_ng, tgt_img, size_average=sa)
    if is_val and scale == 0 and lpips_params is not None:
        lpips_tgt = lpips_fn(lpips_params, tgt_syn_ng, tgt_img, size_average=sa)
    else:
        lpips_tgt = jnp.zeros((b,) if per_example else ())

    loss = (
        loss_disp_tgt + loss_disp_src
        + loss_rgb_tgt + loss_ssim_tgt
        + loss_smooth_tgt
        + loss_smooth_src_v2 + loss_smooth_tgt_v2
    )

    loss_dict = {
        "loss": loss,
        "loss_rgb_src": loss_rgb_src,
        "loss_ssim_src": loss_ssim_src,
        "loss_disp_pt3dsrc": loss_disp_src,
        "loss_smooth_src": loss_smooth_src,
        "loss_smooth_tgt": loss_smooth_tgt,
        "loss_smooth_src_v2": loss_smooth_src_v2,
        "loss_smooth_tgt_v2": loss_smooth_tgt_v2,
        "loss_rgb_tgt": loss_rgb_tgt,
        "loss_ssim_tgt": loss_ssim_tgt,
        "lpips_tgt": lpips_tgt,
        "psnr_tgt": psnr_tgt,
        "loss_disp_pt3dtgt": loss_disp_tgt,
    }
    visualization = {
        "src_disparity_syn": src_disparity_syn,
        "tgt_disparity_syn": tgt_disparity_syn,
        "tgt_imgs_syn": tgt_syn,
        "tgt_mask_syn": tgt_mask,
        "src_imgs_syn": src_syn,
    }
    return loss_dict, visualization, scale_factor


def loss_fcn(
    cfg: Config,
    model: MPINetwork,
    params: Any,
    batch_stats: Any,
    batch: dict[str, Array],
    key: Array,
    is_val: bool,
    lpips_params: dict | None = None,
    train: bool = True,
    plane_axis: str | None = None,
    compositor: ops.Compositor | None = None,
    per_example: bool = False,
) -> tuple[Array, dict[str, Array], dict[str, Array], Any]:
    """Forward + all 4 scale losses + multi-scale aggregation
    (synthesis_task.py:392-418).

    Returns (total_loss, loss_dict, visualization_dict, new_batch_stats).
    With `per_example` (eval only), loss_dict entries — including the
    aggregated "loss" — are (B,) vectors; see loss_fcn_per_scale.
    """
    if compositor is None:
        compositor = ops.compositor_from_config(cfg)
    key_disp, key_fine, key_dropout = jax.random.split(key, 3)
    if plane_axis is not None:
        # the disparity key MUST stay shared across plane devices (each
        # slices one full-S list), but dropout masks must be i.i.d. per
        # plane chunk — an unfolded key would drop the same depth band on
        # every device
        key_dropout = jax.random.fold_in(key_dropout, lax.axis_index(plane_axis))
    k_src_inv = ops.inverse_3x3(batch["k_src"])
    mpis, disparity, new_stats = forward_coarse_to_fine(
        cfg, model, params, batch_stats, batch["src_img"], k_src_inv,
        key_disparity=key_disp, key_fine=key_fine,
        key_dropout=key_dropout if cfg.mpi.sigma_dropout_rate > 0 else None,
        train=train,
        plane_axis=plane_axis,
    )

    scales = sorted(model.scales)
    assert scales and scales[0] == 0, "scale 0 drives calibration + viz"
    scale_factor = None
    loss_dicts, viz_dicts = [], []
    for scale in scales:
        # component scope (obs/attrib.py): everything per-scale that is not
        # inside the warp/composite scopes ops/mpi_render.py sets attributes
        # to "losses"; the nested scopes win for their own ops
        with jax.named_scope("losses"):
            ld, vz, scale_factor = loss_fcn_per_scale(
                cfg, scale, batch, mpis[scale], disparity, scale_factor,
                is_val=is_val, lpips_params=lpips_params, compositor=compositor,
                per_example=per_example,
            )
        loss_dicts.append(ld)
        viz_dicts.append(vz)

    loss_dict = dict(loss_dicts[0])
    total = loss_dict["loss"]
    for ld in loss_dicts[1:]:
        if cfg.training.use_multi_scale:
            total = total + ld["loss_rgb_tgt"] + ld["loss_ssim_tgt"]
        total = total + ld["loss_disp_pt3dsrc"] + ld["loss_disp_pt3dtgt"]
        total = total + ld["loss_smooth_src_v2"] + ld["loss_smooth_tgt_v2"]
    loss_dict["loss"] = total
    return total, loss_dict, viz_dicts[0], new_stats


def make_train_step(
    cfg: Config,
    model: MPINetwork,
    tx: optax.GradientTransformation,
    axis_name: str | tuple[str, ...] | None = None,
    plane_axis: str | None = None,
    compositor: ops.Compositor | None = None,
    param_placements: Any | None = None,
    update_placements: Any | None = None,
) -> Callable[[TrainState, dict[str, Array]], tuple[TrainState, dict[str, Array]]]:
    """Build the train-step function (one optimizer update,
    synthesis_task.py:627-635 under jit).

    With `axis_name` — a single mesh axis or the ("data","fsdp") tuple one
    logical batch spans — the function expects to run inside shard_map over
    those axes: per-replica RNG folding, the scalar loss pmean'd before
    differentiation (which makes AD emit the global-batch gradient — the
    DDP-allreduce + SyncBN equivalent, SURVEY.md §2.4), logged losses
    pmean'd after.

    With `plane_axis` (+ the matching plane-sharded `compositor`), the S
    plane axis additionally shards over that mesh axis (SURVEY.md §5.7). The
    RNG folds the data index only — plane devices of one data replica MUST
    share a key so they sample the same full-S disparity list and slice it.
    The loss is NOT pmean'd over the plane axis: each plane device's params
    cotangent carries only its local planes' contribution, and shard_map's
    automatic psum of the replicated-param cotangent across the mesh sums
    them into the exact full-S gradient (a plane pmean would shrink it by
    the plane count).

    With `training.accum_steps` = k > 1, ONE update is computed from k
    sequential micro-batches: the per-device batch (b, ...) reshapes to
    (k, b/k, ...) and a lax.scan runs the forward+backward on each
    micro-batch, accumulating gradients in fp32. Peak activation memory is
    that of a SINGLE micro-batch (the scan serializes the per-micro
    forward+backwards; nothing lives across iterations but the fp32
    accumulator + BN stats carry), so effective batch decouples from HBM
    (tools/bench_accum.py measures the claim). Numerics: equal-size
    micro-batches make mean-of-micro-means == the full-batch mean, so at
    fp32 accumulation is a numerics no-op up to summation order
    (PARITY.md). BN-stats policy: SEQUENTIAL (running) — the stats carry
    threads through the scan, so every micro-batch contributes exactly as
    k separate steps would have; each micro-batch normalizes by its OWN
    batch moments (synced over the mesh as always), which is the one
    deliberate deviation from a monolithic step's full-batch moments
    (tests/test_accum.py pins both properties). The RNG folds the
    micro-step index so disparity sampling/dropout stay i.i.d. across
    micro-batches. Per-micro-step finiteness flags AND-reduce (and pmean
    to a mesh-consistent verdict) so a single poisoned micro-batch masks
    the whole update bitwise, exactly as a poisoned batch does at k=1.

    With `param_placements` / `update_placements` (the param-structured
    Placement trees the partition-rule table resolves —
    parallel/rules.py, via data_parallel._state_layout; require
    `axis_name`), the step runs the sharded layouts: `state.params` holds
    this device's FSDP shard of every fsdp-sharded leaf (all-gathered once
    at step start, `fsdp_gather` scope), `state.opt_state` holds the local
    shard of the Adam moments, the update is computed on the moment shard
    from the (replicated-in-value, already-reduced) grads, and all_gathers
    reassemble each update chunk back to its param's own layout
    (`sharded_update`) — grads are still reduced exactly once, and the
    params never exist unsharded outside the step.

    Sentinel instrumentation (resilience/sentinel.py): the returned
    loss_dict always carries `grad_norm` (the post-reduction global
    gradient norm) and `update_skipped`. With any
    `resilience.sentinel_policy` other than "off", the step additionally
    masks the whole update in-graph when `isfinite(loss) & isfinite(|g|)`
    is false — params, optimizer state, and BN stats keep their previous
    values (`update_skipped` reports 1.0), while step/RNG still advance so
    the data and key streams move past the poisoned batch.
    """
    if compositor is None:
        compositor = ops.compositor_from_config(cfg)
    sentinel_mask = cfg.resilience.sentinel_policy != "off"
    accum = max(int(cfg.training.accum_steps), 1)
    if update_placements is not None and axis_name is None:
        raise ValueError("sharded layouts live on mesh axes: axis_name is "
                         "required when update_placements is given")

    def micro_grads(params, batch_stats, batch, rng):
        """Forward + backward of one (micro-)batch: the unit both the
        single-pass and the accumulating step build on."""

        def loss_fn(p):
            total, loss_dict, _viz, new_stats = loss_fcn(
                cfg, model, p, batch_stats, batch, rng,
                is_val=False, train=True,
                plane_axis=plane_axis, compositor=compositor,
            )
            # The cross-replica gradient reduction happens HERE, by averaging
            # the scalar loss before differentiation — not by pmean-ing grads
            # after. Under shard_map's varying-manual-axes semantics the
            # cotangent of the replicated params is automatically psum'd
            # across the axis, so a post-grad pmean would be an identity on an
            # already-summed (n-times-too-large) gradient. Averaging the loss
            # makes AD produce exactly the global-batch gradient.
            if axis_name is not None:
                total = lax.pmean(total, axis_name)
            return total, (loss_dict, new_stats)

        return jax.grad(loss_fn, has_aux=True)(params)

    def reduce_grads(grads):
        if not has_vma():
            # Pre-vma shard_map (jax 0.4.x) has none of the
            # replicated-cotangent machinery described in micro_grads:
            # there each device's grad carries only its own shard's
            # contribution, so the reduction is explicit — MEAN over the
            # data axis (each replica grads its local-batch mean; this is
            # the DDP allreduce) and SUM over the plane axis (each device
            # owns its S_local planes' slice of the full-S gradient).
            # On vma jax both reductions happen inside AD and these would
            # double-count — hence the version gate. Under accumulation
            # this runs ONCE on the fp32 accumulator, not per micro-step:
            # the "grads psum'd once" half of the microbatching contract.
            if axis_name is not None:
                grads = lax.pmean(grads, axis_name)
            if plane_axis is not None:
                grads = lax.psum(grads, plane_axis)
        return grads

    def apply_update(grads, opt_state, params_full):
        if update_placements is not None:
            return sharded_update(
                tx, grads, opt_state, params_full,
                update_placements, param_placements,
            )
        return tx.update(grads, opt_state, params_full)

    def accumulate(params_full: Any, state: TrainState,
                   batch: dict[str, Array], rng: Array):
        """k micro-steps -> (mean fp32 grads, mean loss_dict, final BN
        stats, AND-of-micro finiteness), all pre-reduction. `params_full`
        is the (possibly fsdp-gathered) full param tree — gathered ONCE
        outside the scan, not per micro-step."""
        b = jax.tree.leaves(batch)[0].shape[0]
        if b % accum:
            raise ValueError(
                f"training.accum_steps={accum} must divide the per-device "
                f"batch size {b} (batch reshapes to (k, b/k, ...))"
            )
        micro = jax.tree.map(
            lambda x: x.reshape((accum, b // accum) + x.shape[1:]), batch
        )

        def body(carry, xs):
            acc, stats = carry
            mb, i = xs
            # i.i.d. sampling per micro-batch: an unfolded key would give
            # every micro-batch the same disparity draw / dropout mask
            grads, (loss_dict, new_stats) = micro_grads(
                params_full, stats, mb, jax.random.fold_in(rng, i)
            )
            # the per-micro flag catches poison the final post-reduction
            # check could in principle miss (e.g. inf micro-grads cancelling
            # across micro-batches); it AND-reduces below
            finite = jnp.isfinite(loss_dict["loss"]) & jnp.isfinite(
                optax.global_norm(grads)
            )
            acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), acc, grads
            )
            return (acc, new_stats), (loss_dict, finite)

        # the scan IS the memory contract: it serializes the k
        # forward+backwards (jax.grad runs inside the body) and nothing
        # lives across iterations beyond the carry (fp32 accumulator + BN
        # stats), so peak activation memory is ONE micro-batch's —
        # tools/bench_accum.py measures exactly that. jax.checkpoint
        # lowers as a no-op today (nothing differentiates THROUGH this
        # scan); it is armed in case an outer grad ever does
        body = jax.checkpoint(body)
        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params_full
        )
        (acc, new_stats), (loss_dicts, finite_flags) = lax.scan(
            body, (zeros, state.batch_stats), (micro, jnp.arange(accum))
        )
        grads = jax.tree.map(lambda a: a / accum, acc)
        # equal-size micro-batches: mean over k of per-micro batch means ==
        # the full-batch mean, for every (decomposable) logged term
        loss_dict = jax.tree.map(lambda v: jnp.mean(v, axis=0), loss_dicts)
        return grads, loss_dict, new_stats, jnp.all(finite_flags)

    def train_step(state: TrainState, batch: dict[str, Array]):
        rng = jax.random.fold_in(state.rng, state.step)
        if axis_name is not None:
            # a tuple axis_name yields the combined row-major replica index
            rng = jax.random.fold_in(rng, lax.axis_index(axis_name))

        # FSDP weight gather, ONCE per step (and once per step under
        # accumulation — outside the micro-batch scan): the only moment the
        # full params exist on a device; everything upstream and downstream
        # sees shards (obs/attrib.py buckets the traffic as fsdp_gather)
        with jax.named_scope("fsdp_gather"):
            params_full = _gather_placed(state.params, param_placements)

        if accum > 1:
            grads, loss_dict, new_stats, micro_finite = accumulate(
                params_full, state, batch, rng
            )
            # the per-micro AND is computed from LOCAL losses/grads and can
            # disagree across devices (a NaN poisons one shard's flags
            # before any collective) — pmean it into one mesh-wide verdict
            # so the update mask below stays bitwise-identical everywhere
            micro_finite = micro_finite.astype(jnp.float32)
            if axis_name is not None:
                micro_finite = lax.pmean(micro_finite, axis_name)
            if plane_axis is not None:
                micro_finite = lax.pmean(micro_finite, plane_axis)
            micro_finite = micro_finite == 1.0
        else:
            grads, (loss_dict, new_stats) = micro_grads(
                params_full, state.batch_stats, batch, rng
            )
            micro_finite = jnp.asarray(True)
        grads = reduce_grads(grads)
        if axis_name is not None:
            loss_dict = lax.pmean(loss_dict, axis_name)
        # component scope (obs/attrib.py): the update math; the sharded
        # update's all_gathers inside carry their own zero1_gather scope.
        # updates come back in the PARAMS' layout (fsdp shards stay shards)
        with jax.named_scope("optimizer"):
            updates, new_opt_state = apply_update(
                grads, state.opt_state, params_full
            )
            new_params = optax.apply_updates(state.params, updates)
        # post-reduction, so every replica computes the identical norm and
        # the identical finite verdict (a NaN anywhere pmean-poisons all)
        grad_norm = optax.global_norm(grads)
        loss_dict["grad_norm"] = grad_norm
        finite = (
            jnp.isfinite(loss_dict["loss"]) & jnp.isfinite(grad_norm)
            & micro_finite
        )
        if sentinel_mask:
            keep = lambda new, old: jax.tree.map(  # noqa: E731
                lambda n, o: jnp.where(finite, n, o), new, old
            )
            new_params = keep(new_params, state.params)
            new_opt_state = keep(new_opt_state, state.opt_state)
            new_stats = keep(new_stats, state.batch_stats)
            loss_dict["update_skipped"] = 1.0 - finite.astype(jnp.float32)
        else:
            loss_dict["update_skipped"] = jnp.zeros((), jnp.float32)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        )
        return new_state, loss_dict

    return train_step


def make_eval_step(
    cfg: Config,
    model: MPINetwork,
    lpips_params: dict | None = None,
    axis_name: str | tuple[str, ...] | None = None,
    plane_axis: str | None = None,
    compositor: ops.Compositor | None = None,
    param_placements: Any | None = None,
):
    """Eval step: same loss graph, eval-mode BN, no update
    (synthesis_task.py:496-527). Runs on every replica (the reference runs
    eval on rank 0 only — SURVEY.md §5.3 lists that as a gap, not a
    feature). With `param_placements` the incoming params are FSDP shards
    and get the same one-shot gather the train step does."""
    if compositor is None:
        compositor = ops.compositor_from_config(cfg)

    def eval_step(state: TrainState, batch: dict[str, Array], key: Array):
        if axis_name is not None:
            key = jax.random.fold_in(key, lax.axis_index(axis_name))
        with jax.named_scope("fsdp_gather"):
            params_full = _gather_placed(state.params, param_placements)
        batch = dict(batch)
        # per-example validity: 0.0 on wrap-padded val slots (data/llff.py
        # epoch), absent for datasets that never pad
        weight = batch.pop("eval_weight", None)
        _total, loss_dict, viz, _ = loss_fcn(
            cfg, model, params_full, state.batch_stats, batch, key,
            is_val=True, lpips_params=lpips_params, train=False,
            plane_axis=plane_axis, compositor=compositor,
            per_example=True,
        )
        if weight is None:
            weight = jnp.ones_like(loss_dict["psnr_tgt"])
        # exact weighted mean under data sharding: psum numerator and
        # denominator separately (a pmean of per-shard weighted means would
        # over-weight shards whose pad slots landed elsewhere)
        num = jax.tree.map(lambda v: jnp.sum(v * weight), loss_dict)
        den = jnp.sum(weight)
        if axis_name is not None:
            num = lax.psum(num, axis_name)
            den = lax.psum(den, axis_name)
        loss_dict = jax.tree.map(lambda n: n / jnp.maximum(den, 1.0), num)
        # genuine-example count for this batch: the meter weight (reference
        # updates with n=B, synthesis_task.py:535) and the epoch-count audit
        loss_dict["eval_examples"] = den
        return loss_dict, viz

    return eval_step


def init_state(
    cfg: Config,
    model: MPINetwork,
    tx: optax.GradientTransformation,
    rng: Array,
    load_pretrained: bool = True,
) -> TrainState:
    """Initialize params/batch_stats/optimizer into a TrainState.

    With `model.imagenet_pretrained` and a `model.pretrained_backbone_path`
    (an .npz from tools/convert_resnet.py), the encoder starts from converted
    ImageNet weights — the reference's torchvision download
    (resnet_encoder.py:56-60), minus the egress and the rank-0-only
    asymmetry: every process loads the identical artifact. Pass
    load_pretrained=False when the state is only a template for a checkpoint
    restore (resume, inference): the restore overwrites everything, and the
    .npz need not exist on that host.
    """
    key_init, key_state = jax.random.split(rng)
    dummy_img = jnp.zeros((1, cfg.data.img_h, cfg.data.img_w, 3), jnp.float32)
    dummy_disp = jnp.linspace(
        cfg.mpi.disparity_start, cfg.mpi.disparity_end, cfg.mpi.num_bins_coarse
    )[None, :]
    variables = model.init(key_init, dummy_img, dummy_disp, True)
    if cfg.model.imagenet_pretrained and load_pretrained:
        if cfg.model.pretrained_backbone_path:
            from mine_tpu.models import apply_pretrained_backbone

            variables = apply_pretrained_backbone(
                variables, cfg.model.pretrained_backbone_path
            )
        else:
            import logging

            logging.getLogger("mine_tpu").warning(
                "model.imagenet_pretrained is set but "
                "model.pretrained_backbone_path is empty — the backbone "
                "starts RANDOM. Convert weights offline with "
                "tools/convert_resnet.py (no-egress substitute for the "
                "reference's torchvision download)."
            )
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt_state = tx.init(params)
    return TrainState.create(params, batch_stats, opt_state, key_state)
