"""Train state pytree.

Replaces the reference's mutable SynthesisTask attributes (model refs,
optimizer, global_step scattered across synthesis_task.py:65-170) with one
immutable pytree. Unlike the reference checkpoint dict (backbone/decoder/
optimizer only, synthesis_task.py:649-651 — step and RNG are lost on resume,
SURVEY.md §5.3), everything needed for bitwise resume lives here.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct
from jax import Array


class TrainState(struct.PyTreeNode):
    step: Array  # scalar int32 global step
    params: Any
    batch_stats: Any
    opt_state: Any
    rng: Array  # PRNG key consumed (fold_in step) by each train step

    @classmethod
    def create(cls, params, batch_stats, opt_state, rng) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            rng=rng,
        )
