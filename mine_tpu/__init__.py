"""mine_tpu — a TPU-native (JAX/XLA/Pallas) framework for single-image novel view
synthesis with continuous-depth Multiplane Images (MPI + NeRF-style volume rendering).

Re-designed from scratch for TPU hardware with the capability surface of the
reference PyTorch implementation (zubair-irshad/MINE):

  - `ops/`       stateless, jittable geometry / warping / compositing kernels,
                 vmapped over the plane axis S (reference: operations/)
  - `models/`    Flax encoder-decoder predicting an MPI from one RGB image
                 (reference: network/)
  - `training/`  one jit-compiled SPMD train step (fwd + 4-scale loss + grad +
                 update), orbax checkpointing, metric logging
                 (reference: synthesis_task.py + train.py)
  - `data/`      COLMAP / LLFF / synthetic input pipelines feeding sharded
                 device batches (reference: input_pipelines/)
  - `parallel/`  mesh construction, batch/plane sharding rules, plane-axis
                 sharded compositing (the long-context analog of this model)
  - `inference/` predict-once / render-many novel-view video generation
                 (reference: visualizations/image_to_video.py)

Design stance (vs the reference): pure functions over pytrees, explicit PRNG
keys, static shapes under jit, NHWC layouts, closed-form 3x3 inverses instead
of library LAPACK calls, and GSPMD sharding instead of NCCL process groups.
"""

__version__ = "0.1.0"
