"""Backend-platform forcing that actually sticks.

Some PJRT plugins self-register at import time regardless of JAX_PLATFORMS
(the axon TPU plugin in this image does), so the env var alone can leave
the first backend touch initializing — or hanging on — an accelerator the
user explicitly opted out of. The fix is the full recipe: env vars + the
in-process jax.config update, applied BEFORE any backend touch.

`force_cpu_devices(n)` is the shared core used by the driver entry points
(__graft_entry__), tests/conftest.py, and the CLIs' `honor_jax_platforms()`
guard. `fast_compile` disables LLVM's expensive optimization passes —
compile-time over run-time, for correctness gates only, never benches.
"""

from __future__ import annotations

import os

from mine_tpu.utils.compile_cache import enable_persistent_compile_cache


def force_cpu_devices(
    n_devices: int,
    compilation_cache: bool = True,
    fast_compile: bool = False,
) -> None:
    """Force an n-device virtual CPU backend before any JAX backend touch.

    Must run in a process where no JAX backend has been touched yet (both
    XLA_FLAGS and jax_platforms are consumed at backend init and silently
    ignored afterwards); raises RuntimeError otherwise instead of letting
    the caller crash later on a confusing mesh-size error.
    """
    # Replace (not just append) any preset device-count flag: a preset value
    # != n_devices would win and make_mesh(n) would fail.
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    if fast_compile:
        flags.append("--xla_llvm_disable_expensive_passes=true")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    if compilation_cache:
        enable_persistent_compile_cache()
    devices = jax.devices()
    if len(devices) != n_devices or devices[0].platform != "cpu":
        raise RuntimeError(
            f"virtual CPU mesh forcing was a no-op: got {len(devices)} "
            f"{devices[0].platform} device(s), wanted {n_devices} cpu. The "
            "JAX backend was already initialized in this process — force "
            "the platform in a fresh process."
        )


def honor_jax_platforms() -> None:
    """CLI-entry guard: make `JAX_PLATFORMS=cpu` mean what it says.

    Called first thing by the train/evaluate/infer CLIs. Without it, a
    self-registering accelerator plugin can initialize (or hang on) its
    backend even though the user asked for CPU. A no-op for any other
    JAX_PLATFORMS value, and preserves a caller-set virtual device count.
    """
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    preset = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if f.startswith("--xla_force_host_platform_device_count=")
    ]
    n = int(preset[-1].split("=")[1]) if preset else 1
    force_cpu_devices(n)
