"""Backend-platform forcing that actually sticks.

Some PJRT plugins self-register at import time regardless of JAX_PLATFORMS
(the axon TPU plugin in this image does), so the env var alone can leave
the first backend touch initializing — or hanging on — an accelerator the
user explicitly opted out of. The fix is the full recipe: env vars + the
in-process jax.config update, applied BEFORE any backend touch.

`force_cpu_devices(n)` is the shared core used by the driver entry points
(__graft_entry__), tests/conftest.py, and the CLIs' `honor_jax_platforms()`
guard. `fast_compile` disables LLVM's expensive optimization passes —
compile-time over run-time, for correctness gates only, never benches.
"""

from __future__ import annotations

import os
import subprocess
import sys

from mine_tpu.utils.compile_cache import enable_persistent_compile_cache

# THE spelling of XLA's virtual-host-device flag. Lives HERE (stdlib-weight
# module, importable by every pre-backend CLI guard without pulling the
# parallel package) and is re-exported by parallel/mesh.py for mesh
# consumers — everything that fakes a multi-device mesh references one of
# the two names, so the spelling cannot drift.
VIRTUAL_DEVICE_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_devices(
    n_devices: int,
    compilation_cache: bool = False,
    fast_compile: bool = False,
    verify: bool = True,
) -> None:
    """Force an n-device virtual CPU backend before any JAX backend touch.

    Must run in a process where no JAX backend has been touched yet (both
    XLA_FLAGS and jax_platforms are consumed at backend init and silently
    ignored afterwards); raises RuntimeError otherwise instead of letting
    the caller crash later on a confusing mesh-size error.

    compilation_cache defaults OFF on the forced-CPU path: XLA:CPU's
    persistent-cache round trip has been observed to DESERIALIZE a donated
    8-device shard_map train step into an executable that returns the
    params unchanged (all-zero updates, loss still correct) — first run
    after any HLO change compiles fresh and is right, every warm-cache
    rerun is silently wrong. TPU runs keep the cache (different, mature
    serialization path; and the multi-minute compiles it exists for).
    """
    # Replace (not just append) any preset device-count flag: a preset value
    # != n_devices would win and make_mesh(n) would fail.
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith(VIRTUAL_DEVICE_FLAG)
    ]
    flags.append(f"{VIRTUAL_DEVICE_FLAG}={n_devices}")
    if fast_compile:
        flags.append("--xla_llvm_disable_expensive_passes=true")
    os.environ["XLA_FLAGS"] = " ".join(flags)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    if compilation_cache:
        enable_persistent_compile_cache()
    if not verify:
        # env + config are set; the caller (a pending multi-host bring-up)
        # cannot afford the jax.devices() probe — it IS a backend touch
        return
    devices = jax.devices()
    if len(devices) != n_devices or devices[0].platform != "cpu":
        raise RuntimeError(
            f"virtual CPU mesh forcing was a no-op: got {len(devices)} "
            f"{devices[0].platform} device(s), wanted {n_devices} cpu. The "
            "JAX backend was already initialized in this process — force "
            "the platform in a fresh process."
        )


def arm_watchdog(secs: int, emit_failure, label: str = "bench"):
    """Run the caller's failure emitter and os._exit(1) unless the returned
    Event is .set() within secs — the deadline discipline every bench entry
    point shares (one definition, like resolve_backend_probe).

    A THREAD, not SIGALRM: the guarded failure mode is a hang inside a
    blocked C call (PJRT init over the dead tunnel), which never returns to
    the interpreter to run a Python signal handler — but blocked syscalls
    release the GIL, so a watchdog thread keeps running.
    emit_failure(exc) must print the caller's one-line failure JSON.
    """
    import threading

    done = threading.Event()

    def _watch():
        if not done.wait(secs):
            emit_failure(
                TimeoutError(f"{label} exceeded {secs}s (hung TPU tunnel?)")
            )
            sys.stdout.flush()
            os._exit(1)

    threading.Thread(
        target=_watch, daemon=True, name=f"watchdog-{label}"
    ).start()
    return done


def resolve_backend_probe(probe_timeout_s: int) -> str:
    """Decide the backend BEFORE jax is touched in the calling process —
    the shared policy of every bench entry point (bench.py,
    tools/bench_serve.py, tools/bench_composite.py; one definition so a
    probe fix cannot silently miss a bench).

    JAX_PLATFORMS=cpu is honored as-is. Otherwise a subprocess — killable,
    unlike an in-process hung PJRT init — probes the default backend; any
    failure or timeout sets JAX_PLATFORMS=cpu in THIS process and returns a
    degraded label with the reason, so the caller produces a labeled CPU
    measurement instead of a null one. Call honor_jax_platforms() after.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu (JAX_PLATFORMS)"
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=probe_timeout_s,
        )
        platform = (
            out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
        )
        if out.returncode == 0 and platform and platform != "cpu":
            return platform  # accelerator reachable: use it
        if out.returncode == 0 and platform == "cpu":
            # a healthy host that simply has no accelerator is NOT the
            # dead-tunnel failure mode — label it honestly
            os.environ["JAX_PLATFORMS"] = "cpu"
            return "cpu (no accelerator)"
        reason = f"probe rc={out.returncode} platform={platform!r}"
    except subprocess.TimeoutExpired:
        reason = f"probe hung > {probe_timeout_s}s (dead TPU tunnel?)"
    os.environ["JAX_PLATFORMS"] = "cpu"
    return f"cpu (degraded: {reason})"


def honor_jax_platforms() -> None:
    """CLI-entry guard: make `JAX_PLATFORMS=cpu` mean what it says.

    Called first thing by the train/evaluate/infer CLIs. Without it, a
    self-registering accelerator plugin can initialize (or hang on) its
    backend even though the user asked for CPU. A no-op for any other
    JAX_PLATFORMS value, and preserves a caller-set virtual device count.
    """
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    preset = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if f.startswith(VIRTUAL_DEVICE_FLAG + "=")
    ]
    n = int(preset[-1].split("=")[1]) if preset else 1
    # A pending multi-host bring-up (parallel/mesh.py init_multihost reads
    # $MINE_TPU_MULTIHOST) forbids touching the backend here:
    # jax.distributed.initialize() only works on an untouched backend, and
    # the verification probe below IS a backend touch. Set the flags, skip
    # the probe — bring-up itself fails loudly if something pre-initialized.
    force_cpu_devices(n, verify=not os.environ.get("MINE_TPU_MULTIHOST"))
