"""The one-JSON-verdict-line-to-stdout contract, in one place.

Every gate CLI in this repo (bench.py, tools/perf_ledger.py,
tools/conformance_run.py, tools/chaos_drill.py, tools/lint_run.py)
promises the same thing: human progress goes to stderr, stdout carries
EXACTLY ONE JSON object line — the verdict — and the exit code follows
its `ok` field. Three tools had hand-rolled that contract independently;
this module is the single definition they now share, so the contract
cannot drift (a second stdout line breaks every `$(tool | tail -1)`
consumer and the drill's embedded-verdict parsing).

Stdlib-only: importable by pre-backend CLI guards without touching jax.
"""

from __future__ import annotations

import json
import sys
import traceback
from typing import Any


def emit(verdict: dict[str, Any]) -> int:
    """Print the verdict as one JSON line on stdout; return the exit code
    (0 iff verdict["ok"] is truthy) for the caller to raise SystemExit
    with. Flushes, so the line survives an os._exit watchdog."""
    sys.stdout.write(json.dumps(verdict) + "\n")
    sys.stdout.flush()
    return 0 if verdict.get("ok") else 1


def emit_failure(metric: str, exc: BaseException, **extra: Any) -> int:
    """The emit-then-exit contract for a crashed gate: traceback to
    stderr for the human, a well-formed failing verdict to stdout for the
    machine consumer (never a bare stack trace as the only output)."""
    traceback.print_exc(file=sys.stderr)
    return emit({
        "metric": metric, "value": None, "ok": False,
        "error": f"{type(exc).__name__}: {exc}"[:2000], **extra,
    })
