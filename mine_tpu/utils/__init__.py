"""Shared utilities (reference: utils.py)."""

from mine_tpu.utils.logging import (
    AverageMeter,
    MetricWriter,
    make_logger,
    normalize_disparity_for_vis,
)
from mine_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Summary,
)
