"""Persistent XLA compilation cache, one switch for every entry point.

The tunneled TPU backend's compile is slow (minutes for the full train
step) and the tunnel itself is mortal — cache hits make repeat runs
(bench re-invocations, width-knob experiments, profiler reruns, resumed
convergence runs) near-free. Call before the first backend touch.
"""

from __future__ import annotations

import os


def enable_persistent_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at an on-disk compile cache (repo-local by default).

    Safe to call on any jax version/backend: unknown config names are
    swallowed, matching the reference's attitude to optional accelerators.
    """
    import jax

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - config surface varies by version
        pass
