"""Persistent XLA compilation cache, one switch for every entry point.

The tunneled TPU backend's compile is slow (minutes for the full train
step) and the tunnel itself is mortal — cache hits make repeat runs
(bench re-invocations, width-knob experiments, profiler reruns, resumed
convergence runs) near-free. Call before the first backend touch.
"""

from __future__ import annotations

import os


def enable_persistent_compile_cache(cache_dir: str | None = None) -> None:
    """Point jax at an on-disk compile cache (repo-local by default).

    Safe to call on any jax version/backend: unknown config names are
    swallowed, matching the reference's attitude to optional accelerators.

    A NO-OP when the process is pinned to CPU (JAX_PLATFORMS=cpu — tests,
    the benches' degraded fallback, CPU CLIs): XLA:CPU's serialized-
    executable round trip has been observed to reload a donated 8-device
    shard_map train step as an executable that returns the params UNCHANGED
    (all-zero updates, loss still correct) — first run after any HLO change
    compiles fresh and is right, every warm-cache rerun silently wrong
    (mine_tpu/utils/platform.py force_cpu_devices). The cache's payoff is
    the TPU backend's multi-minute compiles; CPU keeps correctness.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return

    import jax

    try:
        # env unset but a backend already committed: trust the real backend.
        # (When no backend exists yet we deliberately do NOT initialize one
        # here — on a hung TPU tunnel that first touch blocks forever, the
        # exact failure every caller of this function routes around. An
        # accelerator-less host with env unset and no backend yet therefore
        # still enables the cache; every CPU entry point in this repo sets
        # JAX_PLATFORMS=cpu, closing that path in practice.)
        from jax._src import xla_bridge

        if xla_bridge._backends and jax.default_backend() == "cpu":
            return
    except Exception:  # pragma: no cover - private surface varies by version
        pass

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            ".jax_cache",
        )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - config surface varies by version
        pass
