"""Metrics, meters, and writers.

Reference: utils.py:123-144 (AverageMeter), synthesis_task.py:529-607
(TensorBoard scalars/image grids), train.py:177-197 (file+stdout logger).
Additions the reference lacks (SURVEY.md §5.1): per-step wall-clock timing
and imgs/sec in every log line, plus a machine-readable metrics.jsonl.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Any

import numpy as np


class AverageMeter:
    """Running mean of a scalar stream (reference utils.py:123-144)."""

    def __init__(self, name: str):
        self.name = name
        self.reset()

    def reset(self) -> None:
        self.sum = 0.0
        self.count = 0

    def update(self, value: float, n: int = 1) -> None:
        self.sum += float(value) * n
        self.count += n

    @property
    def avg(self) -> float:
        return self.sum / max(self.count, 1)

    def __str__(self) -> str:
        return f"{self.name} {self.avg:.4f} ({self.count})"


def make_logger(workspace: str | None, name: str = "mine_tpu") -> logging.Logger:
    """stdout (+ workspace file) logger, process-0 only emits by default
    (reference gates on global_rank==0, train.py:177-197)."""
    import jax

    logger = logging.getLogger(name)
    logger.setLevel(logging.INFO)
    logger.handlers.clear()
    if jax.process_index() != 0:
        logger.addHandler(logging.NullHandler())
        return logger
    fmt = logging.Formatter("[%(asctime)s %(levelname)s] %(message)s")
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    if workspace:
        os.makedirs(workspace, exist_ok=True)
        fh = logging.FileHandler(os.path.join(workspace, "train.log"))
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    return logger


class MetricWriter:
    """Scalars + images to TensorBoard (tensorboardX) and metrics.jsonl.

    The jsonl stream is the machine-readable twin of the reference's
    TB-only logging; each line: {"step": n, "tag": ..., "value": ...}.
    """

    def __init__(self, workspace: str | None):
        self._tb = None
        self._jsonl = None
        import jax

        if workspace and jax.process_index() == 0:
            os.makedirs(workspace, exist_ok=True)
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(workspace)
            except ImportError:
                pass
            self._jsonl = open(os.path.join(workspace, "metrics.jsonl"), "a")

    def scalar(self, tag: str, value: Any, step: int) -> None:
        value = float(value)
        if self._tb:
            self._tb.add_scalar(tag, value, step)
        if self._jsonl:
            self._jsonl.write(json.dumps({"step": step, "tag": tag, "value": value}) + "\n")

    def scalars(self, values: dict[str, Any], step: int, prefix: str = "") -> None:
        for tag, value in values.items():
            self.scalar(prefix + tag, value, step)

    def image_grid(self, tag: str, images: np.ndarray, step: int) -> None:
        """(N, H, W, C) in [0,1] -> single row grid (reference
        synthesis_task.py:537-568 uses torchvision make_grid)."""
        if self._tb is None:
            return
        images = np.clip(np.asarray(images), 0.0, 1.0)
        grid = np.concatenate(list(images), axis=1)  # (H, N*W, C)
        self._tb.add_image(tag, grid, step, dataformats="HWC")

    def flush(self) -> None:
        if self._tb:
            self._tb.flush()
        if self._jsonl:
            self._jsonl.flush()

    def close(self) -> None:
        if self._tb:
            self._tb.close()
        if self._jsonl:
            self._jsonl.close()


def normalize_disparity_for_vis(disp: np.ndarray) -> np.ndarray:
    """Min-max normalize per image for TB display (utils.py:6-17)."""
    disp = np.asarray(disp)
    lo = disp.min(axis=(1, 2, 3), keepdims=True)
    hi = disp.max(axis=(1, 2, 3), keepdims=True)
    return (disp - lo) / np.maximum(hi - lo, 1e-8)
