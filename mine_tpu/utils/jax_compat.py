"""Version-bridging imports for jax surfaces that moved between releases.

The container pins jax 0.4.37 while parts of this repo were written against
the promoted post-0.4 API; both must work:

  - `shard_map` is `jax.shard_map` on new jax and
    `jax.experimental.shard_map.shard_map` before the promotion. The bare
    `from jax import shard_map` made the whole mine_tpu.parallel package —
    and everything importing it (training loop, SPMD tests, tools) —
    unimportable on 0.4.x.
  - `jax.typeof` (the vma-carrying abstract-value probe the Pallas kernels
    use under shard_map's strict vma checking) does not exist before the
    vma concept itself; there the aval has no `vma` attribute, which the
    callers already treat as "not varying over any mesh axis".
"""

from __future__ import annotations

import jax

try:
    from jax import shard_map  # noqa: F401  (new jax)
except ImportError:  # jax <= 0.4.x
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        # check_rep off by default: the plane-sharded compositor's
        # pre-vma gradient correction (plane_sharding._psum_replicated)
        # is replicated in VALUE but not statically inferable as such,
        # and old check_rep rejects exactly that
        kwargs.setdefault("check_rep", False)
        return _shard_map(*args, **kwargs)


def typeof(x):
    """jax.typeof where it exists; the plain abstract value otherwise (no
    `vma` attribute there — callers default it to the empty set)."""
    if hasattr(jax, "typeof"):
        return jax.typeof(x)
    return jax.core.get_aval(x)


def has_vma() -> bool:
    """Whether this jax tracks varying-manual-axes on avals at all."""
    return hasattr(jax, "typeof")


def axis_size(axis_name) -> int:
    """lax.axis_size where it exists; the axis-frame lookup before it was
    added. Both return the STATIC size of a named mesh axis from inside
    shard_map — the callers build python-range chunk loops from it."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    frame = jax.core.axis_frame(axis_name)  # a bare int on jax 0.4.x
    return frame if isinstance(frame, int) else frame.size
