"""Process-local metrics registry with Prometheus text exposition.

No reference analog and no new dependency: the serving subsystem
(mine_tpu/serving/) must report what it is doing — request counts, cache
hit/miss, bytes resident, queue depth, latency quantiles — over a plain
HTTP `/metrics` endpoint, and this image has no `prometheus_client`. The
registry implements the minimal subset of the Prometheus data model the
serving metrics need (counters, gauges, label sets, cumulative-bucket
histograms for latency SLOs, and a windowed summary) and renders text
exposition format 0.0.4.

Thread-safety: every mutation takes the registry lock — the serving stack
updates metrics from HTTP handler threads and the batcher worker thread
concurrently. The lock is registry-wide (not per-family): contention is
irrelevant at serving rates and one lock keeps `render()` a consistent
snapshot.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from collections import deque


def _format_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


def _format_value(v: float) -> str:
    # Prometheus wants plain decimals; ints render without the trailing .0
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Family:
    """One named metric family: help text, type, and labeled children."""

    def __init__(self, registry: "MetricsRegistry", name: str, help_text: str,
                 kind: str):
        self.registry = registry
        self.name = name
        self.help = help_text
        self.kind = kind
        self._children: dict[tuple[tuple[str, str], ...], float] = {}

    def _key(self, labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for labels in sorted(self._children):
            lines.append(
                f"{self.name}{_format_labels(labels)} "
                f"{_format_value(self._children[labels])}"
            )
        return lines


class Counter(_Family):
    """Monotonically increasing counter (optionally labeled)."""

    def __init__(self, registry, name, help_text):
        super().__init__(registry, name, help_text, "counter")

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        key = self._key(labels)
        with self.registry._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        with self.registry._lock:
            return self._children.get(self._key(labels), 0.0)

    def labeled_values(self) -> dict[tuple[tuple[str, str], ...], float]:
        """One consistent snapshot of every child: {sorted label tuple ->
        cumulative value}. The SLO tracker (obs/slo.py) diffs two of these
        to get a rolling-window rate without a second accounting path."""
        with self.registry._lock:
            return dict(self._children)


class Gauge(_Family):
    """Settable point-in-time value (optionally labeled)."""

    def __init__(self, registry, name, help_text):
        super().__init__(registry, name, help_text, "gauge")

    def set(self, v: float, **labels: str) -> None:
        with self.registry._lock:
            self._children[self._key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self.registry._lock:
            self._children[key] = self._children.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels: str) -> None:
        self.inc(-n, **labels)

    def value(self, **labels: str) -> float:
        with self.registry._lock:
            return self._children.get(self._key(labels), 0.0)


class Histogram(_Family):
    """Cumulative-bucket histogram (the real Prometheus latency idiom:
    `le`-labeled monotone bucket counters plus `_sum`/`_count`), so latency
    SLOs are queryable server-side with histogram_quantile() instead of
    being frozen into whatever quantiles a Summary exported.

    `quantile()` interpolates linearly inside the winning bucket — kept so
    call sites that want a quick p50/p95 without a Prometheus server
    (tools/bench_serve.py) survive the Summary -> Histogram migration."""

    # latency-shaped default: 1ms .. 60s, roughly x2.5 per step
    DEFAULT_BUCKETS = (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    )

    def __init__(self, registry, name, help_text,
                 buckets: tuple[float, ...] | None = None):
        super().__init__(registry, name, help_text, "histogram")
        buckets = self.DEFAULT_BUCKETS if buckets is None else tuple(
            float(b) for b in buckets
        )
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(
                f"histogram {name} buckets must be ascending, got {buckets}"
            )
        self.buckets = buckets
        # per-label-set: per-bucket NON-cumulative counts (cumulated at
        # collect time — one increment per observe, not len(buckets))
        self._bucket_counts: dict[tuple, list[int]] = {}
        self._count: dict[tuple, int] = {}
        self._sum: dict[tuple, float] = {}

    def observe(self, v: float, **labels: str) -> None:
        v = float(v)
        key = self._key(labels)
        with self.registry._lock:
            counts = self._bucket_counts.get(key)
            if counts is None:
                # one slot per finite bucket + the +Inf overflow slot
                counts = self._bucket_counts[key] = [0] * (len(self.buckets) + 1)
            # first edge >= v gets the observation (`le` semantics);
            # v beyond the last finite edge lands in the +Inf slot
            counts[bisect_left(self.buckets, v)] += 1
            self._count[key] = self._count.get(key, 0) + 1
            self._sum[key] = self._sum.get(key, 0.0) + v

    def count(self, **labels: str) -> int:
        with self.registry._lock:
            return self._count.get(self._key(labels), 0)

    def sum(self, **labels: str) -> float:
        with self.registry._lock:
            return self._sum.get(self._key(labels), 0.0)

    def labeled_buckets(self) -> dict[tuple, list[int]]:
        """One consistent snapshot of every child's NON-cumulative per-
        bucket counts (index-aligned with `self.buckets` + the +Inf slot).
        Like Counter.labeled_values: the obs/slo.py windowing substrate."""
        with self.registry._lock:
            return {k: list(v) for k, v in self._bucket_counts.items()}

    def bucket_counts(self, **labels: str) -> dict[float, int]:
        """Upper-bound -> CUMULATIVE count (the exposition's view)."""
        key = self._key(labels)
        with self.registry._lock:
            counts = list(self._bucket_counts.get(key, []))
        out: dict[float, int] = {}
        running = 0
        edges = list(self.buckets) + [float("inf")]
        for edge, n in zip(edges, counts or [0] * len(edges)):
            running += n
            out[edge] = running
        return out

    def quantile(self, q: float, **labels: str) -> float:
        """Histogram-estimated quantile: linear interpolation within the
        bucket holding rank q*count (lower bound 0 for the first bucket,
        clamped to the last finite edge for the +Inf bucket). NaN when no
        observations exist for this label set."""
        cum = self.bucket_counts(**labels)
        total = self._count.get(self._key(labels), 0)
        if not total:
            return float("nan")
        rank = q * total
        prev_edge, prev_cum = 0.0, 0
        for edge, c in cum.items():
            if c >= rank and c > prev_cum:
                if edge == float("inf"):
                    return self.buckets[-1]
                frac = (rank - prev_cum) / (c - prev_cum)
                return prev_edge + frac * (edge - prev_edge)
            prev_edge, prev_cum = (0.0 if edge == float("inf") else edge), c
        return self.buckets[-1]

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        for key in sorted(self._bucket_counts):
            running = 0
            for edge, n in zip(
                list(self.buckets) + [float("inf")], self._bucket_counts[key]
            ):
                running += n
                le = "+Inf" if edge == float("inf") else _format_value(edge)
                blabels = key + (("le", le),)
                lines.append(
                    f"{self.name}_bucket{_format_labels(blabels)} {running}"
                )
            lines.append(
                f"{self.name}_sum{_format_labels(key)} "
                f"{_format_value(self._sum[key])}"
            )
            lines.append(
                f"{self.name}_count{_format_labels(key)} "
                f"{self._count[key]}"
            )
        return lines


class Summary(_Family):
    """Windowed summary: running count/sum plus quantiles over the last
    `window` observations (a true streaming quantile sketch is overkill for
    a serving sidecar; a bounded window gives honest recent p50/p95)."""

    def __init__(self, registry, name, help_text, window: int = 1024,
                 quantiles: tuple[float, ...] = (0.5, 0.95)):
        super().__init__(registry, name, help_text, "summary")
        self.window = window
        self.quantiles = quantiles
        self._obs: dict[tuple[tuple[str, str], ...], deque] = {}
        self._count: dict[tuple[tuple[str, str], ...], float] = {}
        self._sum: dict[tuple[tuple[str, str], ...], float] = {}

    def observe(self, v: float, **labels: str) -> None:
        key = self._key(labels)
        with self.registry._lock:
            dq = self._obs.setdefault(key, deque(maxlen=self.window))
            dq.append(float(v))
            self._count[key] = self._count.get(key, 0.0) + 1
            self._sum[key] = self._sum.get(key, 0.0) + float(v)

    def quantile(self, q: float, **labels: str) -> float:
        """Nearest-rank quantile over the current window (nan when empty)."""
        key = self._key(labels)
        with self.registry._lock:
            dq = self._obs.get(key)
            if not dq:
                return float("nan")
            ordered = sorted(dq)
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[idx]

    def collect(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} summary"]
        for key in sorted(self._obs):
            ordered = sorted(self._obs[key])
            for q in self.quantiles:
                idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
                qlabels = key + (("quantile", repr(float(q))),)
                lines.append(
                    f"{self.name}{_format_labels(tuple(sorted(qlabels)))} "
                    f"{_format_value(ordered[idx])}"
                )
            lines.append(
                f"{self.name}_sum{_format_labels(key)} "
                f"{_format_value(self._sum[key])}"
            )
            lines.append(
                f"{self.name}_count{_format_labels(key)} "
                f"{_format_value(self._count[key])}"
            )
        return lines


class MetricsRegistry:
    """Families by name; renders the whole set as one text page."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}

    def _register(self, family: _Family) -> _Family:
        with self._lock:
            existing = self._families.get(family.name)
            if existing is not None:
                if type(existing) is not type(family):
                    raise ValueError(
                        f"metric {family.name} already registered as "
                        f"{existing.kind}"
                    )
                return existing
            self._families[family.name] = family
            return family

    def counter(self, name: str, help_text: str) -> Counter:
        return self._register(Counter(self, name, help_text))

    def gauge(self, name: str, help_text: str) -> Gauge:
        return self._register(Gauge(self, name, help_text))

    def summary(self, name: str, help_text: str, window: int = 1024,
                quantiles: tuple[float, ...] = (0.5, 0.95)) -> Summary:
        return self._register(
            Summary(self, name, help_text, window=window, quantiles=quantiles)
        )

    def histogram(self, name: str, help_text: str,
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        return self._register(Histogram(self, name, help_text, buckets=buckets))

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4, trailing newline."""
        with self._lock:
            families = list(self._families.values())
            lines: list[str] = []
            for fam in families:
                lines.extend(fam.collect())
        return "\n".join(lines) + "\n"
