"""Device mesh construction and multi-host bootstrap.

Replaces the reference's NCCL process-group bootstrap (train.py:61-69,
start_training.sh:75-83) with single-program SPMD over a
`jax.sharding.Mesh`. Two axes:

  data  — batch sharding (the reference's only axis: DDP data parallel)
  plane — MPI plane (S) sharding, this model's sequence-parallel analog
          (SURVEY.md §5.7): activations scale with B*S through decoder and
          renderer, so S is the axis long-context pressure lives on.

Collectives ride ICI within a slice and DCN across slices; XLA picks the
transport — nothing here names a backend (vs NCCL hardcoding, train.py:66).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
PLANE_AXIS = "plane"


def init_multihost(coordinator: str | None = None) -> None:
    """Multi-host bootstrap (reference: torch.distributed.launch + NCCL TCP
    rendezvous, start_training.sh:75-83). On TPU pods jax.distributed
    discovers topology from the environment; coordinator is only needed for
    manual setups.

    MUST run before any other JAX call — jax.distributed can only initialize
    while the backend is untouched, so this probes nothing (not even
    jax.process_count()) before attempting it.

    Opt-in: runs only when `coordinator` is given or MINE_TPU_MULTIHOST is
    set. jax.distributed.initialize()'s auto-detection BLOCKS waiting for
    peers on some single-chip environments (observed with tunneled TPU
    metadata), so it must never fire implicitly on single-host runs.
    """
    import os
    import warnings

    if coordinator is None and not os.environ.get("MINE_TPU_MULTIHOST"):
        return
    try:
        if coordinator:
            jax.distributed.initialize(coordinator_address=coordinator)
        else:
            jax.distributed.initialize()
    except RuntimeError as e:
        msg = str(e)
        if "already initialized" in msg:
            return
        if "must be called before" in msg:
            # Backend already up: a caller-ordering bug for real multi-host
            # jobs. Warn loudly instead of silently training N divergent
            # single-host copies.
            warnings.warn(
                "init_multihost() called after the JAX backend was "
                "initialized; continuing single-host. Call it first for "
                f"multi-host runs. ({msg})",
                stacklevel=2,
            )
            return
        if coordinator is None:
            # no cluster environment detected: plain single-host run
            return
        raise
    except ValueError:
        if coordinator is None:
            return  # auto-detection found no cluster env: single-host
        raise


def make_mesh(data_parallel: int = -1, plane_parallel: int = 1) -> Mesh:
    """Build the (data, plane) mesh. data_parallel=-1 takes every device not
    claimed by plane_parallel."""
    devices = np.asarray(jax.devices())
    n = devices.size
    if plane_parallel < 1 or n % plane_parallel:
        raise ValueError(f"plane_parallel={plane_parallel} must divide {n} devices")
    if data_parallel == -1:
        data_parallel = n // plane_parallel
    if data_parallel * plane_parallel != n:
        raise ValueError(
            f"mesh {data_parallel}x{plane_parallel} != {n} available devices"
        )
    return Mesh(devices.reshape(data_parallel, plane_parallel), (DATA_AXIS, PLANE_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for host batches: batch axis over `data`, replicated over
    `plane`."""
    return NamedSharding(mesh, P(DATA_AXIS))


def shard_batch(mesh: Mesh, batch: dict) -> dict:
    """device_put a host batch with the batch axis sharded over `data`
    (replaces the reference's per-process DistributedSampler slicing,
    train.py:88 — here one logical batch spans the mesh)."""
    sharding = batch_sharding(mesh)
    return jax.device_put(batch, sharding)
