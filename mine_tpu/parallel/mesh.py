"""Device mesh construction and multi-host bootstrap.

Replaces the reference's NCCL process-group bootstrap (train.py:61-69,
start_training.sh:75-83) with single-program SPMD over a named
`jax.sharding.Mesh` with three axes (the MaxText-style factorization,
SNIPPETS.md [1]):

  data  — batch sharding (the reference's only axis: DDP data parallel)
  fsdp  — parameter sharding: batches ALSO shard over it (so data x fsdp
          is the batch-replica product), while params/grad-moments split
          over it per the partition-rule table (parallel/rules.py) — the
          axis that first drops per-device param bytes below replication
  plane — MPI plane (S) sharding, this model's sequence-parallel analog
          (SURVEY.md §5.7): activations scale with B*S through decoder and
          renderer, so S is the axis long-context pressure lives on.

Collectives ride ICI within a slice and DCN across slices; XLA picks the
transport — nothing here names a backend (vs NCCL hardcoding, train.py:66).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PLANE_AXIS = "plane"
AXIS_NAMES = (DATA_AXIS, FSDP_AXIS, PLANE_AXIS)
# THE spelling of XLA's virtual-host-device flag, re-exported for mesh
# consumers (force_virtual_devices below, subprocess envs in
# tools/chaos_drill.py). The definition lives in utils/platform.py — the
# stdlib-weight module every pre-backend CLI guard already imports — so
# neither layer imports the other for a string.
from mine_tpu.utils.platform import VIRTUAL_DEVICE_FLAG  # noqa: E402,F401
# the batch-replica product: batches shard their leading dim over BOTH —
# fsdp contributes batch parallelism like data, it only additionally
# shards the params (parallel/rules.py)
BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


def force_virtual_devices(
    n_devices: int,
    compilation_cache: bool = False,
    fast_compile: bool = False,
) -> None:
    """THE virtual-device setup every mesh consumer shares — tests
    (tests/conftest.py), the driver's `dryrun_multichip`, the slow
    mesh-equivalence subprocesses, and the benches' forced-CPU paths all
    come through here, so the `--xla_force_host_platform_device_count`
    spelling (and the ordering rules around it) cannot drift between them.

    Must run before any JAX backend touch; raises RuntimeError otherwise.
    The implementation core lives in `mine_tpu.utils.platform`
    (`force_cpu_devices`) because the CLI platform guard shares it without
    importing the parallel package.
    """
    from mine_tpu.utils.platform import force_cpu_devices

    force_cpu_devices(
        n_devices,
        compilation_cache=compilation_cache,
        fast_compile=fast_compile,
    )


class MultihostInitTimeout(RuntimeError):
    """jax.distributed.initialize() did not complete within the bring-up
    deadline. The named, actionable replacement for the indefinite hang a
    missing peer otherwise produces (the rendezvous blocks until EVERY host
    of the pod dials in — one crashed worker used to stall the rest
    forever with no diagnosis)."""

    def __init__(self, timeout_s: float, coordinator: str | None):
        super().__init__(
            f"multi-host bring-up did not complete within {timeout_s:.0f}s: "
            "jax.distributed.initialize() is still waiting for peers. "
            "Check that every host of the pod launched the same job, that "
            f"the coordinator {coordinator or '(auto-detected)'} is "
            "reachable (firewall / DNS), and that MINE_TPU_MULTIHOST or "
            "--coordinator was not set on a single-host run. Extend the "
            "deadline with MINE_TPU_MULTIHOST_TIMEOUT_S."
        )
        self.timeout_s = timeout_s
        self.coordinator = coordinator


def init_multihost(
    coordinator: str | None = None,
    timeout_s: float | None = None,
    initialize_fn=None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bootstrap (reference: torch.distributed.launch + NCCL TCP
    rendezvous, start_training.sh:75-83). On TPU pods jax.distributed
    discovers topology from the environment; coordinator is only needed for
    manual setups.

    MUST run before any other JAX call — jax.distributed can only initialize
    while the backend is untouched, so this probes nothing (not even
    jax.process_count()) before attempting it.

    Opt-in: runs only when `coordinator` is given or MINE_TPU_MULTIHOST is
    set. jax.distributed.initialize()'s auto-detection BLOCKS waiting for
    peers on some single-chip environments (observed with tunneled TPU
    metadata), so it must never fire implicitly on single-host runs.

    `num_processes`/`process_id` (or $MINE_TPU_MULTIHOST_NPROCS /
    $MINE_TPU_MULTIHOST_PROC_ID) are required for manual topologies where
    the cluster environment cannot supply them — the CPU multi-process
    harness (tools/multihost_harness.py) is the canonical user: N
    subprocesses on one box running THE SAME bring-up a pod runs. On a
    forced-CPU platform with an explicit process count, cross-process
    collectives are routed through gloo (the only CPU transport this
    jax pins support) before the backend comes up.

    Bring-up deadline: the rendezvous runs on a worker thread joined for
    `timeout_s` (default $MINE_TPU_MULTIHOST_TIMEOUT_S, else 300). On
    expiry this raises MultihostInitTimeout instead of hanging the job
    launcher forever — the operator gets the missing-peer diagnosis, the
    scheduler gets a dead process it can reschedule. `initialize_fn`
    overrides jax.distributed.initialize (unit tests inject a fake
    distributed client; production never passes it).
    """
    import os
    import threading
    import warnings

    if coordinator is None:
        env = os.environ.get("MINE_TPU_MULTIHOST")
        if not env:
            return
        # the env var doubles as the coordinator address (host:port, the
        # harness's channel into subprocesses). Only a value SHAPED like
        # an address (it contains ':') is treated as one — every other
        # non-empty value keeps the pre-harness contract: opt in to
        # cluster auto-detection (a launch script's "1"/"yes"/"on" must
        # not get dialed as a hostname)
        if ":" in env:
            coordinator = env.strip()
    if num_processes is None:
        env_n = os.environ.get("MINE_TPU_MULTIHOST_NPROCS")
        num_processes = int(env_n) if env_n else None
    if process_id is None:
        env_i = os.environ.get("MINE_TPU_MULTIHOST_PROC_ID")
        process_id = int(env_i) if env_i else None
    if timeout_s is None:
        timeout_s = float(os.environ.get("MINE_TPU_MULTIHOST_TIMEOUT_S", 300))
    if initialize_fn is None:
        initialize_fn = jax.distributed.initialize
        if num_processes is not None and (
            os.environ.get("JAX_PLATFORMS", "") == "cpu"
        ):
            # CPU multi-process collectives need the gloo transport; the
            # flag is consumed at backend init, so set it here — the one
            # place that runs before any backend touch on every bring-up
            # path (production never passes initialize_fn; fakes skip this)
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    outcome: list[BaseException | None] = []

    def bring_up():
        kwargs: dict = {}
        # only pass what the caller specified: injected test fakes (and
        # cluster auto-detection) keep their narrow signatures
        if coordinator:
            kwargs["coordinator_address"] = coordinator
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        try:
            initialize_fn(**kwargs)
            outcome.append(None)
        except BaseException as e:  # noqa: BLE001 - re-raised on the caller
            outcome.append(e)

    # daemon thread: on timeout the stuck rendezvous cannot be cancelled,
    # but it must not pin the process open after the launcher gives up
    worker = threading.Thread(
        target=bring_up, name="mine-multihost-init", daemon=True
    )
    worker.start()
    worker.join(timeout_s)
    if worker.is_alive():
        raise MultihostInitTimeout(timeout_s, coordinator)
    exc = outcome[0] if outcome else None
    if exc is None:
        return
    if isinstance(exc, RuntimeError):
        msg = str(exc)
        if "already initialized" in msg:
            return
        if "must be called before" in msg:
            # Backend already up: a caller-ordering bug for real multi-host
            # jobs. Warn loudly instead of silently training N divergent
            # single-host copies.
            warnings.warn(
                "init_multihost() called after the JAX backend was "
                "initialized; continuing single-host. Call it first for "
                f"multi-host runs. ({msg})",
                stacklevel=2,
            )
            return
        if coordinator is None:
            # no cluster environment detected: plain single-host run
            return
        raise exc
    if isinstance(exc, ValueError):
        if coordinator is None:
            return  # auto-detection found no cluster env: single-host
        raise exc
    raise exc


def make_mesh(
    data_parallel: int = -1,
    plane_parallel: int = 1,
    fsdp_parallel: int = 1,
) -> Mesh:
    """Build the (data, fsdp, plane) mesh. data_parallel=-1 takes every
    device not claimed by fsdp_parallel x plane_parallel.

    Keyword order keeps the historical (data, plane) call sites valid;
    fsdp_parallel is the new axis (mesh.fsdp_parallel)."""
    devices = np.asarray(jax.devices())
    n = devices.size
    for name, size in (("plane_parallel", plane_parallel),
                       ("fsdp_parallel", fsdp_parallel)):
        if size < 1 or n % size:
            raise ValueError(f"{name}={size} must divide {n} devices")
    claimed = plane_parallel * fsdp_parallel
    if n % claimed:
        raise ValueError(
            f"fsdp_parallel={fsdp_parallel} x plane_parallel="
            f"{plane_parallel} must divide {n} devices"
        )
    if data_parallel == -1:
        data_parallel = n // claimed
    if data_parallel * claimed != n:
        raise ValueError(
            f"mesh {data_parallel}x{fsdp_parallel}x{plane_parallel} != {n} "
            "available devices"
        )
    return Mesh(
        devices.reshape(data_parallel, fsdp_parallel, plane_parallel),
        AXIS_NAMES,
    )


def data_replica_count(mesh: Mesh) -> int:
    """How many batch shards the mesh holds: the data x fsdp product (the
    quantity every 'global batch' computation multiplies by)."""
    return mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]


def mesh_shape_str(mesh: Mesh) -> str:
    """Canonical 'DxFxP' label (perf-ledger comparability key, bench obs)."""
    return "x".join(str(mesh.shape[a]) for a in AXIS_NAMES)


def batch_sharding(mesh: Mesh, rules: tuple | None = None) -> NamedSharding:
    """Sharding for host batches, read off the rule table's `^batch/` row
    (parallel/rules.py): batch axis over data x fsdp, replicated over
    plane."""
    from mine_tpu.parallel import rules as rules_mod

    if rules is None:
        spec = P(BATCH_AXES)
    else:
        spec = rules_mod.batch_spec(rules)
    return NamedSharding(mesh, spec)


def host_batch_slice(
    mesh: Mesh, global_rows: int, rules: tuple | None = None
) -> tuple[int, int]:
    """(start, count): the contiguous row range of the global batch that
    THIS process's addressable devices own under the table's `^batch/` row
    — what a per-host loader materializes instead of the whole global
    batch (the reference's DistributedSampler role, computed from the
    partition rules so the loader and the compiled step cannot disagree).

    Single-process: (0, global_rows). Multi-process: the union of the
    local devices' row slices, which must be contiguous and equal-sized
    across processes (true for the in-order device-to-process layouts
    jax.distributed produces; anything else is a hard error — a loader
    cannot materialize a strided slice as one array)."""
    sharding = batch_sharding(mesh, rules)
    if jax.process_count() == 1:
        return 0, global_rows
    local = [d for d in mesh.devices.flat if d.process_index == jax.process_index()]
    idx_map = sharding.devices_indices_map((global_rows,))
    rows = sorted(
        {(idx_map[d][0].start or 0, idx_map[d][0].stop or global_rows)
         for d in local}
    )
    start, stop = rows[0][0], rows[-1][1]
    covered = sum(b - a for a, b in rows)
    if covered != stop - start:
        raise ValueError(
            f"host {jax.process_index()}'s batch rows are not contiguous "
            f"under the ^batch/ rule ({rows}); per-host loading needs an "
            "in-order device-to-process mesh layout"
        )
    count = stop - start
    if count * jax.process_count() != global_rows:
        raise ValueError(
            f"global batch {global_rows} does not split evenly over "
            f"{jax.process_count()} processes (this host owns {count} rows)"
        )
    return start, count


def shard_batch(
    mesh: Mesh,
    batch: dict,
    rules: tuple | None = None,
    global_rows: int | None = None,
) -> dict:
    """Place a host batch with the batch axis sharded over data x fsdp
    (replaces the reference's per-process DistributedSampler slicing,
    train.py:88 — here one logical batch spans the mesh).

    Single-process: a plain device_put of the full batch. Multi-process:
    each process contributes only its own rows
    (jax.make_array_from_process_local_data — no host ever materializes
    peers' data on device). The input may then be either

      * this host's LOCAL slice (the per-host loader path — rows ==
        host_batch_slice count), or
      * the full GLOBAL batch (`global_rows` rows): the
        global-load-then-slice compat path for loaders without per-host
        support — sliced down here, numerically identical, just wasteful
        host IO (PARITY.md).

    `global_rows` disambiguates; None means the input is local."""
    sharding = batch_sharding(mesh, rules)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    start, count = host_batch_slice(
        mesh,
        global_rows if global_rows is not None
        else _leading_rows(batch) * jax.process_count(),
        rules,
    )

    def put(x):
        x = np.asarray(x)
        if global_rows is not None and x.shape[0] == global_rows:
            x = x[start:start + count]  # compat: global batch handed in
        if x.shape[0] != count:
            raise ValueError(
                f"host batch has {x.shape[0]} rows; this host owns {count} "
                f"of the global {global_rows} (host_batch_slice)"
            )
        gshape = (count * jax.process_count(),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, gshape)

    return jax.tree.map(put, batch)


def _leading_rows(batch: dict) -> int:
    leaves = jax.tree.leaves(batch)
    if not leaves:
        raise ValueError("empty batch")
    return int(np.shape(leaves[0])[0])
