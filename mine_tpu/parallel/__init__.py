"""SPMD parallelism: the (data, fsdp, plane) named mesh, the declarative
partition-rule table (regex -> PartitionSpec, parallel/rules.py) that is
the single source of every param/grad/opt-state/batch sharding, the
table-driven train/eval step wrappers, and plane-axis sharded compositing
(reference: NCCL DDP, SURVEY.md §2.3-2.4)."""

from mine_tpu.parallel.mesh import (
    AXIS_NAMES,
    BATCH_AXES,
    DATA_AXIS,
    FSDP_AXIS,
    PLANE_AXIS,
    batch_sharding,
    data_replica_count,
    force_virtual_devices,
    host_batch_slice,
    init_multihost,
    make_mesh,
    mesh_shape_str,
    shard_batch,
)
from mine_tpu.parallel import rules
from mine_tpu.parallel.data_parallel import (
    batch_axis_name,
    distribute_state,
    fsdp_enabled,
    make_parallel_eval_step,
    make_parallel_train_step,
    model_axes,
    replicate_state,
    sharding_active,
    zero1_enabled,
)
from mine_tpu.parallel.plane_sharding import (
    plane_compositor,
    sharded_alpha_composition,
    sharded_plane_volume_rendering,
    sharded_render,
    sharded_render_src,
    sharded_render_tgt_rgb_depth,
    sharded_render_tgt_streaming,
    sharded_weighted_sum_mpi,
    sharded_weighted_sum_src,
)
