"""SPMD parallelism: mesh construction, data-parallel step wrappers,
plane-axis sharded compositing (reference: NCCL DDP, SURVEY.md §2.3-2.4)."""

from mine_tpu.parallel.mesh import (
    DATA_AXIS,
    PLANE_AXIS,
    init_multihost,
    make_mesh,
    batch_sharding,
    shard_batch,
)
from mine_tpu.parallel.data_parallel import (
    make_parallel_train_step,
    make_parallel_eval_step,
    model_axes,
    replicate_state,
    distribute_state,
    zero1_enabled,
)
from mine_tpu.parallel import zero1
from mine_tpu.parallel.plane_sharding import (
    plane_compositor,
    sharded_alpha_composition,
    sharded_plane_volume_rendering,
    sharded_render,
    sharded_render_src,
    sharded_render_tgt_rgb_depth,
    sharded_render_tgt_streaming,
    sharded_weighted_sum_mpi,
    sharded_weighted_sum_src,
)
