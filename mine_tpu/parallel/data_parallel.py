"""Data-parallel train/eval step wrappers over the device mesh.

The reference's distributed story (DDP gradient allreduce + SyncBN +
DistributedSampler, SURVEY.md §2.3) becomes: `shard_map` the train step over
the mesh with the batch axis sharded on `data`, the loss averaged across
replicas before differentiation and BN stats synced inside the step
(mine_tpu/training/step.py), state replicated. One jit; XLA
lowers the collectives onto ICI/DCN.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from mine_tpu.config import Config
from mine_tpu.models import MPINetwork
from mine_tpu.parallel.mesh import DATA_AXIS
from mine_tpu.training.step import make_eval_step, make_train_step
from mine_tpu.training.state import TrainState

_REPL = P()  # replicated
_BATCH = P(DATA_AXIS)  # shard axis 0 over data


def make_parallel_train_step(
    cfg: Config, model: MPINetwork, tx: optax.GradientTransformation, mesh: Mesh
) -> Callable:
    """jit(shard_map(train_step)): state replicated, batch data-sharded.

    The model must have been built with axis_name=DATA_AXIS (build_model) so
    BN stats sync; the step pmeans the loss pre-grad and logged losses
    post-grad (step.py).
    """
    step = make_train_step(cfg, model, tx, axis_name=DATA_AXIS)
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(_REPL, _BATCH),
        out_specs=(_REPL, _REPL),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_parallel_eval_step(
    cfg: Config,
    model: MPINetwork,
    mesh: Mesh,
    lpips_params: dict | None = None,
) -> Callable:
    """jit(shard_map(eval_step)): losses pmean'd to replicated; per-replica
    visualizations stay batch-sharded (gather only what gets logged)."""
    step = make_eval_step(cfg, model, lpips_params=lpips_params, axis_name=DATA_AXIS)
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(_REPL, _BATCH, _REPL),
        out_specs=(_REPL, _BATCH),
    )
    return jax.jit(sharded)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place the train state replicated on every mesh device (the DDP initial
    param broadcast, synthesis_task.py:110-115, done once, explicitly)."""
    return jax.device_put(state, jax.sharding.NamedSharding(mesh, _REPL))
