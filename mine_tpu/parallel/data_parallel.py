"""Parallel train/eval step wrappers over the (data, fsdp, plane) mesh.

The reference's distributed story (DDP gradient allreduce + SyncBN +
DistributedSampler, SURVEY.md §2.3) becomes: `shard_map` the train step
over the named mesh with the batch axis sharded over data x fsdp, the loss
averaged across replicas before differentiation and BN stats synced inside
the step (mine_tpu/training/step.py), and the state laid out by the ONE
declarative partition-rule table (parallel/rules.py) — params sharded over
`fsdp` (gathered in-step, FSDP), Adam moments over fsdp x data (the ZeRO-1
rows), everything else replicated. The same table supplies the shard_map
in/out_specs, the explicit `jax.jit` in_shardings/out_shardings, and the
live `distribute_state` placement, so the compiled layout and the resident
layout cannot diverge. One jit; XLA lowers the collectives onto ICI/DCN.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mine_tpu.utils.jax_compat import shard_map

from mine_tpu.config import Config
from mine_tpu.models import MPINetwork
from mine_tpu.ops import compositor_from_config
from mine_tpu.parallel import rules as rules_mod
from mine_tpu.parallel.mesh import (
    BATCH_AXES,
    DATA_AXIS,
    FSDP_AXIS,
    PLANE_AXIS,
    data_replica_count,
)
from mine_tpu.parallel.plane_sharding import plane_compositor
from mine_tpu.training.step import make_eval_step, make_train_step
from mine_tpu.training.state import TrainState

_REPL = P()  # replicated (pytree-prefix spec)


def model_axes(mesh: Mesh) -> dict:
    """build_model kwargs for a model living on this mesh: BN syncs over
    the batch-replica axes — `data` always, plus `fsdp` when that axis is
    wider than 1 (batches shard over both); under plane sharding the
    decoder's post-conditioning BNs additionally pool over `plane` (its
    effective batch B*S splits across the axes — models/decoder.py)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    return {
        "axis_name": batch_axis_name(mesh),
        "plane_axis": PLANE_AXIS if n_plane > 1 else None,
    }


def batch_axis_name(mesh: Mesh) -> str | tuple[str, ...]:
    """The named axis (or axes) one logical batch spans: `data`, or
    ("data","fsdp") when the fsdp axis is non-trivial. Collectives with
    DDP-replica semantics (loss pmean, BN sync, eval psum) use this."""
    if mesh.shape.get(FSDP_AXIS, 1) > 1:
        return BATCH_AXES
    return DATA_AXIS


def _plane_args(cfg: Config, mesh: Mesh) -> dict:
    """plane_axis/compositor kwargs for make_{train,eval}_step, validated.
    cfg.mpi.compositor selects dense vs streaming in BOTH regimes: unsharded
    it resolves through ops.compositor_from_config, plane-sharded the local
    chunk-scan composes with the cross-device exclusive prefix
    (plane_sharding.sharded_render_tgt_streaming)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    unsharded = compositor_from_config(cfg)  # unknown knob values fail loudly
    streaming = cfg.mpi.compositor == "streaming"
    if n_plane <= 1:
        return {"plane_axis": None, "compositor": unsharded}
    if cfg.mpi.num_bins_coarse % n_plane:
        raise ValueError(
            f"mpi.num_bins_coarse={cfg.mpi.num_bins_coarse} must divide by "
            f"the plane-axis size {n_plane}"
        )
    if cfg.mpi.num_bins_fine % n_plane:
        # the merged coarse+fine list re-shards across the same axis
        # (step.py forward_coarse_to_fine); both lists must chunk evenly
        raise ValueError(
            f"mpi.num_bins_fine={cfg.mpi.num_bins_fine} must divide by "
            f"the plane-axis size {n_plane}"
        )
    return {
        "plane_axis": PLANE_AXIS,
        "compositor": plane_compositor(
            PLANE_AXIS, streaming=streaming,
            chunk_planes=cfg.mpi.stream_chunk_planes,
        ),
    }


def zero1_enabled(cfg: Config, mesh: Mesh) -> bool:
    """Whether the ZeRO-1 moment rows actually shard anything: the (alias)
    knob is on AND the batch-replica product is wider than 1 — on a 1-wide
    product the "shard" is the whole state and the rule rows resolve to
    replicated (parallel/rules.py resolve_placement drops size-1 axes)."""
    return bool(cfg.parallel.zero1) and data_replica_count(mesh) > 1


def fsdp_enabled(mesh: Mesh) -> bool:
    """FSDP is the fsdp mesh axis being non-trivial — the axis size IS the
    knob (mesh.fsdp_parallel)."""
    return mesh.shape.get(FSDP_AXIS, 1) > 1


def sharding_active(cfg: Config, mesh: Mesh) -> bool:
    """Whether ANY state leaf leaves full replication under the table —
    the predicate deciding when the step builders need a state template."""
    return fsdp_enabled(mesh) or zero1_enabled(cfg, mesh)


def _state_layout(cfg: Config, mesh: Mesh, state: TrainState | None):
    """(state spec tree, param placements, update placements) from the
    partition-rule table — or the replicated defaults when nothing shards.
    THE single derivation the compiled step, the jit shardings, and the
    live placement all consume."""
    if state is None or not sharding_active(cfg, mesh):
        return _REPL, None, None
    table = rules_mod.partition_rules(cfg)
    min_size = cfg.parallel.zero1_min_size
    placements = rules_mod.state_placements(table, state, mesh, min_size)
    specs = rules_mod.tree_specs(placements)
    return specs, placements.params, rules_mod.update_placements(
        table, state.params, mesh, min_size
    )


def _jit_shardings(mesh: Mesh, state_specs, batch_spec):
    """Explicit NamedShardings for jax.jit from the same spec trees the
    shard_map constrains — stated twice on purpose: jit enforces the
    layout at the executable boundary (a mis-placed input is resharded or
    rejected there, not silently re-laid-out inside)."""
    as_named = lambda s: NamedSharding(mesh, s)  # noqa: E731
    if isinstance(state_specs, P):
        state_sh = as_named(state_specs)
    else:
        state_sh = jax.tree.map(
            as_named, state_specs, is_leaf=lambda x: isinstance(x, P)
        )
    return state_sh, as_named(batch_spec), as_named(P())


def make_parallel_train_step(
    cfg: Config,
    model: MPINetwork,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state: TrainState | None = None,
) -> Callable:
    """jit(shard_map(train_step)) with table-derived shardings: batch
    sharded over data x fsdp and replicated over plane; params sharded over
    `fsdp` per the rule table (all-gathered at step start — FSDP); Adam
    moments sharded over fsdp x data (the ZeRO-1 rows); with a plane axis
    of size > 1, each device runs the decoder + renderer on its S_local
    plane chunk and the compositing reductions cross the plane axis
    (plane_sharding.py).

    The model must have been built with axis_name=model_axes(mesh)
    (build_model) so BN stats sync; the step pmeans the loss pre-grad over
    the batch-replica axes and logged losses post-grad (step.py).

    BOTH arguments are donated: the state is consumed and returned every
    step, and the batch's device buffers are dead the moment the step has
    read them — the prefetch pipeline transfers a FRESH batch each step
    (training/loop.py staged_batches), so holding the old one alive only
    padded peak HBM by one full batch.

    Whenever any rule row shards state (fsdp axis > 1, or `parallel.zero1`
    with a non-trivial batch-replica product), pass the replicated-or-host
    `state` template: the leaf PartitionSpecs are shape-dependent and
    `distribute_state` must have placed the live state with the matching
    layout (both derive from `rules.state_placements`, so they agree by
    construction).
    """
    specs, param_pl, update_pl = _state_layout(cfg, mesh, state)
    if sharding_active(cfg, mesh) and state is None:
        raise ValueError(
            "the partition-rule table shards state on this mesh "
            f"(fsdp={mesh.shape.get(FSDP_AXIS, 1)}, "
            f"zero1={cfg.parallel.zero1}) and the leaf specs are "
            "shape-dependent: pass the state template — "
            "make_parallel_train_step(..., state=state)"
        )
    table = rules_mod.partition_rules(cfg)
    batch_spec = rules_mod.batch_spec(table)
    step = make_train_step(
        cfg, model, tx, axis_name=batch_axis_name(mesh),
        param_placements=param_pl, update_placements=update_pl,
        **_plane_args(cfg, mesh),
    )
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, batch_spec),
        out_specs=(specs, _REPL),
    )
    state_sh, batch_sh, repl_sh = _jit_shardings(mesh, specs, batch_spec)
    return jax.jit(
        sharded,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, repl_sh),
        donate_argnums=(0, 1),
    )


def make_parallel_eval_step(
    cfg: Config,
    model: MPINetwork,
    mesh: Mesh,
    lpips_params: dict | None = None,
    state: TrainState | None = None,
) -> Callable:
    """jit(shard_map(eval_step)): losses psum'd to replicated; per-replica
    visualizations stay batch-sharded (gather only what gets logged).

    The eval body reads only params/batch_stats, but it is handed the whole
    TrainState — under any sharded layout, pass the same `state` template
    as the train step so the leaves keep their table-derived specs through
    shard_map. A replicated in_spec would make jit all-gather the sharded
    Adam moments onto every device on each eval call, spiking HBM right
    back to the replicated footprint the sharding exists to remove; with
    the matching specs the unused shards just flow through (the eval body
    gathers the fsdp param shards itself, exactly like the train step)."""
    if sharding_active(cfg, mesh) and state is None:
        # same guard as the train builder: a replicated eval spec on a
        # sharded mesh would silently re-inflate every sharded leaf per call
        raise ValueError(
            "the partition-rule table shards state on this mesh "
            f"(fsdp={mesh.shape.get(FSDP_AXIS, 1)}, "
            f"zero1={cfg.parallel.zero1}) and the leaf specs are "
            "shape-dependent: pass the state template — "
            "make_parallel_eval_step(..., state=state)"
        )
    specs, param_pl, _ = _state_layout(cfg, mesh, state)
    table = rules_mod.partition_rules(cfg)
    batch_spec = rules_mod.batch_spec(table)
    step = make_eval_step(
        cfg, model, lpips_params=lpips_params,
        axis_name=batch_axis_name(mesh), param_placements=param_pl,
        **_plane_args(cfg, mesh),
    )
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, batch_spec, _REPL),
        out_specs=(_REPL, batch_spec),
    )
    state_sh, batch_sh, repl_sh = _jit_shardings(mesh, specs, batch_spec)
    return jax.jit(
        sharded,
        in_shardings=(state_sh, batch_sh, repl_sh),
        out_shardings=(repl_sh, batch_sh),
    )


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place the train state replicated on every mesh device (the DDP initial
    param broadcast, synthesis_task.py:110-115, done once, explicitly).

    Multi-process meshes: device_put rejects host arrays targeted at
    non-addressable devices (exactly what a RESTORED checkpoint is — orbax
    hands back host numpy, identical on every process), so each process
    contributes its local replica copy via
    jax.make_array_from_process_local_data instead. The single-process
    path stays device_put: it also accepts already-on-device arrays (the
    fresh-init case) without a host round trip."""
    sharding = NamedSharding(mesh, _REPL)
    if jax.process_count() == 1:
        return jax.device_put(state, sharding)
    import numpy as np

    def put(x):
        arr = np.asarray(x)
        return jax.make_array_from_process_local_data(
            sharding, arr, arr.shape
        )

    return jax.tree.map(put, state)


def distribute_state(state: TrainState, cfg: Config, mesh: Mesh) -> TrainState:
    """Place a (host or replicated) TrainState per the partition-rule
    table: fully replicated, FSDP param shards over `fsdp`, and/or Adam
    moments over fsdp x data (parallel/rules.py).

    The single placement entry point for every placement in the training
    loop (initial, warm start, rollback restore), so a restored checkpoint
    — always saved gathered/layout-free — lands back in the live layout."""
    if not sharding_active(cfg, mesh):
        return replicate_state(state, mesh)
    if jax.process_count() > 1:
        # the table's sharded layouts (FSDP/ZeRO-1) place host arrays via
        # device_put, which cannot target peers' devices — and gather-on-
        # save (jax.device_get) cannot gather non-addressable shards
        # either. Multi-host runs therefore train replicated today; the
        # named error here beats the opaque device_put one.
        raise NotImplementedError(
            "multi-host + sharded state layouts (mesh.fsdp_parallel > 1 "
            "or parallel.zero1) is not supported yet: checkpoints are "
            "gathered on save, which requires every shard to be "
            "process-addressable. Run multi-host jobs replicated "
            "(data-parallel only) for now."
        )
    return rules_mod.place_state(
        rules_mod.partition_rules(cfg), state, mesh,
        cfg.parallel.zero1_min_size,
    )
