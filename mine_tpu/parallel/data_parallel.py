"""Data-parallel train/eval step wrappers over the device mesh.

The reference's distributed story (DDP gradient allreduce + SyncBN +
DistributedSampler, SURVEY.md §2.3) becomes: `shard_map` the train step over
the mesh with the batch axis sharded on `data`, the loss averaged across
replicas before differentiation and BN stats synced inside the step
(mine_tpu/training/step.py), state replicated. One jit; XLA
lowers the collectives onto ICI/DCN.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, PartitionSpec as P

from mine_tpu.utils.jax_compat import shard_map

from mine_tpu.config import Config
from mine_tpu.models import MPINetwork
from mine_tpu.ops import compositor_from_config
from mine_tpu.parallel.mesh import DATA_AXIS, PLANE_AXIS
from mine_tpu.parallel.plane_sharding import plane_compositor
from mine_tpu.training.step import make_eval_step, make_train_step
from mine_tpu.training.state import TrainState

_REPL = P()  # replicated
_BATCH = P(DATA_AXIS)  # shard axis 0 over data, replicate over plane


def model_axes(mesh: Mesh) -> dict:
    """build_model kwargs for a model living on this mesh: BN syncs over
    `data` always; under plane sharding the decoder's post-conditioning BNs
    additionally pool over `plane` (its effective batch B*S splits across
    both axes — models/decoder.py)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    return {
        "axis_name": DATA_AXIS,
        "plane_axis": PLANE_AXIS if n_plane > 1 else None,
    }


def _plane_args(cfg: Config, mesh: Mesh) -> dict:
    """plane_axis/compositor kwargs for make_{train,eval}_step, validated.
    cfg.mpi.compositor selects dense vs streaming in BOTH regimes: unsharded
    it resolves through ops.compositor_from_config, plane-sharded the local
    chunk-scan composes with the cross-device exclusive prefix
    (plane_sharding.sharded_render_tgt_streaming)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    unsharded = compositor_from_config(cfg)  # unknown knob values fail loudly
    streaming = cfg.mpi.compositor == "streaming"
    if n_plane <= 1:
        return {"plane_axis": None, "compositor": unsharded}
    if cfg.mpi.num_bins_coarse % n_plane:
        raise ValueError(
            f"mpi.num_bins_coarse={cfg.mpi.num_bins_coarse} must divide by "
            f"the plane-axis size {n_plane}"
        )
    if cfg.mpi.num_bins_fine % n_plane:
        # the merged coarse+fine list re-shards across the same axis
        # (step.py forward_coarse_to_fine); both lists must chunk evenly
        raise ValueError(
            f"mpi.num_bins_fine={cfg.mpi.num_bins_fine} must divide by "
            f"the plane-axis size {n_plane}"
        )
    return {
        "plane_axis": PLANE_AXIS,
        "compositor": plane_compositor(
            PLANE_AXIS, streaming=streaming,
            chunk_planes=cfg.mpi.stream_chunk_planes,
        ),
    }


def make_parallel_train_step(
    cfg: Config, model: MPINetwork, tx: optax.GradientTransformation, mesh: Mesh
) -> Callable:
    """jit(shard_map(train_step)): state replicated, batch sharded over
    `data` and replicated over `plane`; with a plane axis of size > 1, each
    device runs the decoder + renderer on its S_local plane chunk and the
    compositing reductions cross the plane axis (plane_sharding.py).

    The model must have been built with axis_name=model_axis_name(mesh)
    (build_model) so BN stats sync; the step pmeans the loss pre-grad over
    `data` and logged losses post-grad (step.py).
    """
    step = make_train_step(
        cfg, model, tx, axis_name=DATA_AXIS, **_plane_args(cfg, mesh)
    )
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(_REPL, _BATCH),
        out_specs=(_REPL, _REPL),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_parallel_eval_step(
    cfg: Config,
    model: MPINetwork,
    mesh: Mesh,
    lpips_params: dict | None = None,
) -> Callable:
    """jit(shard_map(eval_step)): losses pmean'd to replicated; per-replica
    visualizations stay batch-sharded (gather only what gets logged)."""
    step = make_eval_step(
        cfg, model, lpips_params=lpips_params, axis_name=DATA_AXIS,
        **_plane_args(cfg, mesh),
    )
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(_REPL, _BATCH, _REPL),
        out_specs=(_REPL, _BATCH),
    )
    return jax.jit(sharded)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place the train state replicated on every mesh device (the DDP initial
    param broadcast, synthesis_task.py:110-115, done once, explicitly)."""
    return jax.device_put(state, jax.sharding.NamedSharding(mesh, _REPL))
