"""Data-parallel train/eval step wrappers over the device mesh.

The reference's distributed story (DDP gradient allreduce + SyncBN +
DistributedSampler, SURVEY.md §2.3) becomes: `shard_map` the train step over
the mesh with the batch axis sharded on `data`, the loss averaged across
replicas before differentiation and BN stats synced inside the step
(mine_tpu/training/step.py), state replicated. One jit; XLA
lowers the collectives onto ICI/DCN.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mine_tpu.utils.jax_compat import shard_map

from mine_tpu.config import Config
from mine_tpu.models import MPINetwork
from mine_tpu.ops import compositor_from_config
from mine_tpu.parallel.mesh import DATA_AXIS, PLANE_AXIS
from mine_tpu.parallel import zero1
from mine_tpu.parallel.plane_sharding import plane_compositor
from mine_tpu.training.step import make_eval_step, make_train_step
from mine_tpu.training.state import TrainState

_REPL = P()  # replicated
_BATCH = P(DATA_AXIS)  # shard axis 0 over data, replicate over plane


def model_axes(mesh: Mesh) -> dict:
    """build_model kwargs for a model living on this mesh: BN syncs over
    `data` always; under plane sharding the decoder's post-conditioning BNs
    additionally pool over `plane` (its effective batch B*S splits across
    both axes — models/decoder.py)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    return {
        "axis_name": DATA_AXIS,
        "plane_axis": PLANE_AXIS if n_plane > 1 else None,
    }


def _plane_args(cfg: Config, mesh: Mesh) -> dict:
    """plane_axis/compositor kwargs for make_{train,eval}_step, validated.
    cfg.mpi.compositor selects dense vs streaming in BOTH regimes: unsharded
    it resolves through ops.compositor_from_config, plane-sharded the local
    chunk-scan composes with the cross-device exclusive prefix
    (plane_sharding.sharded_render_tgt_streaming)."""
    n_plane = mesh.shape.get(PLANE_AXIS, 1)
    unsharded = compositor_from_config(cfg)  # unknown knob values fail loudly
    streaming = cfg.mpi.compositor == "streaming"
    if n_plane <= 1:
        return {"plane_axis": None, "compositor": unsharded}
    if cfg.mpi.num_bins_coarse % n_plane:
        raise ValueError(
            f"mpi.num_bins_coarse={cfg.mpi.num_bins_coarse} must divide by "
            f"the plane-axis size {n_plane}"
        )
    if cfg.mpi.num_bins_fine % n_plane:
        # the merged coarse+fine list re-shards across the same axis
        # (step.py forward_coarse_to_fine); both lists must chunk evenly
        raise ValueError(
            f"mpi.num_bins_fine={cfg.mpi.num_bins_fine} must divide by "
            f"the plane-axis size {n_plane}"
        )
    return {
        "plane_axis": PLANE_AXIS,
        "compositor": plane_compositor(
            PLANE_AXIS, streaming=streaming,
            chunk_planes=cfg.mpi.stream_chunk_planes,
        ),
    }


def zero1_enabled(cfg: Config, mesh: Mesh) -> bool:
    """Whether ZeRO-1 actually runs: the knob is on AND there is something
    to shard over — on a 1-wide data axis the "shard" is the whole state
    and the layout degrades to replicated. The one definition of the
    degrade rule: distribute_state, the step builder, and the Trainer's
    opt_layout.json sidecar all consult it, so what the sidecar records is
    by construction what was placed."""
    return bool(cfg.parallel.zero1) and mesh.shape[DATA_AXIS] > 1


def _state_specs(cfg: Config, mesh: Mesh, state: TrainState | None):
    """shard_map PartitionSpecs for the TrainState: a bare P() (replicated,
    prefix-matched over the whole pytree) unless ZeRO-1 is on — then
    zero1.state_specs, the SAME layout rule distribute_state places by, so
    the compiled step and the live placement cannot diverge."""
    if state is None or not zero1_enabled(cfg, mesh):
        return _REPL
    return zero1.state_specs(
        state, mesh.shape[DATA_AXIS], cfg.parallel.zero1_min_size
    )


def make_parallel_train_step(
    cfg: Config,
    model: MPINetwork,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state: TrainState | None = None,
) -> Callable:
    """jit(shard_map(train_step)): state replicated, batch sharded over
    `data` and replicated over `plane`; with a plane axis of size > 1, each
    device runs the decoder + renderer on its S_local plane chunk and the
    compositing reductions cross the plane axis (plane_sharding.py).

    The model must have been built with axis_name=model_axis_name(mesh)
    (build_model) so BN stats sync; the step pmeans the loss pre-grad over
    `data` and logged losses post-grad (step.py).

    BOTH arguments are donated: the state is consumed and returned every
    step, and the batch's device buffers are dead the moment the step has
    read them — the prefetch pipeline transfers a FRESH batch each step
    (training/loop.py staged_batches), so holding the old one alive only
    padded peak HBM by one full batch.

    With `parallel.zero1` (and a data axis wider than 1), pass the
    replicated-or-host `state` template: the optimizer-state leaves get
    data-axis PartitionSpecs (parallel/zero1.py) in both in_ and out_specs,
    and the step computes updates on the local moment shard + all_gather
    (training/step.py apply_update). `distribute_state` must have placed
    the live state with the matching layout.
    """
    use_zero1 = zero1_enabled(cfg, mesh)
    if use_zero1 and state is None:
        raise ValueError(
            "parallel.zero1 needs the state template to derive the "
            "opt-state partition specs: make_parallel_train_step(..., "
            "state=state)"
        )
    dims = None
    if use_zero1:
        dims = zero1.tree_partition_dims(
            state.params, mesh.shape[DATA_AXIS], cfg.parallel.zero1_min_size
        )
    step = make_train_step(
        cfg, model, tx, axis_name=DATA_AXIS, zero1_dims=dims,
        **_plane_args(cfg, mesh),
    )
    specs = _state_specs(cfg, mesh, state)
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(specs, _BATCH),
        out_specs=(specs, _REPL),
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def make_parallel_eval_step(
    cfg: Config,
    model: MPINetwork,
    mesh: Mesh,
    lpips_params: dict | None = None,
    state: TrainState | None = None,
) -> Callable:
    """jit(shard_map(eval_step)): losses pmean'd to replicated; per-replica
    visualizations stay batch-sharded (gather only what gets logged).

    The eval body reads only params/batch_stats, but it is handed the whole
    TrainState — under `parallel.zero1`, pass the same `state` template as
    the train step so the opt-state leaves keep their data-axis specs
    through shard_map. A replicated in_spec would make jit all-gather the
    sharded Adam moments onto every device on each eval call, spiking HBM
    right back to the replicated footprint the sharding exists to remove;
    with the matching specs the unused shards just flow through."""
    step = make_eval_step(
        cfg, model, lpips_params=lpips_params, axis_name=DATA_AXIS,
        **_plane_args(cfg, mesh),
    )
    sharded = shard_map(
        step,
        mesh=mesh,
        in_specs=(_state_specs(cfg, mesh, state), _BATCH, _REPL),
        out_specs=(_REPL, _BATCH),
    )
    return jax.jit(sharded)


def replicate_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place the train state replicated on every mesh device (the DDP initial
    param broadcast, synthesis_task.py:110-115, done once, explicitly)."""
    return jax.device_put(state, NamedSharding(mesh, _REPL))


def distribute_state(state: TrainState, cfg: Config, mesh: Mesh) -> TrainState:
    """Place a (host or replicated) TrainState per the configured layout:
    fully replicated, or — under `parallel.zero1` — params/BN replicated
    with the optimizer state sharded over `data` (parallel/zero1.py).

    The single entry point for every placement in the training loop
    (initial, warm start, rollback restore), so a restored checkpoint —
    always saved gathered/layout-free — lands back in the live layout."""
    if not zero1_enabled(cfg, mesh):
        return replicate_state(state, mesh)
    return zero1.place_state(state, mesh, cfg.parallel.zero1_min_size)
