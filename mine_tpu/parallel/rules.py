"""Declarative partition rules: ONE regex -> PartitionSpec table drives
every sharding in the system.

Before this module the parallel layer stated its layouts in three places:
`zero1.state_specs` (ZeRO-1 optimizer sharding), the `_BATCH = P("data")`
constant in data_parallel.py, and the ad-hoc plane spec construction in the
plane tests. The MaxText-style pattern (SNIPPETS.md [1] named
`("data","fsdp","tensor")` mesh axes, [3] `match_partition_rules`
regex -> PartitionSpec trees) replaces all of it: a single ordered rule
table maps leaf PATHS (params, optimizer state, batch stats, batch
tensors) to mesh-axis assignments, and everything — the compiled step's
`in_specs`/`out_specs`, the `jax.jit` `in_shardings`/`out_shardings`, the
live `device_put` placement, the checkpoint re-placement, and the serving
engine's placement — derives from it.

Rule semantics
--------------
A rule is `(pattern, axes, dim)`:

  pattern  regex, `re.search`ed against the '/'-joined leaf path
           (e.g. `params/decoder/Conv_3/kernel`,
           `opt_state/inner_states/backbone/inner_state/1/mu/.../kernel`,
           `batch/src_img`). FIRST MATCH WINS — order the table from
           specific to general. A leaf no rule matches is a hard error:
           silence here would mean a silently replicated (or silently
           mis-sharded) tensor.
  axes     tuple of mesh-axis names to shard ONE dimension over
           (major-first), or None to replicate.
  dim      which dimension: None applies the shape rule ZeRO-1 proved
           (largest dimension divisible by the axis product; leaves under
           `min_size` elements replicate), an int pins the dimension
           (batch rows pin 0) and non-divisibility is an error.

Anchored resolution keeps params and their optimizer moments consistent
WITHOUT tree pairing: the shape rule is a pure function of the leaf shape,
so a `(3,3,16,2048)` kernel and its same-shaped Adam moments always agree
on the split dimension. Multi-axis assignments resolve left-to-right —
`("fsdp","data")` first anchors the dimension with the `fsdp` axis size
alone (the SAME computation the param's `("fsdp",)` row performs for the
same shape), then extends over the trailing axes while the dimension keeps
dividing. Size-1 mesh axes drop out before resolution, which is exactly
how the old knobs degrade: with `mesh.fsdp_parallel: 1`, the moment row
`("fsdp","data")` resolves to plain ZeRO-1 over `data`, and with
`parallel.zero1: false` on a 1-wide fsdp axis everything replicates — the
pre-mesh layouts are special cases of the table.

The default table (`partition_rules(cfg)`):

  ^(step|rng)$                -> replicated
  ^params/.*kernel$           -> ("fsdp",)          # FSDP: conv kernels
  ^params/                    -> replicated          # biases, BN affine
  ^batch_stats/               -> replicated
  ^opt_state/.*\\b(mu|nu)/     -> ("fsdp","data")    # the ZeRO-1 rows
                                  (("fsdp",) when parallel.zero1 is off)
  ^opt_state/                 -> replicated          # counts, empty states
  ^batch/                     -> ("data","fsdp") at dim 0

`parallel.rules` config rows ("pattern = axes" strings) PREPEND to the
default table, so an override wins by first-match precedence.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Any, Iterable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mine_tpu.parallel.mesh import AXIS_NAMES, DATA_AXIS, FSDP_AXIS

__all__ = [
    "Rule", "Placement", "REPLICATED", "partition_rules",
    "match_partition_rules", "state_placements", "state_specs",
    "state_shardings", "place_state", "batch_spec", "partition_dim",
    "resolve_placement", "update_placements", "placement_bytes",
    "per_device_bytes", "tree_specs", "parse_rule",
]


@dataclass(frozen=True)
class Rule:
    """One row of the table: leaf-path regex -> mesh-axis assignment."""

    pattern: str
    axes: tuple[str, ...] | None  # None = replicate
    dim: int | None = None  # None = shape rule; int = pinned dimension


@dataclass(frozen=True)
class Placement:
    """A resolved rule: which dimension of a leaf splits over which mesh
    axes (major-first). `dim == -1` (the `REPLICATED` singleton) means the
    leaf lives whole on every device."""

    dim: int
    axes: tuple[str, ...] = ()

    @property
    def replicated(self) -> bool:
        return self.dim < 0 or not self.axes

    def spec(self) -> P:
        if self.replicated:
            return P()
        entry = self.axes if len(self.axes) > 1 else self.axes[0]
        return P(*([None] * self.dim + [entry]))

    def shards(self, mesh_shape: dict[str, int]) -> int:
        if self.replicated:
            return 1
        return math.prod(mesh_shape[a] for a in self.axes)


REPLICATED = Placement(dim=-1, axes=())


# ---------------------------------------------------------------- the table


def parse_rule(row: str) -> Rule:
    """One `parallel.rules` config row: `"pattern = axes"` where axes is a
    comma-joined mesh-axis list, `replicated`, or `axes @ dim` to pin the
    dimension — e.g. `"^params/decoder/ = fsdp"`,
    `"^opt_state/.*mu/ = fsdp,data"`, `"^batch/ = data,fsdp @ 0"`."""
    if "=" not in row:
        raise ValueError(
            f"parallel.rules row {row!r} is not 'pattern = axes'"
        )
    pattern, _, rhs = row.partition("=")
    rhs = rhs.strip()
    dim: int | None = None
    if "@" in rhs:
        rhs, _, d = rhs.partition("@")
        dim = int(d.strip())
    rhs = rhs.strip()
    if rhs.lower() in ("", "replicated", "none"):
        axes = None
    else:
        axes = tuple(a.strip() for a in rhs.split(",") if a.strip())
        unknown = set(axes) - set(AXIS_NAMES)
        if unknown:
            raise ValueError(
                f"parallel.rules row {row!r} names unknown mesh axes "
                f"{sorted(unknown)} (mesh axes: {AXIS_NAMES})"
            )
    return Rule(pattern.strip(), axes, dim)


def partition_rules(cfg: Any) -> tuple[Rule, ...]:
    """THE table. `parallel.rules` override rows prepend (first match
    wins); the retired `parallel.zero1` knob survives as the alias that
    selects the Adam-moment row's axes — `("fsdp","data")` (ZeRO-1 over
    the whole batch-replica product) when on, `("fsdp",)` (moments merely
    follow their FSDP param shard) when off."""
    user = tuple(parse_rule(r) for r in getattr(cfg.parallel, "rules", ()))
    opt_axes = (FSDP_AXIS, DATA_AXIS) if cfg.parallel.zero1 else (FSDP_AXIS,)
    return user + (
        Rule(r"^(step|rng)$", None),
        Rule(r"^params/.*kernel$", (FSDP_AXIS,)),
        Rule(r"^params/", None),
        Rule(r"^batch_stats/", None),
        Rule(r"^opt_state/.*\b(mu|nu)/", opt_axes),
        Rule(r"^opt_state/", None),
        Rule(r"^batch/", (DATA_AXIS, FSDP_AXIS), dim=0),
    )


# ----------------------------------------------------------- path utilities


def _key_name(entry: Any) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def leaf_path(path: tuple, prefix: str = "") -> str:
    """'/'-joined leaf path, e.g. `params/decoder/Conv_0/kernel`."""
    parts = [p for p in (prefix.strip("/"),) if p]
    parts += [_key_name(e) for e in path]
    return "/".join(parts)


def _match(rules: Iterable[Rule], path: str) -> Rule:
    for rule in rules:
        if re.search(rule.pattern, path):
            return rule
    raise ValueError(
        f"no partition rule matches leaf {path!r} — every leaf must be "
        "matched explicitly (add a row to parallel.rules or the default "
        "table in parallel/rules.py)"
    )


# ------------------------------------------------------------- resolution


def partition_dim(shape: tuple[int, ...], n_shards: int, min_size: int) -> int:
    """Which dimension of a leaf to split over n_shards, or -1 (replicate).

    The shape rule ZeRO-1 proved (pure function of the SHAPE, so a param,
    its gradient, and its Adam moments always agree): dimensions are tried
    largest-first — a (3,3,16,2048) conv kernel splits its 2048, not the 3
    — and the first one divisible by n_shards wins. Leaves under min_size
    elements, scalars, and leaves with no dividing dimension replicate.
    """
    if not shape or n_shards <= 1:
        return -1
    if math.prod(shape) < min_size:
        return -1
    for d in sorted(range(len(shape)), key=lambda i: shape[i], reverse=True):
        if shape[d] % n_shards == 0 and shape[d] >= n_shards:
            return d
    return -1


def resolve_placement(
    shape: tuple[int, ...],
    axes: tuple[str, ...] | None,
    mesh_shape: dict[str, int],
    min_size: int,
    dim: int | None = None,
    path: str = "?",
) -> Placement:
    """Rule RHS -> Placement for a concrete leaf shape.

    Size-1 mesh axes drop out first (sharding over them is replication —
    this is how `("fsdp","data")` degrades to plain ZeRO-1 on an fsdp-less
    mesh). Pinned dims (batch rows) must divide exactly. Shape-rule dims
    resolve ANCHORED left-to-right: the first surviving axis picks the
    dimension by `partition_dim` with its size alone, then trailing axes
    extend the split while the dimension keeps dividing — so a moment row
    `("fsdp","data")` lands on the same dimension its param's `("fsdp",)`
    row picked for the same shape.
    """
    if not axes:
        return REPLICATED
    live = tuple(a for a in axes if mesh_shape.get(a, 1) > 1)
    if not live:
        return REPLICATED
    if dim is not None:
        n = math.prod(mesh_shape[a] for a in live)
        if dim >= len(shape) or shape[dim] % n:
            raise ValueError(
                f"{path}: dim {dim} of shape {tuple(shape)} does not divide "
                f"over axes {live} (sizes "
                f"{[mesh_shape[a] for a in live]})"
            )
        return Placement(dim, live)
    d = partition_dim(shape, mesh_shape[live[0]], min_size)
    if d < 0:
        return resolve_placement(
            shape, live[1:], mesh_shape, min_size, path=path
        )
    keep = 1
    n = mesh_shape[live[0]]
    for a in live[1:]:
        if shape[d] % (n * mesh_shape[a]):
            break
        n *= mesh_shape[a]
        keep += 1
    return Placement(d, live[:keep])


# ---------------------------------------------------------------- tree APIs


def match_partition_rules(
    rules: Iterable[Rule],
    tree: Any,
    mesh_shape: dict[str, int],
    min_size: int,
    prefix: str = "",
) -> Any:
    """Placement per leaf: first-matching rule, resolved against the leaf
    shape. Unmatched leaves raise (never a silent default)."""
    rules = tuple(rules)

    def one(path, leaf):
        p = leaf_path(path, prefix)
        rule = _match(rules, p)
        return resolve_placement(
            np.shape(leaf), rule.axes, mesh_shape, min_size,
            dim=rule.dim, path=p,
        )

    return jax.tree_util.tree_map_with_path(one, tree)


def tree_specs(placements: Any) -> Any:
    """Placement tree -> bare PartitionSpec tree (shard_map in/out_specs)."""
    return jax.tree.map(
        lambda pl: pl.spec(), placements,
        is_leaf=lambda x: isinstance(x, Placement),
    )


def _mesh_shape(mesh: Mesh | dict[str, int]) -> dict[str, int]:
    return dict(mesh.shape) if isinstance(mesh, Mesh) else dict(mesh)


def state_placements(
    rules: Iterable[Rule], state: Any, mesh: Mesh | dict[str, int],
    min_size: int,
) -> Any:
    """Placement tree for a TrainState: each field matched under its path
    prefix (`params/...`, `opt_state/...`, `batch_stats/...`, `step`,
    `rng`). The one derivation both the compiled step's specs and the live
    `device_put` placement share, so they cannot diverge."""
    shape = _mesh_shape(mesh)
    rules = tuple(rules)
    fields = {
        name: match_partition_rules(
            rules, getattr(state, name), shape, min_size, prefix=name
        )
        for name in ("params", "batch_stats", "opt_state")
    }
    step_pl = match_partition_rules(rules, state.step, shape, min_size,
                                    prefix="step")
    rng_pl = match_partition_rules(rules, state.rng, shape, min_size,
                                   prefix="rng")
    placed = state.replace(step=step_pl, rng=rng_pl, **fields)
    _validate_update_layout(rules, state, placed, shape, min_size)
    return placed


def update_placements(
    rules: Iterable[Rule], params: Any, mesh: Mesh | dict[str, int],
    min_size: int,
) -> Any:
    """The optimizer-shard granularity, PARAM-structured: for each param
    leaf, the placement its Adam moments get from the table. Matched via a
    synthetic `opt_state/mu/<param path>` probe path — moment rows must
    therefore key on `\\b(mu|nu)/` plus the param-path suffix (the default
    table does), not on exact optax chain indices. The in-step sharded
    optimizer update slices grads/params by THIS tree, runs `tx.update` on
    the shard, and gathers the update back to each param's own layout."""
    shape = _mesh_shape(mesh)
    return match_partition_rules(
        tuple(rules), params, shape, min_size, prefix="opt_state/mu"
    )


_MOMENT_RE = re.compile(r"\b(mu|nu)/")


def _validate_update_layout(rules, state, placed, mesh_shape, min_size):
    """The sharded optimizer update requires every param's moment placement
    to EXTEND its own (same dim, axes prefix — or a replicated param with
    any moment layout), AND the resident moment leaves to resolve exactly
    as their `opt_state/mu/<param path>` probe twins do (the in-step
    update slices by the probe-derived tree while the resident opt state
    was placed by the real paths). The anchored shape rule + default table
    guarantee both; a user override row keyed on real optax chain paths
    (or on the probe form alone) can break either, and must fail here with
    names, not inside a compiled step with a shape error."""
    rules = tuple(rules)
    for (path, leaf), pl in zip(
        jax.tree_util.tree_leaves_with_path(state.opt_state),
        jax.tree.leaves(
            placed.opt_state, is_leaf=lambda x: isinstance(x, Placement)
        ),
    ):
        p = leaf_path(path, "opt_state")
        last = None
        for m in _MOMENT_RE.finditer(p):
            last = m
        if last is None:
            continue  # not a moment leaf (counts, empty states)
        probe = "opt_state/mu/" + p[last.end():]
        rule = _match(rules, probe)
        probe_pl = resolve_placement(
            np.shape(leaf), rule.axes, mesh_shape, min_size,
            dim=rule.dim, path=probe,
        )
        if probe_pl != pl:
            raise ValueError(
                f"{p}: resident moment placement {pl} != the placement its "
                f"probe path {probe!r} resolves to ({probe_pl}) — a "
                "parallel.rules row matches one form but not the other; "
                "key moment rows on `\\b(mu|nu)/` plus the param-path "
                "suffix so both resolve identically"
            )
    upd = update_placements(rules, state.params, mesh_shape, min_size)

    def check(path, ppl, upl):
        if upl.replicated:
            if not ppl.replicated:
                raise ValueError(
                    f"params/{leaf_path(path)}: param sharded {ppl} but its "
                    "optimizer moments replicate — the update cannot be "
                    "assembled; align the params/ and opt_state/ rule rows"
                )
            return ppl
        if ppl.replicated:
            return ppl
        if ppl.dim != upl.dim or upl.axes[: len(ppl.axes)] != ppl.axes:
            raise ValueError(
                f"params/{leaf_path(path)}: param placement {ppl} is not a "
                f"prefix of its moment placement {upl} — the rule rows for "
                "params/ and opt_state/ moments must agree on the split"
            )
        return ppl

    jax.tree_util.tree_map_with_path(
        check, placed.params, upd,
        is_leaf=lambda x: isinstance(x, Placement),
    )


def state_specs(rules, state, mesh, min_size) -> Any:
    return tree_specs(state_placements(rules, state, mesh, min_size))


def state_shardings(rules, state, mesh: Mesh, min_size) -> Any:
    """NamedSharding pytree for device_put / jit in_shardings."""
    specs = state_specs(rules, state, mesh, min_size)
    # PartitionSpec is a tuple subclass, i.e. itself a pytree — stop the
    # traversal at spec leaves or tree.map would recurse into them
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def place_state(rules, state: Any, mesh: Mesh, min_size: int) -> Any:
    """device_put a (host or replicated) TrainState into the table's
    layout. The inverse needs no helper: `jax.device_get` of the placed
    state returns full global arrays — what keeps checkpoints layout-free
    (training/checkpoint.py gather-on-save)."""
    return jax.device_put(state, state_shardings(rules, state, mesh, min_size))


def batch_spec(rules: Iterable[Rule]) -> P:
    """The batch sharding the table prescribes, as a pytree-prefix spec
    (every batch tensor shards its leading dim the same way). Read off the
    `^batch/` row directly — batch leaves are placeholder-shaped here, the
    actual divisibility check happens at `shard_batch`/trace time."""
    rule = _match(tuple(rules), "batch/src_img")
    if rule.axes is None:
        return P()
    if (rule.dim or 0) != 0:
        raise ValueError(
            f"the batch rule must pin dim 0 (got dim={rule.dim}); batches "
            "shard their leading (example) axis only"
        )
    entry = rule.axes if len(rule.axes) > 1 else rule.axes[0]
    return P(entry)


# ------------------------------------------------------------- measurement


def placement_bytes(shapes: Any, placements: Any,
                    mesh: Mesh | dict[str, int]) -> int:
    """Analytic per-device bytes of a tree under a placement tree — shapes
    may be real arrays or `jax.eval_shape` ShapeDtypeStructs, so the
    tier-1 tests can pin the FSDP byte reduction without materializing a
    model."""
    shape = _mesh_shape(mesh)
    total = 0
    for leaf, pl in zip(
        jax.tree.leaves(shapes),
        jax.tree.leaves(
            placements, is_leaf=lambda x: isinstance(x, Placement)
        ),
    ):
        nbytes = math.prod(np.shape(leaf) or (1,)) * np.dtype(leaf.dtype).itemsize
        total += nbytes // pl.shards(shape)
    return total


def per_device_bytes(tree: Any, device: Any | None = None) -> int:
    """Bytes of `tree` resident on ONE device — the measurement behind
    every per-device-bytes claim (bench.py obs snapshot,
    tools/bench_accum.py, tests). Sharded leaves count only the local
    shard; replicated leaves their full size; host arrays one replica."""
    if device is None:
        device = jax.devices()[0]
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            total += sum(s.data.nbytes for s in shards if s.device == device)
        else:
            total += np.asarray(leaf).nbytes
    return total
