"""ZeRO-1 optimizer-state sharding over the data mesh axis.

The reference replicates Adam moments on every DDP rank (torch Adam holds
exp_avg/exp_avg_sq per param, synthesis_task.py:85-89), and so did this repo
until now: with ~60M params the moments are 2x params bytes of pure
replication on every device. ZeRO-1 (Rajbhandari et al., arXiv 1910.02054,
stage 1) removes it: each data-parallel device owns a 1/n shard of the
optimizer state, computes the parameter UPDATE for its shard only, and an
all_gather reassembles the full update into the (still replicated) params.
Gradients are reduced exactly once, same as plain data parallel — the only
added traffic is the update all_gather, which replaces the redundant
(n-1)/n of the optimizer math every device used to repeat.

Partitioning rule (`partition_dim`): each leaf is split along its largest
dimension that divides the axis size; leaves smaller than
`parallel.zero1_min_size` elements (biases, scalars, schedule counts) stay
replicated — the epsilon in the ~1/n per-device-bytes claim. The rule is a
pure function of the leaf SHAPE, so a param leaf, its gradient, and its
Adam moments (same shape by construction) always agree on the split, and
no name-based matching between the param tree and optax's state tree is
needed.

The optimizer chain this repo uses (add_decayed_weights, scale_by_adam,
scale_by_learning_rate under multi_transform) is elementwise per leaf, so
update(slice(g), shard_state, slice(p)) == slice(update(g, state, p)) and
the sharded update is EXACT, not approximate (tests/test_parallel.py
mesh-equivalence). A cross-leaf transform (e.g. global-norm clipping)
would break that identity; `make_optimizer` has none.

Checkpoints stay layout-independent: `jax.device_get` of a sharded array
materializes the full global array (gather-on-save), so saved opt state is
always the replicated layout and restores into either placement
(`place_state` re-shards). The layout that produced a workspace is
recorded in the sidecar (training/checkpoint.py `record_opt_layout`).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mine_tpu.parallel.mesh import DATA_AXIS
from mine_tpu.utils.jax_compat import axis_size

REPLICATED = -1  # sentinel partition dim: leaf stays whole on every device


def partition_dim(shape: tuple[int, ...], n_shards: int, min_size: int) -> int:
    """Which dimension of a leaf to split over the data axis, or REPLICATED.

    Dimensions are tried largest-first (the issue of splitting the largest
    dim is skew: a (3,3,16,2048) conv kernel splits its 2048, not the 3);
    the first one divisible by n_shards wins. Leaves under min_size
    elements, scalars, and leaves with no dividing dimension replicate.
    """
    if not shape or n_shards <= 1:
        return REPLICATED
    if math.prod(shape) < min_size:
        return REPLICATED
    for d in sorted(range(len(shape)), key=lambda i: shape[i], reverse=True):
        if shape[d] % n_shards == 0 and shape[d] >= n_shards:
            return d
    return REPLICATED


def tree_partition_dims(tree: Any, n_shards: int, min_size: int) -> Any:
    """partition_dim per leaf (ints, REPLICATED sentinel — never None, which
    jax.tree.map would treat as an empty subtree)."""
    return jax.tree.map(
        lambda leaf: partition_dim(np.shape(leaf), n_shards, min_size), tree
    )


def _spec(dim: int) -> P:
    return P() if dim == REPLICATED else P(*([None] * dim + [DATA_AXIS]))


def opt_state_specs(opt_state: Any, n_shards: int, min_size: int) -> Any:
    """PartitionSpec per opt-state leaf under the shape rule: Adam moments
    land on the same split as their param (same shape), scalar counts and
    small leaves replicate."""
    return jax.tree.map(
        lambda leaf: _spec(partition_dim(np.shape(leaf), n_shards, min_size)),
        opt_state,
    )


def state_specs(state: Any, n_shards: int, min_size: int) -> Any:
    """Bare PartitionSpec tree for a TrainState under ZeRO-1 — THE layout
    rule, stated once: opt_state leaves shard over `data` per the shape
    rule, everything else replicates. Both consumers derive from here, so
    the compiled step's in/out_specs (data_parallel.make_parallel_train_step
    via _state_specs) and the live placement (state_shardings → place_state)
    cannot diverge."""
    repl_tree = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731
    return state.replace(
        step=P(),
        params=repl_tree(state.params),
        batch_stats=repl_tree(state.batch_stats),
        opt_state=opt_state_specs(state.opt_state, n_shards, min_size),
        rng=P(),
    )


def state_shardings(state: Any, mesh: Mesh, min_size: int) -> Any:
    """NamedSharding pytree for a TrainState under ZeRO-1: state_specs
    bound to the mesh. Feed to jax.device_put (place_state)."""
    specs = state_specs(state, mesh.shape[DATA_AXIS], min_size)
    # PartitionSpec is a tuple subclass, i.e. itself a pytree — stop the
    # traversal at spec leaves or tree.map would recurse into them
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def place_state(state: Any, mesh: Mesh, min_size: int) -> Any:
    """device_put a (host or replicated) TrainState into the ZeRO-1 layout.

    The inverse direction needs no helper: jax.device_get of the placed
    state returns full global arrays (this is what makes checkpoints
    layout-independent — training/checkpoint.py gather-on-save)."""
    return jax.device_put(state, state_shardings(state, mesh, min_size))


def shard_update(
    tx: Any,
    grads: Any,
    opt_state_local: Any,
    params: Any,
    dims: Any,
    axis_name: str = DATA_AXIS,
) -> tuple[Any, Any]:
    """The ZeRO-1 optimizer step, called INSIDE shard_map with fully
    reduced (replicated-in-value) grads and params, and the LOCAL shard of
    the optimizer state.

    Each device slices its chunk of every partitioned grad/param leaf,
    runs tx.update on the shard (exact — the chain is elementwise), and
    all_gathers the update chunks back into full update leaves; replicated
    leaves compute identically everywhere and skip both steps. Returns
    (full updates, new LOCAL opt state).
    """
    idx = lax.axis_index(axis_name)
    n = axis_size(axis_name)

    def slc(x, d):
        if d == REPLICATED:
            return x
        chunk = x.shape[d] // n
        return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=d)

    grads_local = jax.tree.map(slc, grads, dims)
    params_local = jax.tree.map(slc, params, dims)
    updates_local, new_opt_local = tx.update(
        grads_local, opt_state_local, params_local
    )

    def gather(u, d):
        if d == REPLICATED:
            return u
        return lax.all_gather(u, axis_name, axis=d, tiled=True)

    # component scope (obs/attrib.py): the added ZeRO-1 traffic is its own
    # attribution bucket, distinct from the elementwise optimizer math
    with jax.named_scope("zero1_gather"):
        updates = jax.tree.map(gather, updates_local, dims)
    return updates, new_opt_local


def per_device_bytes(tree: Any, device: Any | None = None) -> int:
    """Bytes of `tree` resident on one device — the measurement behind the
    "~1/n opt-state bytes" claim (tools/bench_accum.py, test_parallel).
    Sharded leaves count only the local shard; replicated leaves count
    their full size; host arrays count as one replica."""
    if device is None:
        device = jax.devices()[0]
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is not None:
            total += sum(s.data.nbytes for s in shards if s.device == device)
        else:
            total += np.asarray(leaf).nbytes
    return total
