"""Plane-axis (S) sharded MPI compositing — the sequence-parallel analog.

The reference brute-forces the S axis ("memory consumption is huge, only one
supervision is allowed", synthesis_task.py:203-204): every (B, S, H, W, C)
tensor lives whole on one GPU. Here S shards across the `plane` mesh axis and
compositing — a prefix product over planes — runs as a two-level scan
(SURVEY.md §5.7): local cumprod on each device's plane chunk, then one tiny
`all_gather` of per-device products to build the cross-device exclusive
prefix. The heavy (B, S_local, H, W) tensors never move; only (B, H, W)
per-device products cross the ICI — this is the project's honest analog of
ring attention's "ship statistics, not activations".

All functions here expect to run INSIDE shard_map with the plane axis named
`axis_name`; plane order follows mesh position (device p owns planes
[p*S_local, (p+1)*S_local), near planes on low indices, same descending-
disparity convention as ops/mpi_render.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import Array, lax

from mine_tpu.ops.mpi_render import (
    _BG_DIST,
    Compositor,
    DEFAULT_STREAM_CHUNK,
    _chunk_size,
    _finalize_depth,
    _shifted_exclusive,
    _stream_scan,
    ray_norms,
    warp_mpi_to_tgt,
)
from mine_tpu.utils.jax_compat import axis_size, has_vma


def _psum_replicated(x: Array, axis_name: str) -> Array:
    """psum of per-device partial sums whose RESULT is consumed replicated
    (every plane device computes the identical downstream loss graph).

    On vma-tracking jax this is a plain psum: the replicated cotangent
    transposes to the identity, so each device's partial receives exactly
    its cotangent. On pre-vma jax (0.4.x shard_map) psum's transpose is
    psum — the n identical consumer cotangents SUM, inflating every
    gradient through the composite by the plane-axis size. Routing the
    backward through the local summand only restores the exact gradient
    (each logical consumer contributes once) while the forward still
    returns the full replicated total; cross-device cotangent routes that
    are REAL data dependencies (the all_gather prefix, the ppermute halo)
    keep their ordinary collective transposes.
    """
    total = lax.psum(x, axis_name)
    if has_vma():
        return total
    return x + lax.stop_gradient(total - x)


def _exclusive_device_prefix(local_total: Array, axis_name: str) -> Array:
    """Exclusive product of per-device totals over the plane axis.

    local_total: (...) this device's product over its local planes.
    Returns (...) product over all devices strictly before this one.
    """
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    gathered = lax.all_gather(local_total, axis_name)  # (n, ...)
    mask = (jnp.arange(n) < idx).reshape((n,) + (1,) * local_total.ndim)
    return jnp.prod(jnp.where(mask, gathered, 1.0), axis=0)


def sharded_alpha_composition(
    alpha: Array, value: Array, axis_name: str
) -> tuple[Array, Array]:
    """Plane-sharded over-compositing (unsharded twin: ops.alpha_composition).

    alpha: (B, S_local, H, W, 1); value: (B, S_local, H, W, C).
    Returns composed (B, H, W, C) — full sum, replicated across the plane
    axis — and this device's local weights (B, S_local, H, W, 1).
    """
    trans_local = jnp.cumprod(1.0 - alpha, axis=1)
    prefix = _exclusive_device_prefix(trans_local[:, -1], axis_name)
    preserve = _shifted_exclusive(trans_local) * prefix[:, None]
    weights = alpha * preserve
    composed = _psum_replicated(jnp.sum(value * weights, axis=1), axis_name)
    return composed, weights


def _halo_next_first_plane(x: Array, axis_name: str, fill: Array) -> Array:
    """First plane of the NEXT device's chunk (for inter-plane distances).
    The last device receives `fill`. x: (B, S_local, ...) -> (B, ...)."""
    n = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # shift first-plane slices one device towards lower plane indices
    recv = lax.ppermute(x[:, 0], axis_name, [(p, (p - 1) % n) for p in range(n)])
    return jnp.where(idx == n - 1, fill, recv)


def sharded_plane_volume_rendering(
    rgb: Array,
    sigma: Array,
    xyz: Array,
    axis_name: str,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Plane-sharded NeRF-style volume rendering (unsharded twin:
    ops.plane_volume_rendering; reference mpi_rendering.py:42-67).

    rgb/xyz: (B, S_local, H, W, 3); sigma: (B, S_local, H, W, 1).
    Returns (rgb_out (B,H,W,3), depth_out (B,H,W,1)) — psum-replicated —
    plus local transmittance and weights (B, S_local, H, W, 1).
    """
    # inter-plane distances need one halo plane from the next device
    xyz_next = _halo_next_first_plane(xyz, axis_name, xyz[:, -1])  # fill unused
    xyz_ext = jnp.concatenate([xyz, xyz_next[:, None]], axis=1)
    diff = xyz_ext[:, 1:] - xyz_ext[:, :-1]
    # the globally-last plane's diff is the zero halo fill; its dist is
    # overwritten with the background pseudo-distance below, but the zero must
    # be replaced BEFORE the norm — d||v||/dv at v=0 is 0/0, and jnp.where
    # only masks the forward value, so a zero diff would send NaN cotangents
    # into xyz on the backward pass
    n = axis_size(axis_name)
    is_last_device = lax.axis_index(axis_name) == n - 1
    s_local = diff.shape[1]
    last_mask = (jnp.arange(s_local) == s_local - 1).reshape(1, s_local, 1, 1, 1)
    bg_mask = jnp.logical_and(is_last_device, last_mask)
    diff = jnp.where(bg_mask, 1.0, diff)
    dist = jnp.linalg.norm(diff, axis=-1, keepdims=True)  # (B, S_local, H, W, 1)
    dist = jnp.where(bg_mask, _BG_DIST, dist)

    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency

    trans_local = jnp.cumprod(transparency + 1.0e-6, axis=1)
    prefix = _exclusive_device_prefix(trans_local[:, -1], axis_name)
    transparency_acc = _shifted_exclusive(trans_local) * prefix[:, None]
    weights = transparency_acc * alpha

    rgb_out, depth_out = sharded_weighted_sum_mpi(
        rgb, xyz, weights, axis_name, is_bg_depth_inf
    )
    return rgb_out, depth_out, transparency_acc, weights


def sharded_weighted_sum_mpi(
    rgb: Array,
    xyz: Array,
    weights: Array,
    axis_name: str,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array]:
    """Plane-sharded expectation under compositing weights (unsharded twin:
    ops.weighted_sum_mpi)."""
    weights_sum = _psum_replicated(jnp.sum(weights, axis=1), axis_name)
    rgb_out = _psum_replicated(jnp.sum(weights * rgb, axis=1), axis_name)
    z_term = _psum_replicated(
        jnp.sum(weights * xyz[..., 2:3], axis=1), axis_name
    )
    if is_bg_depth_inf:
        depth_out = z_term + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = z_term / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


def sharded_render(
    rgb: Array,
    sigma: Array,
    xyz: Array,
    axis_name: str,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Sigma-vs-alpha compositing dispatch on local plane chunks (unsharded
    twin: ops.render; reference mpi_rendering.py:7-20).

    Composited outputs come back psum-replicated over the plane axis; blend
    weights and compositing weights stay local (B, S_local, H, W, 1)."""
    if not use_alpha:
        return sharded_plane_volume_rendering(
            rgb, sigma, xyz, axis_name, is_bg_depth_inf
        )
    imgs_syn, weights = sharded_alpha_composition(sigma, rgb, axis_name)
    depth_syn, _ = sharded_alpha_composition(sigma, xyz[..., 2:3], axis_name)
    return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights


def sharded_render_src(
    rgb: Array,
    sigma: Array,
    mpi_disparity: Array,
    k_inv: Array,
    axis_name: str,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Plane-sharded source-pose compositing from local disparities alone
    (unsharded twin: ops.render_src — see its factored-distance derivation).

    mpi_disparity: (B, S_local) this device's plane chunk. The inter-plane
    distance at the chunk boundary needs only the NEXT device's first plane
    DEPTH — a (B,) halo instead of the (B, H, W, 3) xyz halo the generic
    sharded path ships.

    Like the dense ops.render_src, assumes normalized intrinsics
    (K[2,2] = 1) so per-plane camera z == 1/disparity.
    """
    if use_alpha:
        imgs_syn, weights = sharded_alpha_composition(sigma, rgb, axis_name)
        z = jnp.broadcast_to(
            (1.0 / mpi_disparity)[:, :, None, None, None], sigma.shape
        )
        depth_syn, _ = sharded_alpha_composition(sigma, z, axis_name)
        return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights

    h, w = rgb.shape[2], rgb.shape[3]
    depth = 1.0 / mpi_disparity  # (B, S_local)
    depth_next = _halo_next_first_plane(
        depth[:, :, None], axis_name, depth[:, -1:]
    )  # (B, 1); fill value unused (overwritten by the bg distance below)
    depth_ext = jnp.concatenate([depth, depth_next], axis=1)  # (B, S_local+1)
    ddiff = jnp.abs(depth_ext[:, 1:] - depth_ext[:, :-1])  # (B, S_local)

    dist = ddiff[:, :, None, None, None] * ray_norms(k_inv, h, w)[:, None]
    n = axis_size(axis_name)
    s_local = ddiff.shape[1]
    last_mask = (jnp.arange(s_local) == s_local - 1).reshape(1, s_local, 1, 1, 1)
    bg_mask = jnp.logical_and(lax.axis_index(axis_name) == n - 1, last_mask)
    dist = jnp.where(bg_mask, _BG_DIST, dist)

    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    trans_local = jnp.cumprod(transparency + 1.0e-6, axis=1)
    prefix = _exclusive_device_prefix(trans_local[:, -1], axis_name)
    transparency_acc = _shifted_exclusive(trans_local) * prefix[:, None]
    weights = transparency_acc * alpha

    rgb_out, depth_out = sharded_weighted_sum_src(
        rgb, mpi_disparity, weights, axis_name, is_bg_depth_inf
    )
    return rgb_out, depth_out, transparency_acc, weights


def sharded_weighted_sum_src(
    rgb: Array,
    mpi_disparity: Array,
    weights: Array,
    axis_name: str,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array]:
    """Plane-sharded weighted_sum_src: per-plane z is the constant local
    plane depth (unsharded twin: ops.weighted_sum_src — including its
    normalized-intrinsics assumption, K[2,2] = 1)."""
    z = (1.0 / mpi_disparity)[:, :, None, None, None]
    weights_sum = _psum_replicated(jnp.sum(weights, axis=1), axis_name)
    rgb_out = _psum_replicated(jnp.sum(weights * rgb, axis=1), axis_name)
    z_term = _psum_replicated(jnp.sum(weights * z, axis=1), axis_name)
    if is_bg_depth_inf:
        depth_out = z_term + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = z_term / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


def sharded_render_tgt_rgb_depth(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    axis_name: str,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array]:
    """Plane-sharded target-view render (unsharded twin:
    ops.render_tgt_rgb_depth; reference mpi_rendering.py:181-241).

    The homography warp — including the analytic per-plane xyz evaluation —
    is per-plane local work and runs unchanged on each device's chunk; only
    the composite and the in-FoV plane count cross the plane axis.
    """
    tgt_rgb, tgt_sigma, tgt_xyz, valid = warp_mpi_to_tgt(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
    )
    tgt_rgb_syn, tgt_depth_syn, _, _ = sharded_render(
        tgt_rgb, tgt_sigma, tgt_xyz, axis_name,
        use_alpha=use_alpha, is_bg_depth_inf=is_bg_depth_inf,
    )
    tgt_mask = lax.psum(
        jnp.sum(valid.astype(mpi_rgb_src.dtype), axis=1), axis_name
    )[..., None]
    return tgt_rgb_syn, tgt_depth_syn, tgt_mask


def sharded_render_tgt_streaming(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    axis_name: str,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
    chunk_planes: int = DEFAULT_STREAM_CHUNK,
) -> tuple[Array, Array, Array]:
    """Plane-sharded STREAMING target render (unsharded twin:
    ops.render_tgt_rgb_depth_streaming): each device chunk-scans its local
    planes with initial transmittance 1 (ops/mpi_render._stream_scan), then
    the existing cross-device exclusive prefix scales the partial sums —
    the local scan composes with the prefix because every accumulator is
    linear in the incoming transmittance.

    Cross-ICI traffic stays statistics-only: one (B,) depth halo (ppermute
    — the next device's first plane DEPTH; its xyz is analytic in it,
    ops.plane_tgt_xyz), the (B, H, W, 1) transmittance all_gather, and the
    psum'd (B, H, W, ·) partials. The (B, S_local, H, W, ·) slabs never
    exist and never move.
    """
    n = axis_size(axis_name)
    is_last = lax.axis_index(axis_name) == n - 1
    depth = 1.0 / mpi_disparity_src  # (B, S_local)
    halo = _halo_next_first_plane(
        depth[:, :, None], axis_name, depth[:, -1:]
    )[:, 0]  # (B,); fill unused (the background distance overwrites it)
    chunk = _chunk_size(mpi_rgb_src.shape[1], chunk_planes)
    rgb_p, z_p, w_p, m_p, t_total = _stream_scan(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
        halo_depth=halo, bg_on_last=is_last, use_alpha=use_alpha, chunk=chunk,
    )
    prefix = _exclusive_device_prefix(t_total, axis_name)  # (B, H, W, 1)
    rgb_out = _psum_replicated(prefix * rgb_p, axis_name)
    z_sum = _psum_replicated(prefix * z_p, axis_name)
    w_sum = _psum_replicated(prefix * w_p, axis_name)
    mask = lax.psum(m_p, axis_name)[..., None]
    depth_out = _finalize_depth(z_sum, w_sum, use_alpha, is_bg_depth_inf)
    return rgb_out, depth_out, mask


def plane_compositor(
    axis_name: str,
    streaming: bool = False,
    chunk_planes: int = DEFAULT_STREAM_CHUNK,
) -> Compositor:
    """The plane-sharded Compositor: drop-in for ops.DENSE_COMPOSITOR inside
    a shard_map whose `axis_name` carries the S-plane axis. Swapping this in
    is the whole difference between the unsharded and plane-parallel loss
    graphs (training/step.py). With `streaming` the target render chunk-scans
    local planes (cfg.mpi.compositor, resolved by data_parallel._plane_args);
    the source sweep keeps its per-plane weights either way (blending)."""
    if streaming:
        render_tgt = partial(_render_tgt_streaming_kw, axis_name, chunk_planes)
    else:
        render_tgt = partial(_render_tgt_kw, axis_name)
    return Compositor(
        render_src=partial(_render_src_kw, axis_name),
        weighted_sum_src=partial(_weighted_sum_src_kw, axis_name),
        render_tgt_rgb_depth=render_tgt,
    )


# keyword-compatible adapters: the loss graph calls the Compositor fields with
# the unsharded ops' signatures (use_alpha=..., is_bg_depth_inf=...)
def _render_src_kw(
    axis_name, rgb, sigma, disparity, k_inv, use_alpha=False, is_bg_depth_inf=False
):
    return sharded_render_src(
        rgb, sigma, disparity, k_inv, axis_name, use_alpha, is_bg_depth_inf
    )


def _weighted_sum_src_kw(axis_name, rgb, disparity, weights, is_bg_depth_inf=False):
    return sharded_weighted_sum_src(
        rgb, disparity, weights, axis_name, is_bg_depth_inf
    )


def _render_tgt_kw(
    axis_name, mpi_rgb, mpi_sigma, disparity, g, k_src_inv, k_tgt,
    use_alpha=False, is_bg_depth_inf=False,
):
    return sharded_render_tgt_rgb_depth(
        mpi_rgb, mpi_sigma, disparity, g, k_src_inv, k_tgt,
        axis_name, use_alpha, is_bg_depth_inf,
    )


def _render_tgt_streaming_kw(
    axis_name, chunk_planes, mpi_rgb, mpi_sigma, disparity, g, k_src_inv,
    k_tgt, use_alpha=False, is_bg_depth_inf=False,
):
    return sharded_render_tgt_streaming(
        mpi_rgb, mpi_sigma, disparity, g, k_src_inv, k_tgt,
        axis_name, use_alpha, is_bg_depth_inf, chunk_planes,
    )
