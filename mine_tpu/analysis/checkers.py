"""The shipped lint rules (REGISTRY at the bottom).

Each rule mechanizes a discipline this repo already paid to learn by
hand-review; the `motivation` attr names the PR whose bug motivates it,
and README's "Static analysis" rule table is drift-tested against these
class attrs in both directions (tests/test_lint.py)."""

from __future__ import annotations

import ast
import re
from typing import Iterable

from mine_tpu.analysis.engine import (
    Checker,
    Finding,
    Module,
    Repo,
    dotted,
    importers_of,
    walk_scoped,
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _enclosing_func(stack: tuple[ast.AST, ...]) -> str:
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node.name
        if isinstance(node, ast.Lambda):
            return "<lambda>"
    return "<module>"


# -- 1. backend-touch-at-import ------------------------------------------------

# Exact jax APIs whose first call initializes (or hangs on) the backend,
# plus prefix families that allocate arrays. `import jax` is free; the
# first DEVICE touch is not — and before multi-host bring-up it is fatal
# (jax.distributed.initialize only works on an untouched backend).
_BACKEND_CALLS = frozenset({
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.device_put", "jax.default_backend",
    "jax.process_index", "jax.process_count", "jax.live_arrays",
})
_BACKEND_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.", "jax.lax.")


class BackendTouchAtImport(Checker):
    rule_id = "backend-touch-at-import"
    catches = ("`jax.devices()` / `device_put` / any `jnp.`/`jax.random.` "
               "call reachable at module import time (module or class "
               "scope, decorators, default argument values)")
    motivation = ("PR 12's `honor_jax_platforms` probe initialized the "
                  "backend before multi-host bring-up; PR 13's router "
                  "rule: never probe a backend into existence")

    def _is_touch(self, call: ast.Call) -> str:
        name = dotted(call.func)
        if name in _BACKEND_CALLS or name.startswith(_BACKEND_PREFIXES):
            return name
        return ""

    def _importers(self, repo: Repo) -> dict[str, set[str]]:
        # one graph build per run, not per module (the hook is per-file)
        cached = getattr(self, "_importers_cache", None)
        if cached is None or cached[0] is not repo:
            cached = (repo, importers_of(repo))
            self._importers_cache = cached
        return cached[1]

    def check_module(self, module: Module, repo: Repo) -> Iterable[Finding]:
        findings: list[Finding] = []
        # import-time code runs for EVERY importer, so the finding names
        # the blast radius: how many corpus modules pull this one in
        n_importers = len(self._importers(repo).get(module.path, ()))
        radius = (f" ({n_importers} corpus modules import this one)"
                  if n_importers else "")

        def scan(node: ast.AST, import_reachable: bool) -> None:
            if isinstance(node, ast.Call) and import_reachable:
                name = self._is_touch(node)
                if name:
                    findings.append(Finding(
                        self.rule_id, module.path, node.lineno, name,
                        f"`{name}(...)` runs at import time{radius} — the "
                        "first backend touch must stay behind an explicit "
                        "entry-point guard (utils/platform.py), never in "
                        "module scope",
                    ))
            if isinstance(node, _FUNC_NODES):
                # decorators and default values evaluate at def time
                # (import time when the def itself is import-reachable);
                # the body only runs when called
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        scan(dec, import_reachable)
                for default in (*node.args.defaults, *node.args.kw_defaults):
                    if default is not None:
                        scan(default, import_reachable)
                for child in node.body if isinstance(node.body, list) else [node.body]:
                    scan(child, False)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, import_reachable)

        scan(module.tree, True)
        return findings


# -- 2. host-sync-in-traced ----------------------------------------------------

# wrapper -> indices of its function-valued arguments
_TRACE_WRAPPERS: dict[str, tuple[int, ...]] = {
    "jax.jit": (0,), "jit": (0,), "pjit": (0,),
    "jax.checkpoint": (0,), "checkpoint": (0,), "jax.remat": (0,),
    "jax.grad": (0,), "jax.value_and_grad": (0,), "jax.vmap": (0,),
    "shard_map": (0,), "jax.lax.scan": (0,), "lax.scan": (0,),
    "jax.lax.map": (0,), "lax.map": (0,),
    "jax.lax.cond": (1, 2), "lax.cond": (1, 2),
    "jax.lax.while_loop": (0, 1), "lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,), "lax.fori_loop": (2,),
}
_TRACE_DECORATOR_RE = re.compile(
    r"(?:^|[.(\s])(?:jit|pjit|shard_map|remat)\b|jax\.checkpoint\b"
)
# host-synchronizing operations: each forces device->host transfer (or
# would raise TracerError at trace time — either way it does not belong
# syntactically inside a traced function)
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
_SYNC_CALLS = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get", "device_get",
})


class HostSyncInTraced(Checker):
    rule_id = "host-sync-in-traced"
    catches = ("`.item()` / `np.asarray` / `jax.device_get` / "
               "`block_until_ready` syntactically inside functions handed "
               "to `jit` / `scan` / `shard_map` / `checkpoint` / `grad`")
    motivation = ("the streaming-compositor and train-step hot paths (PR 5"
                  "-7) are only fast because nothing inside them "
                  "synchronizes the host; a stray .item() is a silent "
                  "per-step device flush")

    def _traced_functions(self, module: Module) -> list[ast.AST]:
        by_name: dict[str, list[ast.AST]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
        traced: dict[int, ast.AST] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _TRACE_DECORATOR_RE.search(ast.unparse(dec)):
                        traced[id(node)] = node
            elif isinstance(node, ast.Call):
                indices = _TRACE_WRAPPERS.get(dotted(node.func))
                if indices is None:
                    continue
                for i in indices:
                    if i >= len(node.args):
                        continue
                    arg = node.args[i]
                    if isinstance(arg, ast.Lambda):
                        traced[id(arg)] = arg
                    elif isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, ()):
                            traced[id(fn)] = fn
        return list(traced.values())

    def check_module(self, module: Module, repo: Repo) -> Iterable[Finding]:
        findings: list[Finding] = []
        seen: set[int] = set()
        for fn in self._traced_functions(module):
            fn_name = getattr(fn, "name", "<lambda>")
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or id(node) in seen:
                        continue
                    op = ""
                    name = dotted(node.func)
                    if name in _SYNC_CALLS:
                        op = name
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _SYNC_METHODS):
                        op = f".{node.func.attr}()"
                    elif (name in ("float", "int", "bool")
                          and len(node.args) == 1
                          and not isinstance(node.args[0], ast.Constant)):
                        # float(x)/int(x) on a traced array is a host sync
                        # (concrete) or a TracerError (abstract); either
                        # way it does not belong inside the traced region
                        op = f"{name}()"
                    if op:
                        seen.add(id(node))
                        findings.append(Finding(
                            self.rule_id, module.path, node.lineno,
                            f"{fn_name}:{op}",
                            f"`{op}` inside traced `{fn_name}` forces a "
                            "host sync (or a TracerError) — hoist it out "
                            "of the jitted region",
                        ))
        return findings


# -- 3. lock-discipline --------------------------------------------------------

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")


class LockDiscipline(Checker):
    rule_id = "lock-discipline"
    catches = ("attributes declared `# guarded-by: <lock>` read or written "
               "outside a `with self.<lock>` block (methods named "
               "`*_locked` and `__init__`/`__post_init__` are exempt: "
               "construction and called-with-lock-held helpers)")
    motivation = ("PR 8's fleet ring and PR 6's tracer ring are only "
                  "correct because every touch holds the lock; an "
                  "off-lock read is a torn-snapshot bug waiting for load")

    def _guarded_attrs(self, cls: ast.ClassDef, module: Module
                       ) -> dict[str, str]:
        guarded: dict[str, str] = {}
        for node in ast.walk(cls):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):
                target = node.target
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                m = _GUARDED_RE.search(module.line_text(node.lineno))
                if m:
                    guarded[target.attr] = m.group(1)
        return guarded

    def check_module(self, module: Module, repo: Repo) -> Iterable[Finding]:
        findings: list[Finding] = []
        for cls in [n for n in ast.walk(module.tree)
                    if isinstance(n, ast.ClassDef)]:
            guarded = self._guarded_attrs(cls, module)
            if not guarded:
                continue

            def on_node(node: ast.AST, stack: tuple[ast.AST, ...],
                        cls: ast.ClassDef = cls) -> None:
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in guarded):
                    return
                method = _enclosing_func(stack)
                if method in ("__init__", "__post_init__") or \
                        method.endswith("_locked"):
                    return
                lock = guarded[node.attr]
                want = f"self.{lock}"
                for anc in stack:
                    if isinstance(anc, (ast.With, ast.AsyncWith)) and any(
                        dotted(item.context_expr) == want
                        for item in anc.items
                    ):
                        return
                findings.append(Finding(
                    self.rule_id, module.path, node.lineno,
                    f"{cls.name}.{method}.{node.attr}",
                    f"`self.{node.attr}` (guarded-by {lock}) touched in "
                    f"`{method}` outside `with {want}` — take the lock or "
                    "rename the helper `*_locked`",
                ))

            walk_scoped(cls, on_node)
        return findings


# -- 4. error-taxonomy ---------------------------------------------------------


class ErrorTaxonomy(Checker):
    rule_id = "error-taxonomy"
    catches = ("`raise Exception(...)` instead of a named error, bare "
               "`except:`, message-less `assert`, and `except Exception:` "
               "handlers that swallow without logging/counting/re-raising "
               "(mine_tpu/ only)")
    motivation = ("PR 4/8 built the named-error + counter taxonomy "
                  "(UnknownDatasetError, ChaosFault, breaker metrics) so "
                  "failures are attributable; a silent `pass` handler "
                  "un-counts exactly the failures the SLO layer bills")

    def check_module(self, module: Module, repo: Repo) -> Iterable[Finding]:
        if not module.path.startswith("mine_tpu/"):
            return ()
        findings: list[Finding] = []

        def on_node(node: ast.AST, stack: tuple[ast.AST, ...]) -> None:
            func = _enclosing_func(stack)
            if isinstance(node, ast.Raise):
                exc = node.exc
                name = ""
                if isinstance(exc, ast.Call):
                    name = dotted(exc.func)
                elif exc is not None:
                    name = dotted(exc)
                if name in ("Exception", "BaseException"):
                    findings.append(Finding(
                        self.rule_id, module.path, node.lineno,
                        f"raise:{func}",
                        f"`raise {name}` in `{func}` — raise a named "
                        "error class so callers and counters can "
                        "discriminate it",
                    ))
            elif isinstance(node, ast.Assert) and node.msg is None:
                findings.append(Finding(
                    self.rule_id, module.path, node.lineno,
                    f"assert:{func}",
                    f"message-less `assert` in `{func}` — when it fires "
                    "the operator learns nothing; add a message or raise "
                    "a named error",
                ))
            elif isinstance(node, ast.ExceptHandler):
                reraises = any(isinstance(n, ast.Raise)
                               for n in ast.walk(node))
                if node.type is None and not reraises:
                    findings.append(Finding(
                        self.rule_id, module.path, node.lineno,
                        f"bare-except:{func}",
                        f"bare `except:` in `{func}` catches SystemExit/"
                        "KeyboardInterrupt — name the exception class",
                    ))
                elif (dotted(node.type) in ("Exception", "BaseException")
                      if node.type is not None else False):
                    swallow = all(
                        isinstance(stmt, (ast.Pass, ast.Continue, ast.Break))
                        or (isinstance(stmt, ast.Expr)
                            and isinstance(stmt.value, ast.Constant))
                        for stmt in node.body
                    )
                    if swallow:
                        findings.append(Finding(
                            self.rule_id, module.path, node.lineno,
                            f"swallow:{func}",
                            f"`except {dotted(node.type)}: pass` in "
                            f"`{func}` swallows the failure uncounted — "
                            "log it, count it, or re-raise",
                        ))

        walk_scoped(module.tree, on_node)
        return findings


# -- 5. config-knob-drift ------------------------------------------------------

_CFG_ROOT_RE = re.compile(r"(?:^|[._])(?:cfg|config)$")


class ConfigKnobDrift(Checker):
    rule_id = "config-knob-drift"
    catches = ("a `cfg.<group>.<name>` access with no configs/default.yaml "
               "key (undocumented knob), and a yaml key no code reads "
               "(dead knob) — the static twin of the README-table guards")
    motivation = ("PR 13/14 added runtime drift guards for metric families "
                  "and the dataset matrix after knobs and docs diverged "
                  "silently; config keys had no guard at all")

    def check_repo(self, repo: Repo) -> Iterable[Finding]:
        yaml_keys = repo.yaml_keys()
        if not yaml_keys:
            return ()
        groups = {k.split(".", 1)[0] for k in yaml_keys}
        findings: list[Finding] = []
        read_attrs: set[str] = set()
        read_strings: list[str] = []

        for module in repo.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Attribute):
                    read_attrs.add(node.attr)
                    # direction A: cfg.<group>.<name> must be a yaml key
                    inner = node.value
                    if (isinstance(inner, ast.Attribute)
                            and inner.attr in groups
                            and _CFG_ROOT_RE.search(dotted(inner.value))
                            and not node.attr.startswith("_")):
                        key = f"{inner.attr}.{node.attr}"
                        if key not in yaml_keys:
                            findings.append(Finding(
                                self.rule_id, module.path, node.lineno, key,
                                f"`{key}` is read here but has no "
                                f"{repo.yaml_file()} entry — document the "
                                "knob (with its default) or retire it",
                            ))
                elif (isinstance(node, ast.Constant)
                      and isinstance(node.value, str)):
                    read_strings.append(node.value)

        # direction B: every yaml key is read somewhere — by attribute
        # name (covers aliased group objects: `res.breaker_reset_s`), by
        # getattr/replace string (covers `getattr(cfg.parallel, "rules")`
        # and `cfg.replace(**{"mpi.fix_disparity": ...})`)
        blob = "\x00".join(read_strings)
        for key, line in sorted(yaml_keys.items()):
            name = key.split(".", 1)[1]
            if name in read_attrs or name in blob or key in blob:
                continue
            findings.append(Finding(
                self.rule_id, repo.yaml_file(), line, key,
                f"config key `{key}` is never read by any scanned code — "
                "dead knob: delete it or wire it up",
            ))
        return findings


# -- 6. chaos-kind-drift -------------------------------------------------------

_CHAOS_BEGIN = "<!-- chaos-kinds:begin -->"
_CHAOS_END = "<!-- chaos-kinds:end -->"
_CHAOS_DOC_RE = re.compile(r"`([a-z0-9_]+)@")
_SEAM_NAMES = frozenset({"should", "maybe_raise"})


class ChaosKindDrift(Checker):
    rule_id = "chaos-kind-drift"
    catches = ("a `MINE_TPU_FAULTS` kind fired at a seam but absent from "
               "chaos.KINDS or README's chaos-kind table, a registered "
               "kind the table does not document, and a documented kind "
               "the registry no longer knows")
    motivation = ("PR 12/13 grew the fault grammar PR by PR; the drill's "
                  "coverage story depends on the kind table, the seams, "
                  "and the docs describing the same set")

    def _registry(self, repo: Repo) -> tuple[dict[str, int], str]:
        """KINDS keys -> lineno, plus the defining module's path."""
        for module in repo.modules:
            for node in ast.walk(module.tree):
                targets: list[ast.expr] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.target:
                    targets = [node.target]
                else:
                    continue
                if (any(isinstance(t, ast.Name) and t.id == "KINDS"
                        for t in targets)
                        and isinstance(node.value, ast.Dict)):
                    kinds = {
                        k.value: k.lineno
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                    if kinds:
                        return kinds, module.path
        return {}, ""

    def check_repo(self, repo: Repo) -> Iterable[Finding]:
        kinds, kinds_path = self._registry(repo)
        if not kinds:
            return ()  # fixture repos without a registry: nothing to check
        findings: list[Finding] = []

        for module in repo.modules:
            if module.path == kinds_path:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if name.rsplit(".", 1)[-1] not in _SEAM_NAMES:
                    continue
                if not (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                kind = node.args[0].value
                if kind not in kinds:
                    findings.append(Finding(
                        self.rule_id, module.path, node.lineno, kind,
                        f"chaos seam fires unknown kind `{kind}` — not in "
                        f"{kinds_path} KINDS, so no schedule can ever "
                        "trigger it",
                    ))

        readme = repo.readme_text()
        readme_file = repo.readme_file()
        if readme is None or _CHAOS_BEGIN not in readme \
                or _CHAOS_END not in readme:
            findings.append(Finding(
                self.rule_id, readme_file or "README.md", 1,
                "chaos-kinds-markers",
                f"README lacks the marker-bounded chaos-kind table "
                f"({_CHAOS_BEGIN} .. {_CHAOS_END})",
            ))
            return findings
        begin = readme.index(_CHAOS_BEGIN)
        table = readme[begin:readme.index(_CHAOS_END)]
        table_line = readme[:begin].count("\n") + 1
        documented = set(_CHAOS_DOC_RE.findall(table))
        for kind in sorted(set(kinds) - documented):
            findings.append(Finding(
                self.rule_id, kinds_path, kinds[kind], kind,
                f"chaos kind `{kind}` is registered but missing from "
                "README's chaos-kind table",
            ))
        for kind in sorted(documented - set(kinds)):
            findings.append(Finding(
                self.rule_id, readme_file, table_line, kind,
                f"README's chaos-kind table documents `{kind}` but the "
                "registry no longer knows it — delete the stale row",
            ))
        return findings


REGISTRY: tuple[Checker, ...] = (
    BackendTouchAtImport(),
    HostSyncInTraced(),
    LockDiscipline(),
    ErrorTaxonomy(),
    ConfigKnobDrift(),
    ChaosKindDrift(),
)


def all_rule_ids() -> tuple[str, ...]:
    return tuple(c.rule_id for c in REGISTRY)
