"""Static analysis: the repo's prose invariants as machine-checked rules.

Fourteen PRs of review kept re-catching the same defect classes by hand —
backend probes firing before multi-host bring-up (PR 12/13), host syncs
inside jitted regions, serving state touched off-lock, config/doc drift.
This package turns those disciplines into an AST lint engine (stdlib
`ast`, compile-free, no jax import) with a checker registry, a checked-in
waiver baseline (`baseline.jsonl`, every waiver carries a reason), and a
CI runner (`tools/lint_run.py`) that emits one JSON verdict line and
exits nonzero on any un-waived finding.

Layout:
  engine.py    Finding / Module / Repo scaffolding, the checker base
               class, waiver matching, repo scanning
  checkers.py  the shipped rules (REGISTRY) — each ~50 LoC on the engine

Adding a rule: subclass `Checker` in checkers.py, implement
`check_module` (per-file) and/or `check_repo` (cross-file), append to
REGISTRY, add positive+negative fixtures under tests/fixtures/lint/, and
a row to README's lint-rules table (drift-tested both directions).
"""

from mine_tpu.analysis.engine import (
    Checker,
    Finding,
    Module,
    Repo,
    Waiver,
    apply_baseline,
    load_baseline,
    run,
    scan_repo,
)
from mine_tpu.analysis.checkers import REGISTRY, all_rule_ids

__all__ = [
    "Checker",
    "Finding",
    "Module",
    "Repo",
    "Waiver",
    "REGISTRY",
    "all_rule_ids",
    "apply_baseline",
    "load_baseline",
    "run",
    "scan_repo",
]
