"""Lint-engine scaffolding: findings, repo scanning, waiver matching.

Stdlib-only on purpose (`ast` + `json` + `pathlib`): the runner must be
usable as a pre-commit / CI gate without initializing jax, and the tier-1
smoke that runs it over the whole tree must cost milliseconds, not a
backend bring-up. Checkers (analysis/checkers.py) build on three pieces
here:

  Module   one parsed source file (path, source lines, AST)
  Repo     the scanned corpus + the non-Python inputs some rules need
           (configs/default.yaml, README.md) — injectable, so fixture
           mini-repos under tests/fixtures/lint/ exercise every rule
           without touching the real tree
  Checker  the registry contract: `check_module` runs once per file,
           `check_repo` once per run (cross-file rules: drift tables)

Waivers: `baseline.jsonl`, one JSON object per line with a mandatory
human reason. A waiver matches findings by (rule_id, file, symbol) — the
`symbol` is each rule's stable anchor (an attribute path, a config key, a
seam name), NOT a line number, so waivers survive unrelated edits above
them. A waiver that matches nothing is reported stale: delete the line.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Iterator

# findings whose file could not even be parsed carry this rule id; it is
# registered in checkers.REGISTRY order-independently (no Checker class —
# a file that does not parse fails every discipline at once)
PARSE_RULE_ID = "parse-error"


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    `symbol` is the waiver anchor: stable under line drift (two findings
    with one symbol in one file are waived by one baseline row — they are
    the same decision)."""

    rule_id: str
    file: str  # repo-relative posix path
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule_id, self.file, self.symbol)

    def render(self) -> str:
        return f"{self.rule_id}:{self.file}:{self.line}: {self.message}"

    def to_json(self) -> dict:
        return {
            "rule_id": self.rule_id, "file": self.file, "line": self.line,
            "symbol": self.symbol, "message": self.message,
        }


@dataclass
class Module:
    """One parsed source file."""

    path: str  # repo-relative posix
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def line_text(self, lineno: int) -> str:
        """1-indexed source line ('' past EOF)."""
        return self.lines[lineno - 1] if 0 < lineno <= len(self.lines) else ""


@dataclass
class Repo:
    """The scanned corpus plus the non-Python rule inputs.

    Tests build tiny Repos by hand (fixture modules + a fixture yaml +
    a fixture README); the runner builds the real one via scan_repo()."""

    root: Path
    modules: list[Module]
    yaml_path: Path | None = None
    readme_path: Path | None = None
    parse_failures: list[Finding] = field(default_factory=list)

    def yaml_keys(self) -> dict[str, int]:
        """Flat dot-key -> 1-indexed line of configs/default.yaml."""
        keys: dict[str, int] = {}
        if self.yaml_path is None or not self.yaml_path.exists():
            return keys
        for i, line in enumerate(
            self.yaml_path.read_text().splitlines(), start=1
        ):
            stripped = line.split("#", 1)[0].strip()
            if ":" in stripped:
                key = stripped.split(":", 1)[0].strip()
                if "." in key and not key.startswith("."):
                    keys[key] = i
        return keys

    def yaml_file(self) -> str:
        return _rel(self.yaml_path, self.root) if self.yaml_path else ""

    def readme_text(self) -> str | None:
        if self.readme_path is None or not self.readme_path.exists():
            return None
        return self.readme_path.read_text()

    def readme_file(self) -> str:
        return _rel(self.readme_path, self.root) if self.readme_path else ""


class Checker:
    """Registry contract. Subclasses set the three class attrs (the README
    rule table is drift-tested against them) and override one or both
    hooks. ~50 LoC per rule is the budget; shared walking lives here."""

    rule_id: str = ""
    catches: str = ""  # one line: what defect class this rule fails on
    motivation: str = ""  # which past PR's bug this rule mechanizes

    def check_module(self, module: Module, repo: Repo) -> Iterable[Finding]:
        return ()

    def check_repo(self, repo: Repo) -> Iterable[Finding]:
        return ()


def run(repo: Repo, checkers: Iterable[Checker]) -> list[Finding]:
    """All findings from all checkers over the repo, stably ordered."""
    findings: list[Finding] = list(repo.parse_failures)
    for checker in checkers:
        for module in repo.modules:
            findings.extend(checker.check_module(module, repo))
        findings.extend(checker.check_repo(repo))
    findings.sort(key=lambda f: (f.file, f.line, f.rule_id, f.symbol))
    return findings


# -- repo scanning -------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", "workspace"}


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def iter_py_files(root: Path, paths: Iterable[str]) -> Iterator[Path]:
    for entry in paths:
        p = root / entry
        if p.is_file():
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not _SKIP_DIRS.intersection(f.parts):
                    yield f


def parse_module(path: Path, root: Path) -> Module | Finding:
    rel = _rel(path, root)
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as exc:
        return Finding(PARSE_RULE_ID, rel, exc.lineno or 1, "syntax",
                       f"file does not parse: {exc.msg}")
    return Module(path=rel, source=source, tree=tree)


def scan_repo(
    root: Path,
    paths: Iterable[str] = ("mine_tpu", "tools", "bench.py"),
    yaml_rel: str = "mine_tpu/configs/default.yaml",
    readme_rel: str = "README.md",
) -> Repo:
    modules: list[Module] = []
    failures: list[Finding] = []
    for f in iter_py_files(root, paths):
        parsed = parse_module(f, root)
        if isinstance(parsed, Finding):
            failures.append(parsed)
        else:
            modules.append(parsed)
    return Repo(
        root=root, modules=modules,
        yaml_path=root / yaml_rel, readme_path=root / readme_rel,
        parse_failures=failures,
    )


# -- waiver baseline -----------------------------------------------------------


@dataclass(frozen=True)
class Waiver:
    rule_id: str
    file: str
    symbol: str
    reason: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule_id, self.file, self.symbol)


def load_baseline(path: Path) -> list[Waiver]:
    """Parse baseline.jsonl; a waiver without a non-empty reason is a
    hard error — an unexplained waiver is exactly the prose-invariant rot
    this subsystem exists to stop."""
    waivers: list[Waiver] = []
    if not path.exists():
        return waivers
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            row = json.loads(line)
        except ValueError as exc:
            raise ValueError(f"{path}:{i}: not JSON: {exc}") from None
        missing = {"rule_id", "file", "symbol", "reason"} - set(row)
        if missing:
            raise ValueError(f"{path}:{i}: waiver missing {sorted(missing)}")
        if not str(row["reason"]).strip():
            raise ValueError(f"{path}:{i}: waiver reason must be non-empty")
        waivers.append(Waiver(row["rule_id"], row["file"], row["symbol"],
                              row["reason"]))
    return waivers


def apply_baseline(
    findings: Iterable[Finding], waivers: Iterable[Waiver],
) -> tuple[list[Finding], list[Finding], list[Waiver]]:
    """Split findings into (unwaived, waived) and report stale waivers.

    A waiver matches every finding sharing its (rule_id, file, symbol) —
    symbol-anchored, so it survives line drift; a waiver matching nothing
    is stale and should be deleted (the baseline only ever shrinks)."""
    waivers = list(waivers)
    by_key = {w.key: w for w in waivers}
    unwaived: list[Finding] = []
    waived: list[Finding] = []
    hit: set[tuple[str, str, str]] = set()
    for f in findings:
        if f.key in by_key:
            waived.append(f)
            hit.add(f.key)
        else:
            unwaived.append(f)
    stale = [w for w in waivers if w.key not in hit]
    return unwaived, waived, stale


# -- import graph --------------------------------------------------------------


def import_graph(repo: Repo) -> dict[str, set[str]]:
    """module path -> set of corpus module paths it imports (absolute
    imports only — this tree's idiom). `import mine_tpu.serving.engine`
    resolves to the module file; `import mine_tpu.serving` to the
    package __init__. Checkers use the REVERSE view ("who imports me")
    to report the import-time blast radius of a finding; later rules can
    walk reachability (e.g. what a CLI entry point pulls in before its
    backend guard runs)."""
    by_dotted: dict[str, str] = {}
    for m in repo.modules:
        dotted_name = m.path[:-3].replace("/", ".")
        if dotted_name.endswith(".__init__"):
            dotted_name = dotted_name[: -len(".__init__")]
        by_dotted[dotted_name] = m.path
    graph: dict[str, set[str]] = {}
    for m in repo.modules:
        edges: set[str] = set()
        for node in ast.walk(m.tree):
            names: list[str] = []
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                # `from pkg.mod import x`: x may be a symbol OR a module
                names = [node.module] + [
                    f"{node.module}.{a.name}" for a in node.names
                ]
            for name in names:
                while name:
                    if name in by_dotted:
                        edges.add(by_dotted[name])
                        break
                    name = name.rpartition(".")[0]
        edges.discard(m.path)
        graph[m.path] = edges
    return graph


def importers_of(repo: Repo) -> dict[str, set[str]]:
    """Reverse import graph: module path -> corpus modules importing it."""
    reverse: dict[str, set[str]] = {m.path: set() for m in repo.modules}
    for importer, imported in import_graph(repo).items():
        for path in imported:
            reverse.setdefault(path, set()).add(importer)
    return reverse


# -- shared AST helpers (the pieces every checker wants) -----------------------


def dotted(node: ast.AST) -> str:
    """'jax.lax.scan' for a Name/Attribute chain; '' for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_scoped(
    tree: ast.AST,
    on_node: Callable[[ast.AST, tuple[ast.AST, ...]], None],
) -> None:
    """Depth-first walk calling on_node(node, ancestors) — ancestors is
    the tuple of enclosing AST nodes, outermost first. The generic walk
    several checkers need (is this call inside a function? inside a
    `with`? which class?), paid for once here."""

    def visit(node: ast.AST, stack: tuple[ast.AST, ...]) -> None:
        on_node(node, stack)
        child_stack = stack + (node,)
        for child in ast.iter_child_nodes(node):
            visit(child, child_stack)

    visit(tree, ())
