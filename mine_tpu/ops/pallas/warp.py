"""Pallas TPU kernel for bilinear image warping (the grid_sample hot op).

Why this exists: XLA lowers the 4-corner gather of a bilinear sampler to a
generic TPU gather that runs ~100x slower than memory bound (measured 2.9 s
for one (64, 384, 512, 7) warp on v5e — the entire train step's budget many
times over; reference hot-op ranking SURVEY.md §3.1). TPU vector hardware has
no general 2-D gather, but Mosaic DOES support `take_along_axis` along the
128-lane axis within a native (8, 128) tile. This kernel restructures the
warp around that primitive:

  * the whole source image (C, H, W) sits in VMEM (≤ ~6 MB for the shapes
    this model uses — checked at dispatch);
  * each program instance produces one (8, 128) output tile for all C
    channels;
  * the source pixels needed by an output tile lie in the projective image
    of that tile — a small axis-aligned bounding box of source (8, 128)
    tiles, computed in-kernel from the coord block (the warps are smooth;
    for near-identity homographies the box is 1-4 tiles);
  * for each source tile in the box, the 4 bilinear corners are fetched
    with 8 broadcast-row passes (two lane-gathers each, shared across the
    corner pairs) + sublane selects, masked by tile membership, accumulated.

The public entry keeps the exact border-padding semantics of
ops.grid_sample.grid_sample_pixel (torch grid_sample parity,
homography_sampler.py:143-148): coordinates clamp to [0, size-1] and the
corner pair is (floor(min(x, size-2)), +1), which is value-identical to the
clamp-both-corners form for every in-range x.

The backward pass is a kernel too (`warp_bilinear_grad_chw`): the source
cotangent is a scatter — XLA's TPU scatter is as pathological as its gather —
reformulated per visited source tile as 8 one-hot MXU contractions
(sublane-row masking x lane one-hot matmul), accumulated into a full-image
VMEM block across the output-tile grid. Coordinate cotangents are elementwise
given the 4 corner values, so the forward variant `warp_bilinear_fwd_chw`
saves them as residuals. Mosaic restrictions shaped all of this: in-tile
`take_along_axis` only at native (8, 128) tiles, no nested dynamic-bound
loops, no scalar div/mod by traced values, tile-aligned dynamic slice starts.

On top of the banded forward sits `warp_composite_chw`, the fused
warp-composite kernel of the streaming target compositor
(ops/mpi_render.py): the plane axis rides the innermost (sequential) grid
dimension, the over-composite accumulators stay resident in the output's
VMEM block across the sweep, and each plane's source band is DMA'd through
the same bbox walk the banded forward uses — one HBM pass for the whole
S-plane sweep, with the warped plane values never leaving registers.

Not used on CPU (Mosaic is TPU-only); tests run interpret mode on tiny shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array, lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_H = 8
TILE_W = 128

# renamed across pallas releases (TPUMemorySpace on jax 0.4.x)
_ANY_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _corner_gather4(tile: Array, ly0: Array, lx0: Array, accs) -> tuple:
    """Accumulate all 4 bilinear corners (y0/y0+1 x x0/x0+1) of one source
    tile into the per-corner accumulators, sharing each source row's
    broadcast and its two lane-gathers (x0, x0+1) across the corner pairs.

    tile: (TILE_H, TILE_W) one channel of one source tile.
    ly0/lx0: (TILE_H, TILE_W) int32 tile-local top-left corner coords (any
    value; only in-range entries are used). accs: 4 running accumulators
    ordered (a00, a01, a10, a11).
    """
    a00, a01, a10, a11 = accs
    ly1 = ly0 + 1
    lx1 = lx0 + 1
    lxc0 = jnp.clip(lx0, 0, TILE_W - 1)
    lxc1 = jnp.clip(lx1, 0, TILE_W - 1)
    z = jnp.zeros_like(a00)
    g00 = g01 = g10 = g11 = z
    for s in range(TILE_H):
        row = jnp.broadcast_to(tile[s][None, :], (TILE_H, TILE_W))
        t0 = jnp.take_along_axis(row, lxc0, axis=1)
        t1 = jnp.take_along_axis(row, lxc1, axis=1)
        on0 = ly0 == s
        on1 = ly1 == s
        g00 = jnp.where(on0, t0, g00)
        g01 = jnp.where(on0, t1, g01)
        g10 = jnp.where(on1, t0, g10)
        g11 = jnp.where(on1, t1, g11)
    y0_in = (ly0 >= 0) & (ly0 < TILE_H)
    y1_in = (ly1 >= 0) & (ly1 < TILE_H)
    x0_in = (lx0 >= 0) & (lx0 < TILE_W)
    x1_in = (lx1 >= 0) & (lx1 < TILE_W)
    return (
        jnp.where(y0_in & x0_in, g00, a00),
        jnp.where(y0_in & x1_in, g01, a01),
        jnp.where(y1_in & x0_in, g10, a10),
        jnp.where(y1_in & x1_in, g11, a11),
    )


def _prep_coords(x: Array, y: Array, h: int, w: int):
    """Shared coordinate munging: border clamp, corner split, row-tile bbox.

    x/y: (TILE_H, TILE_W) raw source-pixel coords of one output tile.
    Returns (wx, wy, x0, y0, r0, r1). The bbox covers the source ROW tiles
    the 4 corners can touch (y1 = y0+1), clamped to the real tile range: the
    coord block's padding lanes (edge output tiles) carry whatever was in
    memory and must not widen the box or poison the visit count. Columns are
    walked statically — Mosaic cannot lower nested dynamic-bound loops (nor
    scalar div/mod by a traced count), and there are at most w/128 = 4
    column tiles.
    """
    x = jnp.clip(x, 0.0, w - 1.0)
    y = jnp.clip(y, 0.0, h - 1.0)
    x0f = jnp.floor(jnp.minimum(x, w - 2.0))
    y0f = jnp.floor(jnp.minimum(y, h - 2.0))
    wx = x - x0f
    wy = y - y0f
    x0 = x0f.astype(jnp.int32)
    y0 = y0f.astype(jnp.int32)
    max_r = (h - 1) // TILE_H
    r0 = jnp.clip(jnp.min(y0) // TILE_H, 0, max_r)
    r1 = jnp.clip((jnp.max(y0) + 1) // TILE_H, r0, max_r)
    return wx, wy, x0, y0, r0, r1


def _warp_kernel(x_ref, y_ref, src_ref, out_ref, *corner_refs,
                 h: int, w: int, c: int):
    """One (8, 128) output tile, all channels.

    x_ref/y_ref: (1, TILE_H, TILE_W) source-pixel coords for this tile.
    src_ref: (1, c, hp, wp) the full source image, padded up to whole
    (TILE_H, TILE_W) tiles; h/w are the LOGICAL dims all coordinate clamping
    uses (the padding is never sampled). out_ref: (1, c, TILE_H, TILE_W).
    corner_refs: optionally a (1, 4, c, TILE_H, TILE_W) ref that receives the
    raw corner values (a00, a01, a10, a11) — the residuals the coordinate
    cotangent needs.
    """
    wp = src_ref.shape[3]
    wx, wy, x0, y0, r0, r1 = _prep_coords(x_ref[0], y_ref[0], h, w)

    def visit(carry, r, cc):
        """Accumulate all 4 corners x all channels from source tile (r, cc).
        The padded dims guarantee aligned, in-bounds tile slices."""
        start_r = pl.multiple_of(r * TILE_H, TILE_H)
        start_c = pl.multiple_of(cc * TILE_W, TILE_W)
        ly0 = y0 - start_r
        lx0 = x0 - start_c
        out = []
        for ch in range(c):
            tile = src_ref[0, ch, pl.ds(start_r, TILE_H),
                           pl.ds(start_c, TILE_W)]
            out.append(_corner_gather4(tile, ly0, lx0, carry[ch]))
        return out

    zero = jnp.zeros((TILE_H, TILE_W), src_ref.dtype)
    carry = [(zero, zero, zero, zero) for _ in range(c)]

    n_col_tiles = max((wp + TILE_W - 1) // TILE_W, 1)

    def row_body(r, carry):
        for cc in range(n_col_tiles):  # static unroll; masked visits no-op
            carry = visit(carry, r, cc)
        return carry

    carry = lax.fori_loop(r0, r1 + 1, row_body, carry)

    wxc = wx.astype(src_ref.dtype)
    wyc = wy.astype(src_ref.dtype)
    for ch in range(c):
        a00, a01, a10, a11 = carry[ch]
        top = a00 * (1.0 - wxc) + a01 * wxc
        bot = a10 * (1.0 - wxc) + a11 * wxc
        out_ref[0, ch] = top * (1.0 - wyc) + bot * wyc
        if corner_refs:
            for k, a in enumerate((a00, a01, a10, a11)):
                corner_refs[0][0, k, ch] = a


def _scatter_tile(vals: Array, ly: Array, lx: Array) -> Array:
    """Within-tile scatter-add: out[ch, s, x] = sum over output pixels (i, j)
    of vals[ch, i, j] * [ly[i, j] == s] * [lx[i, j] == x].

    vals: (C, TILE_H, TILE_W) over OUTPUT pixels, out-of-tile entries
    pre-masked to 0. ly/lx: (TILE_H, TILE_W). Returns the (C, TILE_H, TILE_W)
    source-tile contribution.

    MXU formulation chosen for Mosaic's layout rules: for each output row i,
    both one-hot factors are built in their NATURAL layout (no transposes,
    no cross-tile reshapes) and contracted over their shared LANE axis j —
    an "NT" matmul:  A[c*8+s, j] = vals[c, i, j]*[ly[i,j]==s]  (sublanes
    stack channels*rows),  Xoh[x, j] = [lx[i,j]==x],  P = A @ Xoh^T ->
    (c*8, x). Channels ride the same matmul, so each of the 8 output rows
    costs one (8C, 128) x (128, 128) MXU pass.

    Precision: a hand-rolled two-term bf16 split of the value factor (hi +
    residual; the one-hot factor is exact in bf16, and bf16 products
    accumulate in fp32 on the MXU) — ~3e-6 relative error, 10x faster than
    Precision.HIGHEST's 6-pass algorithm on these shapes (Mosaic does not
    support the 3-pass HIGH).
    """
    c = vals.shape[0]
    sub8 = lax.broadcasted_iota(jnp.int32, (TILE_H, TILE_W), 0)
    subw = lax.broadcasted_iota(jnp.int32, (TILE_W, TILE_W), 0)
    contrib = jnp.zeros((c, TILE_H, TILE_W), vals.dtype)
    for i in range(TILE_H):
        ly_i = ly[i : i + 1, :]  # (1, TILE_W) along lanes
        lx_i = lx[i : i + 1, :]
        xoh = (subw == lx_i).astype(jnp.bfloat16)  # (x, j)
        rows = [
            jnp.where(sub8 == ly_i, vals[ch, i : i + 1, :], 0.0)  # (s, j)
            for ch in range(c)
        ]
        lhs = jnp.concatenate(rows, axis=0)  # (c*8, j)
        hi = lhs.astype(jnp.bfloat16)
        nt = (((1,), (1,)), ((), ()))
        p = lax.dot_general(hi, xoh, nt, preferred_element_type=jnp.float32)
        if lhs.dtype != jnp.bfloat16:  # for bf16 payloads lo is exactly 0
            lo = (lhs - hi.astype(lhs.dtype)).astype(jnp.bfloat16)
            p = p + lax.dot_general(lo, xoh, nt,
                                    preferred_element_type=jnp.float32)
        contrib = contrib + p.astype(vals.dtype).reshape(c, TILE_H, TILE_W)
    return contrib


def _warp_grad_kernel(x_ref, y_ref, g_ref, gsrc_ref, *,
                      h: int, w: int, c: int, ho: int, wo: int):
    """Source cotangent for one (8, 128) output tile, all channels.

    g_ref: (1, c, TILE_H, TILE_W) output cotangent. gsrc_ref: the FULL
    (1, c, hp, wp) source-gradient image, zeroed on this image's first tile
    and accumulated across the whole output-tile grid (sequential on TPU).
    ho/wo: LOGICAL output dims — edge tiles' padding lanes hold arbitrary
    memory in both the coord and cotangent blocks and must not scatter.
    """
    wp = gsrc_ref.shape[3]
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(jnp.logical_and(i == 0, j == 0))
    def _zero():
        gsrc_ref[...] = jnp.zeros(gsrc_ref.shape, gsrc_ref.dtype)

    in_image = (
        (i * TILE_H + lax.broadcasted_iota(jnp.int32, (TILE_H, TILE_W), 0) < ho)
        & (j * TILE_W + lax.broadcasted_iota(jnp.int32, (TILE_H, TILE_W), 1) < wo)
    )
    wx, wy, x0, y0, r0, r1 = _prep_coords(x_ref[0], y_ref[0], h, w)
    # weights in the cotangent's dtype so bf16 cotangents stay bf16 all the
    # way to the store (and _scatter_tile's single-matmul bf16 path engages)
    wx = wx.astype(g_ref.dtype)
    wy = wy.astype(g_ref.dtype)
    corner_weights = (
        (0, 0, (1.0 - wx) * (1.0 - wy)),
        (0, 1, wx * (1.0 - wy)),
        (1, 0, (1.0 - wx) * wy),
        (1, 1, wx * wy),
    )
    n_col_tiles = max((wp + TILE_W - 1) // TILE_W, 1)

    def visit(_, r, cc):
        start_r = pl.multiple_of(r * TILE_H, TILE_H)
        start_c = pl.multiple_of(cc * TILE_W, TILE_W)
        ly0 = y0 - start_r
        lx0 = x0 - start_c
        # whole visit is side-effect-only, so empty column tiles (the warp's
        # footprint is a narrow box; columns are walked statically) skip all
        # MXU work under pl.when
        touches = jnp.any(
            (ly0 >= -1) & (ly0 <= TILE_H) & (lx0 >= -1) & (lx0 <= TILE_W)
        )

        @pl.when(touches)
        def _do_visit():
            for dy, dx, wgt in corner_weights:
                ly = ly0 + dy
                lx = lx0 + dx
                valid = in_image & (ly >= 0) & (ly < TILE_H) \
                    & (lx >= 0) & (lx < TILE_W)
                lyc = jnp.clip(ly, 0, TILE_H - 1)
                lxc = jnp.clip(lx, 0, TILE_W - 1)
                vals = jnp.where(
                    valid[None], g_ref[0] * wgt[None], 0.0
                )  # (c, TILE_H, TILE_W)
                contrib = _scatter_tile(vals, lyc, lxc).astype(gsrc_ref.dtype)
                for ch in range(c):
                    sl = (0, ch, pl.ds(start_r, TILE_H), pl.ds(start_c, TILE_W))
                    gsrc_ref[sl] = gsrc_ref[sl] + contrib[ch]
        return 0

    def row_body(r, carry):
        for cc in range(n_col_tiles):  # static unroll; masked visits no-op
            carry = visit(carry, r, cc)
        return carry

    lax.fori_loop(r0, r1 + 1, row_body, 0)


def padded_dims(h: int, w: int) -> tuple[int, int]:
    """(hp, wp): h/w rounded up to whole (TILE_H, TILE_W) tiles, with at
    least one full tile in each axis. The single source of truth for every
    padded-size computation (kernels, grad shapes, the VMEM budget check)."""
    hp = h + ((-h) % TILE_H if h >= TILE_H else TILE_H - h)
    wp = w + ((-w) % TILE_W if w >= TILE_W else TILE_W - w)
    return hp, wp


def _pad_tiles(src: Array) -> Array:
    """Pad (N, C, H, W) up to whole (TILE_H, TILE_W) tiles: in-kernel dynamic
    slice starts must stay tile-aligned (Mosaic rejects unaligned lane-dim
    starts) and at least one full tile must exist. The padding is never
    sampled — coords clamp to the logical h/w."""
    h, w = src.shape[2], src.shape[3]
    hp, wp = padded_dims(h, w)
    if hp != h or wp != w:
        src = jnp.pad(src, ((0, 0), (0, 0), (0, hp - h), (0, wp - w)))
    return src


def _coord_specs():
    return [
        pl.BlockSpec((1, TILE_H, TILE_W), lambda ni, i, j: (ni, i, j)),
        pl.BlockSpec((1, TILE_H, TILE_W), lambda ni, i, j: (ni, i, j)),
    ]


def _fwd_out(n, c, ho, wo, dtype, save_corners, *operands):
    """(out_shape, out_specs) for a warp forward — the (n, c, ho, wo) output
    plus, with save_corners, the (n, 4, c, ho, wo) corner residuals. One
    definition shared by the resident and banded wrappers so the corners
    contract cannot silently diverge between them."""
    out_shape = [_out_struct((n, c, ho, wo), dtype, *operands)]
    out_specs = [
        pl.BlockSpec((1, c, TILE_H, TILE_W), lambda ni, i, j: (ni, 0, i, j))
    ]
    if save_corners:
        out_shape.append(_out_struct((n, 4, c, ho, wo), dtype, *operands))
        out_specs.append(pl.BlockSpec(
            (1, 4, c, TILE_H, TILE_W), lambda ni, i, j: (ni, 0, 0, i, j)
        ))
        return out_shape, out_specs
    return out_shape[0], out_specs[0]


def _out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct carrying the union of the operands' varying mesh
    axes: under shard_map's strict vma checking, pallas_call outputs must
    declare how they vary across the mesh (they vary exactly as much as the
    inputs do — the kernel is pointwise in the mesh)."""
    from mine_tpu.utils.jax_compat import typeof

    vma = frozenset()
    for op in operands:
        vma |= getattr(typeof(op), "vma", frozenset()) or frozenset()
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def warp_bilinear_chw(src: Array, coords_x: Array, coords_y: Array,
                      interpret: bool = False,
                      save_corners: bool = False):
    """Bilinear border-padded sampling, channels-major.

    src: (N, C, H, W); coords_x/coords_y: (N, Ho, Wo) source-pixel coords.
    Returns (N, C, Ho, Wo) (same dtype as src) — plus, with save_corners,
    the raw corner values (N, 4, C, Ho, Wo) ordered (a00, a01, a10, a11).
    """
    n, c, h, w = src.shape
    _, ho, wo = coords_x.shape
    src = _pad_tiles(src)
    hp, wp = src.shape[2], src.shape[3]
    grid = (n, pl.cdiv(ho, TILE_H), pl.cdiv(wo, TILE_W))
    kernel = functools.partial(_warp_kernel, h=h, w=w, c=c)
    out_shape, out_specs = _fwd_out(
        n, c, ho, wo, src.dtype, save_corners, src, coords_x, coords_y
    )
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=_coord_specs() + [
            # full image, revisited across (i, j) — refetched only when n moves
            pl.BlockSpec((1, c, hp, wp), lambda ni, i, j: (ni, 0, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(coords_x, coords_y, src)
    return result


def _col_bbox(x0: Array, wp: int):
    """Column-tile bbox of the corners (x1 = x0+1), mirroring _prep_coords'
    row bbox: which source COLUMN tiles this output tile can touch."""
    max_c = wp // TILE_W - 1
    c0 = jnp.clip(jnp.min(x0) // TILE_W, 0, max_c)
    c1 = jnp.clip((jnp.max(x0) + 1) // TILE_W, c0, max_c)
    return c0, c1


def _gather_band_corners(dma_src, tile_ref, acc_ref, sem,
                         x0, y0, r0, r1, wp: int, c: int) -> None:
    """DMA every (row, col)-bbox source tile of one plane image and
    accumulate the 4 bilinear corners of all c channels into acc_ref
    (4, c, TILE_H, TILE_W), zeroed here. dma_src(start_r, start_c) -> the
    (c, TILE_H, TILE_W) HBM ref to copy. One definition of the bbox/DMA
    walk, shared by the banded forward and the fused warp-composite kernel.

    Accumulators live in the VMEM scratch ref (not a fori carry) so each
    bbox visit can be skipped wholesale with pl.when when its DMA would be
    wasted — the footprint of a near-identity homography is 1-4 tiles, but
    the static column walk covers wp/128 of them.
    """
    acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)
    c0, c1 = _col_bbox(x0, wp)
    n_col_tiles = wp // TILE_W

    def row_body(r, carry):
        start_r = pl.multiple_of(r * TILE_H, TILE_H)
        ly0 = y0 - start_r
        for cc in range(n_col_tiles):  # static walk; bbox gates the DMA
            @pl.when(jnp.logical_and(cc >= c0, cc <= c1))
            def _visit(cc=cc):
                start_c = pl.multiple_of(cc * TILE_W, TILE_W)
                cp = pltpu.make_async_copy(
                    dma_src(start_r, start_c), tile_ref, sem
                )
                cp.start()
                cp.wait()
                lx0 = x0 - start_c
                for ch in range(c):
                    accs = tuple(acc_ref[k, ch] for k in range(4))
                    new = _corner_gather4(tile_ref[ch], ly0, lx0, accs)
                    for k in range(4):
                        acc_ref[k, ch] = new[k]
        return carry

    lax.fori_loop(r0, r1 + 1, row_body, 0)


def _warp_kernel_banded(x_ref, y_ref, src_hbm, out_ref, *rest,
                        h: int, w: int, c: int, save_corners: bool):
    """Beyond-VMEM forward: the source image stays in HBM (memory space ANY)
    and only the (row, col)-bbox tiles an output tile actually samples are
    DMA'd into a VMEM scratch tile — O(bbox) traffic instead of a resident
    copy of the whole image. This is the row-banded upgrade path the resident
    kernel's docstring promises: at LLFF full-res (1008x756, C=7) the source
    is 21.8 MB fp32 — 2.7x the resident kernel's VMEM budget — while the
    per-tile working set here is c*8*128 floats regardless of image size.

    Accumulators live in a VMEM scratch ref (not a fori carry) so each
    bbox visit can be skipped wholesale with pl.when when its DMA would be
    wasted — the footprint of a near-identity homography is 1-4 tiles, but
    the static column walk covers wp/128 of them.
    """
    if save_corners:
        corners_ref, tile_ref, acc_ref, sem = rest
    else:
        (tile_ref, acc_ref, sem) = rest
        corners_ref = None
    ni = pl.program_id(0)
    wp = src_hbm.shape[3]
    wx, wy, x0, y0, r0, r1 = _prep_coords(x_ref[0], y_ref[0], h, w)
    _gather_band_corners(
        lambda sr, sc: src_hbm.at[ni, :, pl.ds(sr, TILE_H), pl.ds(sc, TILE_W)],
        tile_ref, acc_ref, sem, x0, y0, r0, r1, wp, c,
    )

    wxc = wx.astype(out_ref.dtype)
    wyc = wy.astype(out_ref.dtype)
    for ch in range(c):
        a00, a01, a10, a11 = (acc_ref[k, ch] for k in range(4))
        top = a00 * (1.0 - wxc) + a01 * wxc
        bot = a10 * (1.0 - wxc) + a11 * wxc
        out_ref[0, ch] = top * (1.0 - wyc) + bot * wyc
        if corners_ref is not None:
            for k in range(4):
                corners_ref[0, k, ch] = acc_ref[k, ch]


def _warp_grad_kernel_banded(x_ref, y_ref, g_ref, gsrc_init_hbm, gsrc_hbm,
                             tile_ref, sem, *,
                             h: int, w: int, c: int, ho: int, wo: int):
    """Beyond-VMEM source cotangent: the full gradient image lives in HBM
    (aliased with a pre-zeroed input — no in-kernel zeroing pass) and each
    visited source tile is read-modify-written through a VMEM scratch tile.
    TPU grids run sequentially per core and every visit waits out its write
    DMA, so read-modify-write windows never overlap across output tiles.
    `gsrc_init_hbm` IS `gsrc_hbm` (input_output_aliases) — only the output
    ref is touched."""
    del gsrc_init_hbm
    ni = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    wp = gsrc_hbm.shape[3]

    in_image = (
        (i * TILE_H + lax.broadcasted_iota(jnp.int32, (TILE_H, TILE_W), 0) < ho)
        & (j * TILE_W + lax.broadcasted_iota(jnp.int32, (TILE_H, TILE_W), 1) < wo)
    )
    wx, wy, x0, y0, r0, r1 = _prep_coords(x_ref[0], y_ref[0], h, w)
    c0, c1 = _col_bbox(x0, wp)
    wx = wx.astype(g_ref.dtype)
    wy = wy.astype(g_ref.dtype)
    corner_weights = (
        (0, 0, (1.0 - wx) * (1.0 - wy)),
        (0, 1, wx * (1.0 - wy)),
        (1, 0, (1.0 - wx) * wy),
        (1, 1, wx * wy),
    )
    n_col_tiles = wp // TILE_W

    def row_body(r, carry):
        start_r = pl.multiple_of(r * TILE_H, TILE_H)
        ly0 = y0 - start_r
        for cc in range(n_col_tiles):  # static walk; bbox gates the DMA
            @pl.when(jnp.logical_and(cc >= c0, cc <= c1))
            def _visit(cc=cc):
                start_c = pl.multiple_of(cc * TILE_W, TILE_W)
                lx0 = x0 - start_c
                contrib = jnp.zeros((c, TILE_H, TILE_W), gsrc_hbm.dtype)
                for dy, dx, wgt in corner_weights:
                    ly = ly0 + dy
                    lx = lx0 + dx
                    valid = in_image & (ly >= 0) & (ly < TILE_H) \
                        & (lx >= 0) & (lx < TILE_W)
                    lyc = jnp.clip(ly, 0, TILE_H - 1)
                    lxc = jnp.clip(lx, 0, TILE_W - 1)
                    vals = jnp.where(valid[None], g_ref[0] * wgt[None], 0.0)
                    contrib = contrib + _scatter_tile(vals, lyc, lxc).astype(
                        gsrc_hbm.dtype
                    )
                dst = gsrc_hbm.at[ni, :, pl.ds(start_r, TILE_H),
                                  pl.ds(start_c, TILE_W)]
                rd = pltpu.make_async_copy(dst, tile_ref, sem)
                rd.start()
                rd.wait()
                tile_ref[...] = tile_ref[...] + contrib
                wr = pltpu.make_async_copy(tile_ref, dst, sem)
                wr.start()
                wr.wait()
        return carry

    lax.fori_loop(r0, r1 + 1, row_body, 0)


def warp_bilinear_chw_banded(src: Array, coords_x: Array, coords_y: Array,
                             interpret: bool = False,
                             save_corners: bool = False):
    """warp_bilinear_chw for sources too large to keep resident in VMEM.
    Same contract and semantics; the source is read tile-by-tile over DMA."""
    n, c, h, w = src.shape
    _, ho, wo = coords_x.shape
    src = _pad_tiles(src)
    grid = (n, pl.cdiv(ho, TILE_H), pl.cdiv(wo, TILE_W))
    kernel = functools.partial(
        _warp_kernel_banded, h=h, w=w, c=c, save_corners=save_corners
    )
    out_shape, out_specs = _fwd_out(
        n, c, ho, wo, src.dtype, save_corners, src, coords_x, coords_y
    )
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=_coord_specs() + [
            pl.BlockSpec(memory_space=_ANY_MEMSPACE.ANY),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((c, TILE_H, TILE_W), src.dtype),
            pltpu.VMEM((4, c, TILE_H, TILE_W), src.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(coords_x, coords_y, src)
    return result


def warp_bilinear_grad_chw_banded(coords_x: Array, coords_y: Array, g: Array,
                                  h: int, w: int,
                                  interpret: bool = False) -> Array:
    """warp_bilinear_grad_chw for beyond-VMEM gradient images: HBM-resident
    accumulation through DMA'd scratch tiles."""
    n, c, ho, wo = g.shape
    hp, wp = padded_dims(h, w)
    grid = (n, pl.cdiv(ho, TILE_H), pl.cdiv(wo, TILE_W))
    kernel = functools.partial(
        _warp_grad_kernel_banded, h=h, w=w, c=c, ho=ho, wo=wo
    )
    gsrc_init = jnp.zeros((n, c, hp, wp), g.dtype)
    # under shard_map the aliased output varies over the mesh exactly as the
    # cotangent does; the fresh zeros must be promoted to the same vma set
    # or the alias pairing trips strict vma checking
    from mine_tpu.utils.jax_compat import typeof

    vma = getattr(typeof(g), "vma", frozenset()) or frozenset()
    if vma and hasattr(lax, "pvary"):
        gsrc_init = lax.pvary(gsrc_init, tuple(vma))
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=_coord_specs() + [
            pl.BlockSpec((1, c, TILE_H, TILE_W), lambda ni, i, j: (ni, 0, i, j)),
            pl.BlockSpec(memory_space=_ANY_MEMSPACE.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=_ANY_MEMSPACE.ANY),
        out_shape=_out_struct((n, c, hp, wp), g.dtype, g, coords_x, coords_y),
        scratch_shapes=[
            pltpu.VMEM((c, TILE_H, TILE_W), g.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={3: 0},
        interpret=interpret,
    )(coords_x, coords_y, g, gsrc_init)
    return out[:, :, :h, :w]


def _warp_composite_kernel(x_ref, y_ref, dist_ref, z_ref, src_hbm, out_ref,
                           tile_ref, corner_ref, sem, *,
                           h: int, w: int, c: int):
    """One (8, 128) output tile x one plane of the fused warp-composite
    sweep. The plane axis is the INNERMOST grid dimension, so for a fixed
    output tile the planes run sequentially and the out block (whose index
    map ignores the plane) stays resident in VMEM — the over-composite
    accumulates in place and is flushed to HBM once per output tile, after
    the whole sweep. The source band of each plane is DMA'd through the
    shared bbox walk; the warped plane values exist only as VPU registers.

    x_ref/y_ref/dist_ref/z_ref: (1, 1, TILE_H, TILE_W) this plane's sample
    coords, inter-plane distance, and target-frame z at this output tile.
    src_hbm: (N, S, c, hp, wp) plane payload in HBM (rgb channels first,
    sigma LAST). out_ref: (1, c+3, TILE_H, TILE_W) accumulators — rgb-
    weighted sums (c-1), z-weighted sum, weight sum, in-FoV plane count,
    running transmittance.
    """
    ni = pl.program_id(0)
    s = pl.program_id(3)
    wp = src_hbm.shape[4]
    i_trans = c + 2  # transmittance accumulator channel

    @pl.when(s == 0)
    def _init():
        out_ref[...] = jnp.zeros(out_ref.shape, out_ref.dtype)
        out_ref[0, i_trans] = jnp.ones((TILE_H, TILE_W), out_ref.dtype)

    x = x_ref[0, 0]
    y = y_ref[0, 0]
    wx, wy, x0, y0, r0, r1 = _prep_coords(x, y, h, w)

    _gather_band_corners(
        lambda sr, sc: src_hbm.at[ni, s, :, pl.ds(sr, TILE_H),
                                  pl.ds(sc, TILE_W)],
        tile_ref, corner_ref, sem, x0, y0, r0, r1, wp, c,
    )

    wxc = wx.astype(out_ref.dtype)
    wyc = wy.astype(out_ref.dtype)
    vals = []
    for ch in range(c):
        a00, a01, a10, a11 = (corner_ref[k, ch] for k in range(4))
        top = a00 * (1.0 - wxc) + a01 * wxc
        bot = a10 * (1.0 - wxc) + a11 * wxc
        vals.append(top * (1.0 - wyc) + bot * wyc)

    z = z_ref[0, 0]
    # planes behind the target camera contribute nothing (mpi_render.py
    # warp_mpi_to_tgt); sigma rides last in the payload
    sigma = jnp.where(z >= 0.0, vals[c - 1], 0.0)
    # in-FoV validity, same open interval as homography_sample_coords
    valid = (x > -1.0) & (x < float(w)) & (y > -1.0) & (y < float(h))
    transparency = jnp.exp(-sigma * dist_ref[0, 0])
    t_acc = out_ref[0, i_trans]
    wgt = t_acc * (1.0 - transparency)
    for ch in range(c - 1):
        out_ref[0, ch] = out_ref[0, ch] + wgt * vals[ch]
    out_ref[0, c - 1] = out_ref[0, c - 1] + wgt * z
    out_ref[0, c] = out_ref[0, c] + wgt
    out_ref[0, c + 1] = out_ref[0, c + 1] + valid.astype(out_ref.dtype)
    # the 1e-6 eps matches the dense cumprod (mpi_render.py:82)
    out_ref[0, i_trans] = t_acc * (transparency + 1.0e-6)


def warp_composite_chw(src: Array, coords_x: Array, coords_y: Array,
                       dist: Array, z: Array,
                       interpret: bool = False) -> Array:
    """Fused homography-warp + over-composite: the whole S-plane sweep in
    one HBM pass per output tile.

    src: (N, S, C, H, W) per-plane payload, rgb channels first, SIGMA LAST.
    coords_x/coords_y/dist/z: (N, S, Ho, Wo) — per-plane source sample
    coords, inter-plane distances (background pseudo-distance in the last
    plane's slot), and target-frame plane z at the sample coords (behind-
    camera masking + depth expectation).

    Returns (N, C+3, Ho, Wo) float accumulators: rgb-weighted sums (C-1),
    z-weighted sum, weight sum, in-FoV plane count, and the final
    accumulated transmittance. Forward-only: the streaming compositor's
    custom-vjp backward recomputes through the chunked scan
    (ops/mpi_render.py _render_tgt_fused).
    """
    n, s, c, h, w = src.shape
    _, _, ho, wo = coords_x.shape
    hp, wp = padded_dims(h, w)
    if hp != h or wp != w:
        src = jnp.pad(src, ((0, 0), (0, 0), (0, 0), (0, hp - h), (0, wp - w)))
    grid = (n, pl.cdiv(ho, TILE_H), pl.cdiv(wo, TILE_W), s)
    kernel = functools.partial(_warp_composite_kernel, h=h, w=w, c=c)

    def coord_spec():
        return pl.BlockSpec(
            (1, 1, TILE_H, TILE_W), lambda ni, i, j, sp: (ni, sp, i, j)
        )

    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[coord_spec(), coord_spec(), coord_spec(), coord_spec(),
                  pl.BlockSpec(memory_space=_ANY_MEMSPACE.ANY)],
        # accumulators: index map ignores the plane axis, so the block stays
        # resident across the sweep and flushes once per output tile
        out_specs=pl.BlockSpec(
            (1, c + 3, TILE_H, TILE_W), lambda ni, i, j, sp: (ni, 0, i, j)
        ),
        out_shape=_out_struct((n, c + 3, ho, wo), src.dtype,
                              src, coords_x, coords_y),
        scratch_shapes=[
            pltpu.VMEM((c, TILE_H, TILE_W), src.dtype),
            pltpu.VMEM((4, c, TILE_H, TILE_W), src.dtype),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(coords_x, coords_y, dist, z, src)


def warp_bilinear_grad_chw(coords_x: Array, coords_y: Array, g: Array,
                           h: int, w: int,
                           interpret: bool = False) -> Array:
    """Source cotangent of warp_bilinear_chw: scatters the output cotangent
    g (N, C, Ho, Wo) back through the bilinear footprint into (N, C, h, w).
    """
    n, c, ho, wo = g.shape
    hp, wp = padded_dims(h, w)
    grid = (n, pl.cdiv(ho, TILE_H), pl.cdiv(wo, TILE_W))
    kernel = functools.partial(_warp_grad_kernel, h=h, w=w, c=c, ho=ho, wo=wo)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=_coord_specs() + [
            pl.BlockSpec((1, c, TILE_H, TILE_W), lambda ni, i, j: (ni, 0, i, j)),
        ],
        # the full gradient image accumulates across this image's (i, j) steps
        out_specs=pl.BlockSpec((1, c, hp, wp), lambda ni, i, j: (ni, 0, 0, 0)),
        out_shape=_out_struct((n, c, hp, wp), g.dtype, g, coords_x, coords_y),
        interpret=interpret,
    )(coords_x, coords_y, g)
    return out[:, :, :h, :w]
