"""Hand-written Mosaic/Pallas TPU kernels for ops XLA lowers poorly.

Currently: the bilinear warp behind ops.grid_sample (the per-plane
homography-warp workhorse, reference hot-op #2 — SURVEY.md §3.1)."""

from mine_tpu.ops.pallas.warp import warp_bilinear_chw
