"""Bilinear image sampling with border padding — the TPU replacement for
`torch.nn.functional.grid_sample(..., padding_mode='border', align_corners=False)`
(the per-plane warp workhorse, reference homography_sampler.py:147-148).

Parity notes. The reference normalizes pixel coords p to the grid_sample
convention as g = (p + 0.5) / (0.5 * size) - 1 (homography_sampler.py:145-146),
and torch then unnormalizes with p' = ((g + 1) * size - 1) / 2 == p. So the
composition is the identity: grid_sample effectively samples at raw pixel
coordinates. We therefore skip the normalize/denormalize round-trip entirely
and sample at pixel coordinates directly — fewer flops, bit-identical intent.

Border padding in torch clamps the *coordinate* into [0, size-1] before the
bilinear split, which is what `_clamp_coords` does here.

Implementation: two paths with identical semantics.
  * XLA path: 4-corner gather over a flattened HW axis. XLA lowers this to a
    generic TPU gather that profiled ~100x slower than memory bound (2.9 s
    for one (64, 384, 512, 7) warp on v5e — the whole step budget, several
    times over).
  * Pallas path (TPU only, the default there): mine_tpu/ops/pallas/warp.py —
    restructures the warp around Mosaic's native in-tile lane gather
    (59x faster at the LLFF bench shapes), with the backward scatter as a
    one-hot-MXU kernel and elementwise coordinate cotangents from saved
    corner values (custom_vjp below). Sources past the VMEM budget switch
    to the DMA-banded kernel variants (HBM-resident image, per-tile bbox
    traffic), so full-res shapes stay off the XLA gather too.
Set MINE_TPU_DISABLE_PALLAS_WARP=1 to force the XLA path everywhere.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import Array


def _gather_hw(img: Array, iy: Array, ix: Array) -> Array:
    """img: (H, W, C); iy/ix: (...,) int32 -> (..., C)."""
    h, w, _ = img.shape
    flat = img.reshape(h * w, -1)
    idx = iy * w + ix
    return jnp.take(flat, idx, axis=0)


def _sample_one(img: Array, coords: Array) -> Array:
    """Bilinear-sample one image at pixel coords.

    img: (H, W, C). coords: (..., 2) as (x, y) in pixel units.
    Returns (..., C).
    """
    h, w, _ = img.shape
    x = jnp.clip(coords[..., 0], 0.0, w - 1.0)
    y = jnp.clip(coords[..., 1], 0.0, h - 1.0)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    ix0 = x0.astype(jnp.int32)
    iy0 = y0.astype(jnp.int32)
    ix1 = jnp.minimum(ix0 + 1, w - 1)
    iy1 = jnp.minimum(iy0 + 1, h - 1)

    v00 = _gather_hw(img, iy0, ix0)
    v01 = _gather_hw(img, iy0, ix1)
    v10 = _gather_hw(img, iy1, ix0)
    v11 = _gather_hw(img, iy1, ix1)

    wx = wx[..., None]
    wy = wy[..., None]
    top = v00 * (1.0 - wx) + v01 * wx
    bot = v10 * (1.0 - wx) + v11 * wx
    return top * (1.0 - wy) + bot * wy


def _grid_sample_xla(src: Array, coords: Array) -> Array:
    return jax.vmap(_sample_one)(src, coords)


# interpret-mode toggle so the suite can drive the REAL fwd/bwd path on CPU
_INTERPRET = False


def _banded_disabled() -> bool:
    """MINE_TPU_DISABLE_BANDED_WARP=1 restores the round-3 behavior for
    beyond-VMEM sources (slow XLA gather) without touching the
    hardware-proven resident kernel — the safety valve until the banded
    kernels' Mosaic lowering has run on a real chip (interpret mode
    validates semantics, not Mosaic's layout/DMA constraints)."""
    return os.environ.get("MINE_TPU_DISABLE_BANDED_WARP", "").lower() in (
        "1", "true", "yes", "on"
    )


def _warp_fwd_fn(src: Array):
    """Resident kernel when the padded source fits the VMEM budget, the
    DMA-banded kernel beyond it (1008x756 full-res LLFF and the like)."""
    from mine_tpu.ops.pallas import warp

    return warp.warp_bilinear_chw if _fits_vmem(src) else warp.warp_bilinear_chw_banded


def _warp_grad_fn(src: Array):
    from mine_tpu.ops.pallas import warp

    return (
        warp.warp_bilinear_grad_chw if _fits_vmem(src)
        else warp.warp_bilinear_grad_chw_banded
    )


@jax.custom_vjp
def _grid_sample_pallas(src: Array, coords: Array) -> Array:
    out = _warp_fwd_fn(src)(
        jnp.moveaxis(src, -1, 1), coords[..., 0], coords[..., 1],
        interpret=_INTERPRET,
    )
    return jnp.moveaxis(out, 1, -1)


def _pallas_fwd(src, coords):
    # residuals are references to existing tensors — corner values are
    # re-gathered in the backward (one extra forward-kernel pass) instead of
    # being saved, which would hold 4x the output (1.4 GB at the scale-0
    # LLFF warp) across the whole backward
    return _grid_sample_pallas(src, coords), (src, coords)


def _pallas_bwd(res, g):
    """Both cotangents without XLA gather/scatter: the source cotangent is
    the Pallas scatter kernel; the coordinate cotangent is elementwise in the
    corner values re-gathered by a second forward-kernel pass
    (d out/d wx = (a01-a00)(1-wy)+(a11-a10)wy etc.), masked where the border
    clamp saturates — matching jnp.clip's VJP in the XLA path."""
    src, coords = res
    _, h, w, _ = src.shape
    _, corners = _warp_fwd_fn(src)(
        jnp.moveaxis(src, -1, 1), coords[..., 0], coords[..., 1],
        interpret=_INTERPRET, save_corners=True,
    )
    g_chw = jnp.moveaxis(g, -1, 1)

    grad_src = jnp.moveaxis(
        _warp_grad_fn(src)(coords[..., 0], coords[..., 1], g_chw, h, w,
                           interpret=_INTERPRET),
        1, -1,
    )

    cx = coords[..., 0]
    cy = coords[..., 1]
    x = jnp.clip(cx, 0.0, w - 1.0)
    y = jnp.clip(cy, 0.0, h - 1.0)
    wx = (x - jnp.floor(jnp.minimum(x, w - 2.0)))[:, None]
    wy = (y - jnp.floor(jnp.minimum(y, h - 2.0)))[:, None]
    a00, a01, a10, a11 = (corners[:, k] for k in range(4))  # (N, C, Ho, Wo)
    dx = (a01 - a00) * (1.0 - wy) + (a11 - a10) * wy
    dy = (a10 - a00) * (1.0 - wx) + (a11 - a01) * wx
    gx = jnp.sum(g_chw * dx, axis=1) * ((cx >= 0.0) & (cx <= w - 1.0))
    gy = jnp.sum(g_chw * dy, axis=1) * ((cy >= 0.0) & (cy <= h - 1.0))
    return grad_src, jnp.stack([gx, gy], axis=-1)


_grid_sample_pallas.defvjp(_pallas_fwd, _pallas_bwd)


# The resident warp kernel keeps one whole padded (C, Hp, Wp) source image in
# VMEM (~16 MB/core, shared with the coord/output blocks and their double
# buffers). Above this budget the DMA-banded kernel takes over (warp.py
# warp_bilinear_chw_banded): the source stays in HBM and only each output
# tile's bbox tiles travel, so full-res shapes (1008x756 LLFF eval, 21.8 MB
# fp32) stay on the Pallas path instead of XLA's ~100x-off gather.
_VMEM_SRC_BUDGET_BYTES = 8 * 1024 * 1024


def _fits_vmem(src: Array) -> bool:
    from mine_tpu.ops.pallas.warp import padded_dims

    _, h, w, c = src.shape
    hp, wp = padded_dims(h, w)
    return c * hp * wp * src.dtype.itemsize <= _VMEM_SRC_BUDGET_BYTES


def grid_sample_pixel(src: Array, coords: Array) -> Array:
    """Batched bilinear sampling at pixel coordinates with border padding.

    Args:
      src: (B, H, W, C) source images.
      coords: (B, Ho, Wo, 2) sample locations as (x, y) in src pixel units.
    Returns:
      (B, Ho, Wo, C) sampled values.
    """
    if (
        jax.default_backend() == "tpu"
        and os.environ.get("MINE_TPU_DISABLE_PALLAS_WARP", "").lower()
        not in ("1", "true", "yes", "on")
        and (_fits_vmem(src) or not _banded_disabled())
    ):
        return _grid_sample_pallas(src, coords)
    return _grid_sample_xla(src, coords)
