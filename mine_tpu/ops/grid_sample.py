"""Bilinear image sampling with border padding — the TPU replacement for
`torch.nn.functional.grid_sample(..., padding_mode='border', align_corners=False)`
(the per-plane warp workhorse, reference homography_sampler.py:147-148).

Parity notes. The reference normalizes pixel coords p to the grid_sample
convention as g = (p + 0.5) / (0.5 * size) - 1 (homography_sampler.py:145-146),
and torch then unnormalizes with p' = ((g + 1) * size - 1) / 2 == p. So the
composition is the identity: grid_sample effectively samples at raw pixel
coordinates. We therefore skip the normalize/denormalize round-trip entirely
and sample at pixel coordinates directly — fewer flops, bit-identical intent.

Border padding in torch clamps the *coordinate* into [0, size-1] before the
bilinear split, which is what `_clamp_coords` does here.

Implementation: 4-corner gather over a flattened HW axis, lowered by XLA to a
dynamic-gather. No hand-written kernel exists (profiling has not shown the
gather dominating); if it ever does, this is the function to rewrite in
Pallas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def _gather_hw(img: Array, iy: Array, ix: Array) -> Array:
    """img: (H, W, C); iy/ix: (...,) int32 -> (..., C)."""
    h, w, _ = img.shape
    flat = img.reshape(h * w, -1)
    idx = iy * w + ix
    return jnp.take(flat, idx, axis=0)


def _sample_one(img: Array, coords: Array) -> Array:
    """Bilinear-sample one image at pixel coords.

    img: (H, W, C). coords: (..., 2) as (x, y) in pixel units.
    Returns (..., C).
    """
    h, w, _ = img.shape
    x = jnp.clip(coords[..., 0], 0.0, w - 1.0)
    y = jnp.clip(coords[..., 1], 0.0, h - 1.0)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    ix0 = x0.astype(jnp.int32)
    iy0 = y0.astype(jnp.int32)
    ix1 = jnp.minimum(ix0 + 1, w - 1)
    iy1 = jnp.minimum(iy0 + 1, h - 1)

    v00 = _gather_hw(img, iy0, ix0)
    v01 = _gather_hw(img, iy0, ix1)
    v10 = _gather_hw(img, iy1, ix0)
    v11 = _gather_hw(img, iy1, ix1)

    wx = wx[..., None]
    wy = wy[..., None]
    top = v00 * (1.0 - wx) + v01 * wx
    bot = v10 * (1.0 - wx) + v11 * wx
    return top * (1.0 - wy) + bot * wy


def grid_sample_pixel(src: Array, coords: Array) -> Array:
    """Batched bilinear sampling at pixel coordinates with border padding.

    Args:
      src: (B, H, W, C) source images.
      coords: (B, Ho, Wo, 2) sample locations as (x, y) in src pixel units.
    Returns:
      (B, Ho, Wo, C) sampled values.
    """
    return jax.vmap(_sample_one)(src, coords)
