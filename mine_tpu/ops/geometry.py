"""Camera geometry primitives.

Reference behavior reproduced (file:line cites into /root/reference):
  - pixel grid:            operations/homography_sampler.py:24-33
  - plane-sweep xyz:       operations/mpi_rendering.py:140-178
  - SE(3) point transform: operations/rendering_utils.py:5-24

TPU-first design notes: all matrix inverses are closed-form (adjugate for 3x3,
transpose trick for SE(3)) rather than LAPACK calls — this deletes the NaN
retry-loop workaround the reference carries (utils.py:96-120) and keeps the
whole graph fusible by XLA. Layout is channel-last: xyz tensors are
(B, S, H, W, 3) so spatial dims are contiguous for the MXU/VPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array, lax

# Geometry matmuls are tiny (3x3 / 4x4 against pixel grids) but feed pixel
# coordinates up to ~1000, where the default low-precision matmul path loses
# ~1e-3 relative — half-pixel warp errors. Force full fp32 accumulation here;
# the cost is negligible next to the conv stacks.
_PRECISION = lax.Precision.HIGHEST


def inverse_3x3(m: Array, eps: float = 0.0) -> Array:
    """Closed-form (adjugate / determinant) inverse of (..., 3, 3) matrices.

    Replaces `torch.inverse` + retry workaround (reference utils.py:96-120).
    Differentiable and batched via broadcasting; no LAPACK dispatch.
    """
    a, b, c = m[..., 0, 0], m[..., 0, 1], m[..., 0, 2]
    d, e, f = m[..., 1, 0], m[..., 1, 1], m[..., 1, 2]
    g, h, i = m[..., 2, 0], m[..., 2, 1], m[..., 2, 2]

    co_a = e * i - f * h
    co_b = -(d * i - f * g)
    co_c = d * h - e * g
    det = a * co_a + b * co_b + c * co_c

    adj = jnp.stack(
        [
            jnp.stack([co_a, -(b * i - c * h), b * f - c * e], axis=-1),
            jnp.stack([co_b, a * i - c * g, -(a * f - c * d)], axis=-1),
            jnp.stack([co_c, -(a * h - b * g), a * e - b * d], axis=-1),
        ],
        axis=-2,
    )
    return adj / (det[..., None, None] + eps)


def inverse_se3(g: Array) -> Array:
    """Inverse of (..., 4, 4) rigid transforms: [R|t]^-1 = [R^T | -R^T t].

    The reference inverts pose matrices with a general 4x4 LAPACK inverse
    (synthesis_task.py:211); poses are SE(3), so the closed form is exact.
    """
    r = g[..., :3, :3]
    t = g[..., :3, 3]
    r_inv = jnp.swapaxes(r, -1, -2)
    t_inv = -jnp.einsum("...ij,...j->...i", r_inv, t, precision=_PRECISION)
    out = jnp.zeros_like(g)
    out = out.at[..., :3, :3].set(r_inv)
    out = out.at[..., :3, 3].set(t_inv)
    out = out.at[..., 3, 3].set(1.0)
    return out


def pixel_center_grid(height: int, width: int, dtype=jnp.float32) -> Array:
    """(H, W, 2) grid of (x, y) pixel coordinates, x in [0, W-1], y in [0, H-1].

    Matches HomographySample.grid_generation (homography_sampler.py:24-33):
    integer pixel coordinates (not half-pixel centers).
    """
    x = jnp.arange(width, dtype=dtype)
    y = jnp.arange(height, dtype=dtype)
    xv, yv = jnp.meshgrid(x, y)  # both (H, W)
    return jnp.stack([xv, yv], axis=-1)


def homogeneous_pixel_grid(height: int, width: int, dtype=jnp.float32) -> Array:
    """(H, W, 3) homogeneous pixel grid [x, y, 1]."""
    xy = pixel_center_grid(height, width, dtype)
    ones = jnp.ones((height, width, 1), dtype=dtype)
    return jnp.concatenate([xy, ones], axis=-1)


def scale_intrinsics(k: Array, scale: int) -> Array:
    """Divide K by 2**scale, keeping K[2,2] = 1 (synthesis_task.py:242-245)."""
    k = k / (2.0**scale)
    return k.at[..., 2, 2].set(1.0)


def transform_se3(g: Array, xyz: Array) -> Array:
    """Apply (..., 4, 4) rigid transforms to (..., N, 3) points.

    Reference transform_G_xyz (rendering_utils.py:5-24), channel-last.
    """
    r = g[..., :3, :3]
    t = g[..., :3, 3]
    return jnp.einsum("...ij,...nj->...ni", r, xyz, precision=_PRECISION) + t[..., None, :]


def get_src_xyz_from_plane_disparity(
    grid_homo: Array, mpi_disparity: Array, k_inv: Array
) -> Array:
    """Per-plane 3D coordinates of every pixel in the source camera frame.

    Args:
      grid_homo: (H, W, 3) homogeneous pixel grid.
      mpi_disparity: (B, S) plane disparities.
      k_inv: (B, 3, 3) inverse intrinsics.
    Returns:
      (B, S, H, W, 3) xyz = depth * K^-1 [x, y, 1].

    Reference: mpi_rendering.py:140-163. There the K^-1 matmul is tiled to
    B*S identical copies; here it is computed once per batch element and the
    depth scaling broadcasts over S — same math, S× less matmul work.
    """
    depth = 1.0 / mpi_disparity  # (B, S)
    rays = jnp.einsum("bij,hwj->bhwi", k_inv, grid_homo, precision=_PRECISION)  # (B, H, W, 3)
    return rays[:, None, :, :, :] * depth[:, :, None, None, None]


def get_tgt_xyz_from_plane_disparity(xyz_src: Array, g_tgt_src: Array) -> Array:
    """Transform (B, S, H, W, 3) source-frame xyz into the target frame.

    Reference: mpi_rendering.py:166-178.
    """
    b, s, h, w, _ = xyz_src.shape
    xyz = xyz_src.reshape(b, s * h * w, 3)
    xyz_tgt = transform_se3(g_tgt_src, xyz)
    return xyz_tgt.reshape(b, s, h, w, 3)
