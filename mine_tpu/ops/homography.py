"""Planar homography warping of MPI planes into a target camera.

Reference: operations/homography_sampler.py:58-150 (HomographySample.sample).
The plane at depth d with normal n=[0,0,1] in the source frame induces
  H_tgt_src = K_tgt (R - t n^T / -d) K_src^-1,
a 3x3 map from source pixels to target pixels; we invert it in closed form
and pull target pixels back into the source image with a bilinear gather.

Differences from the reference (deliberate, TPU-first):
  - no cached meshgrid object — the grid is a constant folded into the jit;
  - closed-form 3x3 inverse (no LAPACK, no NaN-retry loop);
  - the S plane axis is folded into the batch axis once at the call site, so
    one shot warps all B*S planes in a single batched einsum + gather.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from mine_tpu.ops.geometry import _PRECISION, homogeneous_pixel_grid, inverse_3x3
from mine_tpu.ops.grid_sample import grid_sample_pixel

# np (not jnp): a module-level jnp constant would initialize the JAX backend at
# import time, committing the platform before callers can set JAX_PLATFORMS /
# XLA_FLAGS. Broadcasts identically inside the einsum.
PLANE_NORMAL = np.array([0.0, 0.0, 1.0], dtype=np.float32)  # fronto-parallel planes


def build_plane_homography(
    g_tgt_src: Array, k_src_inv: Array, k_tgt: Array, plane_depth: Array
) -> Array:
    """H_tgt_src for fronto-parallel planes at `plane_depth` (reference
    homography_sampler.py:100-109).

    Args:
      g_tgt_src: (B, 4, 4) source->target rigid transform.
      k_src_inv: (B, 3, 3).
      k_tgt: (B, 3, 3).
      plane_depth: (B,) plane distance along +z in the source frame.
    Returns:
      (B, 3, 3) homography mapping source pixels to target pixels.
    """
    r = g_tgt_src[:, :3, :3]
    t = g_tgt_src[:, :3, 3]
    # plane equation n^T X - d = 0  =>  H = R - t n^T / (-d)
    t_nt = t[:, :, None] * PLANE_NORMAL[None, None, :]  # (B, 3, 3)
    r_tnd = r - t_nt / (-plane_depth[:, None, None])
    return jnp.einsum("bij,bjk,bkl->bil", k_tgt, r_tnd, k_src_inv, precision=_PRECISION)


def homography_sample_coords(
    plane_depth: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    h_src: int,
    w_src: int,
    tgt_height: int | None = None,
    tgt_width: int | None = None,
) -> tuple[Array, Array]:
    """Source-pixel sample locations for every target pixel, plus validity.

    The coordinate half of the warp (reference homography_sampler.py:110-141),
    exposed separately so callers can evaluate closed-form per-plane fields
    (e.g. plane xyz, affine in pixel coords) directly at the sample locations
    instead of paying gather bandwidth for them — see
    mpi_render.warp_mpi_to_tgt.

    Args:
      plane_depth: (B,) plane depths in the source frame (B may be B*S).
      g_tgt_src, k_src_inv, k_tgt: camera parameters, (B, 4, 4) / (B, 3, 3).
      h_src/w_src: source resolution (bounds the validity test).
      tgt_height/tgt_width: target resolution (defaults to source).
    Returns:
      src_xy: (B, Ht, Wt, 2) fp32 sample locations in source pixel units;
      valid:  (B, Ht, Wt) bool mask of target pixels that land inside the
              source FoV (reference homography_sampler.py:137-141 uses the
              open interval (-1, W) x (-1, H)).
    """
    h_tgt = tgt_height or h_src
    w_tgt = tgt_width or w_src

    h_tgt_src = build_plane_homography(g_tgt_src, k_src_inv, k_tgt, plane_depth)
    # The warp needs tgt->src; invert per-plane in closed form. The reference
    # blocks gradient through the inverse (homography_sampler.py:116-117).
    h_src_tgt = jax.lax.stop_gradient(inverse_3x3(h_tgt_src))

    # Coordinate math stays fp32 regardless of payload dtype: bf16 cannot
    # represent integer pixel coords above 256 (multi-pixel warp error at
    # standard resolutions). Only the gathered payload keeps src.dtype.
    h_src_tgt = h_src_tgt.astype(jnp.float32)
    grid = homogeneous_pixel_grid(h_tgt, w_tgt, jnp.float32)  # (Ht, Wt, 3)
    src_homo = jnp.einsum("bij,hwj->bhwi", h_src_tgt, grid, precision=_PRECISION)  # (B, Ht, Wt, 3)
    # Guard the perspective divide: at degenerate poses (plane edge-on to the
    # target camera) z crosses 0 and NaN/inf coordinates would leak into the
    # gather and poison masked losses downstream (NaN * 0 = NaN). Clamping |z|
    # away from 0 sends those pixels far out of bounds instead, where the
    # border clamp and the validity mask handle them.
    z = src_homo[..., 2:3]
    z = jnp.where(jnp.abs(z) < 1.0e-8, jnp.where(z < 0, -1.0e-8, 1.0e-8), z)
    src_xy = src_homo[..., :2] / z

    valid = (
        (src_xy[..., 0] > -1.0)
        & (src_xy[..., 0] < w_src)
        & (src_xy[..., 1] > -1.0)
        & (src_xy[..., 1] < h_src)
    )
    return src_xy, valid


def homography_sample(
    src: Array,
    plane_depth: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    tgt_height: int | None = None,
    tgt_width: int | None = None,
) -> tuple[Array, Array]:
    """Warp source-frame plane images into the target camera.

    Args:
      src: (B, H, W, C) per-plane source images (B may be batch*planes).
      plane_depth: (B,) plane depths in the source frame.
      g_tgt_src, k_src_inv, k_tgt: camera parameters, (B, 4, 4) / (B, 3, 3).
      tgt_height/tgt_width: target resolution (defaults to source).
    Returns:
      warped: (B, Ht, Wt, C);
      valid:  (B, Ht, Wt) bool mask (see homography_sample_coords).
    """
    b, h_src, w_src, _ = src.shape
    src_xy, valid = homography_sample_coords(
        plane_depth, g_tgt_src, k_src_inv, k_tgt,
        h_src, w_src, tgt_height, tgt_width,
    )
    warped = grid_sample_pixel(src, src_xy).astype(src.dtype)
    return warped, valid
