"""Disparity sampling and sparse-point gathering.

Reference: operations/rendering_utils.py:27-140. All randomness takes an
explicit `jax.random` key (the reference draws from the global CUDA RNG,
rendering_utils.py:65/:86/:115); keys are folded per-step by the train loop so
data-parallel replicas see the shards of one logical stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def _stratified_uniform(key: Array, batch_size: int, num_bins: int) -> Array:
    """(B, S) uniforms where row i depends only on (key, i), never on B.

    A single `jax.random.uniform(key, (B, S))` draw gives example i
    DIFFERENT bits under different batch sizes, which breaks the eval
    wrap-pad contract (training/step.py make_eval_step): a weight-0 padded
    duplicate must leave the genuine examples' losses bit-identical to the
    unpadded batch, and that requires every per-example quantity —
    including the sampled plane disparities — to be a function of the
    example alone. Per-row `fold_in` keys make the draw batch-size
    invariant (prefix-stable: row i is the same in a B=1 and a B=8 batch).
    """
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(batch_size, dtype=jnp.uint32)
    )
    return jax.vmap(lambda k: jax.random.uniform(k, (num_bins,)))(keys)


def uniform_disparity_from_linspace_bins(
    key: Array, batch_size: int, num_bins: int, start: float, end: float
) -> Array:
    """Stratified disparity samples: one uniform draw inside each of S linspace
    bins spanning [start, end], start > end (descending disparity = near-to-far
    planes). Reference: rendering_utils.py:70-88.
    Returns (B, S); row i is batch-size invariant (see _stratified_uniform).
    """
    assert start > end, "disparity must descend (near plane first)"
    edges = jnp.linspace(start, end, num_bins + 1)
    interval = edges[1] - edges[0]  # negative
    u = _stratified_uniform(key, batch_size, num_bins)
    return edges[None, :-1] + interval * u


def uniform_disparity_from_bins(key: Array, batch_size: int, disparity_edges: Array) -> Array:
    """Stratified samples from explicit (S+1,) bin edges, descending.
    Reference: rendering_utils.py:47-67. Returns (B, S); row i is
    batch-size invariant (see _stratified_uniform).
    """
    edges = jnp.asarray(disparity_edges, dtype=jnp.float32)
    interval = edges[1:] - edges[:-1]  # (S,)
    s = edges.shape[0] - 1
    u = _stratified_uniform(key, batch_size, s)
    return edges[None, :-1] + interval[None, :] * u


def fixed_disparity_linspace(batch_size: int, num_bins: int, start: float, end: float) -> Array:
    """Deterministic plane disparities (eval / inference path,
    synthesis_task.py:41-45). Returns (B, S)."""
    d = jnp.linspace(start, end, num_bins)
    return jnp.broadcast_to(d[None, :], (batch_size, num_bins))


def gather_pixel_by_pxpy(img: Array, pxpy: Array) -> Array:
    """Nearest-pixel lookup of image values at continuous (x, y) positions.

    img: (B, H, W, C); pxpy: (B, N, 2) float pixel coords.
    Returns (B, N, C). Reference: rendering_utils.py:27-44 — indices are
    round()ed, clamped, and carry no gradient; the gather itself is
    differentiable w.r.t. img.
    """
    b, h, w, c = img.shape
    idx = jax.lax.stop_gradient(jnp.round(pxpy)).astype(jnp.int32)
    ix = jnp.clip(idx[..., 0], 0, w - 1)
    iy = jnp.clip(idx[..., 1], 0, h - 1)
    flat = img.reshape(b, h * w, c)
    return jnp.take_along_axis(flat, (iy * w + ix)[..., None], axis=1)


def sample_pdf(key: Array, values: Array, weights: Array, n_samples: int) -> Array:
    """Inverse-CDF sampling of the piecewise distribution weights = p(values).

    values/weights: (B, N, S) (the reference carries an extra singleton axis,
    rendering_utils.py:91-140). Returns (B, N, n_samples).
    Used by coarse-to-fine plane placement (mpi_rendering.py:244-268).
    """
    b, n, s = weights.shape

    # midpoints as interior bin edges, endpoint values as outer edges
    mid = 0.5 * (values[..., 1:] + values[..., :-1])
    edges = jnp.concatenate([values[..., :1], mid, values[..., -1:]], axis=-1)  # (B,N,S+1)

    pdf = weights / (jnp.sum(weights, axis=-1, keepdims=True) + 1.0e-5)
    cdf = jnp.cumsum(pdf, axis=-1)
    cdf = jnp.concatenate([jnp.zeros_like(cdf[..., :1]), cdf], axis=-1)  # (B,N,S+1)

    u = jax.random.uniform(key, (b, n, n_samples), dtype=weights.dtype)

    flat_cdf = cdf.reshape(b * n, s + 1)
    flat_u = u.reshape(b * n, n_samples)
    idx = jax.vmap(lambda c, q: jnp.searchsorted(c, q, side="right"))(flat_cdf, flat_u)
    idx = idx.reshape(b, n, n_samples)
    lo = jnp.clip(idx - 1, 0, s)
    hi = jnp.clip(idx, 0, s)

    take = lambda arr, i: jnp.take_along_axis(arr, i, axis=-1)
    cdf_lo, cdf_hi = take(cdf, lo), take(cdf, hi)
    bin_lo, bin_hi = take(edges, lo), take(edges, hi)

    cdf_interval = cdf_hi - cdf_lo
    t = (u - cdf_lo) / jnp.clip(cdf_interval, min=1.0e-5)
    # degenerate (clamped) intervals sample the bin midpoint
    # (rendering_utils.py:133-137)
    t = jnp.where(cdf_interval <= 1.0e-4, 0.5, t)
    return bin_lo + t * (bin_hi - bin_lo)
