"""MPI compositing: NeRF-style plane volume rendering and alpha composition.

Reference: operations/mpi_rendering.py:7-82 (render / alpha_composition /
plane_volume_rendering / weighted_sum_mpi) and :181-241 (render_tgt_rgb_depth).

Layout is channel-last (B, S, H, W, C); the plane axis S is axis 1 and all
scans/cumprods run over it. On a plane-sharded mesh the same math is provided
by mine_tpu/parallel/plane_sharding.py with an explicit cross-device prefix.
"""

from __future__ import annotations

import os
from functools import partial, wraps
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import Array, lax

from mine_tpu.ops.geometry import _PRECISION, homogeneous_pixel_grid
from mine_tpu.ops.homography import homography_sample_coords
from mine_tpu.ops.grid_sample import grid_sample_pixel

_BG_DIST = 1.0e3  # pseudo-distance behind the farthest plane (mpi_rendering.py:50)

# planes per lax.scan step of the streaming target compositor (the live
# working set is chunk/S of the dense path's); cfg.mpi.stream_chunk_planes
# overrides it through compositor_from_config
DEFAULT_STREAM_CHUNK = 4


def _scoped(name: str):
    """Run the wrapped function under jax.named_scope(name) so its XLA ops
    carry the component in their metadata (obs/attrib.py buckets device
    time by these names). Pure metadata: a numerics and perf no-op."""

    def deco(fn):
        @wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.named_scope(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def _shifted_exclusive(x: Array, fill: float = 1.0) -> Array:
    """[a, b, c] -> [fill, a, b] along the plane axis (axis=1)."""
    ones = jnp.full_like(x[:, :1], fill)
    return jnp.concatenate([ones, x[:, :-1]], axis=1)


@_scoped("composite")
def alpha_composition(alpha: Array, value: Array) -> tuple[Array, Array]:
    """Over-compositing of K planes, nearest first (mpi_rendering.py:23-39).

    alpha: (B, K, H, W, 1); value: (B, K, H, W, C).
    Returns composed (B, H, W, C) and per-plane weights (B, K, H, W, 1).
    """
    preserve = _shifted_exclusive(jnp.cumprod(1.0 - alpha, axis=1))
    weights = alpha * preserve
    return jnp.sum(value * weights, axis=1), weights


@_scoped("composite")
def weighted_sum_mpi(
    rgb: Array, xyz: Array, weights: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array]:
    """Expectation of rgb and depth under compositing weights
    (mpi_rendering.py:70-82).

    rgb/xyz: (B, S, H, W, 3); weights: (B, S, H, W, 1).
    Returns rgb_out (B, H, W, 3), depth_out (B, H, W, 1).
    """
    weights_sum = jnp.sum(weights, axis=1)  # (B, H, W, 1)
    rgb_out = jnp.sum(weights * rgb, axis=1)
    z = xyz[..., 2:3]
    if is_bg_depth_inf:
        depth_out = jnp.sum(weights * z, axis=1) + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = jnp.sum(weights * z, axis=1) / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


@_scoped("composite")
def plane_volume_rendering(
    rgb: Array, sigma: Array, xyz: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array, Array, Array]:
    """NeRF-style volume rendering across depth planes (mpi_rendering.py:42-67).

    Per-pixel inter-plane distances turn sigma into transparency
    T = exp(-sigma * dist); transmittance is a shifted cumprod over planes.

    rgb: (B, S, H, W, 3); sigma: (B, S, H, W, 1); xyz: (B, S, H, W, 3).
    Returns (rgb_out, depth_out, transparency_acc, weights).
    """
    diff = xyz[:, 1:] - xyz[:, :-1]  # (B, S-1, H, W, 3)
    dist = jnp.linalg.norm(diff, axis=-1, keepdims=True)  # (B, S-1, H, W, 1)
    dist = jnp.concatenate(
        [dist, jnp.full_like(dist[:, :1], _BG_DIST)], axis=1
    )  # (B, S, H, W, 1)

    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    # eps keeps the accumulated transmittance away from exactly zero
    # (mpi_rendering.py:57-59)
    transparency_acc = _shifted_exclusive(jnp.cumprod(transparency + 1.0e-6, axis=1))
    weights = transparency_acc * alpha

    rgb_out, depth_out = weighted_sum_mpi(rgb, xyz, weights, is_bg_depth_inf)
    return rgb_out, depth_out, transparency_acc, weights


def render(
    rgb: Array,
    sigma: Array,
    xyz: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Dispatch sigma-vs-alpha compositing (mpi_rendering.py:7-20).

    Returns (imgs_syn, depth_syn, blend_weights, weights). With use_alpha the
    blend weights are zeros (no src-RGB blending path), as in the reference.
    """
    if not use_alpha:
        return plane_volume_rendering(rgb, sigma, xyz, is_bg_depth_inf)
    imgs_syn, weights = alpha_composition(sigma, rgb)
    depth_syn, _ = alpha_composition(sigma, xyz[..., 2:3])
    return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights


# -- source-pose fast path ---------------------------------------------------
#
# At the SOURCE pose the plane sweep is fronto-parallel: xyz_s(q) =
# depth_s * K^-1 [qx, qy, 1]. The reference materializes the full
# (B, S, H, W, 3) xyz tensor and takes norms of its plane-to-plane diffs
# (mpi_rendering.py:42-67 fed by :140-163); but the diff factors exactly —
#   xyz_{s+1}(q) - xyz_s(q) = (depth_{s+1} - depth_s) * K^-1 q
#   => dist_s(q) = |d_{s+1} - d_s| * ||K^-1 q||
# an (S,) vector times an (H, W) map — and per-plane z is the CONSTANT
# depth_s. So source-view compositing needs no per-plane xyz at all: S x
# less multiply work and no (B, S, H, W, 3) intermediates. Same math to ~1
# ulp (products are rounded in a different order).


def ray_norms(k_inv: Array, h: int, w: int) -> Array:
    """||K^-1 [x, y, 1]|| per pixel: (B, 3, 3) -> (B, H, W, 1)."""
    grid = homogeneous_pixel_grid(h, w, jnp.float32)
    rays = jnp.einsum("bij,hwj->bhwi", k_inv, grid, precision=_PRECISION)
    return jnp.linalg.norm(rays, axis=-1, keepdims=True)


def _src_dists(mpi_disparity: Array, k_inv: Array, h: int, w: int) -> Array:
    """Factored inter-plane distances for the source sweep:
    (B, S) disparities -> (B, S, H, W, 1) with the background pseudo-distance
    in the last slot (twin of the dist block in plane_volume_rendering)."""
    depth = 1.0 / mpi_disparity  # (B, S)
    ddiff = jnp.abs(depth[:, 1:] - depth[:, :-1])  # (B, S-1)
    dist = ddiff[:, :, None, None, None] * ray_norms(k_inv, h, w)[:, None]
    return jnp.concatenate(
        [dist, jnp.full_like(dist[:, :1], _BG_DIST)], axis=1
    )


@_scoped("composite")
def weighted_sum_src(
    rgb: Array, mpi_disparity: Array, weights: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array]:
    """weighted_sum_mpi for the source sweep, where per-plane z is the
    constant plane depth (no xyz tensor).

    rgb: (B, S, H, W, 3); mpi_disparity: (B, S); weights: (B, S, H, W, 1).

    Assumes NORMALIZED intrinsics — K^-1's third row [0, 0, 1] — so that
    per-plane camera z equals the plane depth 1/disparity; the generic
    weighted_sum_mpi takes z from an explicit xyz tensor and carries no such
    assumption. Every shipped config satisfies it (scale_intrinsics keeps
    K[2,2] = 1); a non-standard K would silently skew depth outputs here.
    """
    z = (1.0 / mpi_disparity)[:, :, None, None, None]  # (B, S, 1, 1, 1)
    weights_sum = jnp.sum(weights, axis=1)
    rgb_out = jnp.sum(weights * rgb, axis=1)
    if is_bg_depth_inf:
        depth_out = jnp.sum(weights * z, axis=1) + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = jnp.sum(weights * z, axis=1) / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


@_scoped("composite")
def render_src(
    rgb: Array,
    sigma: Array,
    mpi_disparity: Array,
    k_inv: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """`render` at the source pose, from disparities + intrinsics alone.

    rgb: (B, S, H, W, 3); sigma: (B, S, H, W, 1); mpi_disparity: (B, S);
    k_inv: (B, 3, 3). Returns (imgs_syn, depth_syn, blend_weights, weights)
    exactly like `render`.

    Assumes normalized intrinsics (K[2,2] = 1): the factored distances and
    the per-plane z both use depth = 1/disparity as the camera-frame z —
    see weighted_sum_src.
    """
    h, w = rgb.shape[2], rgb.shape[3]
    if use_alpha:
        imgs_syn, weights = alpha_composition(sigma, rgb)
        z = jnp.broadcast_to(
            (1.0 / mpi_disparity)[:, :, None, None, None],
            sigma.shape,
        )
        depth_syn, _ = alpha_composition(sigma, z)
        return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights

    dist = _src_dists(mpi_disparity, k_inv, h, w)
    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    transparency_acc = _shifted_exclusive(jnp.cumprod(transparency + 1.0e-6, axis=1))
    weights = transparency_acc * alpha
    rgb_out, depth_out = weighted_sum_src(rgb, mpi_disparity, weights, is_bg_depth_inf)
    return rgb_out, depth_out, transparency_acc, weights


@_scoped("composite")
def plane_contributions(
    sigma: Array, mpi_disparity: Array, k_inv: Array,
    use_alpha: bool = False,
    vis_dilate_px: int = 8,
) -> Array:
    """Per-plane maximum compositing weight: alpha times a PARALLAX-AWARE
    accumulated transmittance — the per-plane quantity the compositors
    (dense cumprod chain and streaming scan alike) weight every plane's
    rgb by — reduced with max over batch and pixels.

    sigma: (B, S, H, W, 1); mpi_disparity: (B, S); k_inv: (B, 3, 3).
    Returns (S,): a plane whose value is ~0 contributes to NO ray, source
    or novel, so dropping it is visually free (the pruning contract quoted
    in serving/compress.py; tolerance pinned by the convergence-harness
    parity gate in tests/test_compress.py).

    The source-pose transmittance alone would over-prune: a plane fully
    occluded at the source pose (T = 0 under a foreground surface) is
    exactly what a novel pose REVEALS in disocclusion regions — the
    content the whole predict-once/render-many product exists to show.
    So visibility is dilated spatially first: each pixel takes the max
    accumulated transmittance within `vis_dilate_px` (a bound on how far
    parallax can slide occluders between the source and any rendered
    pose) before multiplying by alpha. A plane opaque under a foreground
    edge survives; a plane buried EVERYWHERE deeper than the parallax
    radius still prunes.

    The max (not mean) over pixels is deliberate: one small opaque
    foreground object on an otherwise empty plane must keep that plane
    alive.
    """
    h, w = sigma.shape[2], sigma.shape[3]
    if use_alpha:
        alpha = sigma
        transparency = 1.0 - alpha
    else:
        dist = _src_dists(mpi_disparity, k_inv, h, w)
        transparency = jnp.exp(-sigma * dist)
        alpha = 1.0 - transparency
    # same eps'd cumprod as plane_volume_rendering/render_src so the
    # thresholded quantity is the one the renderer actually uses
    transparency_acc = _shifted_exclusive(
        jnp.cumprod(transparency + 1.0e-6, axis=1)
    )
    if vis_dilate_px > 0:
        d = 2 * int(vis_dilate_px) + 1
        transparency_acc = lax.reduce_window(
            transparency_acc, -jnp.inf, lax.max,
            window_dimensions=(1, 1, d, d, 1),
            window_strides=(1, 1, 1, 1, 1), padding="SAME",
        )
    weights = transparency_acc * alpha  # (B, S, H, W, 1)
    return jnp.max(weights, axis=(0, 2, 3, 4))


def _affine_tgt_xyz(
    src_xy: Array, depth: Array, g_flat: Array, k_inv_flat: Array,
    h: int, w: int,
) -> Array:
    """The analytic xyz sample: evaluate the per-plane affine at the clamped
    warp coords (fp32 throughout, like all coordinate math).

    src_xy: (N, H, W, 2); depth: (N,); g_flat: (N, 4, 4);
    k_inv_flat: (N, 3, 3). Returns (N, H, W, 3) target-frame plane xyz.
    """
    qx = jnp.clip(src_xy[..., 0:1], 0.0, float(w - 1))
    qy = jnp.clip(src_xy[..., 1:2], 0.0, float(h - 1))
    q_homo = jnp.concatenate([qx, qy, jnp.ones_like(qx)], axis=-1)
    m = jnp.einsum(
        "nij,njk->nik", g_flat[:, :3, :3], k_inv_flat, precision=_PRECISION
    ) * depth[:, None, None]
    return (
        jnp.einsum("nij,nhwj->nhwi", m, q_homo, precision=_PRECISION)
        + g_flat[:, None, None, :3, 3]
    )


@_scoped("homography_warp")
def plane_tgt_xyz(
    depth: Array, g_tgt_src: Array, k_src_inv: Array, k_tgt: Array,
    h: int, w: int,
) -> Array:
    """Target-frame xyz of ONE plane per batch item at its own warp coords —
    pure coordinate math, no gather. depth: (B,). Returns (B, H, W, 3).

    Bitwise-identical to the xyz warp_mpi_to_tgt produces for the same plane
    (same homography + affine formulas on the same inputs), which is what
    lets the streaming scan compute the chunk-boundary halo plane's xyz
    without touching the next chunk's payload.
    """
    src_xy, _ = homography_sample_coords(
        depth, g_tgt_src, k_src_inv, k_tgt, h, w
    )
    return _affine_tgt_xyz(src_xy, depth, g_tgt_src, k_src_inv, h, w)


@_scoped("homography_warp")
def warp_mpi_to_tgt(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
) -> tuple[Array, Array, Array, Array]:
    """Homography-warp every source plane into the target camera
    (the per-plane half of mpi_rendering.py:181-241 — embarrassingly parallel
    over S, so a plane-sharded mesh runs it on local planes unchanged).

    Only rgb + sigma (4 channels) ride the gather. The reference also warps
    the 3 target-frame xyz channels (mpi_rendering.py:207-219), but per plane
    xyz is AFFINE in source pixel coords — xyz_tgt(q) = depth * (R K^-1)
    [qx, qy, 1] + t, no cross term — and bilinear sampling with border clamp
    of a per-axis-affine field is EXACTLY the field evaluated at the
    per-axis-clamped sample location (corner values interpolate back to the
    affine; clamped corners make both corners equal, reproducing the clamp).
    So the xyz half of the warp is 9 fused FMAs per pixel instead of gather
    bandwidth: the hot op's payload shrinks 7 -> 4 channels and the
    (B, S, H, W, 3) xyz_tgt tensor is never materialized in the source
    frame at all.

    Shapes as in render_tgt_rgb_depth (S may be a local plane chunk).
    Returns (tgt_rgb, tgt_sigma, tgt_xyz, valid) with behind-camera sigma
    already zeroed (mpi_rendering.py:232-235); valid is (B, S, H, W).
    """
    b, s, h, w, _ = mpi_rgb_src.shape
    depth = 1.0 / mpi_disparity_src  # (B, S)

    payload = jnp.concatenate([mpi_rgb_src, mpi_sigma_src], axis=-1)
    payload = payload.reshape(b * s, h, w, 4)

    tile = lambda m: jnp.repeat(m, s, axis=0)  # (B, ...) -> (B*S, ...)
    g_flat = tile(g_tgt_src)
    k_inv_flat = tile(k_src_inv)
    src_xy, valid = homography_sample_coords(
        depth.reshape(b * s), g_flat, k_inv_flat, tile(k_tgt), h, w
    )
    warped = grid_sample_pixel(payload, src_xy).astype(payload.dtype)

    tgt_xyz = _affine_tgt_xyz(
        src_xy, depth.reshape(b * s), g_flat, k_inv_flat, h, w
    )

    warped = warped.reshape(b, s, h, w, 4)
    valid = valid.reshape(b, s, h, w)
    tgt_xyz = tgt_xyz.reshape(b, s, h, w, 3)

    tgt_rgb = warped[..., 0:3]
    tgt_sigma = warped[..., 3:4]

    # planes behind the target camera contribute nothing
    # (mpi_rendering.py:232-235)
    tgt_sigma = jnp.where(tgt_xyz[..., 2:3] >= 0.0, tgt_sigma, 0.0)
    return tgt_rgb, tgt_sigma, tgt_xyz, valid


def render_tgt_rgb_depth(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array]:
    """Warp the source MPI into the target camera and composite
    (mpi_rendering.py:181-241). The target-frame xyz the compositor needs is
    evaluated analytically at the warp coords inside warp_mpi_to_tgt, so —
    unlike the reference — no source-frame xyz tensor enters this function.

    Args:
      mpi_rgb_src: (B, S, H, W, 3); mpi_sigma_src: (B, S, H, W, 1).
      mpi_disparity_src: (B, S).
      g_tgt_src: (B, 4, 4); k_src_inv/k_tgt: (B, 3, 3).
    Returns:
      tgt_rgb (B, H, W, 3), tgt_depth (B, H, W, 1),
      tgt_mask (B, H, W, 1) — number of planes whose warp lands in-FoV.
    """
    tgt_rgb, tgt_sigma, tgt_xyz, valid = warp_mpi_to_tgt(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
    )
    tgt_rgb_syn, tgt_depth_syn, _, _ = render(
        tgt_rgb, tgt_sigma, tgt_xyz, use_alpha=use_alpha, is_bg_depth_inf=is_bg_depth_inf
    )
    tgt_mask = jnp.sum(valid.astype(mpi_rgb_src.dtype), axis=1)[..., None]
    return tgt_rgb_syn, tgt_depth_syn, tgt_mask


class Compositor(NamedTuple):
    """The S-axis reduction primitives the loss graph composites through.

    The default (DENSE_COMPOSITOR) reduces over a whole in-memory plane axis;
    mine_tpu/parallel/plane_sharding.py builds the plane-sharded twin whose
    reductions cross the mesh's `plane` axis. Swapping this triple is the
    entire difference between the unsharded and plane-parallel train steps —
    the loss graph itself is oblivious (SURVEY.md §5.7).
    """

    render_src: Callable
    weighted_sum_src: Callable
    render_tgt_rgb_depth: Callable


DENSE_COMPOSITOR = Compositor(render_src, weighted_sum_src, render_tgt_rgb_depth)


# -- streaming target compositor ---------------------------------------------
#
# render_tgt_rgb_depth materializes every warped plane before compositing —
# the reference's memory ceiling ("memory consumption is huge, only one
# supervision is allowed", synthesis_task.py:203-204), inherited by the dense
# path: at the LLFF recipe (384x512, S=32, fp32) the warped rgb+sigma+xyz
# intermediates are ~125 MB per batch item, all HBM round-trips. But
# over-compositing is a prefix product over S, so the plane axis can be
# STREAMED: a lax.scan over plane chunks carrying only the running
# (rgb, depth-z, weight, mask, transmittance) accumulators — O(H·W) working
# set instead of O(S·H·W); the (B, S, H, W, C) warped tensors never exist.
# The chunk boundary needs exactly one halo quantity: the next chunk's first
# plane's xyz, which is analytic in its depth (plane_tgt_xyz) — a (B,)
# scalar ships where the reference would ship a plane.


def _chunk_size(s: int, requested: int) -> int:
    """Largest divisor of the plane count <= the requested chunk size, so an
    odd S (e.g. a coarse+fine merge) degrades to smaller chunks instead of
    failing; >= 1 always."""
    requested = max(1, min(int(requested), s))
    for d in range(requested, 0, -1):
        if s % d == 0:
            return d
    return 1


def _stream_scan(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    halo_depth: Array,
    bg_on_last,
    use_alpha: bool,
    chunk: int,
) -> tuple[Array, Array, Array, Array, Array]:
    """The chunked streaming composite over the plane axis (core of both the
    unsharded streaming compositor and the plane-sharded local scan).

    Scans S/chunk chunks carrying only (B, H, W, ·) accumulators; each step
    warps a (B, chunk, H, W, ·) slab that dies at the next step, and the
    body is jax.checkpoint'd so the reverse scan RECOMPUTES the per-plane
    warps instead of saving them — neither pass holds (B, S, H, W, ·).

    halo_depth: (B,) depth of the plane AFTER the last plane here (any value
    when bg_on_last puts the background pseudo-distance there instead).
    bg_on_last: bool (python or traced) — whether the globally-last plane
    lives in this plane range (False on all but the last device of a
    plane-sharded mesh).

    Returns (rgb_sum, z_sum, weight_sum, mask_sum, trans_total) with initial
    transmittance 1. Every sum is LINEAR in the incoming transmittance, so a
    plane-sharded caller scales the partials by its cross-device exclusive
    prefix afterwards (parallel/plane_sharding.py).
    """
    b, s, h, w, _ = mpi_rgb_src.shape
    assert s % chunk == 0, (s, chunk)
    n_chunks = s // chunk
    depth = 1.0 / mpi_disparity_src  # (B, S)

    def chunked(x: Array) -> Array:  # (B, S, ...) -> (n_chunks, B, chunk, ...)
        xm = jnp.moveaxis(x, 1, 0)
        return jnp.moveaxis(xm.reshape((n_chunks, chunk) + xm.shape[1:]), 1, 2)

    # depth of the plane after each chunk's last plane: the next chunk's
    # first plane; the trailing chunk takes the caller's halo
    depth_chunk_first = depth.reshape(b, n_chunks, chunk)[:, 1:, 0]  # (B, n-1)
    next_depth = jnp.concatenate(
        [jnp.moveaxis(depth_chunk_first, 1, 0), halo_depth[None]], axis=0
    )  # (n_chunks, B)
    xs = {
        "rgb": chunked(mpi_rgb_src),
        "sigma": chunked(mpi_sigma_src),
        "disp": jnp.moveaxis(
            mpi_disparity_src.reshape(b, n_chunks, chunk), 1, 0
        ),
        "next_depth": next_depth,
        "is_last": jnp.arange(n_chunks) == n_chunks - 1,
    }

    last_plane = (jnp.arange(chunk) == chunk - 1).reshape(1, chunk, 1, 1, 1)

    def body(carry, x):
        rgb_acc, z_acc, w_acc, m_acc, t_acc = carry
        tgt_rgb, tgt_sigma, tgt_xyz, valid = warp_mpi_to_tgt(
            x["rgb"], x["sigma"], x["disp"], g_tgt_src, k_src_inv, k_tgt
        )
        # everything past the warp is compositing math (the warp call above
        # carries its own homography_warp scope)
        with jax.named_scope("composite"):
            z = tgt_xyz[..., 2:3]  # (B, chunk, H, W, 1)
            if use_alpha:
                alpha = tgt_sigma
                trans_local = jnp.cumprod(1.0 - alpha, axis=1)
            else:
                xyz_next = plane_tgt_xyz(
                    x["next_depth"], g_tgt_src, k_src_inv, k_tgt, h, w
                )
                xyz_ext = jnp.concatenate([tgt_xyz, xyz_next[:, None]], axis=1)
                diff = xyz_ext[:, 1:] - xyz_ext[:, :-1]
                # the background slot's diff must be replaced BEFORE the norm
                # (d||v||/dv at v=0 is 0/0 — same NaN-cotangent guard as
                # parallel/plane_sharding.py)
                bg_mask = jnp.logical_and(
                    jnp.logical_and(x["is_last"], bg_on_last), last_plane
                )
                diff = jnp.where(bg_mask, 1.0, diff)
                dist = jnp.linalg.norm(diff, axis=-1, keepdims=True)
                dist = jnp.where(bg_mask, _BG_DIST, dist)
                transparency = jnp.exp(-tgt_sigma * dist)
                alpha = 1.0 - transparency
                trans_local = jnp.cumprod(transparency + 1.0e-6, axis=1)
            weights = t_acc[:, None] * _shifted_exclusive(trans_local) * alpha
            return (
                rgb_acc + jnp.sum(weights * tgt_rgb, axis=1),
                z_acc + jnp.sum(weights * z, axis=1),
                w_acc + jnp.sum(weights, axis=1),
                m_acc + jnp.sum(valid.astype(mpi_rgb_src.dtype), axis=1),
                t_acc * trans_local[:, -1],
            ), None

    dtype = mpi_rgb_src.dtype
    init = (
        jnp.zeros((b, h, w, 3), dtype),
        jnp.zeros((b, h, w, 1), dtype),
        jnp.zeros((b, h, w, 1), dtype),
        jnp.zeros((b, h, w), dtype),
        jnp.ones((b, h, w, 1), dtype),
    )
    carry, _ = lax.scan(jax.checkpoint(body), init, xs)
    return carry


def _finalize_depth(
    z_sum: Array, w_sum: Array, use_alpha: bool, is_bg_depth_inf: bool
) -> Array:
    """Composited z partial sums -> depth, matching the dense reductions
    (alpha_composition / weighted_sum_mpi tails)."""
    if use_alpha:
        return z_sum
    if is_bg_depth_inf:
        return z_sum + (1.0 - w_sum) * 1000.0
    return z_sum / (w_sum + 1.0e-5)


def _render_tgt_scan(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
    chunk_planes: int = DEFAULT_STREAM_CHUNK,
) -> tuple[Array, Array, Array]:
    """The pure-scan streaming twin of render_tgt_rgb_depth (same contract)."""
    depth = 1.0 / mpi_disparity_src
    chunk = _chunk_size(mpi_rgb_src.shape[1], chunk_planes)
    rgb_sum, z_sum, w_sum, mask, _ = _stream_scan(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
        halo_depth=depth[:, -1], bg_on_last=True,
        use_alpha=use_alpha, chunk=chunk,
    )
    depth_out = _finalize_depth(z_sum, w_sum, use_alpha, is_bg_depth_inf)
    return rgb_sum, depth_out, mask[..., None]


# tests force the fused Pallas path in interpret mode through this flag
# (Mosaic itself is TPU-only); production dispatch is _fused_engaged
_FORCE_FUSED_INTERPRET = False


def _fused_engaged() -> bool:
    """The fused warp-composite Pallas kernel runs on TPU unless opted out
    (same escape-hatch idiom as the warp kernels, ops/grid_sample.py)."""
    if _FORCE_FUSED_INTERPRET:
        return True
    return (
        jax.default_backend() == "tpu"
        and os.environ.get("MINE_TPU_DISABLE_FUSED_COMPOSITE", "").lower()
        not in ("1", "true", "yes", "on")
    )


def _fused_forward(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    is_bg_depth_inf: bool,
) -> tuple[Array, Array, Array]:
    """Forward streaming composite through the fused warp-composite kernel
    (ops/pallas/warp.py warp_composite_chw): per output tile the kernel
    DMA's each plane's source band, gathers, and over-composites into
    resident VMEM accumulators — one HBM pass for the whole sweep, no warped
    (B, S, H, W, C) tensor and none of the dense path's cumprod-chain
    intermediates. The coordinate prep (coords/dist/z, ~4 floats per plane
    pixel) is the only S-sized traffic besides the MPI itself."""
    from mine_tpu.ops.pallas.warp import warp_composite_chw

    b, s, h, w, _ = mpi_rgb_src.shape
    with jax.named_scope("homography_warp"):
        depth = (1.0 / mpi_disparity_src).reshape(b * s)
        tile = lambda m: jnp.repeat(m, s, axis=0)  # noqa: E731
        g_flat = tile(g_tgt_src)
        k_inv_flat = tile(k_src_inv)
        src_xy, _ = homography_sample_coords(
            depth, g_flat, k_inv_flat, tile(k_tgt), h, w
        )
        xyz = _affine_tgt_xyz(src_xy, depth, g_flat, k_inv_flat, h, w)
        xyz = xyz.reshape(b, s, h, w, 3)
        dist = jnp.linalg.norm(xyz[:, 1:] - xyz[:, :-1], axis=-1)
        dist = jnp.concatenate(
            [dist, jnp.full_like(dist[:, :1], _BG_DIST)], axis=1
        )  # (B, S, H, W)

    with jax.named_scope("composite"):
        payload = jnp.concatenate([mpi_rgb_src, mpi_sigma_src], axis=-1)
        payload = jnp.moveaxis(payload, -1, 2)  # (B, S, 4, H, W)
        coords = src_xy.reshape(b, s, h, w, 2)
        acc = warp_composite_chw(
            payload, coords[..., 0], coords[..., 1], dist, xyz[..., 2],
            interpret=_FORCE_FUSED_INTERPRET,
        )  # (B, 7, H, W): rgb(3), z_sum, w_sum, valid count, transmittance
        rgb_out = jnp.moveaxis(acc[:, 0:3], 1, -1)
        z_sum = acc[:, 3][..., None]
        w_sum = acc[:, 4][..., None]
        mask = acc[:, 5][..., None]
        depth_out = _finalize_depth(
            z_sum, w_sum, use_alpha=False, is_bg_depth_inf=is_bg_depth_inf
        )
    return rgb_out, depth_out, mask


@partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _render_tgt_fused(
    mpi_rgb_src, mpi_sigma_src, mpi_disparity_src, g_tgt_src, k_src_inv,
    k_tgt, is_bg_depth_inf, chunk_planes,
):
    """Fused-forward / scan-recompute-backward streaming render: the Pallas
    kernel owns the forward sweep, and the backward re-runs the chunked scan
    under jax.vjp — the per-plane warps are recomputed in the reverse scan,
    never saved (the remat discipline the scan path already has)."""
    return _fused_forward(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt, is_bg_depth_inf,
    )


def _render_tgt_fused_fwd(
    mpi_rgb_src, mpi_sigma_src, mpi_disparity_src, g_tgt_src, k_src_inv,
    k_tgt, is_bg_depth_inf, chunk_planes,
):
    out = _fused_forward(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt, is_bg_depth_inf,
    )
    res = (mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
           g_tgt_src, k_src_inv, k_tgt)
    return out, res


def _render_tgt_fused_bwd(is_bg_depth_inf, chunk_planes, res, ct):
    def scan_path(*args):
        return _render_tgt_scan(
            *args, use_alpha=False, is_bg_depth_inf=is_bg_depth_inf,
            chunk_planes=chunk_planes,
        )

    _, vjp = jax.vjp(scan_path, *res)
    return vjp(ct)


_render_tgt_fused.defvjp(_render_tgt_fused_fwd, _render_tgt_fused_bwd)


def render_tgt_rgb_depth_streaming(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
    chunk_planes: int = DEFAULT_STREAM_CHUNK,
) -> tuple[Array, Array, Array]:
    """Streaming twin of render_tgt_rgb_depth — same signature, same outputs
    to fp-reassociation precision (the chunked prefix product rounds in a
    different order; parity pinned at 1e-5 by tests/test_mpi_render.py).

    On TPU the sigma-compositing forward runs through the fused
    warp-composite Pallas kernel (one HBM pass per sweep); everywhere else —
    and for every backward — a jax.checkpoint'd lax.scan over plane chunks
    keeps the working set at O(chunk·H·W).
    """
    chunk = _chunk_size(mpi_rgb_src.shape[1], chunk_planes)
    if not use_alpha and _fused_engaged():
        return _render_tgt_fused(
            mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
            g_tgt_src, k_src_inv, k_tgt, is_bg_depth_inf, chunk,
        )
    return _render_tgt_scan(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
        use_alpha=use_alpha, is_bg_depth_inf=is_bg_depth_inf,
        chunk_planes=chunk,
    )


def streaming_compositor(
    chunk_planes: int = DEFAULT_STREAM_CHUNK,
) -> Compositor:
    """The streaming peer of DENSE_COMPOSITOR. Only the target-view render
    streams: the source sweep's per-plane WEIGHTS feed src-RGB blending
    (training/step.py loss_fcn_per_scale), so render_src must keep them
    materialized — and it already builds no (B, S, H, W, 3) xyz (its
    distances factor into an (S,) x (H, W) product)."""
    return Compositor(
        render_src,
        weighted_sum_src,
        partial(render_tgt_rgb_depth_streaming, chunk_planes=chunk_planes),
    )


STREAMING_COMPOSITOR = streaming_compositor()


def compositor_from_config(cfg) -> Compositor:
    """Resolve cfg.mpi.compositor ("dense" | "streaming") to the matching
    unsharded Compositor; the plane-sharded twin is resolved by
    parallel/data_parallel.py from the same knob. A numerics no-op
    (PARITY.md): the two agree to fp-reassociation precision."""
    name = cfg.mpi.compositor
    if name == "dense":
        return DENSE_COMPOSITOR
    if name == "streaming":
        return streaming_compositor(cfg.mpi.stream_chunk_planes)
    raise ValueError(
        f"mpi.compositor={name!r} must be 'dense' or 'streaming'"
    )
