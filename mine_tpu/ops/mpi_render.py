"""MPI compositing: NeRF-style plane volume rendering and alpha composition.

Reference: operations/mpi_rendering.py:7-82 (render / alpha_composition /
plane_volume_rendering / weighted_sum_mpi) and :181-241 (render_tgt_rgb_depth).

Layout is channel-last (B, S, H, W, C); the plane axis S is axis 1 and all
scans/cumprods run over it. On a plane-sharded mesh the same math is provided
by mine_tpu/parallel/plane_sharding.py with an explicit cross-device prefix.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax.numpy as jnp
from jax import Array

from mine_tpu.ops.geometry import _PRECISION, homogeneous_pixel_grid
from mine_tpu.ops.homography import homography_sample_coords
from mine_tpu.ops.grid_sample import grid_sample_pixel

_BG_DIST = 1.0e3  # pseudo-distance behind the farthest plane (mpi_rendering.py:50)


def _shifted_exclusive(x: Array, fill: float = 1.0) -> Array:
    """[a, b, c] -> [fill, a, b] along the plane axis (axis=1)."""
    ones = jnp.full_like(x[:, :1], fill)
    return jnp.concatenate([ones, x[:, :-1]], axis=1)


def alpha_composition(alpha: Array, value: Array) -> tuple[Array, Array]:
    """Over-compositing of K planes, nearest first (mpi_rendering.py:23-39).

    alpha: (B, K, H, W, 1); value: (B, K, H, W, C).
    Returns composed (B, H, W, C) and per-plane weights (B, K, H, W, 1).
    """
    preserve = _shifted_exclusive(jnp.cumprod(1.0 - alpha, axis=1))
    weights = alpha * preserve
    return jnp.sum(value * weights, axis=1), weights


def weighted_sum_mpi(
    rgb: Array, xyz: Array, weights: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array]:
    """Expectation of rgb and depth under compositing weights
    (mpi_rendering.py:70-82).

    rgb/xyz: (B, S, H, W, 3); weights: (B, S, H, W, 1).
    Returns rgb_out (B, H, W, 3), depth_out (B, H, W, 1).
    """
    weights_sum = jnp.sum(weights, axis=1)  # (B, H, W, 1)
    rgb_out = jnp.sum(weights * rgb, axis=1)
    z = xyz[..., 2:3]
    if is_bg_depth_inf:
        depth_out = jnp.sum(weights * z, axis=1) + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = jnp.sum(weights * z, axis=1) / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


def plane_volume_rendering(
    rgb: Array, sigma: Array, xyz: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array, Array, Array]:
    """NeRF-style volume rendering across depth planes (mpi_rendering.py:42-67).

    Per-pixel inter-plane distances turn sigma into transparency
    T = exp(-sigma * dist); transmittance is a shifted cumprod over planes.

    rgb: (B, S, H, W, 3); sigma: (B, S, H, W, 1); xyz: (B, S, H, W, 3).
    Returns (rgb_out, depth_out, transparency_acc, weights).
    """
    diff = xyz[:, 1:] - xyz[:, :-1]  # (B, S-1, H, W, 3)
    dist = jnp.linalg.norm(diff, axis=-1, keepdims=True)  # (B, S-1, H, W, 1)
    dist = jnp.concatenate(
        [dist, jnp.full_like(dist[:, :1], _BG_DIST)], axis=1
    )  # (B, S, H, W, 1)

    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    # eps keeps the accumulated transmittance away from exactly zero
    # (mpi_rendering.py:57-59)
    transparency_acc = _shifted_exclusive(jnp.cumprod(transparency + 1.0e-6, axis=1))
    weights = transparency_acc * alpha

    rgb_out, depth_out = weighted_sum_mpi(rgb, xyz, weights, is_bg_depth_inf)
    return rgb_out, depth_out, transparency_acc, weights


def render(
    rgb: Array,
    sigma: Array,
    xyz: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """Dispatch sigma-vs-alpha compositing (mpi_rendering.py:7-20).

    Returns (imgs_syn, depth_syn, blend_weights, weights). With use_alpha the
    blend weights are zeros (no src-RGB blending path), as in the reference.
    """
    if not use_alpha:
        return plane_volume_rendering(rgb, sigma, xyz, is_bg_depth_inf)
    imgs_syn, weights = alpha_composition(sigma, rgb)
    depth_syn, _ = alpha_composition(sigma, xyz[..., 2:3])
    return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights


# -- source-pose fast path ---------------------------------------------------
#
# At the SOURCE pose the plane sweep is fronto-parallel: xyz_s(q) =
# depth_s * K^-1 [qx, qy, 1]. The reference materializes the full
# (B, S, H, W, 3) xyz tensor and takes norms of its plane-to-plane diffs
# (mpi_rendering.py:42-67 fed by :140-163); but the diff factors exactly —
#   xyz_{s+1}(q) - xyz_s(q) = (depth_{s+1} - depth_s) * K^-1 q
#   => dist_s(q) = |d_{s+1} - d_s| * ||K^-1 q||
# an (S,) vector times an (H, W) map — and per-plane z is the CONSTANT
# depth_s. So source-view compositing needs no per-plane xyz at all: S x
# less multiply work and no (B, S, H, W, 3) intermediates. Same math to ~1
# ulp (products are rounded in a different order).


def ray_norms(k_inv: Array, h: int, w: int) -> Array:
    """||K^-1 [x, y, 1]|| per pixel: (B, 3, 3) -> (B, H, W, 1)."""
    grid = homogeneous_pixel_grid(h, w, jnp.float32)
    rays = jnp.einsum("bij,hwj->bhwi", k_inv, grid, precision=_PRECISION)
    return jnp.linalg.norm(rays, axis=-1, keepdims=True)


def _src_dists(mpi_disparity: Array, k_inv: Array, h: int, w: int) -> Array:
    """Factored inter-plane distances for the source sweep:
    (B, S) disparities -> (B, S, H, W, 1) with the background pseudo-distance
    in the last slot (twin of the dist block in plane_volume_rendering)."""
    depth = 1.0 / mpi_disparity  # (B, S)
    ddiff = jnp.abs(depth[:, 1:] - depth[:, :-1])  # (B, S-1)
    dist = ddiff[:, :, None, None, None] * ray_norms(k_inv, h, w)[:, None]
    return jnp.concatenate(
        [dist, jnp.full_like(dist[:, :1], _BG_DIST)], axis=1
    )


def weighted_sum_src(
    rgb: Array, mpi_disparity: Array, weights: Array, is_bg_depth_inf: bool = False
) -> tuple[Array, Array]:
    """weighted_sum_mpi for the source sweep, where per-plane z is the
    constant plane depth (no xyz tensor).

    rgb: (B, S, H, W, 3); mpi_disparity: (B, S); weights: (B, S, H, W, 1).

    Assumes NORMALIZED intrinsics — K^-1's third row [0, 0, 1] — so that
    per-plane camera z equals the plane depth 1/disparity; the generic
    weighted_sum_mpi takes z from an explicit xyz tensor and carries no such
    assumption. Every shipped config satisfies it (scale_intrinsics keeps
    K[2,2] = 1); a non-standard K would silently skew depth outputs here.
    """
    z = (1.0 / mpi_disparity)[:, :, None, None, None]  # (B, S, 1, 1, 1)
    weights_sum = jnp.sum(weights, axis=1)
    rgb_out = jnp.sum(weights * rgb, axis=1)
    if is_bg_depth_inf:
        depth_out = jnp.sum(weights * z, axis=1) + (1.0 - weights_sum) * 1000.0
    else:
        depth_out = jnp.sum(weights * z, axis=1) / (weights_sum + 1.0e-5)
    return rgb_out, depth_out


def render_src(
    rgb: Array,
    sigma: Array,
    mpi_disparity: Array,
    k_inv: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array, Array]:
    """`render` at the source pose, from disparities + intrinsics alone.

    rgb: (B, S, H, W, 3); sigma: (B, S, H, W, 1); mpi_disparity: (B, S);
    k_inv: (B, 3, 3). Returns (imgs_syn, depth_syn, blend_weights, weights)
    exactly like `render`.

    Assumes normalized intrinsics (K[2,2] = 1): the factored distances and
    the per-plane z both use depth = 1/disparity as the camera-frame z —
    see weighted_sum_src.
    """
    h, w = rgb.shape[2], rgb.shape[3]
    if use_alpha:
        imgs_syn, weights = alpha_composition(sigma, rgb)
        z = jnp.broadcast_to(
            (1.0 / mpi_disparity)[:, :, None, None, None],
            sigma.shape,
        )
        depth_syn, _ = alpha_composition(sigma, z)
        return imgs_syn, depth_syn, jnp.zeros_like(rgb), weights

    dist = _src_dists(mpi_disparity, k_inv, h, w)
    transparency = jnp.exp(-sigma * dist)
    alpha = 1.0 - transparency
    transparency_acc = _shifted_exclusive(jnp.cumprod(transparency + 1.0e-6, axis=1))
    weights = transparency_acc * alpha
    rgb_out, depth_out = weighted_sum_src(rgb, mpi_disparity, weights, is_bg_depth_inf)
    return rgb_out, depth_out, transparency_acc, weights


def warp_mpi_to_tgt(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
) -> tuple[Array, Array, Array, Array]:
    """Homography-warp every source plane into the target camera
    (the per-plane half of mpi_rendering.py:181-241 — embarrassingly parallel
    over S, so a plane-sharded mesh runs it on local planes unchanged).

    Only rgb + sigma (4 channels) ride the gather. The reference also warps
    the 3 target-frame xyz channels (mpi_rendering.py:207-219), but per plane
    xyz is AFFINE in source pixel coords — xyz_tgt(q) = depth * (R K^-1)
    [qx, qy, 1] + t, no cross term — and bilinear sampling with border clamp
    of a per-axis-affine field is EXACTLY the field evaluated at the
    per-axis-clamped sample location (corner values interpolate back to the
    affine; clamped corners make both corners equal, reproducing the clamp).
    So the xyz half of the warp is 9 fused FMAs per pixel instead of gather
    bandwidth: the hot op's payload shrinks 7 -> 4 channels and the
    (B, S, H, W, 3) xyz_tgt tensor is never materialized in the source
    frame at all.

    Shapes as in render_tgt_rgb_depth (S may be a local plane chunk).
    Returns (tgt_rgb, tgt_sigma, tgt_xyz, valid) with behind-camera sigma
    already zeroed (mpi_rendering.py:232-235); valid is (B, S, H, W).
    """
    b, s, h, w, _ = mpi_rgb_src.shape
    depth = 1.0 / mpi_disparity_src  # (B, S)

    payload = jnp.concatenate([mpi_rgb_src, mpi_sigma_src], axis=-1)
    payload = payload.reshape(b * s, h, w, 4)

    tile = lambda m: jnp.repeat(m, s, axis=0)  # (B, ...) -> (B*S, ...)
    g_flat = tile(g_tgt_src)
    k_inv_flat = tile(k_src_inv)
    src_xy, valid = homography_sample_coords(
        depth.reshape(b * s), g_flat, k_inv_flat, tile(k_tgt), h, w
    )
    warped = grid_sample_pixel(payload, src_xy).astype(payload.dtype)

    # the analytic xyz sample: evaluate the per-plane affine at the clamped
    # coords (fp32 throughout, like all coordinate math)
    qx = jnp.clip(src_xy[..., 0:1], 0.0, float(w - 1))
    qy = jnp.clip(src_xy[..., 1:2], 0.0, float(h - 1))
    q_homo = jnp.concatenate([qx, qy, jnp.ones_like(qx)], axis=-1)
    m = jnp.einsum(
        "nij,njk->nik", g_flat[:, :3, :3], k_inv_flat, precision=_PRECISION
    ) * depth.reshape(b * s)[:, None, None]
    tgt_xyz = (
        jnp.einsum("nij,nhwj->nhwi", m, q_homo, precision=_PRECISION)
        + g_flat[:, None, None, :3, 3]
    )

    warped = warped.reshape(b, s, h, w, 4)
    valid = valid.reshape(b, s, h, w)
    tgt_xyz = tgt_xyz.reshape(b, s, h, w, 3)

    tgt_rgb = warped[..., 0:3]
    tgt_sigma = warped[..., 3:4]

    # planes behind the target camera contribute nothing
    # (mpi_rendering.py:232-235)
    tgt_sigma = jnp.where(tgt_xyz[..., 2:3] >= 0.0, tgt_sigma, 0.0)
    return tgt_rgb, tgt_sigma, tgt_xyz, valid


def render_tgt_rgb_depth(
    mpi_rgb_src: Array,
    mpi_sigma_src: Array,
    mpi_disparity_src: Array,
    g_tgt_src: Array,
    k_src_inv: Array,
    k_tgt: Array,
    use_alpha: bool = False,
    is_bg_depth_inf: bool = False,
) -> tuple[Array, Array, Array]:
    """Warp the source MPI into the target camera and composite
    (mpi_rendering.py:181-241). The target-frame xyz the compositor needs is
    evaluated analytically at the warp coords inside warp_mpi_to_tgt, so —
    unlike the reference — no source-frame xyz tensor enters this function.

    Args:
      mpi_rgb_src: (B, S, H, W, 3); mpi_sigma_src: (B, S, H, W, 1).
      mpi_disparity_src: (B, S).
      g_tgt_src: (B, 4, 4); k_src_inv/k_tgt: (B, 3, 3).
    Returns:
      tgt_rgb (B, H, W, 3), tgt_depth (B, H, W, 1),
      tgt_mask (B, H, W, 1) — number of planes whose warp lands in-FoV.
    """
    tgt_rgb, tgt_sigma, tgt_xyz, valid = warp_mpi_to_tgt(
        mpi_rgb_src, mpi_sigma_src, mpi_disparity_src,
        g_tgt_src, k_src_inv, k_tgt,
    )
    tgt_rgb_syn, tgt_depth_syn, _, _ = render(
        tgt_rgb, tgt_sigma, tgt_xyz, use_alpha=use_alpha, is_bg_depth_inf=is_bg_depth_inf
    )
    tgt_mask = jnp.sum(valid.astype(mpi_rgb_src.dtype), axis=1)[..., None]
    return tgt_rgb_syn, tgt_depth_syn, tgt_mask


class Compositor(NamedTuple):
    """The S-axis reduction primitives the loss graph composites through.

    The default (DENSE_COMPOSITOR) reduces over a whole in-memory plane axis;
    mine_tpu/parallel/plane_sharding.py builds the plane-sharded twin whose
    reductions cross the mesh's `plane` axis. Swapping this triple is the
    entire difference between the unsharded and plane-parallel train steps —
    the loss graph itself is oblivious (SURVEY.md §5.7).
    """

    render_src: Callable
    weighted_sum_src: Callable
    render_tgt_rgb_depth: Callable


DENSE_COMPOSITOR = Compositor(render_src, weighted_sum_src, render_tgt_rgb_depth)
