"""Stateless, jittable rendering / geometry ops (reference: operations/).

Everything here is a pure function of arrays: no modules, no cached buffers,
no device state. Plane-axis (S) batching is done with reshapes + vmap so XLA
sees one large batched op per step.
"""

from mine_tpu.ops.geometry import (
    inverse_3x3,
    inverse_se3,
    pixel_center_grid,
    homogeneous_pixel_grid,
    scale_intrinsics,
    transform_se3,
    get_src_xyz_from_plane_disparity,
    get_tgt_xyz_from_plane_disparity,
)
from mine_tpu.ops.grid_sample import grid_sample_pixel
from mine_tpu.ops.homography import (
    build_plane_homography,
    homography_sample,
    homography_sample_coords,
)
from mine_tpu.ops.mpi_render import (
    Compositor,
    DENSE_COMPOSITOR,
    STREAMING_COMPOSITOR,
    alpha_composition,
    compositor_from_config,
    plane_contributions,
    plane_tgt_xyz,
    plane_volume_rendering,
    ray_norms,
    render,
    render_src,
    render_tgt_rgb_depth,
    render_tgt_rgb_depth_streaming,
    streaming_compositor,
    warp_mpi_to_tgt,
    weighted_sum_mpi,
    weighted_sum_src,
)
from mine_tpu.ops.sampling import (
    uniform_disparity_from_linspace_bins,
    uniform_disparity_from_bins,
    fixed_disparity_linspace,
    sample_pdf,
    gather_pixel_by_pxpy,
)
