"""Scalar image metrics and the sparse-point scale calibration.

Reference: network/layers.py:48-51 (psnr), synthesis_task.py:214-223
(compute_scale_factor), :296-339 (log-disparity point losses).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def psnr(img1: Array, img2: Array, size_average: bool = True) -> Array:
    """Mean (or per-image (B,), when not size_average) PSNR over a batch of
    (B, H, W, C) images in [0, 1] (layers.py:48-51 — the reference averages
    per-image PSNRs, not PSNR of the pooled MSE)."""
    mse = jnp.mean((img1 - img2) ** 2, axis=(1, 2, 3))
    per_image = 20.0 * jnp.log10(1.0 / jnp.sqrt(mse))
    return jnp.mean(per_image) if size_average else per_image


def compute_scale_factor(disparity_syn_pt3d: Array, pt3d_disp: Array) -> Array:
    """Per-image scale between synthesized and COLMAP disparities
    (synthesis_task.py:214-223): exp(mean(log d_syn - log d_gt)).

    Both inputs (B, N, 1) or (B, N). Returns (B,).
    """
    log_ratio = jnp.log(disparity_syn_pt3d) - jnp.log(pt3d_disp)
    return jnp.exp(jnp.mean(log_ratio.reshape(log_ratio.shape[0], -1), axis=1))


def log_disparity_loss(
    disparity_syn_pt3d: Array, pt3d_disp: Array, scale_factor: Array,
    size_average: bool = True,
) -> Array:
    """L1 in log space between scale-calibrated synthesized disparity and
    sparse-point disparity (synthesis_task.py:325-339).

    disparity_syn_pt3d / pt3d_disp: (B, N, 1) or (B, N); scale_factor: (B,).
    Scalar, or per-image (B,) when not size_average (uniform N makes the
    decomposition exact).
    """
    b = disparity_syn_pt3d.shape[0]
    syn = disparity_syn_pt3d.reshape(b, -1)
    gt = pt3d_disp.reshape(b, -1)
    scaled = syn / scale_factor[:, None]
    per_image = jnp.mean(jnp.abs(jnp.log(scaled) - jnp.log(gt)), axis=1)
    return jnp.mean(per_image) if size_average else per_image
