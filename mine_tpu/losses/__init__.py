"""Losses and metrics (reference: network/ssim.py, network/layers.py,
synthesis_task.py loss assembly)."""

from mine_tpu.losses.ssim import ssim
from mine_tpu.losses.smoothness import (
    spatial_gradient,
    edge_aware_loss,
    edge_aware_loss_v2,
)
from mine_tpu.losses.metrics import psnr, compute_scale_factor, log_disparity_loss
from mine_tpu.losses.lpips import lpips, load_lpips_params
