"""LPIPS perceptual metric (VGG16 backbone), eval-only.

Reference: synthesis_task.py:93 constructs `lpips.LPIPS(net="vgg")` and calls
it on [0,1] images at val scale 0 only (:357-361). This module reimplements
that metric as a pure JAX function over an explicit weight pytree:

  * VGG16 features tapped after relu1_2 / relu2_2 / relu3_3 / relu4_3 /
    relu5_3 (the `features` indices 4/9/16/23/30 the lpips package slices);
  * per-tap channel-unit-normalization, squared diff, learned non-negative
    1x1 "lin" weights, spatial mean, sum over taps;
  * the lpips input scaling layer shift/scale constants.

Weights cannot be downloaded in this environment (zero egress); convert them
offline with tools/convert_lpips.py into an .npz and point
`training.lpips_weights` at it. With no weights available the metric is
disabled and reports 0.0 — the same value the reference logs for every
non-val step (synthesis_task.py:357-363).
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np
from jax import Array, lax

# channels per VGG16 conv layer; "M" marks 2x2 maxpools
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512)
# taps: feature index (in conv-only numbering) after which LPIPS reads features
_TAP_AFTER_CONV = (1, 3, 6, 9, 12)  # relu1_2, relu2_2, relu3_3, relu4_3, relu5_3
_TAP_CHANNELS = (64, 128, 256, 512, 512)

# lpips.ScalingLayer constants (input nominally in [-1, 1])
_SHIFT = np.array([-0.030, -0.088, -0.188], dtype=np.float32)
_SCALE = np.array([0.458, 0.448, 0.450], dtype=np.float32)


def load_lpips_params(path: str | None) -> dict | None:
    """Load converted LPIPS weights (.npz from tools/convert_lpips.py).

    Returns None when the path is unset — callers must then skip the metric
    (report 0.0), mirroring the reference's rank-gated LPIPS. A path that is
    set but does not exist raises (a typo must not silently zero the metric).
    """
    if not path:
        return None
    if not os.path.exists(path):
        raise FileNotFoundError(f"LPIPS weights not found: {path!r}")
    data = np.load(path)
    n_conv = sum(1 for c in _VGG16_CFG if c != "M")
    params = {
        "conv_w": [jnp.asarray(data[f"conv{i}_w"]) for i in range(n_conv)],
        "conv_b": [jnp.asarray(data[f"conv{i}_b"]) for i in range(n_conv)],
        "lin_w": [jnp.asarray(data[f"lin{i}_w"]) for i in range(len(_TAP_AFTER_CONV))],
    }
    for i, (w, c) in enumerate(zip(params["lin_w"], _TAP_CHANNELS)):
        if w.shape != (c,):
            raise ValueError(f"lin{i}_w shape {w.shape} != ({c},) in {path!r}")
    return params


def _conv3x3(x: Array, w: Array, b: Array) -> Array:
    return (
        lax.conv_general_dilated(
            x, w, (1, 1), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        + b
    )


def _vgg_taps(params: dict, x: Array) -> list[Array]:
    taps, conv_i = [], 0
    for c in _VGG16_CFG:
        if c == "M":
            x = lax.reduce_window(
                x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
            )
            continue
        x = jnp.maximum(_conv3x3(x, params["conv_w"][conv_i], params["conv_b"][conv_i]), 0.0)
        if conv_i in _TAP_AFTER_CONV:
            taps.append(x)
        conv_i += 1
    return taps


def lpips(
    params: dict, img1: Array, img2: Array, size_average: bool = True
) -> Array:
    """Mean (or per-image (B,), when not size_average) LPIPS distance
    between (B, H, W, 3) image batches.

    Like the reference call site, images are passed through unchanged (the
    reference feeds [0,1] images to an LPIPS configured for [-1,1] — a quirk
    kept for metric comparability).
    """
    b = img1.shape[0]
    # one batched VGG pass over both images (halves the conv count vs two)
    x = (jnp.concatenate([img1, img2], axis=0) - _SHIFT) / _SCALE
    total = jnp.zeros((b,), dtype=jnp.float32)
    for tap, lin_w in zip(_vgg_taps(params, x), params["lin_w"]):
        tap1, tap2 = tap[:b], tap[b:]
        n1 = tap1 * lax.rsqrt(jnp.sum(tap1**2, axis=-1, keepdims=True) + 1.0e-10)
        n2 = tap2 * lax.rsqrt(jnp.sum(tap2**2, axis=-1, keepdims=True) + 1.0e-10)
        diff = (n1 - n2) ** 2
        # lin layer: non-negative per-channel weights, 1x1 conv to 1 channel
        weighted = jnp.sum(diff * lin_w, axis=-1)  # (B, H, W)
        total = total + jnp.mean(weighted, axis=(1, 2))
    return jnp.mean(total) if size_average else total
