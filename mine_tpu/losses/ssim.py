"""Gaussian-window SSIM.

Reference: network/ssim.py:7-65 — 11x11 gaussian window (sigma 1.5), zero
padding of window//2, per-channel (depthwise) filtering, C1=0.01^2,
C2=0.03^2, mean over the full map.

TPU-first: the window is a compile-time constant folded into two depthwise
`lax.conv_general_dilated` calls (NHWC, feature_group_count=C); the five
torch convs collapse to the same convs over a stacked 5C-channel input so XLA
issues one conv instead of five.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np
from jax import Array, lax


@functools.lru_cache(maxsize=None)
def _gaussian_window(window_size: int, sigma: float) -> np.ndarray:
    """1D gaussian, normalized to sum 1 (ssim.py:7-9)."""
    x = np.arange(window_size) - window_size // 2
    g = np.exp(-(x**2) / (2.0 * sigma**2))
    return (g / g.sum()).astype(np.float32)


def _depthwise_filter(x: Array, window_size: int, sigma: float) -> Array:
    """Depthwise gaussian blur with zero padding, NHWC."""
    c = x.shape[-1]
    g = _gaussian_window(window_size, sigma)
    w2d = jnp.asarray(np.outer(g, g))  # (K, K)
    # (K, K, 1, C): HWIO with feature_group_count=C
    kernel = jnp.tile(w2d[:, :, None, None], (1, 1, 1, c)).astype(x.dtype)
    pad = window_size // 2
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


def ssim(
    img1: Array,
    img2: Array,
    window_size: int = 11,
    sigma: float = 1.5,
    size_average: bool = True,
) -> Array:
    """SSIM of two (B, H, W, C) images in [0, 1] (ssim.py:19-39).

    Returns a scalar (size_average) or per-image (B,) means.
    """
    c1 = 0.01**2
    c2 = 0.03**2

    # one fused depthwise conv over [img1, img2, img1^2, img2^2, img1*img2]
    stacked = jnp.concatenate(
        [img1, img2, img1 * img1, img2 * img2, img1 * img2], axis=-1
    )
    blurred = _depthwise_filter(stacked, window_size, sigma)
    c = img1.shape[-1]
    mu1, mu2, m11, m22, m12 = (
        blurred[..., i * c : (i + 1) * c] for i in range(5)
    )

    mu1_sq, mu2_sq, mu1_mu2 = mu1 * mu1, mu2 * mu2, mu1 * mu2
    sigma1_sq = m11 - mu1_sq
    sigma2_sq = m22 - mu2_sq
    sigma12 = m12 - mu1_mu2

    ssim_map = ((2.0 * mu1_mu2 + c1) * (2.0 * sigma12 + c2)) / (
        (mu1_sq + mu2_sq + c1) * (sigma1_sq + sigma2_sq + c2)
    )
    if size_average:
        return jnp.mean(ssim_map)
    return jnp.mean(ssim_map, axis=(1, 2, 3))
