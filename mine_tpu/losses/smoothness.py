"""Edge-aware disparity smoothness losses.

Reference: network/layers.py:54-99. v1 uses kornia `spatial_gradient` (sobel,
replicate padding; normalized /8 for the image, unnormalized for disparity)
plus instance-normalized disparity gradients hinged at `gmin`, masked away
from image edges. v2 is the monodepth2-style mean-normalized first-difference
smoothness.

TPU-first: sobel is a fixed-weight depthwise `lax.conv_general_dilated`
(NHWC); there is no library dependency (kornia's role collapses to an 8-tap
constant kernel XLA folds into the surrounding graph).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import Array, lax

_SOBEL_X = np.array(
    [[-1.0, 0.0, 1.0], [-2.0, 0.0, 2.0], [-1.0, 0.0, 1.0]], dtype=np.float32
)


def spatial_gradient(x: Array, normalized: bool = True) -> tuple[Array, Array]:
    """Sobel x/y gradients of (B, H, W, C), replicate-padded.

    Matches kornia.filters.spatial_gradient (mode='sobel', order=1) as called
    at layers.py:56 and :69: cross-correlation with [[-1,0,1],[-2,0,2],
    [-1,0,1]] (x) and its transpose (y), each divided by 8 when `normalized`.
    Returns (grad_x, grad_y), both (B, H, W, C).
    """
    kx = _SOBEL_X / 8.0 if normalized else _SOBEL_X
    ky = kx.T
    c = x.shape[-1]
    # stack both directions as a depthwise kernel with 2 outputs per channel
    k = np.stack([kx, ky], axis=-1)  # (3, 3, 2)
    kernel = jnp.asarray(
        np.tile(k[:, :, None, :], (1, 1, 1, c)).reshape(3, 3, 1, 2 * c)
    ).astype(x.dtype)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="edge")
    out = lax.conv_general_dilated(
        xp,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )  # (B, H, W, 2C), interleaved [x, y] per channel
    out = out.reshape(*out.shape[:-1], c, 2)
    return out[..., 0], out[..., 1]


def _instance_norm(x: Array, eps: float = 1.0e-5) -> Array:
    """F.instance_norm without affine: per-(B, C) spatial standardization."""
    mean = jnp.mean(x, axis=(1, 2), keepdims=True)
    var = jnp.var(x, axis=(1, 2), keepdims=True)
    return (x - mean) * lax.rsqrt(var + eps)


def edge_aware_loss(
    img: Array, disp: Array, gmin: float, grad_ratio: float = 0.1,
    size_average: bool = True,
) -> Array:
    """Hinged, edge-masked smoothness (layers.py:54-80).

    img: (B, H, W, 3); disp: (B, H, W, 1).
    Image-gradient magnitudes (summed over channels, normalized by the per-
    image max * grad_ratio, clipped at 1) gate an instance-normalized
    disparity-gradient hinge at gmin.

    Returns a scalar (size_average) or per-image (B,) means — the pixel
    count is uniform across the batch, so the scalar equals the mean of the
    per-image values (the decomposition the masked val eval relies on).
    """
    gx, gy = spatial_gradient(img, normalized=True)
    grad_img_x = jnp.sum(jnp.abs(gx), axis=-1, keepdims=True)  # (B, H, W, 1)
    grad_img_y = jnp.sum(jnp.abs(gy), axis=-1, keepdims=True)
    max_x = jnp.max(grad_img_x, axis=(1, 2, 3), keepdims=True)
    max_y = jnp.max(grad_img_y, axis=(1, 2, 3), keepdims=True)
    edge_mask_x = jnp.minimum(grad_img_x / (max_x * grad_ratio), 1.0)
    edge_mask_y = jnp.minimum(grad_img_y / (max_y * grad_ratio), 1.0)

    dx, dy = spatial_gradient(disp, normalized=False)
    grad_disp_x = _instance_norm(jnp.abs(dx)) - gmin
    grad_disp_y = _instance_norm(jnp.abs(dy)) - gmin

    loss_x = jnp.maximum(grad_disp_x, 0.0) * (1.0 - edge_mask_x)
    loss_y = jnp.maximum(grad_disp_y, 0.0) * (1.0 - edge_mask_y)
    if size_average:
        return jnp.mean(loss_x + loss_y)
    return jnp.mean(loss_x + loss_y, axis=(1, 2, 3))


def edge_aware_loss_v2(
    img: Array, disp: Array, size_average: bool = True
) -> Array:
    """monodepth2-style mean-normalized smoothness (layers.py:83-99).

    img: (B, H, W, 3); disp: (B, H, W, 1). Scalar, or per-image (B,) when
    not size_average (see edge_aware_loss on why the decomposition is
    exact).
    """
    mean_disp = jnp.mean(disp, axis=(1, 2), keepdims=True)
    disp = disp / (mean_disp + 1.0e-7)

    grad_disp_x = jnp.abs(disp[:, :, :-1] - disp[:, :, 1:])
    grad_disp_y = jnp.abs(disp[:, :-1] - disp[:, 1:])

    grad_img_x = jnp.mean(
        jnp.abs(img[:, :, :-1] - img[:, :, 1:]), axis=-1, keepdims=True
    )
    grad_img_y = jnp.mean(
        jnp.abs(img[:, :-1] - img[:, 1:]), axis=-1, keepdims=True
    )

    axes = (1, 2, 3) if not size_average else None
    return jnp.mean(grad_disp_x * jnp.exp(-grad_img_x), axis=axes) + jnp.mean(
        grad_disp_y * jnp.exp(-grad_img_y), axis=axes
    )
