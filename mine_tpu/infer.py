"""Inference CLI: one image -> camera-path novel-view videos.

    python -m mine_tpu.infer --checkpoint workspace/run --image photo.png \
        --output_dir out/

Reference entry point: visualizations/image_to_video.py:260-315 (loads the
params.yaml paired with the checkpoint, fabricates a fov-90 camera, renders
zoom-in + swing trajectories to video). `--checkpoint` is the training
workspace directory (containing params.yaml and checkpoints/), matching this
framework's orbax layout rather than a single .pth path.
"""

from __future__ import annotations

import argparse
import os


def load_image(path: str):
    import numpy as np
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


def main(argv: list[str] | None = None) -> list[str]:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--checkpoint", required=True,
        help="training workspace dir (params.yaml + checkpoints/)",
    )
    parser.add_argument("--image", required=True, help="input rgb image")
    parser.add_argument("--output_dir", required=True)
    parser.add_argument(
        "--fov", type=float, default=90.0,
        help="assumed horizontal field of view in degrees "
        "(the reference hardcodes 90, image_to_video.py:195)",
    )
    parser.add_argument(
        "--allow-random-init", action="store_true",
        help="render with untrained weights when no checkpoint exists "
        "(smoke runs only)",
    )
    args = parser.parse_args(argv)

    from mine_tpu.utils.platform import honor_jax_platforms

    honor_jax_platforms()

    from mine_tpu.inference import load_video_generator

    generator = load_video_generator(
        args.checkpoint,
        load_image(args.image),
        fov_deg=args.fov,
        allow_random_init=args.allow_random_init,
    )
    basename = os.path.splitext(os.path.basename(args.image))[0]
    written = generator.render_videos(args.output_dir, basename)
    for path in written:
        print(path)
    return written


if __name__ == "__main__":
    main()
