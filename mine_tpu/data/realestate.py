"""RealEstate10K pipeline — MINE's headline dataset (PAPER.md), absent from
the reference fork (its train.py raises NotImplementedError for
realestate10k).

Protocol: the camera-trajectory txt format defined with the dataset and
used by the single-view MPI line of work ("Single-View View Synthesis with
Multiplane Images", arxiv 2004.11364, §4) that MINE's RealEstate10K
results follow:

  * `<root>/<split>/<sequence>.txt` — line 1 is the source video URL;
    every following line is one frame:
    `timestamp fx fy cx cy k1 k2 p11 p12 p13 p14 ... p34`
    where (fx, fy, cx, cy) are intrinsics NORMALIZED by image width/height
    and p11..p34 is the row-major 3x4 world-to-camera pose.
  * `<root>/frames/<sequence>/<timestamp>.png` — the extracted frames.
  * `<root>/points/<sequence>.npz` (key `xyz`, (N, 3) world points) — the
    SfM sparse cloud MINE's scale-invariant depth supervision needs
    (realestate10k is NOT in training/step.py NO_DISP_SUPERVISION: the
    headline protocol trains WITH sparse-depth calibration, so a missing
    cloud is a loud error, not a silently weaker recipe).

Normalized intrinsics are resolution-independent, so K at the target
(img_h, img_w) is exact with no stored-resolution bookkeeping — the one
convention difference from the COLMAP loaders (data/conformance/ records
it in the LoaderContract).

Per-frame sparse points are the world cloud transformed to the camera,
culled to in-view (z past the shared near cull, projecting inside the
image): the cloud is sequence-global, and an out-of-view point would
gather its 1/z supervision from a clamped border pixel.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data.frames import (
    PosedFrame,
    PosedFrameDataset,
    cull_near_points,
)

# target candidates: same-sequence frames within this many list positions —
# the small-baseline pair sampling the RealEstate10K MPI protocol trains on
# (2004.11364 samples nearby video frames)
FRAME_WINDOW = 10


@dataclass
class CameraLine:
    timestamp: str
    k_norm: np.ndarray  # (fx, fy, cx, cy) normalized by (W, H, W, H)
    g_cam_world: np.ndarray  # (4, 4) world -> camera


def parse_camera_file(path: str) -> tuple[str, list[CameraLine]]:
    """One sequence txt -> (video url, per-frame camera lines). Fails with
    the offending line number on malformed rows (truncated downloads are
    the common real-world corruption)."""
    with open(path) as fh:
        lines = [ln.strip() for ln in fh if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty camera file")
    url, rows = lines[0], []
    for lineno, line in enumerate(lines[1:], start=2):
        parts = line.split()
        if len(parts) != 19:
            raise ValueError(
                f"{path}:{lineno}: expected 19 fields "
                f"(timestamp, 4 intrinsics, 2 distortion, 12 pose), got "
                f"{len(parts)}"
            )
        vals = np.asarray([float(v) for v in parts[1:]], np.float64)
        g = np.eye(4, dtype=np.float64)
        g[:3, :4] = vals[6:18].reshape(3, 4)
        rows.append(CameraLine(
            timestamp=parts[0], k_norm=vals[0:4], g_cam_world=g,
        ))
    return url, rows


def _pixel_intrinsics(k_norm: np.ndarray, img_hw: tuple[int, int]) -> np.ndarray:
    h, w = img_hw
    fx, fy, cx, cy = k_norm
    return np.array(
        [[fx * w, 0.0, cx * w], [0.0, fy * h, cy * h], [0.0, 0.0, 1.0]],
        dtype=np.float32,
    )


def load_sequence(
    root: str, split: str, seq: str, img_hw: tuple[int, int],
    min_points: int = 1,
) -> list[PosedFrame]:
    """Load every posed frame of one sequence whose image exists on disk."""
    _, rows = parse_camera_file(os.path.join(root, split, seq + ".txt"))
    pts_path = os.path.join(root, "points", seq + ".npz")
    if not os.path.exists(pts_path):
        raise FileNotFoundError(
            f"{pts_path}: sequence {seq} has no SfM point cloud — "
            "realestate10k trains with sparse-depth calibration "
            "(see module docstring for the expected layout)"
        )
    world = np.asarray(np.load(pts_path)["xyz"], np.float64)
    if world.ndim != 2 or world.shape[1] != 3:
        raise ValueError(f"{pts_path}: xyz must be (N, 3), got {world.shape}")
    homo = np.concatenate([world, np.ones((len(world), 1))], axis=1)

    h, w = img_hw
    frames: list[PosedFrame] = []
    for row in rows:
        img_path = os.path.join(root, "frames", seq, row.timestamp + ".png")
        if not os.path.exists(img_path):
            continue  # the txt indexes the full video; only some frames ship
        with Image.open(img_path) as im:
            img = np.asarray(
                im.convert("RGB").resize((w, h), Image.BICUBIC),
                dtype=np.float32,
            ) / 255.0
        k = _pixel_intrinsics(row.k_norm, img_hw)
        cam = (row.g_cam_world @ homo.T).T[:, :3]
        pts_cam, _ = cull_near_points(cam.astype(np.float32))
        # keep only points this camera actually sees: the cloud is
        # sequence-global, unlike COLMAP's per-image tracks
        uvw = pts_cam @ k.T
        uv = uvw[:, :2] / uvw[:, 2:3]
        inside = (
            (uv[:, 0] >= 0) & (uv[:, 0] < w)
            & (uv[:, 1] >= 0) & (uv[:, 1] < h)
        )
        pts_cam = pts_cam[inside]
        if len(pts_cam) < min_points:
            raise ValueError(
                f"{img_path}: {len(pts_cam)} in-view SfM points < required "
                f"{min_points} ({len(world)} in the sequence cloud) — "
                "frame/point-cloud mismatch?"
            )
        frames.append(PosedFrame(
            scene=seq, img=img, k=k,
            g_cam_world=row.g_cam_world.astype(np.float32),
            pts_cam=pts_cam,
        ))
    return frames


class RealEstateDataset(PosedFrameDataset):
    """Loader-protocol dataset over RealEstate10K camera-txt sequences."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        root = cfg.data.training_set_path
        split_dir = os.path.join(root, split)
        if not os.path.isdir(split_dir):
            raise FileNotFoundError(
                f"no {split!r} split under {root!r} (expected "
                f"{split_dir}/<sequence>.txt camera files)"
            )
        frames: list[PosedFrame] = []
        for name in sorted(os.listdir(split_dir)):
            if not name.endswith(".txt"):
                continue
            frames.extend(load_sequence(
                root, split, name[:-4],
                (cfg.data.img_h, cfg.data.img_w),
            ))
        if not frames:
            raise FileNotFoundError(
                f"no posed frames under {root!r} ({split} split)"
            )
        super().__init__(cfg, split, global_batch, frames,
                         host_slice=host_slice)

    def candidate_targets(self, src_idx: int) -> list[int]:
        # nearby-frame pairs (the protocol's small-baseline sampling);
        # per-sequence frame indices are contiguous by construction
        return [
            i for i in self.scene_indices[self.frames[src_idx].scene]
            if i != src_idx and abs(i - src_idx) <= FRAME_WINDOW
        ]

    def _validate_candidates(self) -> None:
        if self.num_tgt_views > FRAME_WINDOW:
            raise ValueError(
                f"data.num_tgt_views={self.num_tgt_views} exceeds the "
                f"±{FRAME_WINDOW}-frame candidate window"
            )
        # contiguous per-sequence indices: an edge frame of a sequence with
        # >= k+1 frames always has min(window, len-1) >= k in-window
        # neighbors once num_tgt_views <= FRAME_WINDOW holds
        for seq, idxs in self.scene_indices.items():
            if len(idxs) < self.num_tgt_views + 1:
                raise ValueError(
                    f"sequence {seq} has {len(idxs)} frame(s); need >= "
                    f"{self.num_tgt_views + 1}"
                )
