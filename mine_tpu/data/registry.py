"""Dataset registry: ONE enumerable name -> builder table.

Replaces the train.py if-chain (which could construct three families and
raised NotImplementedError for the other four shipped configs). Every
consumer — the train CLI, the evaluate CLI, and the conformance runner
(data/conformance/) — enumerates THIS table, so "which datasets exist" has
one answer and an unknown name errors with the registered list instead of
a dead end.

Builders are lazy (imports inside), so `registered_names()` costs nothing
and a CLI only pays for the loader it uses. Builder signature:

    builder(cfg, split, global_batch, host_slice) -> dataset

where the dataset speaks the loader protocol (`__len__`, `epoch(n)`,
optional `num_eval_examples`) and `host_slice=(start, count)` asks for
only those rows of each global batch (per-host data sharding,
parallel/mesh.py host_batch_slice; every registered loader honors it —
the conformance contract's `host_slice` flag, data/conformance/).
"""

from __future__ import annotations

from typing import Any, Callable, Protocol

from mine_tpu.config import Config

Builder = Callable[[Config, str, int, "tuple[int, int] | None"], Any]


class UnknownDatasetError(KeyError):
    """`data.name` names no registered dataset; the message lists what IS
    registered and points at the conformance runner."""

    def __init__(self, name: str):
        super().__init__(
            f"dataset {name!r} is not registered; registered datasets: "
            f"{', '.join(registered_names())} (data/registry.py; "
            "`python tools/conformance_run.py` checks every registered "
            "config end-to-end against its hermetic fixture)"
        )


class _LoaderProtocol(Protocol):  # documentation aid only
    def __len__(self) -> int: ...
    def epoch(self, epoch: int): ...


_REGISTRY: dict[str, Builder] = {}


def register(name: str) -> Callable[[Builder], Builder]:
    def deco(fn: Builder) -> Builder:
        _REGISTRY[name] = fn
        return fn
    return deco


def registered_names() -> tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


def build_dataset(
    cfg: Config,
    split: str,
    global_batch: int,
    host_slice: tuple[int, int] | None = None,
) -> Any:
    """Dataset factory (reference train.py:72-164 get_dataset, now total:
    every shipped config constructs)."""
    try:
        builder = _REGISTRY[cfg.data.name]
    except KeyError:
        raise UnknownDatasetError(cfg.data.name) from None
    return builder(cfg, split, global_batch, host_slice)


# -- the registered families -------------------------------------------------


@register("synthetic")
def _synthetic(cfg, split, global_batch, host_slice):
    # data.num_tgt_views is a no-op here by design: every synthetic batch
    # slot is a fresh procedural scene, so "k targets per source" has no
    # shared-source meaning (the real loaders implement it)
    from mine_tpu.data.synthetic import SyntheticDataset

    return SyntheticDataset(
        cfg.data.img_h, cfg.data.img_w, global_batch,
        steps_per_epoch=12 if split == "train" else 2,
        n_points=cfg.data.visible_point_count,
        seed=cfg.training.seed + (0 if split == "train" else 10_000),
        host_slice=host_slice,
    )


@register("llff")
@register("nocs_llff")
def _llff(cfg, split, global_batch, host_slice):
    from mine_tpu.data.llff import LLFFDataset

    return LLFFDataset(cfg, split, global_batch, host_slice=host_slice)


@register("objectron")
def _objectron(cfg, split, global_batch, host_slice):
    from mine_tpu.data.objectron import ObjectronDataset

    return ObjectronDataset(cfg, split, global_batch, host_slice=host_slice)


@register("realestate10k")
def _realestate(cfg, split, global_batch, host_slice):
    from mine_tpu.data.realestate import RealEstateDataset

    return RealEstateDataset(cfg, split, global_batch, host_slice=host_slice)


@register("kitti_raw")
def _kitti(cfg, split, global_batch, host_slice):
    from mine_tpu.data.kitti import KittiRawDataset

    return KittiRawDataset(cfg, split, global_batch, host_slice=host_slice)


@register("dtu")
def _dtu(cfg, split, global_batch, host_slice):
    from mine_tpu.data.dtu import DTUDataset

    return DTUDataset(cfg, split, global_batch, host_slice=host_slice)


@register("flowers")
def _flowers(cfg, split, global_batch, host_slice):
    from mine_tpu.data.flowers import FlowersDataset

    return FlowersDataset(cfg, split, global_batch, host_slice=host_slice)
