"""The conformance runner: every shipped config, proven end to end.

Two rungs:

  * `check_contract(config)` — compile-free, in-process, seconds: writes
    the family fixture, builds the train/val datasets through the
    REGISTRY (the same factory the CLIs use), and verifies every
    LoaderContract claim against live batches — required keys/shapes/
    dtypes, K structure, pose composition, sparse-depth presence,
    point reprojection (where the family guarantees in-view points),
    wrap-padded val tails with eval_weight bookkeeping, and the
    host_slice bitwise slice-vs-global equality.
  * `check_loader(config)` — the full rung: the contract checks PLUS the
    config driven through the real product CLIs against its fixture —
    `python -m mine_tpu.train` (subprocess, tiny-shape overrides),
    `python -m mine_tpu.evaluate` over the trained workspace, and
    `python -m mine_tpu.serving.server` answering a live
    /predict -> /render -> /healthz round over HTTP. One XLA compile
    per stage; minutes per config on a CPU box — the slow rung
    (tests/test_conformance.py slow marks it; tools/conformance_run.py
    and `chaos_drill.py --half datasets` drive it).

Each config yields ONE JSON-serializable verdict dict; `run_matrix`
sweeps a config list and aggregates.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from mine_tpu.data.conformance.contract import (
    CONFIG_FAMILIES,
    LoaderContract,
    all_config_names,
    configs_dir,
    contract_for_config,
)
from mine_tpu.data.conformance.fixtures import write_fixture

STAGES = ("contract", "train", "eval", "serve")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# smallest full-model shape: H, W must be 128-multiples (decoder
# receptive-field extension), resnet-18, S=2 — the verify-skill recipe
_TINY_H, _TINY_W = 128, 128


def conformance_overrides(fixture_path: str) -> dict:
    """The tiny-shape override layer every stage shares: the config keeps
    its own recipe identity (dataset name, disparity range, loss weights,
    LR schedule) while the model/batch shrink to the smallest full-model
    CPU shape and the data path points at the hermetic fixture."""
    return {
        "data.training_set_path": fixture_path,
        "data.img_h": _TINY_H, "data.img_w": _TINY_W,
        "data.img_pre_downsample_ratio": 1.0,
        "data.per_gpu_batch_size": 2,
        "data.num_tgt_views": 1,
        "data.visible_point_count": 16,
        "data.num_workers": 0,
        "model.num_layers": 18, "model.dtype": "float32",
        "model.imagenet_pretrained": False,
        "model.pretrained_backbone_path": "",
        "mpi.num_bins_coarse": 2, "mpi.num_bins_fine": 0,
        "training.epochs": 1,
        "training.eval_interval": 100000,  # the eval CLI is its own stage
        "training.checkpoint_interval": 2,
        "training.log_interval": 1,
        "training.pretrained_checkpoint_path": "",
        "training.lpips_weights_path": "",
        "mesh.data_parallel": 1, "mesh.fsdp_parallel": 1,
        "mesh.plane_parallel": 1,
    }


def _load_cfg(config_name: str, overrides: dict):
    from mine_tpu.config import load_config

    return load_config(
        os.path.join(configs_dir(), "default.yaml"),
        os.path.join(configs_dir(), config_name + ".yaml"),
        overrides=overrides,
    )


# -- the compile-free contract rung ------------------------------------------


def _check(checks: dict, name: str, fn) -> None:
    try:
        fn()
        checks[name] = "ok"
    except Exception as exc:  # noqa: BLE001 - the verdict carries it
        checks[name] = f"FAIL: {type(exc).__name__}: {exc}"


def check_contract(config_name: str, fixture_root: str) -> dict:
    """Compile-free contract verification for one shipped config; writes
    (or reuses) the family fixture under `fixture_root`."""
    from mine_tpu.data.registry import build_dataset

    contract = contract_for_config(config_name)
    path = write_fixture(contract.family, fixture_root)
    cfg = _load_cfg(config_name, conformance_overrides(path))
    checks: dict[str, str] = {}
    h, w = cfg.data.img_h, cfg.data.img_w
    global_batch = 2

    train_ds = build_dataset(cfg, "train", global_batch)
    val_ds = build_dataset(cfg, "val", global_batch)
    batch = next(iter(train_ds.epoch(0)))

    def keys_and_shapes():
        got = tuple(sorted(batch))
        want = tuple(sorted(contract.required_keys))
        assert got == want, f"batch keys {got} != contract {want}"
        b = global_batch
        assert batch["src_img"].shape == (b, h, w, 3), batch["src_img"].shape
        assert batch["tgt_img"].shape == (b, h, w, 3)
        assert batch["k_src"].shape == (b, 3, 3)
        assert batch["g_tgt_src"].shape == (b, 4, 4)
        for key, v in batch.items():
            assert v.dtype == np.float32, f"{key} dtype {v.dtype}"
            assert np.isfinite(v).all(), f"{key} carries non-finite values"
        assert batch["src_img"].min() >= 0.0 and batch["src_img"].max() <= 1.0

    _check(checks, "keys_and_shapes", keys_and_shapes)

    def intrinsics():
        for key in ("k_src", "k_tgt"):
            k = batch[key]
            np.testing.assert_allclose(k[:, 2], [[0.0, 0.0, 1.0]] *
                                       global_batch, atol=1e-6)
            assert (k[:, 0, 0] > 0).all() and (k[:, 1, 1] > 0).all()
            # pixels at the TARGET resolution: principal point inside
            assert ((k[:, 0, 2] > 0) & (k[:, 0, 2] < w)).all(), k[:, 0, 2]
            assert ((k[:, 1, 2] > 0) & (k[:, 1, 2] < h)).all(), k[:, 1, 2]

    _check(checks, "intrinsics_pixels_at_target", intrinsics)

    def pose():
        g = batch["g_tgt_src"]
        np.testing.assert_allclose(g[:, 3], [[0, 0, 0, 1]] * global_batch,
                                   atol=1e-6)
        r = g[:, :3, :3]
        np.testing.assert_allclose(
            np.einsum("bij,bkj->bik", r, r),
            np.tile(np.eye(3), (global_batch, 1, 1)), atol=1e-4,
        )

    _check(checks, "pose_rigid", pose)

    def sparse_depth():
        present = "pt3d_src" in batch
        assert present == contract.sparse_depth, (
            f"sparse-depth presence {present} != contract "
            f"{contract.sparse_depth} (training/step.py "
            "NO_DISP_SUPERVISION must agree)"
        )
        if present:
            n_pt = cfg.data.visible_point_count
            assert batch["pt3d_src"].shape == (global_batch, n_pt, 3)
            assert (batch["pt3d_src"][..., 2] > 0).all(), "points behind camera"
            assert (batch["pt3d_tgt"][..., 2] > 0).all()
            if contract.points_in_view:
                uvw = np.einsum("bij,bnj->bni", batch["k_src"],
                                batch["pt3d_src"])
                uv = uvw[..., :2] / uvw[..., 2:]
                assert (uv[..., 0] > -0.5).all() and (uv[..., 0] < w + 0.5).all()
                assert (uv[..., 1] > -0.5).all() and (uv[..., 1] < h + 0.5).all()

    _check(checks, "sparse_depth", sparse_depth)

    def ragged_val_tail():
        batches = list(val_ds.epoch(0))
        assert len(batches) == len(val_ds)
        if contract.ragged_val_tail == "fixed_steps":
            assert all("eval_weight" not in b for b in batches)
            return
        assert contract.ragged_val_tail == "wrap_pad"
        assert all(b["src_img"].shape[0] == global_batch for b in batches)
        assert all("eval_weight" in b for b in batches)
        weights = np.concatenate([b["eval_weight"] for b in batches])
        assert weights.sum() == val_ds.num_eval_examples, (
            f"eval_weight sum {weights.sum()} != num_eval_examples "
            f"{val_ds.num_eval_examples}"
        )

    _check(checks, "ragged_val_tail", ragged_val_tail)

    def host_slice():
        assert contract.host_slice, "contract says no host_slice support"
        sliced_ds = build_dataset(cfg, "train", global_batch,
                                  host_slice=(1, 1))
        sliced = next(iter(sliced_ds.epoch(0)))
        for key in batch:
            assert np.array_equal(batch[key][1:2], sliced[key]), (
                f"host_slice rows of {key} differ from the global build's "
                "slice — per-example seeding is broken"
            )

    _check(checks, "host_slice_bitwise", host_slice)

    ok = all(v == "ok" for v in checks.values())
    return {"ok": ok, "checks": checks, "fixture": path}


# -- the product-CLI rung ----------------------------------------------------


def _cli_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("MINE_TPU_PERF_LEDGER", "off")
    return env


def _run_cli(argv: list[str], timeout_s: float) -> dict:
    t0 = time.monotonic()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", *argv], cwd=REPO_ROOT, env=_cli_env(),
            capture_output=True, text=True, timeout=timeout_s,
        )
        rc = proc.returncode
        out, err = proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc, out = -1, (exc.stdout or "")
        err = (exc.stderr or "") + f"\n[timeout after {timeout_s}s]"
    return {
        "ok": rc == 0, "rc": rc,
        "seconds": round(time.monotonic() - t0, 1),
        "stdout_tail": out[-2000:], "stderr_tail": err[-2000:],
    }


def _fixture_png() -> bytes:
    """One analytic-scene view as PNG bytes (the /predict payload)."""
    from PIL import Image

    from mine_tpu.data.synthetic import _intrinsics, _render_view

    img, _ = _render_view(64, 64, _intrinsics(64, 64), np.zeros(3),
                          phase=0.3)
    buf = io.BytesIO()
    Image.fromarray((img * 255).astype(np.uint8)).save(buf, format="PNG")
    return buf.getvalue()


def _http(base: str, path: str, data=None, headers=None, timeout=60):
    req = urllib.request.Request(base + path, data=data,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def _serve_stage(workspace: str, timeout_s: float) -> dict:
    """Start the REAL serving CLI over the trained workspace, drive one
    predict -> render -> healthz round over HTTP, shut it down."""
    t0 = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-m", "mine_tpu.serving.server",
         "--workspace", workspace, "--port", "0"],
        cwd=REPO_ROOT, env=_cli_env(),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    url_box: dict[str, str] = {}
    lines: list[str] = []

    def read_stdout():
        for line in proc.stdout:  # type: ignore[union-attr]
            lines.append(line.rstrip())
            if " on http://" in line:
                url_box["base"] = line.split(" on ", 1)[1].split()[0]

    reader = threading.Thread(target=read_stdout, daemon=True)
    reader.start()
    try:
        deadline = time.monotonic() + timeout_s
        while "base" not in url_box:
            if proc.poll() is not None or time.monotonic() > deadline:
                err = proc.stderr.read()[-2000:] if proc.stderr else ""
                return {"ok": False, "error": "server never bound",
                        "stdout_tail": "\n".join(lines)[-2000:],
                        "stderr_tail": err,
                        "seconds": round(time.monotonic() - t0, 1)}
            time.sleep(0.2)
        base = url_box["base"]
        code, body = _http(base, "/predict", data=_fixture_png(),
                           headers={"Content-Type": "image/png"},
                           timeout=timeout_s)
        assert code == 200, f"/predict {code}: {body[:300]!r}"
        key = json.loads(body)["mpi_key"]
        code, body = _http(
            base, "/render",
            data=json.dumps({"mpi_key": key,
                             "offsets": [[0.01, 0.0, 0.0]]}).encode(),
            headers={"Content-Type": "application/json"}, timeout=timeout_s,
        )
        assert code == 200, f"/render {code}: {body[:300]!r}"
        frames = json.loads(body)["frames_png_b64"]
        assert len(frames) == 1
        code, body = _http(base, "/healthz", timeout=30)
        assert code == 200, f"/healthz {code}"
        health = json.loads(body)
        return {"ok": True, "seconds": round(time.monotonic() - t0, 1),
                "checkpoint_step": health.get("checkpoint_step"),
                "compiles": health.get("compiles")}
    except Exception as exc:  # noqa: BLE001 - the verdict carries it
        return {"ok": False, "error": f"{type(exc).__name__}: {exc}",
                "seconds": round(time.monotonic() - t0, 1)}
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def check_loader(
    config_name: str,
    workdir: str,
    stages: tuple[str, ...] = STAGES,
    timeout_s: float = 900.0,
) -> dict:
    """One config's full conformance verdict (the acceptance rung):
    contract checks + train -> eval -> serve through the product CLIs,
    everything against the hermetic fixture under `workdir`."""
    contract = contract_for_config(config_name)
    fixture_root = os.path.join(workdir, "fixtures", contract.family)
    workspace = os.path.join(workdir, "ws_" + config_name)
    verdict: dict = {
        "config": config_name,
        "dataset": contract.family,
        "contract": dataclasses.asdict(contract),
        "stages": {},
    }
    stage_results = verdict["stages"]

    if "contract" in stages:
        try:
            stage_results["contract"] = check_contract(
                config_name, fixture_root
            )
        except Exception as exc:  # noqa: BLE001 - the verdict carries it
            stage_results["contract"] = {
                "ok": False, "error": f"{type(exc).__name__}: {exc}",
            }
    fixture_path = stage_results.get("contract", {}).get(
        "fixture"
    ) or write_fixture(contract.family, fixture_root)
    overrides = conformance_overrides(fixture_path)
    verdict["overrides"] = overrides

    if "train" in stages and stage_results.get("contract", {}).get("ok", True):
        stage_results["train"] = _run_cli([
            "mine_tpu.train",
            "--config", os.path.join(configs_dir(), config_name + ".yaml"),
            "--extra_config", json.dumps(overrides),
            "--workspace", workspace,
        ], timeout_s)
    if "eval" in stages and stage_results.get("train", {}).get("ok", True):
        result = _run_cli(
            ["mine_tpu.evaluate", "--checkpoint", workspace], timeout_s
        )
        if result["ok"]:
            try:
                metrics = json.loads(
                    result["stdout_tail"].strip().splitlines()[-1]
                )
                result["loss"] = metrics.get("loss")
                result["psnr_tgt"] = metrics.get("psnr_tgt")
                if not np.isfinite(metrics.get("loss", np.nan)):
                    result["ok"] = False
                    result["error"] = "non-finite eval loss"
            except (ValueError, IndexError) as exc:
                result["ok"] = False
                result["error"] = f"unparseable eval output: {exc}"
        stage_results["eval"] = result
    if "serve" in stages and stage_results.get("train", {}).get("ok", True):
        stage_results["serve"] = _serve_stage(workspace, timeout_s)

    verdict["ok"] = bool(stage_results) and all(
        s.get("ok") for s in stage_results.values()
    )
    return verdict


def run_matrix(
    workdir: str,
    config_names: tuple[str, ...] | None = None,
    stages: tuple[str, ...] = STAGES,
    timeout_s: float = 900.0,
    on_verdict=None,
) -> dict:
    """Sweep the config matrix; returns the aggregate verdict document."""
    names = config_names if config_names is not None else all_config_names()
    results = []
    for name in names:
        verdict = check_loader(name, workdir, stages=stages,
                               timeout_s=timeout_s)
        results.append(verdict)
        if on_verdict is not None:
            on_verdict(verdict)
    return {
        "metric": "dataset_conformance",
        "configs_checked": len(results),
        "configs_ok": sum(1 for r in results if r["ok"]),
        "stages": list(stages),
        "ok": all(r["ok"] for r in results),
        "results": results,
    }
