"""LoaderContract: the declarative per-family dataset contract.

Before this table, "what a loader must produce" lived in folklore spread
over training/step.py's docstring, each loader's implementation, and the
tests. The contract states it once, checkably:

  * every loader yields the training-step batch pytree (BASE_KEYS, plus
    the pt3d pair when `sparse_depth` — families without SfM tracks are
    the NO_DISP_SUPERVISION set in training/step.py and their batches
    carry NO pt3d keys);
  * K is always PIXELS AT THE TARGET (img_h, img_w) — `intrinsics` names
    where it came from (COLMAP rescale, normalized txt, calib P2, ...);
  * poses compose as `g_tgt_src = G_tgt_world @ inv(G_src_world)`;
  * `ragged_val_tail` — how a val epoch's short tail keeps static shapes
    ("wrap_pad": duplicated slots masked by eval_weight 0; "fixed_steps":
    procedurally sized epochs, no tail exists);
  * `host_slice` — the loader materializes only (start, count) rows of
    each global batch, bitwise-equal to slicing a global build (per-host
    data sharding, PARITY.md 5.12);
  * `zoo_shape` — the pretrained-zoo capability envelope (H, W, S) from
    BASELINE.md that the serving buckets and benches must exercise.

`runner.check_contract` verifies each flag against the live loader;
tests/test_conformance.py pins table <-> registry <-> README-matrix drift.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

# the training-step batch pytree (training/step.py module docstring)
BASE_KEYS = ("src_img", "tgt_img", "k_src", "k_tgt", "g_tgt_src")
SPARSE_KEYS = ("pt3d_src", "pt3d_tgt")


@dataclass(frozen=True)
class LoaderContract:
    family: str  # the registry name (data/registry.py)
    loader: str  # implementing class, for humans and the README matrix
    sparse_depth: bool  # pt3d supervision present (else NO_DISP_SUPERVISION)
    intrinsics: str  # where pixels-at-target K comes from
    # loaders whose per-frame points are guaranteed to project INSIDE the
    # image (per-image COLMAP tracks; in-view-culled clouds) — the
    # reprojection conformance check only applies where this holds
    points_in_view: bool = True
    pose: str = "g_tgt_src = G_tgt_world @ inv(G_src_world)"
    ragged_val_tail: str = "wrap_pad"  # or "fixed_steps"
    host_slice: bool = True
    zoo_shape: tuple[int, int, int] | None = None  # (H, W, S), BASELINE.md
    notes: str = ""
    extra_keys: tuple[str, ...] = field(default=())

    @property
    def required_keys(self) -> tuple[str, ...]:
        keys = BASE_KEYS + (SPARSE_KEYS if self.sparse_depth else ())
        return keys + self.extra_keys


CONTRACTS: dict[str, LoaderContract] = {c.family: c for c in (
    LoaderContract(
        family="synthetic",
        loader="data.synthetic.SyntheticDataset",
        sparse_depth=True,
        intrinsics="analytic (fov-fixed, generated at target)",
        ragged_val_tail="fixed_steps",
        notes="procedural; zero disk footprint",
    ),
    LoaderContract(
        family="llff",
        loader="data.llff.LLFFDataset",
        sparse_depth=True,
        intrinsics="COLMAP SIMPLE_* camera, per-axis rescale to target",
        zoo_shape=(384, 512, 32),  # the reference LLFF recipe shape
    ),
    LoaderContract(
        family="nocs_llff",
        loader="data.llff.LLFFDataset",
        sparse_depth=True,
        intrinsics="COLMAP SIMPLE_* camera, center-crop-shifted principal "
                   "point, per-axis rescale to target",
        notes="384x640 center crop + first-51-images cap",
    ),
    LoaderContract(
        family="objectron",
        loader="data.objectron.ObjectronDataset",
        sparse_depth=True,
        intrinsics="per-frame metadata focal/c, crop-shifted",
        # one shared world cloud per scene transformed per frame — a point
        # may sit outside a given frame's view frustum
        points_in_view=False,
        notes="±10-frame target window; 90° CCW rotate + crop",
    ),
    LoaderContract(
        family="realestate10k",
        loader="data.realestate.RealEstateDataset",
        sparse_depth=True,
        intrinsics="normalized txt intrinsics x (img_w, img_h) — exact at "
                   "any target size",
        zoo_shape=(256, 384, 64),  # RealEstate10K 384x256 N=64 (BASELINE)
        notes="camera-txt protocol of arxiv 2004.11364; per-frame points "
              "are the sequence SfM cloud culled to in-view",
    ),
    LoaderContract(
        family="kitti_raw",
        loader="data.kitti.KittiRawDataset",
        sparse_depth=False,
        intrinsics="calib.txt P2 (rectified left color), per-axis rescale "
                   "to target",
        zoo_shape=(256, 768, 64),  # KITTI 768x256 N=64 (BASELINE)
        notes="±10-frame target window; poses.txt cam-to-world rows",
    ),
    LoaderContract(
        family="dtu",
        loader="data.dtu.DTUDataset",
        sparse_depth=False,
        intrinsics="MVSNet cam.txt intrinsic, per-axis rescale to target",
        notes="per-view <id>_cam.txt extrinsic/intrinsic pairs",
    ),
    LoaderContract(
        family="flowers",
        loader="data.flowers.FlowersDataset",
        sparse_depth=False,
        intrinsics="shared focal_px from meta.json, per-axis rescale to "
                   "target, centered principal point",
        zoo_shape=(384, 512, 64),  # Flowers 512x384 N=64 (BASELINE)
        notes="G x G sub-aperture tiles; planar camera array poses",
    ),
)}

# shipped recipe yaml (mine_tpu/configs/<name>.yaml) -> contract family.
# This IS "the nine configs": every non-default yaml must appear here
# (pinned against the configs directory by tests/test_conformance.py).
CONFIG_FAMILIES: dict[str, str] = {
    "llff": "llff",
    "llff_highres": "llff",
    "nocs_llff": "nocs_llff",
    "objectron": "objectron",
    "realestate": "realestate10k",
    "kitti_raw": "kitti_raw",
    "dtu": "dtu",
    "flowers": "flowers",
    "synthetic": "synthetic",
}

# the pretrained-zoo shape set (BASELINE.md capability envelope), deduped
# in a stable order — what the serving bucket allowlists, the mixed-bucket
# fleet bench (tools/bench_fleet.py --zoo), and bench.py's BENCH_SHAPE
# exercise. Every shape satisfies the model's 128-multiple constraint.
ZOO_BUCKETS: tuple[tuple[int, int, int], ...] = tuple(sorted(
    {c.zoo_shape for c in CONTRACTS.values() if c.zoo_shape is not None}
))


def contract_for_config(config_name: str) -> LoaderContract:
    """Shipped recipe name ('realestate', 'llff_highres', ...) -> its
    family contract; unknown names list what exists."""
    try:
        return CONTRACTS[CONFIG_FAMILIES[config_name]]
    except KeyError:
        raise KeyError(
            f"config {config_name!r} is not in the conformance matrix; "
            f"known configs: {', '.join(sorted(CONFIG_FAMILIES))}"
        ) from None


def configs_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "configs")


def all_config_names() -> tuple[str, ...]:
    """Every shipped recipe yaml except the defaults layer — the matrix
    the conformance runner sweeps."""
    names = sorted(
        os.path.splitext(f)[0] for f in os.listdir(configs_dir())
        if f.endswith(".yaml") and f != "default.yaml"
    )
    return tuple(names)
