"""Dataset conformance subsystem: "a loader works" as a checkable contract.

Three pieces (ROADMAP "scenario diversity at fleet realism"):

  * `contract.py` — the declarative LoaderContract per dataset family
    (intrinsics/pose conventions, required batch keys, sparse-depth
    supervision presence, ragged-val-tail behavior, host_slice capability,
    pretrained-zoo shape) plus the shipped-config -> family table and the
    ZOO_BUCKETS the serving/bench layers exercise.
  * `fixtures.py` — one deterministic on-disk synthetic fixture generator
    per family (COLMAP dir, RealEstate10K txt sequences, KITTI raw layout,
    DTU cam grids, light-field tiles, Objectron annotations), all rendering
    the analytic two-plane scene (data/synthetic.py), so every loader runs
    hermetically on CPU with nothing downloaded.
  * `runner.py` — `check_contract` (compile-free batch/geometry/host-slice
    checks) and `check_loader` (drives the config through the REAL
    train -> eval -> serve product CLIs against its fixture), emitting one
    JSON conformance verdict per config.

CLI: `python tools/conformance_run.py` (also `tools/chaos_drill.py --half
datasets`); tier-1 units in tests/test_conformance.py.
"""

from mine_tpu.data.conformance.contract import (
    CONFIG_FAMILIES,
    CONTRACTS,
    ZOO_BUCKETS,
    LoaderContract,
    contract_for_config,
)
from mine_tpu.data.conformance.fixtures import write_fixture
from mine_tpu.data.conformance.runner import check_contract, check_loader
