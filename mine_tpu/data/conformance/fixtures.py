"""Deterministic on-disk fixtures: one writer per dataset family, all
rendering the SAME analytic two-plane scene (data/synthetic.py — textured
far plane at z=4, near occluder strip at z=1, cameras translated along
+x), so every loader in the registry can run hermetically on CPU with
nothing downloaded, and the geometry each loader reconstructs is knowable
in closed form.

Each writer lays the scene down in its family's REAL wire format — COLMAP
binary models, RealEstate10K camera-txt lines, KITTI calib/pose files,
MVSNet cam.txt grids, tiled light fields, Objectron metadata pickles — so
the loaders' parsers are exercised against the actual byte layouts, not
test doubles. All writers are seeded and content-addressed by their
arguments: same call, same bytes.

`write_fixture(family, root)` dispatches; returns the path to use as
`data.training_set_path` ('' for the procedural synthetic family).
"""

from __future__ import annotations

import json
import os
import pickle

import numpy as np

from mine_tpu.data.synthetic import (
    _intrinsics,
    _render_view,
    _sample_points,
    write_colmap_scene,
)

# train cameras sit at BASELINE * i along +x (write_colmap_scene idiom);
# val cameras offset half a step so no val pose equals a train pose
BASELINE = 0.06


def _save_png(img01: np.ndarray, path: str) -> None:
    from PIL import Image

    Image.fromarray((np.clip(img01, 0.0, 1.0) * 255).astype(np.uint8)).save(
        path
    )


def _cam_positions(n: int, val: bool = False) -> list[np.ndarray]:
    off = BASELINE / 2 if val else 0.0
    return [np.array([BASELINE * i + off, 0.02 * i + off / 3, 0.0])
            for i in range(n)]


# -- per-family writers ------------------------------------------------------


def write_llff_fixture(root: str, hw=(64, 64), n_views: int = 4,
                       n_val_views: int = 3) -> str:
    """LLFF: COLMAP sparse/0 binary model + images[_val]/ (the shared
    write_colmap_scene — the layout tests/test_data.py always used)."""
    write_colmap_scene(root, "scene_a", n_views=n_views, hw=hw,
                       n_val_views=n_val_views)
    return root


def write_nocs_fixture(root: str, n_views: int = 4,
                       n_val_views: int = 3) -> str:
    """NOCS: same COLMAP layout, images stored at EXACTLY 640x384 so the
    loader's hardcoded (384, 640) center crop is the identity and the
    crop-shifted principal point stays put (data/llff.py)."""
    write_colmap_scene(root, "scene_a", n_views=n_views, hw=(384, 640),
                       n_val_views=n_val_views)
    return root


def write_realestate_fixture(root: str, hw=(64, 64), n_frames: int = 4,
                             n_val_frames: int = 3) -> str:
    """RealEstate10K: <split>/<seq>.txt camera lines (19 normalized
    fields), frames/<seq>/<timestamp>.png, points/<seq>.npz SfM cloud."""
    h, w = hw
    k = _intrinsics(h, w)
    rng = np.random.default_rng(7)
    world = _sample_points(rng, 64, np.zeros(3)).astype(np.float64)

    for split, n, val in (("train", n_frames, False),
                          ("val", n_val_frames, True)):
        seq = f"seq_{split}"
        os.makedirs(os.path.join(root, split), exist_ok=True)
        os.makedirs(os.path.join(root, "frames", seq), exist_ok=True)
        os.makedirs(os.path.join(root, "points"), exist_ok=True)
        np.savez(os.path.join(root, "points", seq + ".npz"), xyz=world)
        lines = [f"https://example.test/{seq}"]
        for i, pos in enumerate(_cam_positions(n, val)):
            ts = str(100 + i)
            img, _ = _render_view(h, w, k, pos, phase=0.3)
            _save_png(img, os.path.join(root, "frames", seq, ts + ".png"))
            pose = np.eye(4)[:3, :4].copy()
            pose[:, 3] = -pos  # world -> camera: [I | -pos]
            vals = [
                k[0, 0] / w, k[1, 1] / h, k[0, 2] / w, k[1, 2] / h,
                0.0, 0.0, *pose.reshape(-1),
            ]
            lines.append(ts + " " + " ".join(f"{v:.9f}" for v in vals))
        with open(os.path.join(root, split, seq + ".txt"), "w") as fh:
            fh.write("\n".join(lines) + "\n")
    return root


def write_kitti_fixture(root: str, hw=(64, 64), n_frames: int = 4,
                        n_val_frames: int = 3) -> str:
    """KITTI raw: <drive>/image_02/data[_val]/<idx>.png + poses[_val].txt
    (3x4 cam-to-world rows) + calib.txt (P2 row at stored resolution)."""
    h, w = hw
    k = _intrinsics(h, w)
    drive = os.path.join(root, "2011_09_26_drive_0001_sync")
    p2 = np.zeros((3, 4))
    p2[:3, :3] = k
    os.makedirs(drive, exist_ok=True)
    with open(os.path.join(drive, "calib.txt"), "w") as fh:
        fh.write("P0: " + " ".join(["0.0"] * 12) + "\n")
        fh.write("P2: " + " ".join(f"{v:.9f}" for v in p2.reshape(-1)) + "\n")
    for suffix, n, val in (("", n_frames, False),
                           ("_val", n_val_frames, True)):
        img_dir = os.path.join(drive, "image_02", "data" + suffix)
        os.makedirs(img_dir, exist_ok=True)
        rows = []
        for i, pos in enumerate(_cam_positions(n, val)):
            img, _ = _render_view(h, w, k, pos, phase=0.3)
            _save_png(img, os.path.join(img_dir, f"{i:010d}.png"))
            c2w = np.eye(4)
            c2w[:3, 3] = pos
            rows.append(" ".join(f"{v:.9f}" for v in c2w[:3, :4].reshape(-1)))
        with open(os.path.join(drive, f"poses{suffix}.txt"), "w") as fh:
            fh.write("\n".join(rows) + "\n")
    return root


def write_dtu_fixture(root: str, hw=(64, 64), n_views: int = 4,
                      n_val_views: int = 3) -> str:
    """DTU: <scan>/images[_val]/<id>.png + <scan>/cams/<id>_cam.txt
    (MVSNet extrinsic/intrinsic sections)."""
    h, w = hw
    k = _intrinsics(h, w)
    scan = os.path.join(root, "scan1")
    os.makedirs(os.path.join(scan, "cams"), exist_ok=True)
    view_id = 0
    for folder, n, val in (("images", n_views, False),
                           ("images_val", n_val_views, True)):
        img_dir = os.path.join(scan, folder)
        os.makedirs(img_dir, exist_ok=True)
        for pos in _cam_positions(n, val):
            stem = f"{view_id:08d}"
            img, _ = _render_view(h, w, k, pos, phase=0.3)
            _save_png(img, os.path.join(img_dir, stem + ".png"))
            extr = np.eye(4)
            extr[:3, 3] = -pos  # world -> camera
            with open(os.path.join(scan, "cams", stem + "_cam.txt"),
                      "w") as fh:
                fh.write("extrinsic\n")
                for row in extr:
                    fh.write(" ".join(f"{v:.9f}" for v in row) + "\n")
                fh.write("\nintrinsic\n")
                for row in k:
                    fh.write(" ".join(f"{v:.9f}" for v in row) + "\n")
                fh.write("\n425.0 2.5\n")  # depth_min/interval: ignored
            view_id += 1
    return root


def write_flowers_fixture(root: str, hw=(64, 64), grid: int = 3,
                          n_samples: int = 1, n_val_samples: int = 1) -> str:
    """Flowers: meta.json + grids[_val]/<sample>.png tiled G x G
    sub-aperture views of the analytic scene (planar camera array)."""
    h, w = hw
    k = _intrinsics(h, w)
    center = (grid - 1) / 2.0
    baseline = 0.08
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "meta.json"), "w") as fh:
        json.dump({"grid": grid, "focal_px": float(k[0, 0]),
                   "baseline": baseline}, fh)
    # square views keep the scalar focal exact on both axes
    assert h == w, "flowers fixture uses square sub-aperture views"
    for folder, n, phase0 in (("grids", n_samples, 0.3),
                              ("grids_val", n_val_samples, 1.1)):
        os.makedirs(os.path.join(root, folder), exist_ok=True)
        for s in range(n):
            tile = np.zeros((grid * h, grid * w, 3), np.float32)
            for r in range(grid):
                for c in range(grid):
                    pos = baseline * np.array(
                        [c - center, r - center, 0.0]
                    )
                    img, _ = _render_view(h, w, k, pos,
                                          phase=phase0 + 0.7 * s)
                    tile[r * h:(r + 1) * h, c * w:(c + 1) * w] = img
            _save_png(tile, os.path.join(root, folder, f"sample_{s}.png"))
    return root


def write_objectron_fixture(root: str, hw=(64, 64), n_frames: int = 6,
                            n_val_frames: int = 3) -> str:
    """Objectron: <scene>/<scene>_metadata.pickle + mask-driven frame
    lists in masks_3[_val]/ + images_3[_val]/ (the reference's layout).
    Frame indices: train 0..n-1, val n..n+m-1, all posed in ONE metadata
    pose array (how real scenes store their held-out tail)."""
    from mine_tpu.data.objectron import ADJUST

    h, w = hw
    k = _intrinsics(h, w)
    scene = "chair_batch-1_0"
    scene_dir = os.path.join(root, scene)
    for d in ("images_3", "masks_3", "images_3_val", "masks_3_val"):
        os.makedirs(os.path.join(scene_dir, d), exist_ok=True)

    rng = np.random.default_rng(11)
    # tight world cloud in front of the cameras (|xy| small at z ~ 0.4:
    # projects inside even the smallest fixture frames)
    world_pts = rng.uniform(-0.08, 0.08, size=(64, 3)) + np.array([0, 0, 0.4])

    from PIL import Image

    poses, focals, centers = [], [], []
    total = n_frames + n_val_frames
    for i in range(total):
        g_cam_world = np.eye(4)
        g_cam_world[:3, 3] = [0.01 * i, 0.0, 0.0]
        # reference stores c2w with G = inv(c2w @ ADJUST)
        poses.append(np.linalg.inv(g_cam_world) @ np.linalg.inv(ADJUST))
        focals.append([float(k[0, 0]), float(k[1, 1])])
        centers.append([w / 2, h / 2])

        suffix = "" if i < n_frames else "_val"
        img, _ = _render_view(h, w, k, np.array([0.01 * i, 0.0, 0.0]),
                              phase=0.3)
        # image is rotated 90° CCW at load; store pre-rotated so the
        # loaded frame lands at (h, w)
        Image.fromarray((img * 255).astype(np.uint8)).transpose(
            Image.ROTATE_270
        ).save(os.path.join(scene_dir, "images_3" + suffix, f"{i}.png"))
        Image.new("L", (8, 8)).save(
            os.path.join(scene_dir, "masks_3" + suffix, f"seg_{i}.png")
        )

    with open(os.path.join(scene_dir, f"{scene}_metadata.pickle"),
              "wb") as fh:
        pickle.dump({
            "poses": np.stack(poses),
            "focal": np.array(focals),
            "c": np.array(centers),
            "RT": np.eye(4),
            "scale": 1.0,
            "all_scene_points": world_pts,
        }, fh)
    return root


def write_synthetic_fixture(root: str, **_) -> str:
    """Synthetic is procedural: nothing on disk, empty set path."""
    return ""


_WRITERS = {
    "llff": write_llff_fixture,
    "nocs_llff": write_nocs_fixture,
    "objectron": write_objectron_fixture,
    "realestate10k": write_realestate_fixture,
    "kitti_raw": write_kitti_fixture,
    "dtu": write_dtu_fixture,
    "flowers": write_flowers_fixture,
    "synthetic": write_synthetic_fixture,
}


def write_fixture(family: str, root: str, **kwargs) -> str:
    """Write `family`'s fixture under `root`; returns the
    data.training_set_path to point the config at."""
    try:
        writer = _WRITERS[family]
    except KeyError:
        raise KeyError(
            f"no fixture writer for family {family!r}; have: "
            f"{', '.join(sorted(_WRITERS))}"
        ) from None
    os.makedirs(root, exist_ok=True)
    return writer(root, **kwargs)
