"""COLMAP sparse-model I/O: cameras / images / points3D, binary and text.

Reference: input_pipelines/colmap_utils.py:420-439 (read_model) and the
per-table readers (:225-257 images, :336-363 points). Implemented from the
COLMAP file-format spec (scripts/python/read_write_model.py documents it):

  cameras.bin : u64 count; per camera: i32 id, i32 model_id, u64 w, u64 h,
                f64 params[num_params(model)]
  images.bin  : u64 count; per image: i32 id, f64 qvec[4], f64 tvec[3],
                i32 camera_id, cstring name, u64 n_pts, (f64 x, f64 y,
                i64 point3D_id)[n_pts]
  points3D.bin: u64 count; per point: i64 id, f64 xyz[3], u8 rgb[3],
                f64 error, u64 track_len, (i32 image_id, i32 p2d_idx)[len]

Writers exist for test fixtures (the reference ships no fixtures at all,
SURVEY.md §4 — synthetic COLMAP scenes are how this repo integration-tests
its data pipelines without dataset downloads).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass

import numpy as np

CAMERA_MODELS = {
    0: ("SIMPLE_PINHOLE", 3),
    1: ("PINHOLE", 4),
    2: ("SIMPLE_RADIAL", 4),
    3: ("RADIAL", 5),
    4: ("OPENCV", 8),
    5: ("OPENCV_FISHEYE", 8),
    6: ("FULL_OPENCV", 12),
    7: ("FOV", 5),
    8: ("SIMPLE_RADIAL_FISHEYE", 4),
    9: ("RADIAL_FISHEYE", 5),
    10: ("THIN_PRISM_FISHEYE", 12),
}
_MODEL_IDS = {name: mid for mid, (name, _) in CAMERA_MODELS.items()}


@dataclass(frozen=True)
class Camera:
    id: int
    model: str
    width: int
    height: int
    params: np.ndarray  # (num_params,) f64


@dataclass(frozen=True)
class ImageMeta:
    id: int
    qvec: np.ndarray  # (4,) wxyz
    tvec: np.ndarray  # (3,)
    camera_id: int
    name: str
    xys: np.ndarray  # (N, 2) keypoints
    point3d_ids: np.ndarray  # (N,) i64, -1 = untracked


@dataclass(frozen=True)
class Point3D:
    id: int
    xyz: np.ndarray  # (3,)
    rgb: np.ndarray  # (3,) u8
    error: float


def qvec2rotmat(qvec: np.ndarray) -> np.ndarray:
    """COLMAP wxyz quaternion -> rotation matrix (colmap_utils.py:454-464)."""
    w, x, y, z = qvec
    return np.array([
        [1 - 2 * y**2 - 2 * z**2, 2 * x * y - 2 * z * w, 2 * x * z + 2 * y * w],
        [2 * x * y + 2 * z * w, 1 - 2 * x**2 - 2 * z**2, 2 * y * z - 2 * x * w],
        [2 * x * z - 2 * y * w, 2 * y * z + 2 * x * w, 1 - 2 * x**2 - 2 * y**2],
    ])


def rotmat2qvec(r: np.ndarray) -> np.ndarray:
    """Rotation matrix -> wxyz quaternion (for the test-fixture writers)."""
    k = np.array([
        [r[0, 0] - r[1, 1] - r[2, 2], 0, 0, 0],
        [r[0, 1] + r[1, 0], r[1, 1] - r[0, 0] - r[2, 2], 0, 0],
        [r[0, 2] + r[2, 0], r[1, 2] + r[2, 1], r[2, 2] - r[0, 0] - r[1, 1], 0],
        [r[2, 1] - r[1, 2], r[0, 2] - r[2, 0], r[1, 0] - r[0, 1],
         r[0, 0] + r[1, 1] + r[2, 2]],
    ]) / 3.0
    vals, vecs = np.linalg.eigh(k)
    q = vecs[[3, 0, 1, 2], np.argmax(vals)]
    return -q if q[0] < 0 else q


# ------------------------------- binary IO ---------------------------------


def _read(fh, fmt: str):
    return struct.unpack(fmt, fh.read(struct.calcsize(fmt)))


def read_cameras_binary(path: str) -> dict[int, Camera]:
    out = {}
    with open(path, "rb") as fh:
        (n,) = _read(fh, "<Q")
        for _ in range(n):
            cam_id, model_id, w, h = _read(fh, "<iiQQ")
            name, n_params = CAMERA_MODELS[model_id]
            params = np.array(_read(fh, f"<{n_params}d"))
            out[cam_id] = Camera(cam_id, name, w, h, params)
    return out


def read_images_binary(path: str) -> dict[int, ImageMeta]:
    out = {}
    with open(path, "rb") as fh:
        (n,) = _read(fh, "<Q")
        for _ in range(n):
            img_id = _read(fh, "<i")[0]
            qvec = np.array(_read(fh, "<4d"))
            tvec = np.array(_read(fh, "<3d"))
            (camera_id,) = _read(fh, "<i")
            name = b""
            while (c := fh.read(1)) != b"\x00":
                name += c
            (n_pts,) = _read(fh, "<Q")
            data = np.frombuffer(
                fh.read(24 * n_pts), dtype=[("xy", "<2f8"), ("id", "<i8")]
            )
            out[img_id] = ImageMeta(
                img_id, qvec, tvec, camera_id, name.decode(),
                data["xy"].reshape(-1, 2).copy(), data["id"].copy(),
            )
    return out


def read_points3d_binary(path: str) -> dict[int, Point3D]:
    out = {}
    with open(path, "rb") as fh:
        (n,) = _read(fh, "<Q")
        for _ in range(n):
            pt_id = _read(fh, "<q")[0]
            xyz = np.array(_read(fh, "<3d"))
            rgb = np.array(_read(fh, "<3B"), dtype=np.uint8)
            (error,) = _read(fh, "<d")
            (track_len,) = _read(fh, "<Q")
            fh.read(8 * track_len)  # (i32 image_id, i32 point2D_idx) pairs
            out[pt_id] = Point3D(pt_id, xyz, rgb, float(error))
    return out


def write_cameras_binary(cameras: dict[int, Camera], path: str) -> None:
    with open(path, "wb") as fh:
        fh.write(struct.pack("<Q", len(cameras)))
        for cam in cameras.values():
            fh.write(struct.pack("<iiQQ", cam.id, _MODEL_IDS[cam.model],
                                 cam.width, cam.height))
            fh.write(struct.pack(f"<{len(cam.params)}d", *cam.params))


def write_images_binary(images: dict[int, ImageMeta], path: str) -> None:
    with open(path, "wb") as fh:
        fh.write(struct.pack("<Q", len(images)))
        for im in images.values():
            fh.write(struct.pack("<i", im.id))
            fh.write(struct.pack("<4d", *im.qvec))
            fh.write(struct.pack("<3d", *im.tvec))
            fh.write(struct.pack("<i", im.camera_id))
            fh.write(im.name.encode() + b"\x00")
            fh.write(struct.pack("<Q", len(im.xys)))
            for xy, pid in zip(im.xys, im.point3d_ids):
                fh.write(struct.pack("<ddq", xy[0], xy[1], pid))


def write_points3d_binary(points: dict[int, Point3D], path: str) -> None:
    with open(path, "wb") as fh:
        fh.write(struct.pack("<Q", len(points)))
        for pt in points.values():
            fh.write(struct.pack("<q", pt.id))
            fh.write(struct.pack("<3d", *pt.xyz))
            fh.write(struct.pack("<3B", *pt.rgb))
            fh.write(struct.pack("<d", pt.error))
            fh.write(struct.pack("<Q", 0))  # empty track


# -------------------------------- text IO ----------------------------------


def read_cameras_text(path: str) -> dict[int, Camera]:
    out = {}
    with open(path) as fh:
        for line in fh:
            if not line.strip() or line.startswith("#"):
                continue
            parts = line.split()
            cam_id, model = int(parts[0]), parts[1]
            out[cam_id] = Camera(
                cam_id, model, int(parts[2]), int(parts[3]),
                np.array([float(p) for p in parts[4:]]),
            )
    return out


def read_images_text(path: str) -> dict[int, ImageMeta]:
    out = {}
    with open(path) as fh:
        lines = [ln for ln in fh if ln.strip() and not ln.startswith("#")]
    for meta_line, pts_line in zip(lines[0::2], lines[1::2]):
        parts = meta_line.split()
        img_id = int(parts[0])
        qvec = np.array([float(p) for p in parts[1:5]])
        tvec = np.array([float(p) for p in parts[5:8]])
        camera_id, name = int(parts[8]), parts[9]
        pts = pts_line.split()
        xys = np.array([[float(x), float(y)] for x, y in zip(pts[0::3], pts[1::3])])
        ids = np.array([int(i) for i in pts[2::3]], dtype=np.int64)
        out[img_id] = ImageMeta(
            img_id, qvec, tvec, camera_id, name,
            xys.reshape(-1, 2), ids,
        )
    return out


def read_points3d_text(path: str) -> dict[int, Point3D]:
    out = {}
    with open(path) as fh:
        for line in fh:
            if not line.strip() or line.startswith("#"):
                continue
            parts = line.split()
            pt_id = int(parts[0])
            out[pt_id] = Point3D(
                pt_id,
                np.array([float(p) for p in parts[1:4]]),
                np.array([int(p) for p in parts[4:7]], dtype=np.uint8),
                float(parts[7]),
            )
    return out


def read_model(
    path: str, ext: str = ".bin"
) -> tuple[dict[int, Camera], dict[int, ImageMeta], dict[int, Point3D]]:
    """Load a sparse model directory (colmap_utils.py:420-439)."""
    if ext == ".bin":
        return (
            read_cameras_binary(os.path.join(path, "cameras.bin")),
            read_images_binary(os.path.join(path, "images.bin")),
            read_points3d_binary(os.path.join(path, "points3D.bin")),
        )
    if ext == ".txt":
        return (
            read_cameras_text(os.path.join(path, "cameras.txt")),
            read_images_text(os.path.join(path, "images.txt")),
            read_points3d_text(os.path.join(path, "points3D.txt")),
        )
    raise ValueError(f"unknown model extension {ext!r}")
