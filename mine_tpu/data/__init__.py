"""Data pipelines (reference: input_pipelines/).

The dataset registry (data/registry.py) is the one name -> builder table;
data/conformance/ is the contract-and-fixture harness that proves every
registered config runs train -> eval -> serve hermetically on CPU.
"""

from mine_tpu.data.pipeline import (
    LoaderRetriesExhausted,
    TransientLoaderError,
    prefetch,
)
from mine_tpu.data.registry import (
    UnknownDatasetError,
    build_dataset,
    registered_names,
)
from mine_tpu.data.synthetic import SyntheticDataset, make_synthetic_batch
