"""Data pipelines (reference: input_pipelines/)."""

from mine_tpu.data.pipeline import (
    LoaderRetriesExhausted,
    TransientLoaderError,
    prefetch,
)
from mine_tpu.data.synthetic import SyntheticDataset, make_synthetic_batch
