"""DTU multi-view pipeline (the `dtu` recipe the reference fork ships a
params yaml for but raises NotImplementedError on).

Layout per scan, the MVSNet-lineage camera convention DTU is almost always
distributed in:

  * `<root>/<scan>/images[_val]/<id>.png` — the posed views.
  * `<root>/<scan>/cams/<id>_cam.txt` — per-view camera file:

        extrinsic
        <4x4 world-to-camera, row per line>

        intrinsic
        <3x3 K at the stored image resolution>

    (a trailing `depth_min depth_interval` line may follow; ignored — the
    recipe's mpi.disparity_start/end carry the sweep range).

Val views are a held-out id set in `images_val/`, sharing the one `cams/`
directory (ids are global per scan). K rescales per-axis from the stored
image size to the target (img_h, img_w). DTU's structured-light ground
truth is dense depth, not sparse SfM tracks, and MINE's dtu recipe trains
without sparse-depth supervision (`dtu` is in training/step.py
NO_DISP_SUPERVISION) — frames ship `pts_cam=None`.
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data.frames import PosedFrame, PosedFrameDataset


def parse_cam_file(path: str) -> tuple[np.ndarray, np.ndarray]:
    """MVSNet cam.txt -> (extrinsic (4,4) world-to-camera, intrinsic (3,3)
    at stored resolution)."""
    with open(path) as fh:
        tokens = fh.read().split()
    try:
        e_at = tokens.index("extrinsic")
        i_at = tokens.index("intrinsic")
    except ValueError:
        raise ValueError(
            f"{path}: missing 'extrinsic'/'intrinsic' section headers "
            "(MVSNet cam.txt format)"
        ) from None
    try:
        extr = np.asarray(
            [float(v) for v in tokens[e_at + 1:e_at + 17]], np.float64
        ).reshape(4, 4)
        intr = np.asarray(
            [float(v) for v in tokens[i_at + 1:i_at + 10]], np.float64
        ).reshape(3, 3)
    except ValueError as exc:
        raise ValueError(f"{path}: malformed camera matrix: {exc}") from None
    return extr, intr


def load_scan(
    scan_dir: str, split: str, img_hw: tuple[int, int]
) -> list[PosedFrame]:
    """Load every posed view of one scan directory."""
    suffix = "_val" if split == "val" else ""
    image_dir = os.path.join(scan_dir, "images" + suffix)
    if not os.path.isdir(image_dir):
        return []
    scan = os.path.basename(scan_dir.rstrip("/"))
    h, w = img_hw
    frames: list[PosedFrame] = []
    for name in sorted(os.listdir(image_dir)):
        stem, ext = os.path.splitext(name)
        if ext.lower() not in (".png", ".jpg", ".jpeg"):
            continue
        cam_path = os.path.join(scan_dir, "cams", f"{stem}_cam.txt")
        if not os.path.exists(cam_path):
            raise FileNotFoundError(
                f"{image_dir}/{name}: no paired camera file {cam_path}"
            )
        extr, intr = parse_cam_file(cam_path)
        with Image.open(os.path.join(image_dir, name)) as im:
            stored_w, stored_h = im.width, im.height
            img = np.asarray(
                im.convert("RGB").resize((w, h), Image.BICUBIC),
                dtype=np.float32,
            ) / 255.0
        k = np.array(
            [[intr[0, 0] * w / stored_w, 0.0, intr[0, 2] * w / stored_w],
             [0.0, intr[1, 1] * h / stored_h, intr[1, 2] * h / stored_h],
             [0.0, 0.0, 1.0]],
            dtype=np.float32,
        )
        frames.append(PosedFrame(
            scene=scan, img=img, k=k,
            g_cam_world=extr.astype(np.float32),
            pts_cam=None,  # no sparse supervision (module docstring)
        ))
    return frames


class DTUDataset(PosedFrameDataset):
    """Loader-protocol dataset over DTU scan directories; target candidates
    are all other views of the scan (DTU cameras all see the one object —
    no temporal window)."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        root = cfg.data.training_set_path
        frames: list[PosedFrame] = []
        for scan in sorted(os.listdir(root)):
            scan_dir = os.path.join(root, scan)
            if not os.path.isdir(scan_dir):
                continue
            frames.extend(load_scan(
                scan_dir, split, (cfg.data.img_h, cfg.data.img_w)
            ))
        if not frames:
            raise FileNotFoundError(
                f"no DTU views under {root!r} (expected <scan>/images"
                f"{'_val' if split == 'val' else ''}/ + <scan>/cams/)"
            )
        super().__init__(cfg, split, global_batch, frames,
                         host_slice=host_slice)
