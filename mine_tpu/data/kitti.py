"""KITTI raw pipeline — the second headline MINE benchmark (768x256 N=64 in
the pretrained zoo, BASELINE.md capability envelope); the reference fork
raises NotImplementedError for it.

Layout per drive (the KITTI wire formats, trimmed to what the recipe
needs — monocular left-color stream + poses):

  * `<root>/<drive>/image_02/data[_val]/*.png` — the rectified left color
    frames; the filename stem is the frame index (KITTI's zero-padded
    numbering).
  * `<root>/<drive>/poses[_val].txt` — one row-major 3x4 CAM-to-WORLD
    matrix per frame index (the KITTI odometry pose convention, which the
    raw-data GPS/IMU chain is usually baked down to for view-synthesis
    use; `pykitti`-style oxts integration happens offline, not in the
    loader).
  * `<root>/<drive>/calib.txt` — the `P2:` projection row of the
    rectified left color camera (12 values; fx = P[0], cx = P[2],
    fy = P[5], cy = P[6] at the STORED frame resolution, like KITTI's
    calib_cam_to_cam P_rect_02).

K scales per-axis from the stored frame size to the target (img_h, img_w)
exactly like the COLMAP loaders. KITTI carries no per-frame sparse point
tracks in this stream, so frames ship `pts_cam=None`: the recipe trains
WITHOUT sparse-depth supervision — `kitti_raw` is in training/step.py's
NO_DISP_SUPERVISION, the contract's `sparse_depth=False`
(data/conformance/).
"""

from __future__ import annotations

import os

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data.frames import PosedFrame, PosedFrameDataset

# target candidates: same-drive frames within this many list positions
# (KITTI is 10 Hz video; nearby frames give usable stereo-like baselines)
FRAME_WINDOW = 10


def parse_calib(path: str) -> np.ndarray:
    """`P2:` row of a KITTI calib file -> (3, 4) projection matrix."""
    with open(path) as fh:
        for line in fh:
            key, _, rest = line.partition(":")
            if key.strip() == "P2":
                vals = [float(v) for v in rest.split()]
                if len(vals) != 12:
                    raise ValueError(
                        f"{path}: P2 row has {len(vals)} values, expected 12"
                    )
                return np.asarray(vals, np.float64).reshape(3, 4)
    raise ValueError(f"{path}: no P2 row (rectified left color projection)")


def parse_poses(path: str) -> np.ndarray:
    """Pose file -> (N, 4, 4) cam-to-world stack."""
    rows = np.loadtxt(path, dtype=np.float64)
    rows = np.atleast_2d(rows)
    if rows.shape[1] != 12:
        raise ValueError(
            f"{path}: pose rows must be 12 values (3x4 cam-to-world), got "
            f"{rows.shape[1]}"
        )
    out = np.tile(np.eye(4), (len(rows), 1, 1))
    out[:, :3, :4] = rows.reshape(-1, 3, 4)
    return out


def load_drive(
    drive_dir: str, split: str, img_hw: tuple[int, int]
) -> list[PosedFrame]:
    """Load every posed frame of one drive directory."""
    suffix = "_val" if split == "val" else ""
    image_dir = os.path.join(drive_dir, "image_02", "data" + suffix)
    if not os.path.isdir(image_dir):
        return []
    p2 = parse_calib(os.path.join(drive_dir, "calib.txt"))
    c2w = parse_poses(os.path.join(drive_dir, f"poses{suffix}.txt"))
    drive = os.path.basename(drive_dir.rstrip("/"))

    h, w = img_hw
    frames: list[PosedFrame] = []
    for name in sorted(os.listdir(image_dir)):
        stem, ext = os.path.splitext(name)
        if ext.lower() not in (".png", ".jpg", ".jpeg"):
            continue
        try:
            frame_idx = int(stem)
        except ValueError:
            raise ValueError(
                f"{image_dir}/{name}: filename stem must be the KITTI frame "
                "index (the pose-row key)"
            ) from None
        if frame_idx >= len(c2w):
            raise ValueError(
                f"{image_dir}/{name}: frame index {frame_idx} beyond the "
                f"{len(c2w)} rows of poses{suffix}.txt — truncated pose file?"
            )
        with Image.open(os.path.join(image_dir, name)) as im:
            stored_w, stored_h = im.width, im.height
            img = np.asarray(
                im.convert("RGB").resize((w, h), Image.BICUBIC),
                dtype=np.float32,
            ) / 255.0
        # P2 intrinsics live at the stored frame resolution; per-axis
        # rescale to the target exactly like the COLMAP loaders
        k = np.array(
            [[p2[0, 0] * w / stored_w, 0.0, p2[0, 2] * w / stored_w],
             [0.0, p2[1, 1] * h / stored_h, p2[1, 2] * h / stored_h],
             [0.0, 0.0, 1.0]],
            dtype=np.float32,
        )
        g_cam_world = np.linalg.inv(c2w[frame_idx]).astype(np.float32)
        frames.append(PosedFrame(
            scene=drive, img=img, k=k, g_cam_world=g_cam_world,
            pts_cam=None,  # no sparse supervision (module docstring)
        ))
    return frames


class KittiRawDataset(PosedFrameDataset):
    """Loader-protocol dataset over KITTI drive directories."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        root = cfg.data.training_set_path
        frames: list[PosedFrame] = []
        for drive in sorted(os.listdir(root)):
            drive_dir = os.path.join(root, drive)
            if not os.path.isdir(drive_dir):
                continue
            frames.extend(load_drive(
                drive_dir, split, (cfg.data.img_h, cfg.data.img_w)
            ))
        if not frames:
            raise FileNotFoundError(
                f"no KITTI frames under {root!r} "
                f"(expected <drive>/image_02/data"
                f"{'_val' if split == 'val' else ''}/)"
            )
        super().__init__(cfg, split, global_batch, frames,
                         host_slice=host_slice)

    def candidate_targets(self, src_idx: int) -> list[int]:
        # nearby-frame pairs; per-drive indices are contiguous
        return [
            i for i in self.scene_indices[self.frames[src_idx].scene]
            if i != src_idx and abs(i - src_idx) <= FRAME_WINDOW
        ]

    def _validate_candidates(self) -> None:
        if self.num_tgt_views > FRAME_WINDOW:
            raise ValueError(
                f"data.num_tgt_views={self.num_tgt_views} exceeds the "
                f"±{FRAME_WINDOW}-frame candidate window"
            )
        for drive, idxs in self.scene_indices.items():
            if len(idxs) < self.num_tgt_views + 1:
                raise ValueError(
                    f"drive {drive} has {len(idxs)} frame(s); need >= "
                    f"{self.num_tgt_views + 1}"
                )
