"""LLFF / NOCS posed-multi-view pipelines over COLMAP sparse models.

Reference: input_pipelines/llff/nerf_dataset.py (LLFF) and nocs_dataset.py
(NOCS variant: center-crop + first-50-images cap). Behaviors kept:

  * scene layout <root>/<scene>/{sparse/0, images_<ratio>[_val]/}
  * eager RAM load of the (small) scene set at construction
    (nerf_dataset.py:61-98)
  * K built from the single SIMPLE_RADIAL camera with per-axis ratios
    between stored-image and target resolution (nerf_dataset.py:152-163)
  * per-image COLMAP points transformed to the camera frame; per-item random
    point subsets; train targets sampled uniformly from the same scene, val
    target = deterministic neighbor (nerf_dataset.py:199-236)

Deliberate fixes (cited deviations):
  * NOCS center-crop now shifts the principal point by the crop offset; the
    reference computes its ratios from the post-crop size so the crop never
    reaches K (nocs_dataset.py:96-109) — a geometry error, not a feature.
  * batches come out in this framework's channel-last contract
    (training/step.py) with G_tgt_src precomputed, replacing the reference's
    collate + set_data staging (nerf_dataset.py:15-30,
    synthesis_task.py:187-212).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data import colmap

# near-plane cull threshold as a fraction of an image's median track depth
# (load_scene): small enough that genuine foreground (a near occluder at
# 1/10th the median) survives, large enough that lens-grazing COLMAP
# artifacts (z ~ 1e-5 of scene scale) cannot reach 1/z supervision.
# The cull itself moved to the shared frame core (data/frames.py) when the
# RealEstate10K loader grew the same need; re-exported for compat.
from mine_tpu.data.frames import (  # noqa: F401 - re-export
    MIN_DEPTH_FRACTION,
    PosedFrameDataset,
    cull_near_points,
)


@dataclass
class PosedImage:
    scene: str
    img: np.ndarray  # (H, W, 3) f32 in [0, 1]
    k: np.ndarray  # (3, 3) f32
    g_cam_world: np.ndarray  # (4, 4) f32
    pts_cam: np.ndarray  # (N, 3) f32 camera-frame COLMAP points


def _load_image(path: str, img_hw: tuple[int, int], center_crop: tuple[int, int] | None):
    """PIL load; optional center crop; bicubic resize to (H, W). Returns
    (img f32 HWC, stored (w, h), crop offset (left, top))."""
    img = Image.open(path).convert("RGB")
    left = top = 0
    if center_crop is not None:
        ch, cw = center_crop
        left = (img.width - cw) // 2
        top = (img.height - ch) // 2
        img = img.crop((left, top, left + cw, top + ch))
    w, h = img.width, img.height
    img = img.resize((img_hw[1], img_hw[0]), Image.BICUBIC)
    arr = np.asarray(img, dtype=np.float32) / 255.0
    return arr, (w, h), (left, top)


def load_scene(
    scene_dir: str,
    image_folder: str,
    img_hw: tuple[int, int],
    pre_downsample_ratio: float,
    center_crop: tuple[int, int] | None = None,
    max_images: int | None = None,
    min_points: int = 1,
) -> list[PosedImage]:
    """Load every posed image of one COLMAP scene (nerf_dataset.py:61-98).

    Robustness deviations from the reference (all fail-loud or accounted,
    VERDICT r4 #6 — real COLMAP output is messier than fixtures):
      * SIMPLE_RADIAL distortion is read and IGNORED exactly like the
        reference (nerf_dataset.py:154-163 uses params[0:3] only), but a
        non-trivial coefficient warns instead of silently mis-projecting.
      * points behind the camera OR closer than MIN_DEPTH_FRACTION of the
        image's median track depth are dropped from that image's track — a
        negative/zero depth would NaN the 1/z disparity supervision, and a
        lens-grazing near outlier would dominate the exp(mean(log)) scale
        calibration (losses/metrics.py compute_scale_factor, ADVICE r5).
      * a track referencing a 3D point id missing from points3D fails with
        the offending image, not a bare KeyError.
    """
    cameras, images, points3d = colmap.read_model(os.path.join(scene_dir, "sparse/0"))
    assert len(cameras) == 1, f"{scene_dir}: expected a single shared camera"
    cam = next(iter(cameras.values()))
    # K below is built from params[0:3] as (f, cx, cy) — the SIMPLE_* layout.
    # Other COLMAP models (PINHOLE: fx,fy,cx,cy; RADIAL/OPENCV: more) would
    # be silently MISREAD under that indexing, so reject them loudly rather
    # than warn (the reference hard-assumes SIMPLE_RADIAL and would misread
    # them the same way, nerf_dataset.py:154-163).
    if cam.model not in ("SIMPLE_PINHOLE", "SIMPLE_RADIAL"):
        raise ValueError(
            f"{scene_dir}: camera model {cam.model} has a parameter layout "
            "this loader (and the reference) cannot read; re-run COLMAP "
            "with a SIMPLE_* camera model, or extend load_scene"
        )
    if len(cam.params) > 3 and np.any(np.abs(cam.params[3:]) > 1e-8):
        import warnings

        warnings.warn(
            f"{scene_dir}: camera model {cam.model} has non-trivial "
            f"distortion params {cam.params[3:].tolist()} which are IGNORED "
            "(reference parity, nerf_dataset.py:154-163); undistort images "
            "first (colmap image_undistorter) for geometric accuracy",
            stacklevel=2,
        )

    out: list[PosedImage] = []
    for img_id in sorted(images):
        if max_images is not None and len(out) >= max_images:
            break
        meta = images[img_id]
        path = os.path.join(scene_dir, image_folder, meta.name)
        if not os.path.exists(path):
            continue
        arr, (w, h), (left, top) = _load_image(path, img_hw, center_crop)

        # stored image is the original divided by pre_downsample_ratio; the
        # COLMAP camera lives at original resolution (nerf_dataset.py:152-158)
        ratio_x = w * pre_downsample_ratio / img_hw[1]
        ratio_y = h * pre_downsample_ratio / img_hw[0]
        f = cam.params[0]
        cx, cy = cam.params[1], cam.params[2]
        # principal point shifts by the crop offset at stored resolution
        # (deviation from nocs_dataset.py:96-109 — see module docstring)
        cx -= left * pre_downsample_ratio
        cy -= top * pre_downsample_ratio
        k = np.array(
            [[f / ratio_x, 0.0, cx / ratio_x],
             [0.0, f / ratio_y, cy / ratio_y],
             [0.0, 0.0, 1.0]],
            dtype=np.float32,
        )

        r = colmap.qvec2rotmat(meta.qvec).astype(np.float32)
        t = meta.tvec.astype(np.float32)
        g = np.eye(4, dtype=np.float32)
        g[:3, :3] = r
        g[:3, 3] = t

        tracked = meta.point3d_ids >= 0
        try:
            world = np.stack(
                [points3d[pid].xyz for pid in meta.point3d_ids[tracked]]
            ) if tracked.any() else np.zeros((0, 3))
        except KeyError as e:
            raise ValueError(
                f"{path}: track references 3D point id {e.args[0]} absent "
                "from points3D — corrupt/truncated COLMAP model"
            ) from None
        pts_cam = (world @ r.T + t).astype(np.float32)  # (N, 3)
        n_tracked = len(pts_cam)
        # Scene-meaningful near-plane cull, not just z > 0 (shared with the
        # RealEstate10K loader, data/frames.py cull_near_points): a single
        # lens-grazing COLMAP artifact would dominate the exp(mean(log))
        # scale calibration and the log-disparity loss (ADVICE r5).
        pts_cam, min_depth = cull_near_points(pts_cam)
        if len(pts_cam) < min_points:
            raise ValueError(
                f"{path}: {len(pts_cam)} usable points < required "
                f"{min_points} ({n_tracked} tracked, "
                f"{n_tracked - len(pts_cam)} culled below the scene min "
                f"depth {min_depth:.3g})"
            )
        out.append(PosedImage(os.path.basename(scene_dir), arr, k, g, pts_cam))
    return out


class LLFFDataset(PosedFrameDataset):
    """Loader-protocol dataset over COLMAP scene directories (the shared
    frame core, data/frames.py, owns the epoch machinery: drop-last vs
    wrap-pad tails, num_tgt_views flattening, per-example-seeded
    host_slice rows)."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        is_val = split == "val"
        ratio = cfg.data.img_pre_downsample_ratio
        folder = "images" if ratio is None or ratio <= 1 else f"images_{ratio}"
        if is_val:
            folder += "_val"
        is_nocs = cfg.data.name == "nocs_llff"
        crop = (384, 640) if is_nocs else None

        root = cfg.data.training_set_path
        images: list[PosedImage] = []
        for scene in sorted(os.listdir(root)):
            scene_dir = os.path.join(root, scene)
            if not os.path.isdir(scene_dir):
                continue
            images.extend(
                load_scene(
                    scene_dir, folder, (cfg.data.img_h, cfg.data.img_w),
                    1.0 if is_nocs else ratio,
                    center_crop=crop,
                    # reference NOCS caps at the first ~50 images
                    # (nocs_dataset.py:71-75)
                    max_images=51 if is_nocs else None,
                    min_points=cfg.data.visible_point_count,
                )
            )
        if not images:
            raise FileNotFoundError(f"no posed images under {root!r} ({folder})")
        super().__init__(cfg, split, global_batch, images,
                         host_slice=host_slice)
        self.images = self.frames  # historical attribute name
