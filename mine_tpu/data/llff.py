"""LLFF / NOCS posed-multi-view pipelines over COLMAP sparse models.

Reference: input_pipelines/llff/nerf_dataset.py (LLFF) and nocs_dataset.py
(NOCS variant: center-crop + first-50-images cap). Behaviors kept:

  * scene layout <root>/<scene>/{sparse/0, images_<ratio>[_val]/}
  * eager RAM load of the (small) scene set at construction
    (nerf_dataset.py:61-98)
  * K built from the single SIMPLE_RADIAL camera with per-axis ratios
    between stored-image and target resolution (nerf_dataset.py:152-163)
  * per-image COLMAP points transformed to the camera frame; per-item random
    point subsets; train targets sampled uniformly from the same scene, val
    target = deterministic neighbor (nerf_dataset.py:199-236)

Deliberate fixes (cited deviations):
  * NOCS center-crop now shifts the principal point by the crop offset; the
    reference computes its ratios from the post-crop size so the crop never
    reaches K (nocs_dataset.py:96-109) — a geometry error, not a feature.
  * batches come out in this framework's channel-last contract
    (training/step.py) with G_tgt_src precomputed, replacing the reference's
    collate + set_data staging (nerf_dataset.py:15-30,
    synthesis_task.py:187-212).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data import colmap

# near-plane cull threshold as a fraction of an image's median track depth
# (load_scene): small enough that genuine foreground (a near occluder at
# 1/10th the median) survives, large enough that lens-grazing COLMAP
# artifacts (z ~ 1e-5 of scene scale) cannot reach 1/z supervision
MIN_DEPTH_FRACTION = 0.01


@dataclass
class PosedImage:
    scene: str
    img: np.ndarray  # (H, W, 3) f32 in [0, 1]
    k: np.ndarray  # (3, 3) f32
    g_cam_world: np.ndarray  # (4, 4) f32
    pts_cam: np.ndarray  # (N, 3) f32 camera-frame COLMAP points


def _load_image(path: str, img_hw: tuple[int, int], center_crop: tuple[int, int] | None):
    """PIL load; optional center crop; bicubic resize to (H, W). Returns
    (img f32 HWC, stored (w, h), crop offset (left, top))."""
    img = Image.open(path).convert("RGB")
    left = top = 0
    if center_crop is not None:
        ch, cw = center_crop
        left = (img.width - cw) // 2
        top = (img.height - ch) // 2
        img = img.crop((left, top, left + cw, top + ch))
    w, h = img.width, img.height
    img = img.resize((img_hw[1], img_hw[0]), Image.BICUBIC)
    arr = np.asarray(img, dtype=np.float32) / 255.0
    return arr, (w, h), (left, top)


def load_scene(
    scene_dir: str,
    image_folder: str,
    img_hw: tuple[int, int],
    pre_downsample_ratio: float,
    center_crop: tuple[int, int] | None = None,
    max_images: int | None = None,
    min_points: int = 1,
) -> list[PosedImage]:
    """Load every posed image of one COLMAP scene (nerf_dataset.py:61-98).

    Robustness deviations from the reference (all fail-loud or accounted,
    VERDICT r4 #6 — real COLMAP output is messier than fixtures):
      * SIMPLE_RADIAL distortion is read and IGNORED exactly like the
        reference (nerf_dataset.py:154-163 uses params[0:3] only), but a
        non-trivial coefficient warns instead of silently mis-projecting.
      * points behind the camera OR closer than MIN_DEPTH_FRACTION of the
        image's median track depth are dropped from that image's track — a
        negative/zero depth would NaN the 1/z disparity supervision, and a
        lens-grazing near outlier would dominate the exp(mean(log)) scale
        calibration (losses/metrics.py compute_scale_factor, ADVICE r5).
      * a track referencing a 3D point id missing from points3D fails with
        the offending image, not a bare KeyError.
    """
    cameras, images, points3d = colmap.read_model(os.path.join(scene_dir, "sparse/0"))
    assert len(cameras) == 1, f"{scene_dir}: expected a single shared camera"
    cam = next(iter(cameras.values()))
    # K below is built from params[0:3] as (f, cx, cy) — the SIMPLE_* layout.
    # Other COLMAP models (PINHOLE: fx,fy,cx,cy; RADIAL/OPENCV: more) would
    # be silently MISREAD under that indexing, so reject them loudly rather
    # than warn (the reference hard-assumes SIMPLE_RADIAL and would misread
    # them the same way, nerf_dataset.py:154-163).
    if cam.model not in ("SIMPLE_PINHOLE", "SIMPLE_RADIAL"):
        raise ValueError(
            f"{scene_dir}: camera model {cam.model} has a parameter layout "
            "this loader (and the reference) cannot read; re-run COLMAP "
            "with a SIMPLE_* camera model, or extend load_scene"
        )
    if len(cam.params) > 3 and np.any(np.abs(cam.params[3:]) > 1e-8):
        import warnings

        warnings.warn(
            f"{scene_dir}: camera model {cam.model} has non-trivial "
            f"distortion params {cam.params[3:].tolist()} which are IGNORED "
            "(reference parity, nerf_dataset.py:154-163); undistort images "
            "first (colmap image_undistorter) for geometric accuracy",
            stacklevel=2,
        )

    out: list[PosedImage] = []
    for img_id in sorted(images):
        if max_images is not None and len(out) >= max_images:
            break
        meta = images[img_id]
        path = os.path.join(scene_dir, image_folder, meta.name)
        if not os.path.exists(path):
            continue
        arr, (w, h), (left, top) = _load_image(path, img_hw, center_crop)

        # stored image is the original divided by pre_downsample_ratio; the
        # COLMAP camera lives at original resolution (nerf_dataset.py:152-158)
        ratio_x = w * pre_downsample_ratio / img_hw[1]
        ratio_y = h * pre_downsample_ratio / img_hw[0]
        f = cam.params[0]
        cx, cy = cam.params[1], cam.params[2]
        # principal point shifts by the crop offset at stored resolution
        # (deviation from nocs_dataset.py:96-109 — see module docstring)
        cx -= left * pre_downsample_ratio
        cy -= top * pre_downsample_ratio
        k = np.array(
            [[f / ratio_x, 0.0, cx / ratio_x],
             [0.0, f / ratio_y, cy / ratio_y],
             [0.0, 0.0, 1.0]],
            dtype=np.float32,
        )

        r = colmap.qvec2rotmat(meta.qvec).astype(np.float32)
        t = meta.tvec.astype(np.float32)
        g = np.eye(4, dtype=np.float32)
        g[:3, :3] = r
        g[:3, 3] = t

        tracked = meta.point3d_ids >= 0
        try:
            world = np.stack(
                [points3d[pid].xyz for pid in meta.point3d_ids[tracked]]
            ) if tracked.any() else np.zeros((0, 3))
        except KeyError as e:
            raise ValueError(
                f"{path}: track references 3D point id {e.args[0]} absent "
                "from points3D — corrupt/truncated COLMAP model"
            ) from None
        pts_cam = (world @ r.T + t).astype(np.float32)  # (N, 3)
        n_tracked = len(pts_cam)
        # Scene-meaningful near-plane cull, not just z > 0: COLMAP tracks
        # occasionally triangulate a point millimeters in front of the lens,
        # and a single z=1e-5 survivor contributes log(1/z) ~ 11.5 to
        # compute_scale_factor's exp(mean(log...)) — one outlier can shift
        # the whole image's scale calibration and the log-disparity loss
        # (ADVICE r5). A point closer than a small fraction of the image's
        # MEDIAN track depth is a reconstruction artifact, not geometry.
        z = pts_cam[:, 2]
        positive = z[z > 0]
        min_depth = (
            max(MIN_DEPTH_FRACTION * float(np.median(positive)), 1e-6)
            if len(positive) else 1e-6
        )
        pts_cam = pts_cam[z > min_depth]
        if len(pts_cam) < min_points:
            raise ValueError(
                f"{path}: {len(pts_cam)} usable points < required "
                f"{min_points} ({n_tracked} tracked, "
                f"{n_tracked - len(pts_cam)} culled below the scene min "
                f"depth {min_depth:.3g})"
            )
        out.append(PosedImage(os.path.basename(scene_dir), arr, k, g, pts_cam))
    return out


class LLFFDataset:
    """Loader-protocol dataset: steps_per_epoch + epoch(n) batch iterator.

    Replaces torch Dataset + DistributedSampler + DataLoader + collate
    (train.py:76-132): one logical global batch per step, sharded onto the
    mesh by the loop.
    """

    def __init__(self, cfg: Config, split: str, global_batch: int):
        self.cfg = cfg
        self.split = split
        self.global_batch = global_batch
        is_val = split == "val"
        self.is_val = is_val
        self.rng_seed = cfg.training.seed + (991 if is_val else 0)
        # num_tgt_views targets per source, each filling one batch slot (the
        # reference's supervision_count, which it caps at 1 in practice —
        # synthesis_task.py:203-204; here any k dividing the batch works)
        self.num_tgt_views = cfg.data.num_tgt_views
        if self.num_tgt_views < 1 or global_batch % self.num_tgt_views:
            raise ValueError(
                f"data.num_tgt_views={self.num_tgt_views} must be >= 1 and "
                f"divide the global batch {global_batch}"
            )

        ratio = cfg.data.img_pre_downsample_ratio
        folder = "images" if ratio is None or ratio <= 1 else f"images_{ratio}"
        if is_val:
            folder += "_val"
        is_nocs = cfg.data.name == "nocs_llff"
        crop = (384, 640) if is_nocs else None

        root = cfg.data.training_set_path
        self.images: list[PosedImage] = []
        for scene in sorted(os.listdir(root)):
            scene_dir = os.path.join(root, scene)
            if not os.path.isdir(scene_dir):
                continue
            self.images.extend(
                load_scene(
                    scene_dir, folder, (cfg.data.img_h, cfg.data.img_w),
                    1.0 if is_nocs else ratio,
                    center_crop=crop,
                    # reference NOCS caps at the first ~50 images
                    # (nocs_dataset.py:71-75)
                    max_images=51 if is_nocs else None,
                    min_points=cfg.data.visible_point_count,
                )
            )
        if not self.images:
            raise FileNotFoundError(f"no posed images under {root!r} ({folder})")
        if not is_val and len(self.images) < global_batch // self.num_tgt_views:
            # with drop_last a too-small train set would yield ZERO batches
            # per epoch — a silent no-op training run; fail loudly instead
            raise ValueError(
                f"train split has {len(self.images)} source image(s) but one "
                f"global batch needs {global_batch // self.num_tgt_views}; "
                "every epoch would be empty (reduce the batch or add data)"
            )
        # scene -> global indices (nerf_dataset.py scene_to_indices)
        self.scene_indices: dict[str, list[int]] = {}
        for i, im in enumerate(self.images):
            self.scene_indices.setdefault(im.scene, []).append(i)
        for scene, idxs in self.scene_indices.items():
            if len(idxs) < self.num_tgt_views + 1:
                raise ValueError(
                    f"scene {scene} has {len(idxs)} image(s); need >= "
                    f"{self.num_tgt_views + 1} for {self.num_tgt_views} target(s)"
                )

    def __len__(self) -> int:
        n_src = self.global_batch // self.num_tgt_views
        if self.is_val:
            # val covers EVERY image (reference run_eval iterates the full
            # val DataLoader, drop_last=False — synthesis_task.py:506-515);
            # the final short batch is wrap-padded to keep shapes static
            return -(-len(self.images) // n_src)
        # train drops the short tail (reference DataLoader drop_last=True,
        # train.py:110); __len__ must agree with what epoch() yields
        return len(self.images) // n_src

    @property
    def num_eval_examples(self) -> int:
        """Genuine (weight-1) examples one val epoch yields: every image
        serves as source exactly once, num_tgt_views pairs each. The eval
        loop audits its metered count against this (training/loop.py
        run_evaluation) so a wrap-pad miscount can't silently skew the one
        number users compare."""
        return len(self.images) * self.num_tgt_views

    def _examples(self, src_idx: int, rng: np.random.Generator) -> list[dict[str, np.ndarray]]:
        """num_tgt_views (src, tgt) pairs for one source view."""
        src = self.images[src_idx]
        scene_idxs = [i for i in self.scene_indices[src.scene] if i != src_idx]
        k = self.num_tgt_views
        if self.is_val:
            # deterministic neighbor(s) (nerf_dataset.py:205-208)
            base = (src_idx + 1) % len(scene_idxs) - 1
            tgt_idxs = [scene_idxs[(base + j) % len(scene_idxs)] for j in range(k)]
        else:
            tgt_idxs = [int(i) for i in rng.choice(scene_idxs, size=k, replace=False)]

        n_pt = self.cfg.data.visible_point_count
        out = []
        for tgt_idx in tgt_idxs:
            tgt = self.images[tgt_idx]
            src_pts = src.pts_cam[rng.choice(len(src.pts_cam), n_pt, replace=False)]
            tgt_pts = tgt.pts_cam[rng.choice(len(tgt.pts_cam), n_pt, replace=False)]
            # G_tgt_src maps src-camera coords to tgt-camera coords
            # (reference builds G_src_tgt then inverts at set_data,
            # nerf_dataset.py:219-221 + synthesis_task.py:211)
            g_tgt_src = tgt.g_cam_world @ np.linalg.inv(src.g_cam_world)
            out.append({
                "src_img": src.img,
                "tgt_img": tgt.img,
                "k_src": src.k,
                "k_tgt": tgt.k,
                "g_tgt_src": g_tgt_src.astype(np.float32),
                "pt3d_src": src_pts,
                "pt3d_tgt": tgt_pts,
            })
        return out

    def epoch(self, epoch: int):
        rng = np.random.default_rng((self.rng_seed, epoch))
        order = rng.permutation(len(self.images))
        n_src = self.global_batch // self.num_tgt_views
        for start in range(0, len(self) * n_src, n_src):
            idxs = order[start : start + n_src]
            n_genuine = len(idxs)
            if n_genuine < n_src:
                if not self.is_val:  # drop_last, like the reference's train
                    break            # DataLoader (train.py:110, drop_last=True)
                # Val: wrap-pad the tail from the start of the order so every
                # image is evaluated under one static batch shape (XLA: no
                # ragged batches; a short batch would force a recompile and
                # break even sharding across the data mesh axis). Padded
                # slots carry eval_weight 0.0 below, so the epoch average
                # counts every genuine example exactly once — parity with
                # the reference's full-set mean over its ragged final batch
                # (synthesis_task.py:506-515, update(..., n=B)).
                idxs = np.concatenate([idxs, np.resize(order, n_src - len(idxs))])
            examples = [e for i in idxs for e in self._examples(int(i), rng)]
            batch = {
                k: np.stack([e[k] for e in examples]) for k in examples[0]
            }
            if self.is_val:
                # per-example validity: num_tgt_views examples per source
                src_w = (np.arange(len(idxs)) < n_genuine).astype(np.float32)
                batch["eval_weight"] = np.repeat(src_w, self.num_tgt_views)
            yield batch
