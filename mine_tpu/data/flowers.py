"""Flowers light-field pipeline (the `flowers` recipe; reference fork
raises NotImplementedError). Zoo envelope: 512x384 N=32/N=64 (BASELINE.md).

The Flowers dataset (Srinivasan et al.'s Lytro light fields, the corpus
MINE's flowers recipe targets) ships each sample as ONE image tiling the
G x G grid of sub-aperture views; the sub-aperture cameras form a planar
translation array with a shared focal length. Layout:

  * `<root>/meta.json` — {"grid": G, "focal_px": f, "baseline": b}:
    G x G views per sample, focal in pixels at the STORED sub-aperture
    resolution, baseline = camera spacing in scene units.
  * `<root>/grids[_val]/*.png` — the tiled light-field samples; each file
    is one scene of G*G posed frames.

Geometry: view (row r, col c) sits at
t = baseline * (c - (G-1)/2, r - (G-1)/2, 0) with identity rotation, so
g_cam_world = [I | -t]; K has the shared focal (per-axis rescaled
stored -> target) and a centered principal point. Light fields carry no
sparse SfM tracks — frames ship `pts_cam=None` (`flowers` is in
training/step.py NO_DISP_SUPERVISION).
"""

from __future__ import annotations

import json
import os

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data.frames import PosedFrame, PosedFrameDataset


def load_meta(root: str) -> tuple[int, float, float]:
    """meta.json -> (grid, focal_px, baseline), validated."""
    path = os.path.join(root, "meta.json")
    try:
        with open(path) as fh:
            meta = json.load(fh)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"{path}: flowers needs the light-field metadata "
            '({"grid": G, "focal_px": f, "baseline": b})'
        ) from None
    try:
        grid = int(meta["grid"])
        focal = float(meta["focal_px"])
        baseline = float(meta["baseline"])
    except (KeyError, TypeError, ValueError) as exc:
        raise ValueError(f"{path}: bad metadata: {exc}") from None
    if grid < 2 or focal <= 0 or baseline <= 0:
        raise ValueError(
            f"{path}: grid must be >= 2 and focal_px/baseline > 0, got "
            f"grid={grid} focal_px={focal} baseline={baseline}"
        )
    return grid, focal, baseline


def load_grid(
    path: str, scene: str, grid: int, focal_px: float, baseline: float,
    img_hw: tuple[int, int],
) -> list[PosedFrame]:
    """One tiled light-field image -> G*G posed frames."""
    h, w = img_hw
    with Image.open(path) as im:
        full = np.asarray(im.convert("RGB"))
    fh, fw = full.shape[:2]
    if fh % grid or fw % grid:
        raise ValueError(
            f"{path}: image {fw}x{fh} is not a {grid}x{grid} tiling "
            "(dimensions must divide by the grid)"
        )
    vh, vw = fh // grid, fw // grid
    center = (grid - 1) / 2.0
    frames: list[PosedFrame] = []
    for r in range(grid):
        for c in range(grid):
            view = full[r * vh:(r + 1) * vh, c * vw:(c + 1) * vw]
            img = np.asarray(
                Image.fromarray(view).resize((w, h), Image.BICUBIC),
                dtype=np.float32,
            ) / 255.0
            k = np.array(
                [[focal_px * w / vw, 0.0, w / 2.0],
                 [0.0, focal_px * h / vh, h / 2.0],
                 [0.0, 0.0, 1.0]],
                dtype=np.float32,
            )
            t = baseline * np.array([c - center, r - center, 0.0])
            g = np.eye(4, dtype=np.float32)
            g[:3, 3] = -t  # world -> camera: X_cam = X_world - t
            frames.append(PosedFrame(
                scene=scene, img=img, k=k, g_cam_world=g,
                pts_cam=None,  # no sparse supervision (module docstring)
            ))
    return frames


class FlowersDataset(PosedFrameDataset):
    """Loader-protocol dataset over tiled light-field samples; target
    candidates are the other sub-aperture views of the same sample."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        root = cfg.data.training_set_path
        grid, focal_px, baseline = load_meta(root)
        folder = "grids_val" if split == "val" else "grids"
        grid_dir = os.path.join(root, folder)
        if not os.path.isdir(grid_dir):
            raise FileNotFoundError(
                f"no {folder}/ under {root!r} (tiled light-field samples)"
            )
        frames: list[PosedFrame] = []
        for name in sorted(os.listdir(grid_dir)):
            if os.path.splitext(name)[1].lower() not in (".png", ".jpg",
                                                         ".jpeg"):
                continue
            frames.extend(load_grid(
                os.path.join(grid_dir, name), os.path.splitext(name)[0],
                grid, focal_px, baseline,
                (cfg.data.img_h, cfg.data.img_w),
            ))
        if not frames:
            raise FileNotFoundError(f"no light-field samples in {grid_dir!r}")
        super().__init__(cfg, split, global_batch, frames,
                         host_slice=host_slice)
