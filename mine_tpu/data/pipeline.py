"""Host-side input-pipeline overlap.

Reference gap (SURVEY.md §7.4.7): the reference builds every batch
synchronously inside the step loop (single-threaded PIL + numpy,
nerf_dataset.py:199-236) — at TPU step rates the host starves the device.
Here a daemon thread keeps up to `data.num_workers` batches ready ahead of
the consumer, and the device transfer (shard_batch / device_put) runs inside
that thread too, so H2D copies overlap the previous step's compute
(double-buffering at depth >= 1). depth <= 0 degrades to the reference's
synchronous behavior.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator


class _End:
    pass


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch(
    iterable: Iterable[Any],
    depth: int,
    transfer: Callable[[Any], Any] | None = None,
) -> Iterator[Any]:
    """Yield items of `iterable`, produced (and `transfer`ed) up to `depth`
    items ahead on a background thread. Exceptions from the producer re-raise
    at the consumer's next pull. If the consumer abandons the generator early,
    the producer thread is unblocked and exits (daemon either way)."""
    if depth <= 0:
        for item in iterable:
            yield transfer(item) if transfer is not None else item
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_stop(item: Any) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for item in iterable:
                out = transfer(item) if transfer is not None else item
                if not put_or_stop(out):
                    return
            put_or_stop(_End())
        except BaseException as exc:  # noqa: BLE001 - relayed to the consumer
            put_or_stop(_Raised(exc))

    thread = threading.Thread(target=worker, daemon=True, name="batch-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, _End):
                return
            if isinstance(item, _Raised):
                raise item.exc
            yield item
    finally:
        stop.set()
