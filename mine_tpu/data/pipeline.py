"""Host-side input-pipeline overlap.

Reference gap (SURVEY.md §7.4.7): the reference builds every batch
synchronously inside the step loop (single-threaded PIL + numpy,
nerf_dataset.py:199-236) — at TPU step rates the host starves the device.
Here a daemon thread keeps up to `data.num_workers` batches ready ahead of
the consumer, and the device transfer (shard_batch / device_put) runs inside
that thread too, so H2D copies overlap the previous step's compute
(double-buffering at depth >= 1). depth <= 0 degrades to the reference's
synchronous behavior.

Transient-fault containment (`data.loader_retries`): a flaky network
filesystem or a GC-paused storage daemon should cost one retried batch, not
the whole epoch. Two stages are covered, both with exponential backoff +
jitter on transient errors (TransientLoaderError, ChaosFault, OSError,
TimeoutError), re-raising only after `retries` attempts, with `on_retry`
ticking the caller's counter per attempt:

  * the per-item stage — the optional chaos seam plus the `transfer`
    callable — always;
  * the source-iterator PULL (`next()`), only when the iterable declares
    `retry_safe_iter = True`. The opt-in is load-bearing: a Python
    generator closes on raise, so re-pulling a dead generator returns
    StopIteration and would silently TRUNCATE the epoch — only loaders
    whose `__next__` does independent per-batch work (e.g. per-batch
    image reads) may claim the flag. Generators' exceptions still relay
    to the consumer on the first failure.
"""

from __future__ import annotations

import queue
import random
import threading
import time
from typing import Any, Callable, Iterable, Iterator

from mine_tpu.resilience import chaos


class TransientLoaderError(RuntimeError):
    """A loader error worth retrying (the pipeline's opt-in marker)."""


class LoaderRetriesExhausted(RuntimeError):
    """Bounded retries ran out: the NAMED terminal error of the retry
    machinery (`data.loader_retries`). Carries the attempt count and
    chains the last underlying error, so a pod-scale log line says "host
    retried the flaky mount 3x and gave up" instead of surfacing the raw
    OSError (or, worse, a bare StopIteration swallowed by generator
    machinery) with no hint that retries already happened. Raised only
    when retries were actually configured — `retries=0` keeps fail-fast
    semantics and relays the original error untouched."""

    def __init__(self, attempts: int, cause: BaseException):
        super().__init__(
            f"loader retries exhausted after {attempts} attempt(s); last "
            f"error: {type(cause).__name__}: {cause}"
        )
        self.attempts = attempts
        self.cause = cause


# what the bounded retry treats as transient; anything else re-raises at
# the consumer immediately (a shape bug retried 3 times is 3x the noise)
_RETRYABLE = (TransientLoaderError, chaos.ChaosFault, OSError, TimeoutError)


class _End:
    pass


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


def _retrying(
    fn: Callable[[], Any],
    retries: int,
    retry_base_delay_s: float,
    on_retry: Callable[[int, BaseException], None] | None,
) -> Any:
    """Call fn() with bounded transient-error retry + backoff/jitter."""
    attempt = 0
    while True:
        try:
            return fn()
        except _RETRYABLE as exc:
            if attempt >= retries:
                if retries > 0:
                    # retries were configured and ran out: name it
                    # (module docstring; retries=0 stays fail-fast raw)
                    raise LoaderRetriesExhausted(attempt + 1, exc) from exc
                raise
            # exponential backoff with jitter: correlated retries from
            # many hosts must not re-stampede the storage that just
            # buckled (the classic thundering-herd discipline)
            delay = retry_base_delay_s * (2.0 ** attempt)
            delay *= 1.0 + 0.25 * random.random()
            attempt += 1
            if on_retry is not None:
                on_retry(attempt, exc)
            time.sleep(delay)


def prefetch(
    iterable: Iterable[Any],
    depth: int,
    transfer: Callable[[Any], Any] | None = None,
    retries: int = 0,
    retry_base_delay_s: float = 0.05,
    on_retry: Callable[[int, BaseException], None] | None = None,
    fault_seam: str | None = None,
) -> Iterator[Any]:
    """Yield items of `iterable`, produced (and `transfer`ed) up to `depth`
    items ahead on a background thread. Exceptions from the producer re-raise
    at the consumer's next pull — after `retries` bounded retries of the
    per-item stage for transient errors (module docstring). `fault_seam`
    names the chaos seam consulted once per produced item
    (resilience/chaos.py; None = no seam on this stage). If the consumer
    abandons the generator early, the producer thread is unblocked and
    exits (daemon either way)."""

    def produce(item: Any) -> Any:
        def stage():
            if fault_seam is not None:
                chaos.maybe_raise(fault_seam)
            return transfer(item) if transfer is not None else item

        return _retrying(stage, retries, retry_base_delay_s, on_retry)

    # pull-retry only for iterables that declare their __next__ re-callable
    # after a failure (module docstring: a dead generator would truncate)
    pull_retries = (
        retries if getattr(iterable, "retry_safe_iter", False) else 0
    )
    src = iter(iterable)
    _END_PULL = object()

    def pull() -> Any:
        def one():
            try:
                return next(src)
            except StopIteration:
                return _END_PULL

        return _retrying(one, pull_retries, retry_base_delay_s, on_retry)

    if depth <= 0:
        while True:
            item = pull()
            if item is _END_PULL:
                return
            yield produce(item)

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_stop(item: Any) -> bool:
        """Blocking put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            while True:
                item = pull()
                if item is _END_PULL:
                    put_or_stop(_End())
                    return
                if not put_or_stop(produce(item)):
                    return
        except BaseException as exc:  # noqa: BLE001 - relayed to the consumer
            put_or_stop(_Raised(exc))

    thread = threading.Thread(target=worker, daemon=True, name="batch-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, _End):
                return
            if isinstance(item, _Raised):
                raise item.exc
            yield item
    finally:
        stop.set()
