"""Shared posed-frame dataset core: the loader protocol, implemented once.

Every real dataset family here reduces to the same shape — a list of posed
frames (image + intrinsics + world pose + optional camera-frame sparse
points), grouped into scenes, paired (src, tgt) per batch slot — and before
this module each loader re-implemented the epoch machinery around that list
(LLFF and Objectron duplicated ~80 lines each; four more families would
have sextupled it). `PosedFrameDataset` owns the protocol once:

  * `__len__` / `epoch(n)` / `num_eval_examples` — the loader contract the
    training loop and conformance runner consume (data/conformance/).
  * train drop-last vs val wrap-pad tails with `eval_weight` masking
    (VERDICT r4 #5): EVERY family now evaluates its full val set under
    static shapes, not just LLFF.
  * `data.num_tgt_views` k-targets-per-source flattening.
  * `host_slice` — per-host data sharding (parallel/mesh.py
    host_batch_slice): every example's randomness comes from a generator
    seeded by its GLOBAL (epoch, step, source-slot) coordinates, never
    from a shared sequential stream, so a host materializing only its
    `host_slice` rows produces BITWISE the rows a global-batch build
    would slice out — the same contract SyntheticDataset pinned first
    (PARITY.md 5.12). This retires the global-load-then-slice compat
    path for every family built on this base.

Subclasses provide the frames (their on-disk layout knowledge) and may
override `candidate_targets` (e.g. Objectron's ±frame window) — nothing
else.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from mine_tpu.config import Config


@dataclass
class PosedFrame:
    """One posed view. `pts_cam=None` marks a family without sparse-depth
    supervision (the contract's `sparse_depth` flag; training/step.py zeros
    the disparity terms for those families via NO_DISP_SUPERVISION)."""

    scene: str
    img: np.ndarray  # (H, W, 3) f32 in [0, 1]
    k: np.ndarray  # (3, 3) f32, pixels at the TARGET (img_h, img_w)
    g_cam_world: np.ndarray  # (4, 4) f32 world -> camera
    pts_cam: np.ndarray | None  # (N, 3) f32 camera-frame points, or None


class PosedFrameDataset:
    """Loader-protocol dataset over a frame list (duck-typed: any object
    with .scene/.img/.k/.g_cam_world/.pts_cam works — LLFF's PosedImage and
    Objectron's ObjectronFrame predate PosedFrame and stay as they are).

    Replaces torch Dataset + DistributedSampler + DataLoader + collate
    (reference train.py:76-132): one logical global batch per step; with
    `host_slice=(start, count)` only those rows are materialized.
    """

    def __init__(
        self,
        cfg: Config,
        split: str,
        global_batch: int,
        frames: list,
        host_slice: tuple[int, int] | None = None,
    ):
        self.cfg = cfg
        self.split = split
        self.is_val = split == "val"
        self.global_batch = global_batch
        self.rng_seed = cfg.training.seed + (991 if self.is_val else 0)
        self.frames = frames
        # num_tgt_views targets per source, each filling one batch slot (the
        # reference's supervision_count, capped at 1 in practice —
        # synthesis_task.py:203-204; here any k dividing the batch works)
        self.num_tgt_views = cfg.data.num_tgt_views
        if self.num_tgt_views < 1 or global_batch % self.num_tgt_views:
            raise ValueError(
                f"data.num_tgt_views={self.num_tgt_views} must be >= 1 and "
                f"divide the global batch {global_batch}"
            )
        if not self.is_val and len(frames) < global_batch // self.num_tgt_views:
            # with drop_last a too-small train set would yield ZERO batches
            # per epoch — a silent no-op training run; fail loudly instead
            raise ValueError(
                f"train split has {len(frames)} source image(s) but one "
                f"global batch needs {global_batch // self.num_tgt_views}; "
                "every epoch would be empty (reduce the batch or add data)"
            )
        if host_slice is not None:
            start, count = host_slice
            if start < 0 or count < 1 or start + count > global_batch:
                raise ValueError(
                    f"host_slice={host_slice} outside the global batch "
                    f"of {global_batch}"
                )
        self.host_slice = host_slice
        # scene -> global indices (reference nerf_dataset.py scene_to_indices)
        self.scene_indices: dict[str, list[int]] = {}
        for i, fr in enumerate(frames):
            self.scene_indices.setdefault(fr.scene, []).append(i)
        self._validate_candidates()

    # -- subclass surface ----------------------------------------------------

    def candidate_targets(self, src_idx: int) -> list[int]:
        """Target candidates for one source view; default: every other view
        of the same scene. Subclasses narrow this (Objectron: ±frame
        window)."""
        scene = self.frames[src_idx].scene
        return [i for i in self.scene_indices[scene] if i != src_idx]

    def _validate_candidates(self) -> None:
        """Fail at construction, not mid-epoch: every source needs >=
        num_tgt_views distinct targets. The default same-scene candidate
        set makes this a per-scene size check."""
        for scene, idxs in self.scene_indices.items():
            if len(idxs) < self.num_tgt_views + 1:
                raise ValueError(
                    f"scene {scene} has {len(idxs)} image(s); need >= "
                    f"{self.num_tgt_views + 1} for {self.num_tgt_views} "
                    "target(s)"
                )

    # -- the loader protocol -------------------------------------------------

    def __len__(self) -> int:
        n_src = self.global_batch // self.num_tgt_views
        if self.is_val:
            # val covers EVERY image (reference run_eval iterates the full
            # val DataLoader, drop_last=False — synthesis_task.py:506-515);
            # the final short batch is wrap-padded to keep shapes static
            return -(-len(self.frames) // n_src)
        # train drops the short tail (reference DataLoader drop_last=True,
        # train.py:110); __len__ must agree with what epoch() yields
        return len(self.frames) // n_src

    @property
    def num_eval_examples(self) -> int:
        """Genuine (weight-1) examples one val epoch yields: every image
        serves as source exactly once, num_tgt_views pairs each. The eval
        loop audits its metered count against this (training/loop.py
        run_evaluation) so a wrap-pad miscount can't silently skew the one
        number users compare."""
        return len(self.frames) * self.num_tgt_views

    def _examples(
        self, src_idx: int, rng: np.random.Generator
    ) -> list[dict[str, np.ndarray]]:
        """num_tgt_views (src, tgt) pairs for one source view, from ONE
        per-source generator (the k 'without replacement' targets must be
        drawn together; the host slice trims rows afterwards)."""
        src = self.frames[src_idx]
        candidates = self.candidate_targets(src_idx)
        k = self.num_tgt_views
        if self.is_val:
            # deterministic neighbor(s) (nerf_dataset.py:205-208)
            base = (src_idx + 1) % len(candidates) - 1
            tgt_idxs = [candidates[(base + j) % len(candidates)]
                        for j in range(k)]
        else:
            tgt_idxs = [int(i) for i in
                        rng.choice(candidates, size=k, replace=False)]

        n_pt = self.cfg.data.visible_point_count
        out = []
        for tgt_idx in tgt_idxs:
            tgt = self.frames[tgt_idx]
            # G_tgt_src maps src-camera coords to tgt-camera coords
            # (reference builds G_src_tgt then inverts at set_data,
            # nerf_dataset.py:219-221 + synthesis_task.py:211)
            g_tgt_src = tgt.g_cam_world @ np.linalg.inv(src.g_cam_world)
            example = {
                "src_img": src.img,
                "tgt_img": tgt.img,
                "k_src": src.k,
                "k_tgt": tgt.k,
                "g_tgt_src": g_tgt_src.astype(np.float32),
            }
            if src.pts_cam is not None:
                # sampling with replacement only when a frame holds fewer
                # tracked points than requested (Objectron's small clouds)
                example["pt3d_src"] = src.pts_cam[rng.choice(
                    len(src.pts_cam), n_pt,
                    replace=len(src.pts_cam) < n_pt,
                )]
                example["pt3d_tgt"] = tgt.pts_cam[rng.choice(
                    len(tgt.pts_cam), n_pt,
                    replace=len(tgt.pts_cam) < n_pt,
                )]
            out.append(example)
        return out

    def epoch(self, epoch: int):
        """Batches for one epoch — only this host's `host_slice` rows.

        Per-example determinism contract: the epoch ORDER comes from one
        (seed, epoch) generator shared by every host, and each source
        slot's targets/point-subsets come from a generator seeded by the
        slot's global (seed, epoch, step, position) coordinates — so the
        rows a host materializes are a pure function of their global
        coordinates, bitwise-equal to the same rows of a global build
        (tests/test_conformance.py pins this per family)."""
        order = np.random.default_rng((self.rng_seed, epoch)).permutation(
            len(self.frames)
        )
        n_src = self.global_batch // self.num_tgt_views
        k = self.num_tgt_views
        start, count = self.host_slice or (0, self.global_batch)
        for step in range(len(self)):
            idxs = order[step * n_src:(step + 1) * n_src]
            n_genuine = len(idxs)
            if n_genuine < n_src:
                if not self.is_val:  # drop_last, like the reference's train
                    break            # DataLoader (train.py:110)
                # Val: wrap-pad the tail from the start of the order so
                # every image is evaluated under one static batch shape
                # (XLA: no ragged batches). Padded slots carry eval_weight
                # 0.0 below, so the epoch average counts every genuine
                # example exactly once (synthesis_task.py:506-515 parity).
                idxs = np.concatenate(
                    [idxs, np.resize(order, n_src - len(idxs))]
                )
            examples: list[dict[str, np.ndarray]] = []
            weights: list[float] = []
            for p, src_idx in enumerate(idxs):
                lo = p * k
                if lo + k <= start or lo >= start + count:
                    continue  # no overlap with this host's rows
                rng = np.random.default_rng(
                    (self.rng_seed, epoch, step, p)
                )
                group = self._examples(int(src_idx), rng)
                for j, e in enumerate(group):
                    if start <= lo + j < start + count:
                        examples.append(e)
                        weights.append(1.0 if p < n_genuine else 0.0)
            batch = {
                key: np.stack([e[key] for e in examples])
                for key in examples[0]
            }
            if self.is_val:
                # per-example validity mask for the wrap-padded tail
                batch["eval_weight"] = np.asarray(weights, np.float32)
            yield batch


MIN_DEPTH_FRACTION = 0.01


def cull_near_points(pts_cam: np.ndarray) -> tuple[np.ndarray, float]:
    """Drop behind-camera and lens-grazing points from one frame's track.

    A negative/zero depth would NaN the 1/z disparity supervision, and a
    single z ~ 1e-5 survivor contributes log(1/z) ~ 11.5 to
    compute_scale_factor's exp(mean(log...)) — one reconstruction artifact
    can shift a whole image's scale calibration (ADVICE r5). A point closer
    than MIN_DEPTH_FRACTION of the frame's MEDIAN track depth is an
    artifact, not geometry. Returns (kept points, the threshold used)."""
    z = pts_cam[:, 2]
    positive = z[z > 0]
    min_depth = (
        max(MIN_DEPTH_FRACTION * float(np.median(positive)), 1e-6)
        if len(positive) else 1e-6
    )
    return pts_cam[z > min_depth], min_depth
