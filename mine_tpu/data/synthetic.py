"""Synthetic two-view scene with analytically known geometry.

No reference analog — the reference has no test fixtures at all (SURVEY.md
§4); this is the "textured plane at known depth" scene the test strategy
calls for. Also serves as the zero-setup dataset for smoke-training and
benchmarking (`data.name: synthetic`): every batch is generated procedurally,
so the training loop runs with nothing on disk.

Scene: a far fronto-parallel plane at FAR_DEPTH plus a near occluder strip at
NEAR_DEPTH; texture is a smooth analytic function of the plane point, so ANY
camera pose renders exactly (no image resampling anywhere — pixels are
evaluated, not warped). Ground-truth depth per pixel comes with the batch.
"""

from __future__ import annotations

import numpy as np

NEAR_DEPTH = 1.0
FAR_DEPTH = 4.0
_NEAR_HALF_WIDTH = 0.25  # near strip spans plane-x in [-w, w] at z=NEAR_DEPTH


def _texture(x: np.ndarray, y: np.ndarray, phase: float) -> np.ndarray:
    """Smooth rgb texture of plane coordinates, in [0, 1]. (..., 3)."""
    r = 0.5 + 0.5 * np.sin(7.0 * x + phase) * np.cos(5.0 * y)
    g = 0.5 + 0.5 * np.cos(11.0 * x - 3.0 * y + phase)
    b = 0.5 + 0.5 * np.sin(4.0 * x * y + 2.0 * phase)
    return np.stack([r, g, b], axis=-1).astype(np.float32)


def _intrinsics(height: int, width: int) -> np.ndarray:
    f = 0.8 * width
    return np.array(
        [[f, 0.0, width / 2.0], [0.0, f, height / 2.0], [0.0, 0.0, 1.0]],
        dtype=np.float32,
    )


def _render_view(
    height: int, width: int, k: np.ndarray, cam_pos: np.ndarray, phase: float
) -> tuple[np.ndarray, np.ndarray]:
    """Render the scene from a camera at `cam_pos` (world axes == camera axes,
    no rotation). Returns (img (H,W,3), depth (H,W))."""
    u, v = np.meshgrid(np.arange(width), np.arange(height))
    k_inv = np.linalg.inv(k)
    rays = np.einsum("ij,hwj->hwi", k_inv, np.stack([u, v, np.ones_like(u)], -1).astype(np.float64))

    # intersection with plane world-z = Z: world point = cam_pos + rays * (Z - cam_pos_z)
    def plane_point(z_world):
        t = (z_world - cam_pos[2]) / rays[..., 2]
        return cam_pos[None, None, :] + rays * t[..., None]

    p_near = plane_point(NEAR_DEPTH)
    p_far = plane_point(FAR_DEPTH)
    near_hit = np.abs(p_near[..., 0]) < _NEAR_HALF_WIDTH

    img = np.where(
        near_hit[..., None],
        _texture(p_near[..., 0] * 6.0, p_near[..., 1] * 6.0, phase + 1.7),
        _texture(p_far[..., 0], p_far[..., 1], phase),
    )
    depth = np.where(near_hit, NEAR_DEPTH - cam_pos[2], FAR_DEPTH - cam_pos[2])
    return img.astype(np.float32), depth.astype(np.float32)


def _sample_points(
    rng: np.random.Generator, n_points: int, cam_pos: np.ndarray
) -> np.ndarray:
    """Sparse scene points visible from both cameras (COLMAP stand-ins),
    in the frame of a camera at cam_pos. (N, 3)."""
    n_near = n_points // 4
    n_far = n_points - n_near
    # far points away from the near strip's shadow (|x| < 4*half_width at
    # z=4) to dodge occlusion, but inside the fov: u = f x/z + cx < W needs
    # |x| < z/(2*0.8) = 2.5 at the border, margin for the baseline shift
    sign = rng.choice([-1.0, 1.0], size=n_far)
    x_far = sign * rng.uniform(_NEAR_HALF_WIDTH * 6.0, 2.2, size=n_far)
    y_far = rng.uniform(-1.4, 1.4, size=n_far)
    far = np.stack([x_far, y_far, np.full(n_far, FAR_DEPTH)], axis=-1)
    x_near = rng.uniform(-_NEAR_HALF_WIDTH, _NEAR_HALF_WIDTH, size=n_near)
    y_near = rng.uniform(-0.3, 0.3, size=n_near)
    near = np.stack([x_near, y_near, np.full(n_near, NEAR_DEPTH)], axis=-1)
    pts = np.concatenate([far, near], axis=0)
    return (pts - cam_pos[None, :]).astype(np.float32)


def write_colmap_scene(
    root: str,
    scene: str,
    n_views: int = 4,
    hw: tuple[int, int] = (64, 64),
    n_val_views: int = 0,
    phase: float = 0.3,
) -> list[np.ndarray]:
    """Write the analytic scene to disk in LLFF/COLMAP layout (images/ +
    sparse/0 binary model), for fixtures, loader benchmarks, and end-to-end
    quality runs. Camera i sits at [0.06i, 0.02i, 0] with identity rotation;
    every 3D point is tracked in every view. With n_val_views > 0, extra
    held-out cameras (offset half a baseline step from the train line, so no
    val pose equals a train pose) land in images_val/ — the `<folder>_val`
    layout LLFFDataset's val split reads (llff.py:149-150); all poses live
    in the one sparse/0 model. Returns the train camera positions."""
    import os

    from PIL import Image

    from mine_tpu.data import colmap

    h, w = hw
    k = _intrinsics(h, w)
    scene_dir = os.path.join(root, scene)
    os.makedirs(os.path.join(scene_dir, "sparse/0"), exist_ok=True)
    os.makedirs(os.path.join(scene_dir, "images"), exist_ok=True)
    if n_val_views:
        os.makedirs(os.path.join(scene_dir, "images_val"), exist_ok=True)

    rng = np.random.default_rng(0)
    world_pts = _sample_points(rng, 80, np.zeros(3))  # camera-0 frame == world
    points3d = {
        i + 1: colmap.Point3D(i + 1, world_pts[i].astype(np.float64),
                              np.array([255, 0, 0], np.uint8), 0.5)
        for i in range(len(world_pts))
    }

    cameras = {1: colmap.Camera(1, "SIMPLE_RADIAL", w, h,
                                np.array([k[0, 0], k[0, 2], k[1, 2], 0.0]))}
    images = {}
    positions = []
    views = [(f"view_{i:03d}.png", np.array([0.06 * i, 0.02 * i, 0.0]), "images")
             for i in range(n_views)]
    views += [(f"val_{j:03d}.png",
               np.array([0.06 * j + 0.03, 0.02 * j + 0.01, 0.0]), "images_val")
              for j in range(n_val_views)]
    for img_id, (name, pos, folder) in enumerate(views, start=1):
        if folder == "images":
            positions.append(pos)
        img, _ = _render_view(h, w, k, pos, phase=phase)
        Image.fromarray((img * 255).astype(np.uint8)).save(
            os.path.join(scene_dir, folder, name)
        )
        # G_cam_world = [I | -pos]; all points tracked in every view
        uvw = (world_pts - pos) @ k.T
        xys = uvw[:, :2] / uvw[:, 2:]
        images[img_id] = colmap.ImageMeta(
            img_id, np.array([1.0, 0, 0, 0]), (-pos).astype(np.float64), 1, name,
            xys.astype(np.float64), np.arange(1, len(world_pts) + 1, dtype=np.int64),
        )

    colmap.write_cameras_binary(cameras, os.path.join(scene_dir, "sparse/0/cameras.bin"))
    colmap.write_images_binary(images, os.path.join(scene_dir, "sparse/0/images.bin"))
    colmap.write_points3d_binary(points3d, os.path.join(scene_dir, "sparse/0/points3D.bin"))
    return positions


class SyntheticDataset:
    """Procedural dataset speaking the loader protocol (steps_per_epoch +
    epoch(n) iterator of batch pytrees). Zero disk footprint; every batch is
    a fresh scene, deterministic in (seed, epoch, step) — and, since every
    example is seeded by its GLOBAL index, in the example alone: a host
    materializing only its `host_slice` rows produces bitwise the rows a
    global-batch load would slice out (the per-host data-sharding contract,
    parallel/mesh.py host_batch_slice; PARITY.md)."""

    def __init__(
        self,
        height: int,
        width: int,
        global_batch: int,
        steps_per_epoch: int = 50,
        n_points: int = 256,
        seed: int = 0,
        host_slice: tuple[int, int] | None = None,
    ):
        self.height = height
        self.width = width
        self.global_batch = global_batch
        self.steps_per_epoch = steps_per_epoch
        self.n_points = n_points
        self.seed = seed
        # (start, count) of the global batch THIS host materializes per
        # step; None = the whole batch (single-process, and the
        # global-load-then-slice compat path)
        if host_slice is not None:
            start, count = host_slice
            if start < 0 or count < 1 or start + count > global_batch:
                raise ValueError(
                    f"host_slice={host_slice} outside the global batch "
                    f"of {global_batch}"
                )
        self.host_slice = host_slice

    def __len__(self) -> int:
        return self.steps_per_epoch

    def epoch(self, epoch: int):
        start, count = self.host_slice or (0, self.global_batch)
        for step in range(self.steps_per_epoch):
            batch = make_synthetic_batch(
                count,
                self.height,
                self.width,
                n_points=self.n_points,
                seed=self.seed + epoch * 1_000_003 + step,
                example_offset=start,
            )
            batch.pop("src_depth")
            yield batch


def make_synthetic_batch(
    batch_size: int,
    height: int,
    width: int,
    n_points: int = 64,
    seed: int = 0,
    baseline: float = 0.08,
    example_offset: int = 0,
) -> dict[str, np.ndarray]:
    """Batch pytree in the training-step contract (mine_tpu/training/step.py).

    The target camera is the source camera translated by `baseline` along +x
    (and a touch of +y), like an LLFF stereo pair.

    Every example draws from its OWN generator seeded by (seed,
    example_offset + row): example content is a pure function of its
    global index, never of which rows happen to share the array — so
    `make_synthetic_batch(n, ..., example_offset=s)` is bitwise the rows
    [s:s+n] of the full batch, and a multi-host run where each host
    materializes only its slice sees exactly the global stream
    (the data-sharding numerics no-op, PARITY.md).
    """
    k = _intrinsics(height, width)

    out = {
        "src_img": np.zeros((batch_size, height, width, 3), np.float32),
        "tgt_img": np.zeros((batch_size, height, width, 3), np.float32),
        "k_src": np.tile(k[None], (batch_size, 1, 1)),
        "k_tgt": np.tile(k[None], (batch_size, 1, 1)),
        "g_tgt_src": np.zeros((batch_size, 4, 4), np.float32),
        "pt3d_src": np.zeros((batch_size, n_points, 3), np.float32),
        "pt3d_tgt": np.zeros((batch_size, n_points, 3), np.float32),
        "src_depth": np.zeros((batch_size, height, width), np.float32),
    }
    for b in range(batch_size):
        rng = np.random.default_rng([seed, example_offset + b])
        phase = float(rng.uniform(0.0, 6.28))
        src_pos = np.zeros(3)
        tgt_pos = np.array([baseline, 0.3 * baseline, 0.0])
        out["src_img"][b], out["src_depth"][b] = _render_view(height, width, k, src_pos, phase)
        out["tgt_img"][b], _ = _render_view(height, width, k, tgt_pos, phase)
        # world axes == camera axes: X_tgt = X_src - tgt_pos
        g = np.eye(4, dtype=np.float32)
        g[:3, 3] = (src_pos - tgt_pos).astype(np.float32)
        out["g_tgt_src"][b] = g
        out["pt3d_src"][b] = _sample_points(rng, n_points, src_pos)
        out["pt3d_tgt"][b] = out["pt3d_src"][b] - (tgt_pos - src_pos)[None, :].astype(np.float32)
    return out
