"""Objectron pipeline (zubair-irshad fork addition).

Reference: input_pipelines/objectron.py. Scene layout:
  <root>/<scene>/<scene>_metadata.pickle   poses (c2w), focal, c, RT, scale,
                                           all_scene_points
  <root>/<scene>/masks_3[_val]/*.png       frame list (mask name encodes the
                                           image name: "<prefix>_<img>.png")
  <root>/<scene>/images_3[_val]/<img>

Behaviors kept: the frame list is mask-driven (objectron.py:72-74); the pose
is inv(c2w @ ADJUST) with the axis-adjust matrix (objectron.py:53-57, :110);
images are BGR->RGB, rotated 90° CCW, center-cropped to 384x640
(objectron.py:130-135); K comes per-frame from metadata focal/c
(objectron.py:150-158); one shared world point cloud per scene, transformed
per frame (objectron.py:117-147); targets sampled within a ±10-frame window
(objectron.py:176-186), deterministic neighbor for val; ~150-frame cap per
scene (objectron.py:122-123). The debug prints in the reference __getitem__
(objectron.py:233-236) are, naturally, not kept.

Deviation: the center crop shifts K's principal point by the crop offset (the
reference leaves K untouched, same geometry error as its NOCS crop).
"""

from __future__ import annotations

import bisect
import glob
import os
import pickle
from dataclasses import dataclass

import numpy as np
from PIL import Image

from mine_tpu.config import Config
from mine_tpu.data.frames import PosedFrameDataset

ADJUST = np.array(
    [[0.0, 1.0, 0.0, 0.0],
     [1.0, 0.0, 0.0, 0.0],
     [0.0, 0.0, -1.0, 0.0],
     [0.0, 0.0, 0.0, 1.0]]
)
CROP_HW = (384, 640)
FRAME_WINDOW = 10
MAX_FRAMES_PER_SCENE = 150


@dataclass
class ObjectronFrame:
    scene: str
    img: np.ndarray  # (H, W, 3) f32
    k: np.ndarray  # (3, 3) f32
    g_cam_world: np.ndarray  # (4, 4) f32
    pts_cam: np.ndarray  # (N, 3) f32


def _load_frame_image(path: str, img_hw: tuple[int, int]):
    """RGB load + 90° CCW rotate + center crop; returns (img, crop offsets at
    cropped-orientation resolution)."""
    img = Image.open(path).convert("RGB")
    img = img.transpose(Image.ROTATE_90)
    ch, cw = CROP_HW
    left = max((img.width - cw) // 2, 0)
    top = max((img.height - ch) // 2, 0)
    img = img.crop((left, top, min(left + cw, img.width), min(top + ch, img.height)))
    if (img.height, img.width) != img_hw:
        img = img.resize((img_hw[1], img_hw[0]), Image.BICUBIC)
    return np.asarray(img, dtype=np.float32) / 255.0, (left, top)


def load_objectron_scene(
    scene_dir: str, split: str, img_hw: tuple[int, int]
) -> list[ObjectronFrame]:
    scene = os.path.basename(scene_dir.rstrip("/"))
    suffix = "_val" if split == "val" else ""
    meta_path = os.path.join(scene_dir, f"{scene}_metadata.pickle")
    with open(meta_path, "rb") as fh:
        meta = pickle.load(fh)

    poses_c2w = np.asarray(meta["poses"])
    focals = np.asarray(meta["focal"])
    centers = np.asarray(meta["c"])
    world_pts = np.asarray(meta["all_scene_points"], dtype=np.float64)

    mask_files = sorted(glob.glob(os.path.join(scene_dir, f"masks_3{suffix}", "*.png")))
    frames: list[ObjectronFrame] = []
    for seg_name in mask_files[: MAX_FRAMES_PER_SCENE + 1]:
        img_name = os.path.basename(seg_name).split("_")[1]
        img_path = os.path.join(scene_dir, f"images_3{suffix}", img_name)
        if not os.path.exists(img_path):
            continue
        frame_idx = int(img_name.split(".")[0])

        c2w = np.squeeze(poses_c2w[frame_idx])
        g_cam_world = np.linalg.inv(c2w @ ADJUST)

        img, (left, top) = _load_frame_image(img_path, img_hw)
        fx, fy = focals[frame_idx][0], focals[frame_idx][1]
        cx, cy = centers[frame_idx][0], centers[frame_idx][1]
        k = np.array(
            [[fx, 0.0, cx - left], [0.0, fy, cy - top], [0.0, 0.0, 1.0]],
            dtype=np.float32,
        )

        homo = np.concatenate([world_pts, np.ones((len(world_pts), 1))], axis=1)
        cam = (g_cam_world @ homo.T).T
        pts_cam = (cam[:, :3] / cam[:, 3:4]).astype(np.float32)

        frames.append(
            ObjectronFrame(scene, img, k, g_cam_world.astype(np.float32), pts_cam)
        )
    return frames


class ObjectronDataset(PosedFrameDataset):
    """Loader-protocol dataset over Objectron scene directories (shared
    frame core, data/frames.py; target candidates narrowed to the
    reference's ±FRAME_WINDOW same-scene neighbors). Val epochs now get
    the frame core's wrap-padded tail + eval_weight masking — previously
    a short Objectron val tail was silently dropped."""

    def __init__(self, cfg: Config, split: str, global_batch: int,
                 host_slice: tuple[int, int] | None = None):
        root = cfg.data.training_set_path
        frames: list[ObjectronFrame] = []
        for scene in sorted(os.listdir(root)):
            scene_dir = os.path.join(root, scene)
            if not os.path.isdir(scene_dir):
                continue
            frames.extend(
                load_objectron_scene(scene_dir, split, (cfg.data.img_h, cfg.data.img_w))
            )
        if not frames:
            raise FileNotFoundError(f"no objectron frames under {root!r}")
        super().__init__(cfg, split, global_batch, frames,
                         host_slice=host_slice)

    def candidate_targets(self, src_idx: int) -> list[int]:
        # ±FRAME_WINDOW same-scene candidates (objectron.py:176-186)
        return [
            i for i in self.scene_indices[self.frames[src_idx].scene]
            if i != src_idx and abs(i - src_idx) <= FRAME_WINDOW
        ]

    def _validate_candidates(self) -> None:
        # fail at construction, not hours into an epoch: every frame must
        # have enough in-window neighbors for num_tgt_views distinct targets
        # (bisect count — idxs are sorted — keeps this O(F log F) per scene)
        for scene, idxs in self.scene_indices.items():
            for i in idxs:
                lo = bisect.bisect_left(idxs, i - FRAME_WINDOW)
                hi = bisect.bisect_right(idxs, i + FRAME_WINDOW)
                n = hi - lo - 1  # excluding the frame itself
                if n < self.num_tgt_views:
                    raise ValueError(
                        f"frame {i} of scene {scene} has {n} neighbors within "
                        f"±{FRAME_WINDOW}; need >= num_tgt_views="
                        f"{self.num_tgt_views}"
                    )
