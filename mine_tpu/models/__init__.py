"""Flax model zoo (reference: network/).

ResNetEncoder + MPIDecoder mirror the reference's
ResnetEncoder/DepthDecoder contracts (5-feature pyramid; per-plane
disparity-conditioned 4-scale RGB+sigma MPI output) in NHWC with
cross-replica-syncable BatchNorm.
"""

from mine_tpu.models.embedder import embed_dim, positional_encode
from mine_tpu.models.encoder import ResNetEncoder, encoder_channels
from mine_tpu.models.decoder import MPIDecoder, NUM_CH_DEC, nearest_up2
from mine_tpu.models.mpi import MPINetwork, predict_mpi_coarse_to_fine
from mine_tpu.models.pretrained import (
    apply_pretrained_backbone,
    apply_pretrained_npz,
    load_npz_variables,
)
