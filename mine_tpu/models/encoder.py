"""ResNet pyramid encoder (the monodepth2-style backbone).

Reference contract: network/monodepth2/resnet_encoder.py:64-113 —
ResNet-18/34/50/101/152, ImageNet mean/std normalization applied inline on the
raw [0,1] input, returns the 5-feature pyramid
(conv1_out, block1..4_out) at strides (2, 4, 8, 16, 32) with channel widths
[64, 64, 128, 256, 512] (x4 on the last four for Bottleneck nets,
resnet_encoder.py:86-87). Multi-image input variant = `num_input_images` frames
stacked on channels (resnet_encoder.py:19-61).

TPU-first design: NHWC layout, Flax BatchNorm with `axis_name` for
cross-replica stat sync (the reference reaches the same semantics by wrapping
in torch SyncBatchNorm at the task layer, synthesis_task.py:107-115 — here it
is a property of the module, not a wrapper). Compute dtype is configurable
(bf16 for MXU); BN statistics always accumulate in fp32.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import Array

from mine_tpu.models.norm import SyncBatchNorm

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

_STAGE_BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3),
                 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
_BOTTLENECK = {50, 101, 152}


def encoder_channels(num_layers: int) -> tuple[int, ...]:
    """Pyramid channel widths (resnet_encoder.py:70, :86-87)."""
    base = (64, 64, 128, 256, 512)
    if num_layers in _BOTTLENECK:
        return (base[0],) + tuple(c * 4 for c in base[1:])
    return base


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    axis_name: str | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        bn = lambda: SyncBatchNorm(self.axis_name, self.dtype)
        residual = x
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides),
                    padding=1, use_bias=False, dtype=self.dtype)(x)
        y = bn()(y, train)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(y)
        y = bn()(y, train)
        if self.strides != 1 or x.shape[-1] != self.features:
            residual = nn.Conv(self.features, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.dtype)(x)
            residual = bn()(residual, train)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int  # output width (4x the squeeze width)
    strides: int = 1
    axis_name: str | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        bn = lambda: SyncBatchNorm(self.axis_name, self.dtype)
        squeeze = self.features // 4
        residual = x
        y = nn.Conv(squeeze, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.relu(bn()(y, train))
        y = nn.Conv(squeeze, (3, 3), (self.strides, self.strides), padding=1,
                    use_bias=False, dtype=self.dtype)(y)
        y = nn.relu(bn()(y, train))
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = bn()(y, train)
        if self.strides != 1 or x.shape[-1] != self.features:
            residual = nn.Conv(self.features, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.dtype)(x)
            residual = bn()(residual, train)
        return nn.relu(y + residual)


class ResNetEncoder(nn.Module):
    """5-feature pyramid backbone (resnet_encoder.py:94-113).

    __call__ takes NHWC [0,1] images, returns a list of 5 NHWC features at
    strides 2/4/8/16/32.
    """

    num_layers: int = 50
    num_input_images: int = 1
    axis_name: str | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool = True) -> list[Array]:
        if self.num_layers not in _STAGE_BLOCKS:
            raise ValueError(f"{self.num_layers} is not a valid resnet depth")
        blocks = _STAGE_BLOCKS[self.num_layers]
        block_cls = Bottleneck if self.num_layers in _BOTTLENECK else BasicBlock
        widths = encoder_channels(self.num_layers)[1:]

        # inline ImageNet normalization (resnet_encoder.py:96); the mean/std
        # tile across stacked input frames for multi-image input
        mean = jnp.asarray(IMAGENET_MEAN * self.num_input_images, x.dtype)
        std = jnp.asarray(IMAGENET_STD * self.num_input_images, x.dtype)
        x = (x - mean) / std
        x = x.astype(self.dtype)

        x = nn.Conv(64, (7, 7), (2, 2), padding=3, use_bias=False,
                    dtype=self.dtype)(x)
        x = SyncBatchNorm(self.axis_name, self.dtype)(x, train)
        conv1_out = nn.relu(x)

        x = nn.max_pool(conv1_out, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        feats = [conv1_out]
        for stage, (n_blocks, width) in enumerate(zip(blocks, widths)):
            for b in range(n_blocks):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = block_cls(width, strides, self.axis_name, self.dtype)(x, train)
            feats.append(x)
        return feats
