"""The full MPI prediction network: encoder + disparity-conditioned decoder,
plus the coarse-to-fine plane-placement wrapper.

Reference: synthesis_task.py:225-232 (mpi_predictor) and
operations/mpi_rendering.py:244-276 (predict_mpi_coarse_to_fine).

Input images must have H, W divisible by 128 (2^5 encoder stride x 2^2 extra
maxpools in the decoder extension) — the same constraint the reference carries
(mpi_rendering.py:270 comment).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

from mine_tpu.models.decoder import MPIDecoder
from mine_tpu.models.encoder import ResNetEncoder
from mine_tpu.ops.mpi_render import plane_volume_rendering
from mine_tpu.ops.sampling import sample_pdf


class MPINetwork(nn.Module):
    """src image (B,H,W,3 in [0,1]) + plane disparities (B,S) ->
    {scale: (B,S,H/2^s,W/2^s,4)} rgb+sigma MPIs."""

    num_layers: int = 50
    multires: int = 10
    use_alpha: bool = False
    scales: Sequence[int] = (0, 1, 2, 3)
    sigma_dropout_rate: float = 0.0
    axis_name: str | None = None  # data-replica BN sync axis
    # mesh axis the S planes shard over (SURVEY.md §5.7); the encoder and the
    # decoder's pre-conditioning layers see plane-replicated activations, so
    # only the decoder's post-conditioning BNs sync over it (decoder.py)
    plane_axis: str | None = None
    dtype: Any = jnp.float32
    decoder_width_multiple: int = 1  # perf knob, see decoder.py

    @nn.compact
    def __call__(self, src_imgs: Array, disparity: Array, train: bool = True):
        # component scopes (obs/attrib.py): every XLA op's metadata carries
        # the owning component, so profiler traces attribute device time to
        # encoder vs decoder — pure metadata, a numerics no-op (PARITY.md)
        with jax.named_scope("encoder"):
            feats = ResNetEncoder(
                num_layers=self.num_layers, axis_name=self.axis_name,
                dtype=self.dtype, name="backbone",
            )(src_imgs, train)
        with jax.named_scope("decoder"):
            return MPIDecoder(
                multires=self.multires, use_alpha=self.use_alpha,
                scales=self.scales, sigma_dropout_rate=self.sigma_dropout_rate,
                axis_name=self.axis_name, plane_axis=self.plane_axis,
                dtype=self.dtype, width_multiple=self.decoder_width_multiple,
                name="decoder",
            )(feats, disparity, train)


def predict_mpi_coarse_to_fine(
    predictor: Callable[[Array, Array], dict[int, Array]],
    src_imgs: Array,
    xyz_src_coarse: Array,
    disparity_coarse: Array,
    s_fine: int,
    key: Array | None = None,
    is_bg_depth_inf: bool = False,
) -> tuple[dict[int, Array], Array]:
    """Optionally refine plane placement with a second forward pass
    (mpi_rendering.py:244-276).

    With s_fine > 0: a stop-gradient coarse pass yields per-plane compositing
    weights, whose PDF is inverse-CDF sampled for S_fine extra disparities;
    the union is sorted descending (static shape S_coarse+S_fine — the sort
    runs inside jit) and a full differentiable pass is run on it.

    All shipped reference configs set num_bins_fine=0 (params_default.yaml:30),
    so the common path is a single pass.
    """
    if s_fine <= 0:
        return predictor(src_imgs, disparity_coarse), disparity_coarse

    assert key is not None, "coarse-to-fine sampling needs a PRNG key"
    coarse = jax.lax.stop_gradient(predictor(src_imgs, disparity_coarse))
    mpi0 = coarse[0]  # full-scale (B,S,H,W,4)
    _, _, _, weights = plane_volume_rendering(
        mpi0[..., 0:3], mpi0[..., 3:4], xyz_src_coarse, is_bg_depth_inf
    )
    # per-plane scalar weight: mean over pixels (mpi_rendering.py:258)
    w = jnp.mean(weights, axis=(2, 3, 4))  # (B, S)
    disparity_all = merge_fine_disparity(key, disparity_coarse, w, s_fine)
    return predictor(src_imgs, disparity_all), disparity_all


def merge_fine_disparity(
    key: Array, disparity_coarse: Array, w: Array, s_fine: int
) -> Array:
    """PDF-refine plane placement: (B, S) coarse disparities + (B, S)
    per-plane scalar weights -> stop-gradient (B, S + s_fine) merged list,
    sorted descending (the compositing order). The single home of the merge
    convention — the plane-sharded path (training/step.py) rebuilds `w`
    with one all_gather and must stay bit-compatible with the dense twin."""
    fine = sample_pdf(
        key, disparity_coarse[:, None, :],
        jax.lax.stop_gradient(w)[:, None, :], s_fine,
    )[:, 0, :]  # (B, s_fine)
    disparity_all = jnp.concatenate([disparity_coarse, fine], axis=1)
    return jax.lax.stop_gradient(-jnp.sort(-disparity_all, axis=1))
