"""Disparity-conditioned MPI decoder (U-Net over encoder skips).

Reference contract: network/monodepth2/depth_decoder.py:35-141 —
  * per-plane conditioning: disparity (B,S) is positionally encoded to
    (B*S, E) and concatenated onto EVERY skip feature; the batch axis becomes
    B*S so one decoder pass renders all planes (depth_decoder.py:88-109);
  * encoder extension (receptive-field bump): maxpool->1x1conv->maxpool->
    3x3conv->up->3x3conv->up->1x1conv over the deepest feature
    (depth_decoder.py:56-61, :92-96);
  * decoder: 5 up-stages of [ConvBlock, nearest-up x2, skip concat, ConvBlock]
    with widths [16,32,64,128,256] (depth_decoder.py:65-80, :117-126);
  * heads at scales 0..3: reflect-pad 3x3 conv -> 4ch; rgb=sigmoid, sigma =
    abs(x)+1e-4 (or sigmoid under use_alpha); optional per-plane sigma dropout
    (depth_decoder.py:127-139).

TPU-first: NHWC; nearest-up is two jnp.repeat's (bit-exact, fuses);
BatchNorm carries `axis_name` for cross-replica sync; optional remat over the
two heaviest (highest-resolution) stages trades FLOPs for HBM — the knob the
reference lacks and the reason it is stuck at one target view
(synthesis_task.py:203-204 "memory consumption is huge").
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

from mine_tpu.models.embedder import positional_encode
from mine_tpu.models.norm import SyncBatchNorm

NUM_CH_DEC = (16, 32, 64, 128, 256)


def nearest_up2(x: Array) -> Array:
    """Nearest-neighbor x2 upsample, NHWC (torch UpsamplingNearest2d parity)."""
    return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)


def _maxpool_3x3_s2(x: Array) -> Array:
    return nn.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))


class Conv3x3(nn.Module):
    """Reflection-pad 3x3 conv (monodepth2/layers.py:123-138)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
        return nn.Conv(self.features, (3, 3), padding="VALID", dtype=self.dtype)(x)


class ConvBlock(nn.Module):
    """Conv3x3 -> BN -> ELU (monodepth2/layers.py:106-120)."""

    features: int
    axis_name: str | tuple[str, ...] | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x = Conv3x3(self.features, self.dtype)(x)
        x = SyncBatchNorm(self.axis_name, self.dtype)(x, train)
        return nn.elu(x)


class ConvBNLeaky(nn.Module):
    """k x k conv (no bias) -> BN -> LeakyReLU(0.1) (depth_decoder.py:17-32)."""

    features: int
    kernel: int
    axis_name: str | tuple[str, ...] | None = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        pad = (self.kernel - 1) // 2
        x = nn.Conv(self.features, (self.kernel, self.kernel), padding=pad,
                    use_bias=False, dtype=self.dtype)(x)
        x = SyncBatchNorm(self.axis_name, self.dtype)(x, train)
        return nn.leaky_relu(x, negative_slope=0.1)


def join_axis_names(
    a: str | tuple[str, ...] | None, b: str | tuple[str, ...] | None
) -> str | tuple[str, ...] | None:
    """Combine BN sync-axis specs (None-aware)."""
    ta = (a,) if isinstance(a, str) else tuple(a or ())
    tb = (b,) if isinstance(b, str) else tuple(b or ())
    joined = ta + tb
    return joined if joined else None


class MPIDecoder(nn.Module):
    """features (5 x NHWC) + disparity (B,S) -> {scale: (B,S,h,w,4)} MPIs.

    `plane_axis`: mesh axis the S planes shard over (SURVEY.md §5.7), if any.
    Only layers DOWNSTREAM of the disparity concat vary over that axis, so
    only the up-stage BNs include it in their stat sync; the encoder-extension
    BNs see plane-replicated activations and sync over `axis_name` alone
    (pooling identical replicas would change nothing but waste a collective —
    and strict varying-axes checking rejects it outright).
    """

    multires: int = 10  # model.pos_encoding_multires (params_default.yaml:24)
    use_alpha: bool = False
    scales: Sequence[int] = (0, 1, 2, 3)
    use_skips: bool = True
    sigma_dropout_rate: float = 0.0
    axis_name: str | tuple[str, ...] | None = None
    plane_axis: str | None = None
    dtype: Any = jnp.float32
    # round up-stage widths UP to this multiple (model.decoder_width_multiple;
    # 1 = exact reference widths). The narrow 16/32-ch stages drive the MXU
    # at a fraction of its 128 lanes — padding trades wasted FLOPs for
    # better tiling; measure, don't assume
    width_multiple: int = 1

    @nn.compact
    def __call__(
        self, features: list[Array], disparity: Array, train: bool = True
    ) -> dict[int, Array]:
        b, s = disparity.shape

        # positional-encode disparity once; broadcast onto every skip
        # (depth_decoder.py:88-90). (B,S) -> (B*S, E)
        embed = positional_encode(disparity.reshape(b * s, 1), self.multires)
        embed = embed.astype(self.dtype)

        # encoder extension (depth_decoder.py:92-96)
        x = features[-1].astype(self.dtype)
        x = ConvBNLeaky(512, 1, self.axis_name, self.dtype)(_maxpool_3x3_s2(x), train)
        x = ConvBNLeaky(256, 3, self.axis_name, self.dtype)(_maxpool_3x3_s2(x), train)
        x = ConvBNLeaky(256, 3, self.axis_name, self.dtype)(nearest_up2(x), train)
        x = ConvBNLeaky(features[-1].shape[-1], 1, self.axis_name, self.dtype)(
            nearest_up2(x), train)

        def to_plane_batch(feat: Array) -> Array:
            """(B,h,w,C) -> (B*S,h,w,C+E): tile over planes, concat embedding
            (depth_decoder.py:97-109)."""
            _, h, w, c = feat.shape
            tiled = jnp.broadcast_to(feat[:, None], (b, s, h, w, c))
            tiled = tiled.reshape(b * s, h, w, c).astype(self.dtype)
            e = jnp.broadcast_to(embed[:, None, None, :], (b * s, h, w, embed.shape[-1]))
            return jnp.concatenate([tiled, e], axis=-1)

        # the loop only consumes skips[0..3]; the deepest feature enters via x
        skips = [to_plane_batch(f) for f in features[:-1]]
        x = to_plane_batch(x)

        # Rematerialization note: plane-axis memory pressure is handled one
        # level up — the train step wraps the whole (pure) decoder apply in
        # jax.checkpoint when cfg.remat_decoder is set, which composes cleanly
        # with BN's mutable batch_stats (see mine_tpu/training/step.py).
        outputs: dict[int, Array] = {}
        for i in range(4, -1, -1):
            stage = self._stage(i, train)
            x = stage(x, skips[i - 1] if (self.use_skips and i > 0) else None)
            if i in self.scales:
                raw = Conv3x3(4, self.dtype, name=f"dispconv_{i}")(x)
                h, w = raw.shape[1], raw.shape[2]
                mpi = raw.reshape(b, s, h, w, 4).astype(jnp.float32)
                rgb = nn.sigmoid(mpi[..., 0:3])
                if self.use_alpha:
                    sigma = nn.sigmoid(mpi[..., 3:4])
                else:
                    sigma = jnp.abs(mpi[..., 3:4]) + 1.0e-4
                if self.sigma_dropout_rate > 0.0 and train:
                    # per-plane channel dropout (depth_decoder.py:136-137)
                    keep = jax.random.bernoulli(
                        self.make_rng("dropout"),
                        1.0 - self.sigma_dropout_rate, (b, s, 1, 1, 1),
                    )
                    sigma = sigma * keep / (1.0 - self.sigma_dropout_rate)
                outputs[i] = jnp.concatenate([rgb, sigma], axis=-1)
        return outputs

    def _stage(self, i: int, train: bool):
        """One decoder up-stage (depth_decoder.py:120-126). Activations here
        carry the per-plane conditioning, so BN stats pool over the plane
        mesh axis too (matching the unsharded B*S batch statistics)."""
        stage_axes = join_axis_names(self.axis_name, self.plane_axis)
        m = max(self.width_multiple, 1)
        width = -(-NUM_CH_DEC[i] // m) * m
        up0 = ConvBlock(width, stage_axes, self.dtype,
                        name=f"upconv_{i}_0")
        up1 = ConvBlock(width, stage_axes, self.dtype,
                        name=f"upconv_{i}_1")

        def run(x: Array, skip: Array | None) -> Array:
            x = nearest_up2(up0(x, train))
            if skip is not None:
                x = jnp.concatenate([x, skip], axis=-1)
            return up1(x, train)

        return run
